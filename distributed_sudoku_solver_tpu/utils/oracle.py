"""Clean-room CPU oracle: deterministic DFS backtracker + solution validator.

Purpose (SURVEY.md §4): the reference has no tests, and its own checker is
broken (``/root/reference/sudoku.py:68`` NameError), so correctness there
rests on construction-time validity only.  Here the oracle is a *test
authority*: an independent, geometry-generic Python solver whose search order
deliberately matches the reference kernel's observable semantics —

* branch on the **first empty cell in row-major order**
  (``/root/reference/utils.py:14-25`` ``find_next_empty``), and
* try digits in **ascending order** (``/root/reference/DHT_Node.py:522``),

so the first solution it returns is the lexicographically-least completion,
the same solution the reference's DFS finds.  The TPU solver is tested
bit-exact against this oracle (and, on unique-solution puzzles, against any
complete solver).

Not written for speed — written to be obviously correct.  It still uses
bitmasks rather than the reference's list scans; there is no shared code or
structure with ``/root/reference``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import Geometry, geometry_for_size


def _box_index(geom: Geometry, r: int, c: int) -> int:
    return (r // geom.box_h) * geom.n_hboxes + (c // geom.box_w)


def is_valid_solution(grid, geom: Optional[Geometry] = None) -> bool:
    """True iff ``grid`` is a complete, consistent board (every unit = 1..n)."""
    g = np.asarray(grid, dtype=np.int64)
    n = g.shape[0]
    geom = geom or geometry_for_size(n)
    if g.shape != (n, n) or g.min() < 1 or g.max() > n:
        return False
    want = frozenset(range(1, n + 1))
    for i in range(n):
        if frozenset(g[i, :]) != want or frozenset(g[:, i]) != want:
            return False
    for br in range(geom.n_vboxes):
        for bc in range(geom.n_hboxes):
            box = g[
                br * geom.box_h : (br + 1) * geom.box_h,
                bc * geom.box_w : (bc + 1) * geom.box_w,
            ]
            if frozenset(box.ravel()) != want:
                return False
    return True


def is_consistent_partial(grid, geom: Optional[Geometry] = None) -> bool:
    """True iff no unit of ``grid`` repeats a nonzero digit (0 = empty ok)."""
    g = np.asarray(grid, dtype=np.int64)
    n = g.shape[0]
    geom = geom or geometry_for_size(n)
    rows = [0] * n
    cols = [0] * n
    boxes = [0] * n
    for r in range(n):
        for c in range(n):
            v = int(g[r, c])
            if v == 0:
                continue
            bit = 1 << (v - 1)
            b = _box_index(geom, r, c)
            if (rows[r] | cols[c] | boxes[b]) & bit:
                return False
            rows[r] |= bit
            cols[c] |= bit
            boxes[b] |= bit
    return True


def solve_oracle(
    grid,
    geom: Optional[Geometry] = None,
    count_nodes: bool = False,
):
    """Solve by deterministic DFS; returns np.int64[n, n] or None if unsat.

    With ``count_nodes=True`` returns ``(solution_or_None, nodes_expanded)``
    where a "node" is one cell-assignment attempt — comparable to the
    reference's ``validations`` counter (``/root/reference/DHT_Node.py:512``).
    """
    g = np.asarray(grid, dtype=np.int64).copy()
    n = g.shape[0]
    geom = geom or geometry_for_size(n)
    full = geom.full_mask

    rows = [0] * n
    cols = [0] * n
    boxes = [0] * n
    empties = []
    for r in range(n):
        for c in range(n):
            v = int(g[r, c])
            if v == 0:
                empties.append((r, c))
                continue
            bit = 1 << (v - 1)
            b = _box_index(geom, r, c)
            if (rows[r] | cols[c] | boxes[b]) & bit:
                return (None, 0) if count_nodes else None
            rows[r] |= bit
            cols[c] |= bit
            boxes[b] |= bit

    nodes = 0

    def dfs(i: int) -> bool:
        nonlocal nodes
        if i == len(empties):
            return True
        r, c = empties[i]  # first-empty, row-major: empties was built row-major
        b = _box_index(geom, r, c)
        avail = full & ~(rows[r] | cols[c] | boxes[b])
        while avail:
            bit = avail & -avail  # ascending digit order
            avail &= avail - 1
            nodes += 1
            rows[r] |= bit
            cols[c] |= bit
            boxes[b] |= bit
            g[r, c] = bit.bit_length()
            if dfs(i + 1):
                return True
            rows[r] &= ~bit
            cols[c] &= ~bit
            boxes[b] &= ~bit
            g[r, c] = 0
        return False

    ok = dfs(0)
    sol = g if ok else None
    return (sol, nodes) if count_nodes else sol


def count_solutions(grid, geom: Optional[Geometry] = None, limit: int = 2) -> int:
    """Count solutions up to ``limit`` (uniqueness checks for test fixtures)."""
    g = np.asarray(grid, dtype=np.int64).copy()
    n = g.shape[0]
    geom = geom or geometry_for_size(n)
    full = geom.full_mask

    rows = [0] * n
    cols = [0] * n
    boxes = [0] * n
    empties = []
    for r in range(n):
        for c in range(n):
            v = int(g[r, c])
            if v == 0:
                empties.append((r, c))
                continue
            bit = 1 << (v - 1)
            b = _box_index(geom, r, c)
            if (rows[r] | cols[c] | boxes[b]) & bit:
                return 0
            rows[r] |= bit
            cols[c] |= bit
            boxes[b] |= bit

    found = 0

    def dfs(i: int) -> bool:
        nonlocal found
        if i == len(empties):
            found += 1
            return found >= limit
        r, c = empties[i]
        b = _box_index(geom, r, c)
        avail = full & ~(rows[r] | cols[c] | boxes[b])
        while avail:
            bit = avail & -avail
            avail &= avail - 1
            rows[r] |= bit
            cols[c] |= bit
            boxes[b] |= bit
            stop = dfs(i + 1)
            rows[r] &= ~bit
            cols[c] &= ~bit
            boxes[b] &= ~bit
            if stop:
                return True
        return False

    dfs(0)
    return found
