"""Force JAX onto the in-process CPU backend, evicting device plugins.

Setting ``JAX_PLATFORMS=cpu`` in the environment is not enough when a device
plugin (e.g. a TPU tunnel) was already *registered* by the interpreter's
sitecustomize: the captured env is stale, and the first ``jax.devices()``
would still initialize the tunnel backend (dialing out, and serializing on
the tunnel).  Used by both ``tests/conftest.py`` (8-virtual-device suite)
and ``__graft_entry__.dryrun_multichip`` — keep the private-API poking in
this one place.
"""

from __future__ import annotations

import os


def force_cpu_backend(n_devices: int | None = None) -> None:
    """Pin this process to the CPU backend; optionally fake ``n_devices`` chips.

    Must run before any JAX *backend* is initialized (importing jax is fine;
    calling ``jax.devices()`` is not).  Safe to call more than once.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if n_devices is not None and "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        )
    try:
        import jax
        import jax._src.xla_bridge as _xb

        # Import every module that registers per-platform MLIR lowering rules
        # *before* evicting backend factories: once the 'tpu' factory is
        # popped the platform name is unknown, and a later first import of
        # e.g. pallas/checkify would raise NotImplementedError registering
        # its tpu rules.
        import jax._src.checkify  # noqa: F401
        from jax.experimental import pallas  # noqa: F401

        # sitecustomize may have imported jax already (capturing the outer
        # env), so update the live config, not just the env var, and drop
        # every non-CPU backend factory.
        jax.config.update("jax_platforms", "cpu")
        for name in list(_xb._backend_factories):
            if name != "cpu":
                _xb._backend_factories.pop(name, None)
    except Exception:  # pragma: no cover - plugin layout changed; env remains
        pass
