"""Node CLI — flag-compatible superset of the reference's entry point.

The reference boots one node with ``-p`` (HTTP port), ``-s`` (P2P port),
``-a`` (anchor host:port), ``-d`` (handicap ms) — ``/root/reference/
DHT_Node.py:623-628``.  Same four knobs here, same meanings, plus the TPU
knobs the reference could never expose (mesh size, lanes, stack depth).

The handicap is kept as a *slow-node simulator* for observing cluster load
balancing, exactly the reference's purpose for it (SURVEY.md §5.3): an
artificial per-job sleep in the host engine.  It never touches the device
path.
"""

from __future__ import annotations

import argparse
import time

from distributed_sudoku_solver_tpu.cluster.node import ClusterConfig, ClusterNode
from distributed_sudoku_solver_tpu.cluster.wire import parse_addr
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.ops.propagate import RULE_TIERS
from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
from distributed_sudoku_solver_tpu.serving.http import ApiServer


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="distributed_sudoku_solver_tpu",
        description=(
            "TPU-native distributed constraint-satisfaction node "
            "(default command), or `solve-file` for offline bulk solving"
        ),
    )
    ap.add_argument("-p", "--http-port", type=int, default=8000)
    ap.add_argument("-s", "--p2p-port", type=int, default=7000)
    ap.add_argument("-a", "--anchor", type=str, default=None, help="host:port of any cluster member")
    ap.add_argument("-d", "--handicap", type=float, default=0, help="artificial per-job delay, ms (slow-node simulator)")
    ap.add_argument("--host", type=str, default="0.0.0.0", help="bind address")
    ap.add_argument(
        "--advertise-host",
        type=str,
        default=None,
        help="address peers dial (default: auto-detected routable IP)",
    )
    ap.add_argument("--lanes", type=int, default=0, help="frontier lanes (0 = auto)")
    ap.add_argument("--stack-slots", type=int, default=64)
    ap.add_argument(
        "--rules",
        choices=RULE_TIERS,
        default="basic",
        help="propagation strength (extended adds box-line reductions, "
        "subsets adds naked-subset eliminations)",
    )
    ap.add_argument(
        "--branch",
        choices=(
            "minrem", "first", "mixed", "minrem-desc",
            "head:minrem", "head:cw-slack", "head:mlp",
        ),
        default="minrem",
        help="branch heuristic (first = reference-order bit-exact DFS; "
        "minrem-desc = MRV with descending digit order, the portfolio "
        "mirror; head:* = scored branch heads, ops/ordering.py — "
        "head:minrem is bit-exact to minrem, head:cw-slack weights MRV "
        "by peer-unit slack, head:mlp is the trained prior from "
        "benchmarks/train_ordering.py)",
    )
    ap.add_argument("--max-batch", type=int, default=256)
    ap.add_argument(
        "--no-resident",
        action="store_true",
        help="disable the continuous-batching resident flight (serving/"
        "scheduler.py); /solve then never answers 429 and every job runs "
        "in a static flight",
    )
    ap.add_argument(
        "--resident-slots",
        type=int,
        default=16,
        help="resident job slots per geometry (concurrent jobs packed into "
        "one long-lived frontier)",
    )
    ap.add_argument(
        "--resident-gang",
        type=int,
        default=8,
        help="lanes per resident job slot (per-job speculation width)",
    )
    ap.add_argument(
        "--resident-queue",
        type=int,
        default=64,
        help="resident admission-queue bound; beyond it /solve answers "
        "429 + Retry-After",
    )
    ap.add_argument(
        "--mesh-devices",
        type=int,
        default=0,
        help="shard the resident flight's lane axis over N devices "
        "(serving/mesh_scheduler.py): slot pool and throughput scale "
        "with N, one host sync per chunk still.  0/1 = single-chip "
        "resident flight; N must divide the visible device count",
    )
    ap.add_argument(
        "--latency-mode",
        action="store_true",
        help="serve every eligible /solve through the megastep tier "
        "(serving/megastep.py): the whole advance loop fuses into one "
        "donated device dispatch with in-graph early exit — one host "
        "sync per request instead of one per chunk.  Per-request opt-in "
        "stays available via POST /solve?latency=1 without this flag",
    )
    ap.add_argument(
        "--megastep-chunks",
        type=int,
        default=64,
        help="megastep in-graph loop bound (flight step budget = "
        "chunk-steps x this); a board still holding work past it "
        "degrades to the chunked resident path",
    )
    ap.add_argument(
        "--no-frontdoor",
        action="store_true",
        help="bypass the front door (serving/frontdoor): no symmetry-"
        "canonical result cache, no propagation probe, no native "
        "routing — every /solve pays the direct engine path, as before "
        "round 17",
    )
    ap.add_argument(
        "--cache-entries",
        type=int,
        default=65536,
        help="front-door result-cache capacity (canonical entries; LRU "
        "beyond it).  An entry is one solved or proven-unsat orbit — "
        "every symmetry-equivalent resubmission answers from it",
    )
    ap.add_argument(
        "--easy-score",
        type=int,
        default=64,
        help="front-door difficulty threshold: boards whose post-"
        "propagation branching slack (sum of candidates-1 over undecided "
        "cells) is at or below this race the native DFS instead of "
        "paying a device dispatch",
    )
    ap.add_argument(
        "--learn-easy-score",
        type=str,
        default=None,
        metavar="TRACE",
        help="learn the --easy-score threshold from a recorded ordering "
        "trace (obs/ordertrace.py JSONL, recorded with --ordering-trace) "
        "instead of the fixed default: the route/wall outcomes in the "
        "trace pick the score cut that minimizes estimated total wall "
        "(serving/frontdoor/learn.py).  Falls back to --easy-score when "
        "the trace is too thin to price both routes",
    )
    ap.add_argument(
        "--ordering-trace",
        type=str,
        default=None,
        metavar="PATH",
        help="journal route outcomes + sampled grids to this JSONL file "
        "(obs/ordertrace.py) — the training input for "
        "benchmarks/train_ordering.py (the mlp branch head and the "
        "learned easy-score threshold).  Off by default: zero overhead "
        "when unset",
    )
    ap.add_argument(
        "--ordering-sample",
        type=int,
        default=8,
        metavar="K",
        help="with --ordering-trace, record every K-th resolved grid as "
        "a branch-example source (1 = every grid)",
    )
    ap.add_argument(
        "--fault-retries",
        type=int,
        default=3,
        help="per-job retry budget for transient device faults (OOM, "
        "preemption, runtime errors) before the job fails "
        "(serving/faults.py)",
    )
    ap.add_argument(
        "--rebuild-cooldown",
        type=float,
        default=0.25,
        help="seconds before a failed resident flight is rebuilt (its jobs "
        "are requeued, not errored)",
    )
    ap.add_argument(
        "--breaker-failures",
        type=int,
        default=3,
        help="consecutive resident rebuild failures that open the circuit "
        "breaker (admission then falls back to static flights)",
    )
    ap.add_argument(
        "--breaker-cooldown",
        type=float,
        default=2.0,
        help="seconds an open breaker waits before half-opening (the next "
        "admission probes a rebuild)",
    )
    ap.add_argument("--sharded", action="store_true", help="shard lanes over all visible devices")
    ap.add_argument("--heartbeat-s", type=float, default=1.0)
    ap.add_argument(
        "--part-deadline",
        type=float,
        default=0.0,
        help="seconds before a shed subtree part stuck on a wedged-but-"
        "alive peer is re-homed locally (0 = off: the failure detector "
        "covers real deaths, and a deep search can legitimately run long; "
        "see README 'Cluster failure semantics' for the false-death-vs-"
        "duplicated-work tradeoff)",
    )
    ap.add_argument(
        "--trace",
        action="store_true",
        help="enable the per-job flight-recorder trace plane (obs/trace.py): "
        "spans from HTTP accept through device chunks to resolution, "
        "served on GET /trace[/uuid] and GET /trace?format=perfetto, with "
        "automatic flight-recorder dumps on permanent faults and "
        "breaker-open transitions",
    )
    ap.add_argument(
        "--trace-ring",
        type=int,
        default=4096,
        help="flight-recorder ring size in spans (the recent past the "
        "trace endpoints and crash dumps can see)",
    )
    ap.add_argument(
        "--trace-dump",
        type=str,
        default=None,
        help="directory for automatic flight-recorder dumps "
        "(default: <tmpdir>/dsst-flightrec when --trace is on)",
    )
    ap.add_argument(
        "--no-compile-watch",
        action="store_true",
        help="disable the production compile/recompile watch "
        "(obs/compilewatch.py) — on by default: per-program XLA compile "
        "counts/walls on /metrics, and after the warmup window an "
        "unexpected recompile logs [compile <program>], counts, and "
        "fires one flight-recorder dump per excursion (with --trace)",
    )
    ap.add_argument(
        "--compile-warmup",
        type=float,
        default=300.0,
        help="seconds after boot during which compilations are expected "
        "(the serving set compiling once); afterwards any compile is an "
        "unexpected-recompile alarm",
    )
    ap.add_argument(
        "--compile-rearm",
        type=float,
        default=300.0,
        help="quiet seconds after which the one-dump-per-excursion "
        "recompile alarm re-arms",
    )
    ap.add_argument(
        "--peak-gflops",
        type=float,
        default=None,
        help="the device's peak GFLOP/s (operator-supplied; no backend "
        "exposes it) — turns the cost plane's achieved-GFLOP/s gauge "
        "into a device-efficiency ratio against the cost-model ceiling",
    )
    ap.add_argument(
        "--critpath-slow-ms",
        type=float,
        default=0.0,
        help="slow-job watchdog threshold for per-job critical-path "
        "dumps (obs/critpath.py; needs --trace).  0 = derive from the "
        "--slo latency objectives (off when neither is set)",
    )
    ap.add_argument(
        "--slo",
        type=str,
        default=None,
        help="declarative service-level objectives (obs/slo.py), e.g. "
        '--slo "solve_p95_ms<=250,error_rate<=0.01" — windowed error-'
        "budget burn rates surface on GET /slo and /metrics, and a "
        "burn-rate threshold crossing triggers a flight-recorder dump "
        "(when --trace is on)",
    )
    ap.add_argument(
        "--slo-window",
        type=float,
        default=60.0,
        help="sliding window (seconds) for SLO burn-rate computation",
    )
    ap.add_argument(
        "--slo-burn",
        type=float,
        default=1.0,
        help="burn-rate threshold that flips an objective to burning "
        "(1.0 = consuming the error budget exactly at the sustained "
        "allowable rate)",
    )
    ap.add_argument(
        "--brownout",
        action="store_true",
        help="force the brownout controller on even without --slo "
        "(serving/brownout.py; queue/wait/floor signals still drive the "
        "ladder — there is just no burn signal).  With --slo the "
        "controller is on by default",
    )
    ap.add_argument(
        "--no-brownout",
        action="store_true",
        help="disable SLO-burn-driven load shedding: a burn crossing "
        "then only dumps the flight recorder, as before round 18",
    )
    ap.add_argument(
        "--brownout-enter",
        type=float,
        default=1.0,
        help="pressure (normalized: 1.0 = at the configured limit — max "
        "over SLO burn, resident queue fill, admission-wait p95, "
        "rpc-floor drift) at which the brownout ladder climbs one stage",
    )
    ap.add_argument(
        "--brownout-exit",
        type=float,
        default=0.5,
        help="pressure at or below which calm accrues; after "
        "--brownout-quiet continuous seconds of calm the ladder steps "
        "down one stage (must be < --brownout-enter: the hysteresis band)",
    )
    ap.add_argument(
        "--brownout-quiet",
        type=float,
        default=15.0,
        help="continuous calm (pressure <= --brownout-exit) before the "
        "brownout ladder de-escalates one stage",
    )
    ap.add_argument(
        "--journal-dir",
        type=str,
        default=None,
        help="durable job journal (serving/journal.py): every accepted "
        "/solve is WAL-logged here before the 201, unresolved entries "
        "replay through the normal submit path on restart (at-least-once "
        "with uuid dedupe), SIGTERM walks the drain ladder (finish / "
        "hand off to a healthy peer / journal) instead of dropping "
        "accepted work, and the front-door hot set persists beside the "
        "WAL.  Off by default: zero disk I/O when unset",
    )
    ap.add_argument(
        "--access-log",
        action="store_true",
        help="log one INFO record per HTTP request (logger "
        "distributed_sudoku_solver_tpu.serving.http.access); previously "
        "access logging was silently swallowed",
    )
    ap.add_argument(
        "--profile-dir",
        type=str,
        default=None,
        help="capture a jax.profiler device trace into this dir "
        "(TensorBoard-compatible; SURVEY.md §5.1); bounded windows are "
        "also available at runtime via POST /profile",
    )
    ap.add_argument(
        "--profile-secs",
        type=float,
        default=60.0,
        help="bound the --profile-dir capture window (trace data grows "
        "unboundedly on a long-lived node; 0 = whole lifetime)",
    )
    sub = ap.add_subparsers(dest="cmd", metavar="{solve-file}")
    build_solve_file_parser(sub)
    return ap


def make_engine(args) -> SolverEngine:
    cfg = SolverConfig(
        lanes=args.lanes,
        stack_slots=args.stack_slots,
        rules=args.rules,
        branch=args.branch,
    )
    solve_fn = None
    if args.sharded:
        from distributed_sudoku_solver_tpu.parallel import solve_batch_sharded

        solve_fn = lambda grids, geom, c: solve_batch_sharded(grids, geom, c)  # noqa: E731
    resident = None
    if not args.no_resident and solve_fn is None:
        # Continuous batching is on by default for serving nodes (the
        # sharded solve_fn override keeps the legacy one-dispatch path and
        # has no flight loop to host a resident frontier).
        from distributed_sudoku_solver_tpu.serving.scheduler import ResidentConfig

        resident = ResidentConfig(
            job_slots=args.resident_slots,
            gang_lanes=args.resident_gang,
            queue_depth=args.resident_queue,
            mesh_devices=args.mesh_devices,
        )
    from distributed_sudoku_solver_tpu.serving.faults import RecoveryPolicy

    frontdoor = None
    if not args.no_frontdoor:
        # The front door (serving/frontdoor) is the default routing layer
        # for POST /solve: canonical result cache, propagation probe,
        # native routing for the easy tier (ISSUE 14 / ROADMAP #3).
        from distributed_sudoku_solver_tpu.serving.frontdoor.router import (
            FrontDoorConfig,
        )

        easy_score = args.easy_score
        if args.learn_easy_score:
            # The learned routing threshold (ROADMAP #4 follow-through):
            # replayed route/wall outcomes pick the cut; a too-thin trace
            # keeps the flag default (the learner says why).
            from distributed_sudoku_solver_tpu.serving.frontdoor.learn import (
                learned_easy_score,
            )

            easy_score, report = learned_easy_score(
                args.learn_easy_score, default=args.easy_score
            )
            print(
                f"easy-score: {easy_score} "
                f"({'learned from ' + args.learn_easy_score if report.get('fitted') else report.get('reason', 'default')})"
            )
        frontdoor = FrontDoorConfig(
            cache_entries=args.cache_entries,
            easy_score=easy_score,
        )
    megastep = None
    if solve_fn is None:
        # The megastep tier needs the flight loop's jitted seams, so the
        # sharded solve_fn override (legacy one-dispatch path) excludes
        # it.  The config exists even when latency_mode is off: the
        # per-request /solve?latency=1 opt-in still routes here.
        from distributed_sudoku_solver_tpu.serving.megastep import MegastepConfig

        megastep = MegastepConfig(
            gang_lanes=args.resident_gang,
            max_chunks=args.megastep_chunks,
        )
    journal = None
    if getattr(args, "journal_dir", None):
        # The durable lifecycle (ISSUE 20): the WAL boots BEFORE the
        # engine so the very first accepted job is journaled; recovery
        # replays after the cluster node joins (main()).
        from distributed_sudoku_solver_tpu.serving.journal import Journal

        journal = Journal(args.journal_dir)
    return SolverEngine(
        config=cfg,
        max_batch=args.max_batch,
        solve_fn=solve_fn,
        handicap_s=args.handicap / 1000.0,
        resident=resident,
        recovery=RecoveryPolicy(
            max_retries=args.fault_retries,
            rebuild_cooldown_s=args.rebuild_cooldown,
            breaker_failures=args.breaker_failures,
            breaker_cooldown_s=args.breaker_cooldown,
        ),
        frontdoor=frontdoor,
        latency_mode=args.latency_mode,
        megastep=megastep,
        journal=journal,
    )


def build_solve_file_parser(sub) -> argparse.ArgumentParser:
    desc = "Bulk-solve a puzzle file (one board per line / Kaggle CSV)"
    ap = sub.add_parser("solve-file", help=desc, description=desc)
    ap.add_argument("input", help="input board file")
    ap.add_argument("-o", "--output", default=None, help="write solutions (line-aligned)")
    ap.add_argument("-n", "--size", type=int, default=9, help="board size n (9/16/25)")
    ap.add_argument("--batch", type=int, default=65536, help="boards per device batch")
    ap.add_argument(
        "--rules",
        choices=RULE_TIERS,
        default="extended",
        help="propagation strength (extended adds box-line reductions, "
        "subsets adds naked-subset eliminations)",
    )
    return ap


def solve_file_main(args) -> None:
    """`solve-file` subcommand: bulk-solve a board file through ops/bulk.py."""
    import json

    from distributed_sudoku_solver_tpu.models.geometry import geometry_for_size
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig
    from distributed_sudoku_solver_tpu.utils import dataset

    geom = geometry_for_size(args.size)
    t0 = time.perf_counter()
    stats = dataset.solve_file(
        args.input,
        args.output,
        geom,
        batch=args.batch,
        bulk_config=BulkConfig(rules=args.rules),
    )
    stats["wall_s"] = round(time.perf_counter() - t0, 3)
    stats["boards_per_s"] = round(stats["total"] / max(stats["wall_s"], 1e-9), 1)
    print(json.dumps(stats))


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache: a fresh CLI process reuses compiled
    programs from any earlier run (first compile of the bulk shapes costs
    ~20-40 s; warm processes skip it entirely)."""
    import os

    import jax

    cache = os.environ.get(
        "DSST_XLA_CACHE",
        # User cache dir, not the package tree: an installed distribution's
        # site-packages is often read-only (cache silently never persists)
        # or shared (root-owned pollution).
        os.path.join(
            os.environ.get(
                "XDG_CACHE_HOME", os.path.join(os.path.expanduser("~"), ".cache")
            ),
            "distributed_sudoku_solver_tpu",
            "xla",
        ),
    )
    jax.config.update("jax_compilation_cache_dir", os.path.abspath(cache))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    _enable_compile_cache()
    if getattr(args, "cmd", None) == "solve-file":
        solve_file_main(args)
        return
    import contextlib

    from distributed_sudoku_solver_tpu.utils.profiling import device_trace

    if args.access_log:
        # The access logger emits INFO records; logging's lastResort
        # handler only surfaces WARNING+ — give it a real stderr handler
        # so the flag actually produces output on an unconfigured process.
        import logging

        acc = logging.getLogger(
            "distributed_sudoku_solver_tpu.serving.http.access"
        )
        if not acc.handlers:
            handler = logging.StreamHandler()
            handler.setFormatter(
                logging.Formatter("%(asctime)s %(name)s %(message)s")
            )
            acc.addHandler(handler)
            acc.setLevel(logging.INFO)
    if args.trace:
        import os
        import tempfile

        from distributed_sudoku_solver_tpu.obs import critpath as critpath_mod
        from distributed_sudoku_solver_tpu.obs import trace as trace_mod

        trace_mod.install(
            trace_mod.TraceRecorder(
                ring=args.trace_ring,
                dump_dir=args.trace_dump
                or os.path.join(tempfile.gettempdir(), "dsst-flightrec"),
            )
        )
        # Per-job critical-path attribution rides the trace plane (it
        # decomposes the recorder's stitched spans, so without --trace
        # there is nothing to attribute).  The slow-job threshold falls
        # back to the --slo latency objectives when not pinned here.
        critpath_mod.install(
            critpath_mod.CritPathMonitor(
                slow_ms=args.critpath_slow_ms or None
            )
        )
    if args.ordering_trace:
        # The opt-in ordering journal (obs/ordertrace.py): route outcomes
        # + sampled grids, the raw material for the offline branch-head
        # and threshold trainers.  Installed before the engine boots so
        # the warmup solves are journaled too.
        from distributed_sudoku_solver_tpu.obs import (
            ordertrace as ordertrace_mod,
        )

        ordertrace_mod.install(
            ordertrace_mod.OrderTraceRecorder(
                args.ordering_trace, sample_grids=args.ordering_sample
            )
        )
    if not args.no_compile_watch:
        # The production compile watch is on by default: registering the
        # jax monitoring listeners costs one global read per compile
        # event, and the watch resolves the ENTRY_POINTS programs the
        # node is about to import anyway.  Installed BEFORE the engine
        # boots so the warmup window covers the serving set's first
        # compilations.
        from distributed_sudoku_solver_tpu.obs import (
            compilewatch as compilewatch_mod,
        )

        compilewatch_mod.install(
            compilewatch_mod.CompileWatch(
                warmup_s=args.compile_warmup,
                rearm_s=args.compile_rearm,
                peak_gflops=args.peak_gflops,
            )
        )
    slo_monitor = None
    if args.slo:
        from distributed_sudoku_solver_tpu.obs import slo as slo_mod

        # Parse before anything heavy boots: a typo in the grammar should
        # fail the command, not a node an hour into serving.
        slo_monitor = slo_mod.SloMonitor(
            slo_mod.parse_slo(args.slo),
            window_s=args.slo_window,
            burn_threshold=args.slo_burn,
        )
        slo_mod.install(slo_monitor)
    trace = device_trace(args.profile_dir) if args.profile_dir else contextlib.nullcontext()
    with contextlib.ExitStack() as stack:
        # try/finally semantics: the trace survives any exit path.  A bounded
        # window (--profile-secs) stops capture without stopping the node —
        # a lifetime-long trace grows without bound on a serving process.
        stack.enter_context(trace)
        if args.profile_dir and args.profile_secs > 0:
            import threading

            def _stop_trace():
                # Swallows only the already-stopped case; a real profiler
                # failure is logged (utils/profiling.py satellite fix).
                from distributed_sudoku_solver_tpu.utils.profiling import (
                    _stop_trace_quietly,
                )

                _stop_trace_quietly()
                print(f"profile window closed ({args.profile_secs:g}s)")

            timer = threading.Timer(args.profile_secs, _stop_trace)
            timer.daemon = True
            timer.start()
            stack.callback(timer.cancel)
        engine = make_engine(args).start()
        if slo_monitor is not None:
            # Burn dumps embed a metrics snapshot; injected here because
            # obs/slo.py never imports the serving layer back.
            slo_monitor.metrics_fn = engine.metrics
        if not args.no_brownout and (args.brownout or args.slo):
            # Close the observability->control loop (serving/brownout.py):
            # on by default whenever --slo is set — a node that measures
            # its burn should act on it.  Bound post-boot because the
            # signal closures read the live engine (the slo metrics_fn
            # pattern above).
            from distributed_sudoku_solver_tpu.serving import (
                brownout as brownout_mod,
            )

            ctrl = brownout_mod.BrownoutController(
                brownout_mod.BrownoutConfig(
                    enter=args.brownout_enter,
                    exit=args.brownout_exit,
                    quiet_s=args.brownout_quiet,
                )
            )
            brownout_mod.bind_engine(ctrl, engine)
            brownout_mod.install(ctrl)
        node = ClusterNode(
            engine,
            host=args.host,
            port=args.p2p_port,
            anchor=parse_addr(args.anchor) if args.anchor else None,
            config=ClusterConfig(
                heartbeat_s=args.heartbeat_s,
                part_deadline_s=args.part_deadline,
            ),
            advertise_host=args.advertise_host,
        ).start()
        api = ApiServer(
            node, host=args.host, port=args.http_port,
            access_log=args.access_log,
        ).start()
        print(
            f"node up: http={args.host}:{api.port} p2p={node.addr_s} "
            f"coordinator={node.coordinator}"
        )
        if args.journal_dir:
            # Crash recovery AFTER the ring join: replayed jobs route
            # through the normal submit seam, exactly like fresh ones
            # (at-least-once; verdict dedupe makes the replay idempotent).
            n = node.recover()
            if n:
                print(f"journal: replayed {n} unresolved job(s)")
        import signal
        import threading

        term = threading.Event()
        try:
            # Orchestrators speak SIGTERM: flag it, drain on the main
            # thread below (signal handlers must stay trivial).
            signal.signal(signal.SIGTERM, lambda signum, frame: term.set())
        except ValueError:
            pass  # not the main thread (embedded use): ^C still works
        try:
            while not term.is_set():
                time.sleep(1)
            # Graceful stop: walk the drain ladder (finish in-flight work,
            # hand unstarted jobs to a healthy peer or journal them,
            # persist the front-door hot set, fsync the WAL) BEFORE
            # leaving the ring — an accepted job is never dropped.
            print("SIGTERM: draining...")
            print(f"drain: {node.drain()}")
        except KeyboardInterrupt:
            print("stopping...")
        api.stop()
        node.stop()
        engine.stop()
        if engine.journal is not None:
            engine.journal.shutdown()


if __name__ == "__main__":
    main()
