"""Pentomino tilings as exact cover (BASELINE.json config 5).

Tile an h x w rectangle (h*w == 60) with the 12 distinct pentominoes, each
used exactly once.  Row = one placement (piece, orientation, offset);
columns = 12 piece ids + h*w board cells, all primary — the classic DLX
benchmark instance, solved by the same engine as Sudoku.
"""

from __future__ import annotations

import functools

import numpy as np

from distributed_sudoku_solver_tpu.models.cover import ExactCoverCSP, build_cover

# The 12 pentominoes (Conway naming), as (row, col) cell sets.
PENTOMINOES: dict[str, tuple[tuple[int, int], ...]] = {
    "F": ((0, 1), (0, 2), (1, 0), (1, 1), (2, 1)),
    "I": ((0, 0), (1, 0), (2, 0), (3, 0), (4, 0)),
    "L": ((0, 0), (1, 0), (2, 0), (3, 0), (3, 1)),
    "N": ((0, 1), (1, 1), (2, 0), (2, 1), (3, 0)),
    "P": ((0, 0), (0, 1), (1, 0), (1, 1), (2, 0)),
    "T": ((0, 0), (0, 1), (0, 2), (1, 1), (2, 1)),
    "U": ((0, 0), (0, 2), (1, 0), (1, 1), (1, 2)),
    "V": ((0, 0), (1, 0), (2, 0), (2, 1), (2, 2)),
    "W": ((0, 0), (1, 0), (1, 1), (2, 1), (2, 2)),
    "X": ((0, 1), (1, 0), (1, 1), (1, 2), (2, 1)),
    "Y": ((0, 1), (1, 0), (1, 1), (2, 1), (3, 1)),
    "Z": ((0, 0), (0, 1), (1, 1), (2, 1), (2, 2)),
}

PIECE_NAMES = tuple(PENTOMINOES)


def _normalize(cells: frozenset[tuple[int, int]]) -> tuple[tuple[int, int], ...]:
    r0 = min(r for r, _ in cells)
    c0 = min(c for _, c in cells)
    return tuple(sorted((r - r0, c - c0) for r, c in cells))


def orientations(cells) -> list[tuple[tuple[int, int], ...]]:
    """All distinct rotations/reflections of a cell set (1, 2, 4 or 8)."""
    seen = set()
    cur = frozenset(cells)
    for _ in range(2):
        for _ in range(4):
            seen.add(_normalize(cur))
            cur = frozenset((c, -r) for r, c in cur)  # rotate 90 degrees
        cur = frozenset((r, -c) for r, c in cur)  # reflect
    return sorted(seen)


@functools.lru_cache(maxsize=None)
def placements(
    height: int, width: int
) -> tuple[tuple[int, tuple[int, int], tuple[tuple[int, int], ...]], ...]:
    """All (piece, offset, oriented-shape) placements, in cover-row order.

    This enumeration order *defines* the row indices of
    :func:`pentomino_cover`; decoding looks placements up by that index.
    """
    out = []
    for p, name in enumerate(PIECE_NAMES):
        for shape in orientations(PENTOMINOES[name]):
            sh = max(r for r, _ in shape) + 1
            sw = max(c for _, c in shape) + 1
            for r0 in range(height - sh + 1):
                for c0 in range(width - sw + 1):
                    out.append((p, (r0, c0), shape))
    return tuple(out)


def pentomino_cover(
    height: int = 6, width: int = 10, max_sweeps: int = 64
) -> ExactCoverCSP:
    if height * width != 60:
        raise ValueError(f"board must have 60 cells, got {height}x{width}")
    n_primary = len(PIECE_NAMES) + height * width
    rows: list[np.ndarray] = []
    for p, (r0, c0), shape in placements(height, width):
        row = np.zeros(n_primary, dtype=bool)
        row[p] = True
        for r, c in shape:
            row[len(PIECE_NAMES) + (r0 + r) * width + (c0 + c)] = True
        rows.append(row)
    return build_cover(
        f"pentomino{height}x{width}",
        np.stack(rows),
        n_primary,
        max_sweeps=max_sweeps,
    )


def decode_tiling(problem: ExactCoverCSP, solution_state, height: int, width: int):
    """Solved state -> int grid [h, w] of piece ids (0..11)."""
    placed = placements(height, width)
    grid = np.full((height, width), -1, dtype=np.int32)
    for r in problem.chosen_rows(solution_state):
        piece, (r0, c0), shape = placed[int(r)]
        for dr, dc in shape:
            grid[r0 + dr, c0 + dc] = piece
    return grid


def is_valid_tiling(grid) -> bool:
    """Every cell covered; every piece used exactly once (5 cells each)."""
    grid = np.asarray(grid)
    if (grid < 0).any():
        return False
    counts = np.bincount(grid.ravel(), minlength=len(PIECE_NAMES))
    return grid.size == 60 and (counts == 5).all() and len(counts) == len(PIECE_NAMES)
