from distributed_sudoku_solver_tpu.models.geometry import (  # noqa: F401
    Geometry,
    SUDOKU_4,
    SUDOKU_9,
    SUDOKU_16,
    SUDOKU_25,
    geometry_for_size,
)
