"""Board geometry — the config object that kills the reference's hard-coding.

The reference hard-codes board size 9 and box size 3 inside its kernel
(``/root/reference/utils.py:20-21,48-53``) and its checker
(``/root/reference/sudoku.py:22-31,48-68``), which is why its 16x16/25x25
configs cannot run (SURVEY.md §2.5 #9).  Here geometry is a frozen dataclass
threaded through every kernel, so one compiled code path serves 4x4 test
boards, 9x9, 16x16 hexadoku and 25x25 giant boards (BASELINE.json configs).

Candidate masks are uint32 bitmasks: bit d set  <=>  digit d+1 still possible.
25x25 needs 25 bits, so uint32 covers every supported geometry.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Geometry:
    """Sudoku-family board geometry: an n x n grid of (box_h x box_w) boxes."""

    box_h: int
    box_w: int

    def __post_init__(self) -> None:
        if self.box_h < 1 or self.box_w < 1:
            raise ValueError(f"box dims must be >= 1, got {self.box_h}x{self.box_w}")
        if self.n > 32:
            raise ValueError(f"n={self.n} exceeds uint32 mask capacity (32 digits)")

    @property
    def n(self) -> int:
        """Digits per unit == rows == cols (n = box_h * box_w)."""
        return self.box_h * self.box_w

    @property
    def n_cells(self) -> int:
        return self.n * self.n

    @property
    def full_mask(self) -> int:
        """Bitmask with all n digit bits set (the 'anything possible' cell)."""
        return (1 << self.n) - 1

    @property
    def mask_dtype(self):
        return jnp.uint32

    @property
    def n_vboxes(self) -> int:
        """Boxes stacked vertically: n / box_h."""
        return self.n // self.box_h

    @property
    def n_hboxes(self) -> int:
        """Boxes side by side: n / box_w."""
        return self.n // self.box_w

    def __str__(self) -> str:  # pragma: no cover - cosmetic
        return f"{self.n}x{self.n}({self.box_h}x{self.box_w})"


SUDOKU_4 = Geometry(2, 2)
SUDOKU_6 = Geometry(2, 3)
SUDOKU_9 = Geometry(3, 3)
SUDOKU_16 = Geometry(4, 4)
SUDOKU_25 = Geometry(5, 5)

_BY_SIZE = {g.n: g for g in (SUDOKU_4, SUDOKU_6, SUDOKU_9, SUDOKU_16, SUDOKU_25)}


def geometry_for_size(n: int) -> Geometry:
    """Geometry for a square-box (or known) board size n."""
    try:
        return _BY_SIZE[n]
    except KeyError:
        root = int(round(n**0.5))
        if root * root == n:
            return Geometry(root, root)
        raise ValueError(f"no known geometry for board size {n}") from None
