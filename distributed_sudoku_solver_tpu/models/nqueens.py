"""N-queens as generalized exact cover (BASELINE.json config 5).

Row r*n+c = "a queen on square (r, c)".  Primary columns: the n ranks and
n files (each must hold exactly one queen).  Secondary columns: the 2n-1
diagonals and 2n-1 anti-diagonals (at most one queen) — the textbook
primary/secondary DLX encoding, solved here by the same compiled lane-stack
engine as Sudoku.
"""

from __future__ import annotations

import numpy as np

from distributed_sudoku_solver_tpu.models.cover import ExactCoverCSP, build_cover


def nqueens_cover(n: int, max_sweeps: int = 64) -> ExactCoverCSP:
    if n < 1:
        raise ValueError(f"need n >= 1, got {n}")
    n_primary = 2 * n
    n_cols = n_primary + 2 * (2 * n - 1)
    a = np.zeros((n * n, n_cols), dtype=bool)
    for r in range(n):
        for c in range(n):
            row = r * n + c
            a[row, r] = True  # rank
            a[row, n + c] = True  # file
            a[row, n_primary + r + c] = True  # diagonal
            a[row, n_primary + (2 * n - 1) + (r - c + n - 1)] = True  # anti-diag
    return build_cover(f"nqueens{n}", a, n_primary, max_sweeps=max_sweeps)


def decode_queens(problem: ExactCoverCSP, solution_state, n: int) -> list[tuple[int, int]]:
    """Solved state -> [(rank, file), ...] queen placements."""
    return [(int(r) // n, int(r) % n) for r in problem.chosen_rows(solution_state)]


def is_valid_queens(placements, n: int) -> bool:
    """n queens, no two sharing a rank, file, diagonal or anti-diagonal."""
    if len(placements) != n:
        return False
    rs = {r for r, _ in placements}
    cs = {c for _, c in placements}
    ds = {r + c for r, c in placements}
    ads = {r - c for r, c in placements}
    return len(rs) == len(cs) == len(ds) == len(ads) == n
