"""Sudoku as a :class:`~distributed_sudoku_solver_tpu.ops.csp.CSProblem`.

The flagship problem family: candidate-bitmask boards with elimination +
hidden-singles propagation (``ops/propagate.py``) and binary digit
branching.  This file is only the thin adapter between those kernels and
the generic lane-stack engine; the search semantics match the reference's
DFS (``/root/reference/DHT_Node.py:474-538``) as documented per-method.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.ops import ordering
from distributed_sudoku_solver_tpu.ops.bitmask import highest_bit, lowest_bit, popcount
from distributed_sudoku_solver_tpu.ops.propagate import board_status, propagate


@dataclasses.dataclass(frozen=True)
class SudokuCSP:
    """Sudoku-family CSP at a fixed geometry (jit-static, hashable).

    ``branch``: 'minrem' picks the cell with fewest remaining candidates
    (MRV, fastest); 'first' picks the first undecided cell row-major — the
    reference's ``find_next_empty`` order (``/root/reference/utils.py:14-25``),
    used by the bit-exactness tests; 'minrem-desc' is MRV with *descending*
    digit order (the portfolio-racing mirror, ``serving/portfolio.py``);
    'mixed' hashes each state to one of minrem/first — heuristic
    *diversification* across subtrees (the expert-parallel analog, SURVEY.md
    §2.2: heterogeneous strategies per subproblem), which hedges against
    boards adversarial to any single rule.  All rules are deterministic, so
    solves stay reproducible.
    """

    geom: Geometry
    branch_rule: str = "minrem"
    max_sweeps: int = 64
    propagator: str = "xla"
    rules: str = "basic"

    def __post_init__(self) -> None:
        # Shared spelling with SolverConfig: legacy rules plus the scored
        # branch heads ('head:<name>', ops/ordering.py — ROADMAP #4).
        ordering.validate_branch(self.branch_rule)
        if self.propagator not in ("xla", "pallas", "slices"):
            raise ValueError(f"unknown propagator {self.propagator!r}")
        from distributed_sudoku_solver_tpu.ops.propagate import RULE_TIERS

        if self.rules not in RULE_TIERS:
            raise ValueError(f"unknown rules {self.rules!r}")

    @property
    def state_shape(self) -> tuple[int, int]:
        return (self.geom.n, self.geom.n)

    def propagate(self, states: jax.Array) -> tuple[jax.Array, jax.Array]:
        # All three backends are bit-identical (tests/test_pallas.py pins it);
        # they differ in layout/residency: 'pallas' = VMEM-tile kernel (bulk
        # batches), 'slices' = boards-last XLA (large lane counts inside the
        # frontier loop), 'xla' = boards-first XLA (small lane counts, where
        # the whole loop state lives in VMEM anyway).
        if self.propagator == "pallas":
            from distributed_sudoku_solver_tpu.ops.pallas_propagate import (
                propagate_fixpoint_pallas,
            )

            return propagate_fixpoint_pallas(
                states, self.geom, self.max_sweeps, rules=self.rules
            )
        if self.propagator == "slices":
            from distributed_sudoku_solver_tpu.ops.pallas_propagate import (
                propagate_fixpoint_slices,
            )

            return propagate_fixpoint_slices(
                states, self.geom, self.max_sweeps, rules=self.rules
            )
        return propagate(states, self.geom, self.max_sweeps, self.rules)

    def status(self, states: jax.Array) -> tuple[jax.Array, jax.Array]:
        st = board_status(states, self.geom)
        return st.solved, st.contradiction

    def branch(self, states: jax.Array) -> tuple[jax.Array, jax.Array]:
        """Split one cell binarily: lowest candidate digit vs. the rest.

        The guess child carries the *lowest* remaining digit (ascending
        order, the reference's ``for number in arr`` at
        ``/root/reference/DHT_Node.py:522``); the rest child keeps the other
        candidates, so the two children partition the parent exactly.
        """
        onehot = self._branch_cell_onehot(states)
        pick = (
            highest_bit(states)
            if self.branch_rule == "minrem-desc"
            else lowest_bit(states)
        )
        guess = jnp.where(onehot, pick, states)
        rest = jnp.where(onehot, states & ~pick, states)
        return guess, rest

    def branch3(self, states: jax.Array):
        """Three-way split of the branch cell: two singleton children + rest.

        ``(guess, second, rest3, has_rest3)`` where guess carries the lowest
        candidate digit, ``second`` the next-lowest as its own *singleton*
        child (immediately propagation-ready for a thief, no re-split step),
        and ``rest3`` the remaining candidates (``has_rest3`` False when the
        cell had exactly two — rest3 is then an empty-cell contradiction and
        must not be pushed).  Exploration order under LIFO (push rest3 then
        second) is ascending digits, like the binary scheme; the *pruning*
        can differ slightly (a binary rest-blob propagates as one state), so
        ``branch_k=3`` is a distinct deterministic strategy, not a bit-exact
        re-encoding of ``branch_k=2``.
        """
        onehot = self._branch_cell_onehot(states)
        pick_low = self.branch_rule != "minrem-desc"
        b1 = lowest_bit(states) if pick_low else highest_bit(states)
        rem1 = states & ~b1
        b2 = lowest_bit(rem1) if pick_low else highest_bit(rem1)
        rem2 = rem1 & ~b2
        guess = jnp.where(onehot, b1, states)
        second = jnp.where(onehot, b2, states)
        rest3 = jnp.where(onehot, rem2, states)
        has_rest3 = jnp.any(onehot & (rem2 != 0), axis=(-1, -2))
        return guess, second, rest3, has_rest3

    def _branch_cell_onehot(self, cand: jax.Array) -> jax.Array:
        """bool[L, n, n] one-hot of the cell to branch on per board."""
        n = self.geom.n
        lanes = cand.shape[0]
        pc = popcount(cand).reshape(lanes, n * n).astype(jnp.int32)
        cell_idx = jnp.arange(n * n, dtype=jnp.int32)
        if ordering.is_head_rule(self.branch_rule):
            # Scored branch head (ops/ordering.py): f32 score -> the same
            # packed argmin key shape the legacy rules select on.  A
            # Python-level static branch — the legacy jaxprs below stay
            # byte-identical (jaxck goldens pass un-blessed).
            head = ordering.get_head(self.branch_rule)
            score = head.score_lanes(cand, self.geom)
            key = ordering.pack_key(score, pc > 1, cell_idx, n, head.quant)
            chosen = jnp.argmin(key, axis=-1)
            onehot = cell_idx[None, :] == chosen[:, None]
            return onehot.reshape(lanes, n, n)
        minrem_key = jnp.where(pc > 1, pc * (n * n) + cell_idx, jnp.int32(2**30))
        first_key = jnp.where(pc > 1, cell_idx, jnp.int32(2**30))
        if self.branch_rule in ("minrem", "minrem-desc"):
            key = minrem_key
        elif self.branch_rule == "first":
            key = first_key
        else:  # 'mixed': deterministic per-state hash picks the rule, so
            # sibling subtrees explore under different heuristics.
            h = jnp.sum(pc * (cell_idx + 1), axis=-1)
            key = jnp.where((h & 1)[:, None] == 0, minrem_key, first_key)
        chosen = jnp.argmin(key, axis=-1)
        onehot = cell_idx[None, :] == chosen[:, None]
        return onehot.reshape(lanes, n, n)

    def signature(self) -> str:
        return (
            f"sudoku:{self.geom.box_h}x{self.geom.box_w}"
            f":{self.branch_rule}:{self.max_sweeps}:{self.propagator}:{self.rules}"
        )
