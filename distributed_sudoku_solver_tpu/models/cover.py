"""Generalized exact cover as a CSProblem: the same engine, a second family.

BASELINE.json config 5 ("Generic exact-cover CSP (N-queens / pentomino)
reusing the bitmask kernel").  The reference can express exactly one problem
(9x9 Sudoku, ``/root/reference/utils.py``); this module gives the lane-stack
engine (``ops/frontier.py``) and the multi-chip path
(``parallel/sharded.py``) a whole problem *class*:

    choose a subset of ROWS such that every PRIMARY column is covered
    exactly once and every SECONDARY column at most once

— the dancing-links (DLX) problem, tensorized.  A search state packs two
bitmask vectors into one ``uint32[1, D]`` tensor:

* ``avail``  (W_r words over R rows): rows not conflicting with the current
  partial selection.  Chosen rows *stay available* (they conflict with
  nothing chosen, by construction), which yields the decode invariant: at a
  solved state ``avail`` is exactly the chosen-row set — any other
  available row would cover some primary column twice and would have been
  eliminated when that column's chooser was taken.
* ``covered`` (W_c words over the primary columns only): columns covered so
  far.  Secondary columns need no covered-bits — their at-most-once
  semantics live entirely in the row-conflict matrix.

The three kernels mirror Sudoku's structurally: *propagate* repeatedly
takes the unique row of any 1-candidate column (naked singles), *status*
reads "all primary covered" / "some uncovered column has 0 candidates",
and *branch* splits on an MRV column — take its lowest available row
vs. exclude that row, a binary partition exactly like the digit split in
``models/sudoku.py``.

Instance matrices are baked into the compiled program as constants; the
problem object is jit-static via a content digest.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_sudoku_solver_tpu.ops.bitmask import lowest_bit, popcount

_BIG = jnp.int32(2**30)


def _pack_bits(a: np.ndarray) -> np.ndarray:
    """bool[..., K] -> uint32[..., ceil(K/32)], bit b of word w = index w*32+b."""
    a = np.asarray(a, dtype=bool)
    k = a.shape[-1]
    w = -(-k // 32) if k else 1
    pad = [(0, 0)] * (a.ndim - 1) + [(0, w * 32 - k)]
    a = np.pad(a, pad)
    a = a.reshape(*a.shape[:-1], w, 32)
    weights = (np.uint64(1) << np.arange(32, dtype=np.uint64))
    return (a.astype(np.uint64) * weights).sum(-1).astype(np.uint32)


def _unpack_bits(packed: np.ndarray, k: int) -> np.ndarray:
    """Inverse of :func:`_pack_bits` (host-side, for decoding solutions)."""
    packed = np.asarray(packed, dtype=np.uint32)
    bits = (packed[..., :, None] >> np.arange(32, dtype=np.uint32)) & 1
    return bits.reshape(*packed.shape[:-1], -1)[..., :k].astype(bool)


@dataclasses.dataclass(frozen=True, eq=False)
class ExactCoverCSP:
    """One generalized-exact-cover instance (jit-static via content digest)."""

    name: str
    n_rows: int
    n_primary: int
    col_rows: np.ndarray  # uint32[C, W_r]: rows covering each primary column
    row_cols: np.ndarray  # uint32[R, W_c]: primary columns covered by each row
    elim: np.ndarray  # uint32[R, W_r]: rows conflicting with row r (r excluded)
    max_sweeps: int = 64
    # Full incidence (primary + secondary columns), bit-packed [R, ceil(Cf/32)].
    # The composite kernels never read it (their conflict source is ``elim``);
    # the fused VMEM kernel (``ops/pallas_cover.py``) derives per-take
    # conflicts from it as two MXU matmuls instead of an R x R gather.
    incidence: Optional[np.ndarray] = None
    n_cols_full: int = 0

    def __post_init__(self) -> None:
        h = hashlib.sha256()
        for arr in (self.col_rows, self.row_cols, self.elim):
            h.update(np.ascontiguousarray(arr).tobytes())
        h.update(f"{self.name}:{self.n_rows}:{self.n_primary}:{self.max_sweeps}".encode())
        if self.incidence is not None:
            # Distinct secondary-column structure must trace distinctly: the
            # fused kernel bakes the full incidence into its program.
            h.update(np.ascontiguousarray(self.incidence).tobytes())
            h.update(str(self.n_cols_full).encode())
        object.__setattr__(self, "_digest", h.hexdigest())

    def __hash__(self) -> int:
        return hash(self._digest)

    def __eq__(self, other) -> bool:
        return isinstance(other, ExactCoverCSP) and self._digest == other._digest

    # -- geometry ------------------------------------------------------------
    @property
    def w_rows(self) -> int:
        return self.elim.shape[1]

    @property
    def w_cols(self) -> int:
        return self.row_cols.shape[1]

    @property
    def state_shape(self) -> tuple[int, int]:
        return (1, self.w_rows + self.w_cols)

    def signature(self) -> str:
        return f"cover:{self.name}:{self._digest[:16]}"

    # -- state packing -------------------------------------------------------
    def _split(self, states: jax.Array) -> tuple[jax.Array, jax.Array]:
        flat = states[..., 0, :]
        return flat[..., : self.w_rows], flat[..., self.w_rows :]

    def _join(self, avail: jax.Array, covered: jax.Array) -> jax.Array:
        return jnp.concatenate([avail, covered], axis=-1)[..., None, :]

    def initial_state(self) -> np.ndarray:
        """Root state: every row available, nothing covered — uint32[1, D]."""
        avail = _pack_bits(np.ones((self.n_rows,), dtype=bool))
        covered = np.zeros((self.w_cols,), dtype=np.uint32)
        return np.concatenate([avail, covered])[None, :]

    def state_with_rows_taken(self, rows) -> np.ndarray:
        """Root state after pre-selecting ``rows`` (host-side; e.g. clues)."""
        avail = _unpack_bits(self.initial_state()[0, : self.w_rows], self.n_rows)
        covered = np.zeros((self.n_primary,), dtype=bool)
        elim = _unpack_bits(self.elim, self.n_rows)
        cols = _unpack_bits(self.row_cols, self.n_primary)
        for r in rows:
            if not avail[r]:
                raise ValueError(f"row {r} conflicts with an earlier selection")
            if (covered & cols[r]).any():
                raise ValueError(f"row {r} re-covers an already-covered column")
            avail &= ~elim[r]
            covered |= cols[r]
        return np.concatenate([_pack_bits(avail), _pack_bits(covered)])[None, :]

    def chosen_rows(self, solution_state) -> np.ndarray:
        """Solved state -> sorted row indices (the decode invariant above)."""
        avail = _unpack_bits(
            np.asarray(solution_state)[..., 0, : self.w_rows], self.n_rows
        )
        return np.nonzero(avail)[-1]

    # -- shared pieces -------------------------------------------------------
    def _counts(self, avail: jax.Array, covered: jax.Array):
        """cnt[L, C] available rows per primary column; unc[L, C] uncovered."""
        cr = jnp.asarray(self.col_rows)
        cnt = popcount(avail[:, None, :] & cr[None]).sum(-1).astype(jnp.int32)
        c_idx = np.arange(self.n_primary)
        word = jnp.asarray(c_idx // 32, dtype=jnp.int32)
        bit = jnp.asarray(c_idx % 32, dtype=jnp.uint32)
        unc = ((covered[:, word] >> bit) & 1) == 0
        return cnt, unc

    def _lowest_row(self, rowmask: jax.Array) -> jax.Array:
        """[L, W_r] -> lowest set row index int32[L] (garbage -1 if empty)."""
        first_w = jnp.argmax(rowmask != 0, axis=-1).astype(jnp.int32)
        word = jnp.take_along_axis(rowmask, first_w[:, None], axis=-1)[:, 0]
        low = lowest_bit(word)
        bitpos = (31 - jax.lax.clz(low)).astype(jnp.int32)  # -1 if word == 0
        return first_w * 32 + bitpos

    def _take_row(
        self, avail: jax.Array, covered: jax.Array, row: jax.Array, active: jax.Array
    ):
        """Select ``row`` where ``active``: cover its columns, drop conflicts."""
        r = jnp.clip(row, 0, self.n_rows - 1)
        new_avail = avail & ~jnp.asarray(self.elim)[r]
        new_covered = covered | jnp.asarray(self.row_cols)[r]
        return (
            jnp.where(active[:, None], new_avail, avail),
            jnp.where(active[:, None], new_covered, covered),
        )

    def _row_bit(self, row: jax.Array) -> jax.Array:
        """int32[L] -> one-hot packed row mask uint32[L, W_r]."""
        r = jnp.clip(row, 0, self.n_rows - 1)
        w_idx = jnp.arange(self.w_rows, dtype=jnp.int32)
        return jnp.where(
            w_idx[None, :] == (r // 32)[:, None],
            jnp.uint32(1) << (r % 32).astype(jnp.uint32)[:, None],
            jnp.uint32(0),
        )

    # -- the three kernels ---------------------------------------------------
    def propagate(self, states: jax.Array) -> tuple[jax.Array, jax.Array]:
        """Take the unique row of any 1-candidate column, to a fixpoint.

        One forced take per lane per sweep (lowest column first): simultaneous
        takes could select two conflicting rows and corrupt the covered set,
        so forcing is serialized per lane — sweeps are cheap tensor ops.
        """
        c_idx = jnp.arange(self.n_primary, dtype=jnp.int32)

        def cond(s):
            _, changed, k = s
            return changed & (k < self.max_sweeps)

        def body(s):
            flat, _, k = s
            avail, covered = self._split(flat)
            cnt, unc = self._counts(avail, covered)
            forced = unc & (cnt == 1)
            has = jnp.any(forced, axis=-1)
            col = jnp.argmin(jnp.where(forced, c_idx[None], _BIG), axis=-1)
            rowmask = jnp.asarray(self.col_rows)[col] & avail
            row = self._lowest_row(rowmask)
            avail, covered = self._take_row(avail, covered, row, has)
            return self._join(avail, covered), jnp.any(has), k + 1

        states, _, sweeps = jax.lax.while_loop(
            cond, body, (states, jnp.bool_(True), jnp.int32(0))
        )
        return states, sweeps

    def status(self, states: jax.Array) -> tuple[jax.Array, jax.Array]:
        avail, covered = self._split(states)
        cnt, unc = self._counts(avail, covered)
        contradiction = jnp.any(unc & (cnt == 0), axis=-1)
        solved = ~jnp.any(unc, axis=-1) & ~contradiction
        return solved, contradiction

    def branch(self, states: jax.Array) -> tuple[jax.Array, jax.Array]:
        """MRV column; guess = take its lowest row, rest = exclude that row.

        Candidate columns include cnt == 1: after a propagation fixpoint none
        exist, but if ``max_sweeps`` capped the forced chain mid-way the
        branch then *continues* it (rest is immediately contradictory), so an
        undecided lane always has an active branch column — guess/rest childs
        are a true partition in every reachable undecided state.
        """
        c_idx = jnp.arange(self.n_primary, dtype=jnp.int32)
        avail, covered = self._split(states)
        cnt, unc = self._counts(avail, covered)
        branchable = unc & (cnt >= 1)
        key = jnp.where(branchable, cnt * self.n_primary + c_idx[None], _BIG)
        col = jnp.argmin(key, axis=-1)
        rowmask = jnp.asarray(self.col_rows)[col] & avail
        row = self._lowest_row(rowmask)
        active = jnp.any(branchable, axis=-1)
        g_avail, g_covered = self._take_row(avail, covered, row, active)
        r_avail = jnp.where(
            active[:, None], avail & ~self._row_bit(row), avail
        )
        return self._join(g_avail, g_covered), self._join(r_avail, covered)


def build_cover(
    name: str, incidence, n_primary: int, max_sweeps: int = 64
) -> ExactCoverCSP:
    """Build an instance from a bool incidence matrix [R, C_full].

    Columns ``[0, n_primary)`` are primary (covered exactly once); the rest
    are secondary (at most once, enforced purely through row conflicts).
    Every row must cover at least one primary column — that is what makes
    the chosen-rows decode invariant hold (see module docstring).
    """
    a = np.asarray(incidence, dtype=bool)
    if a.ndim != 2:
        raise ValueError(f"incidence must be 2-D, got {a.shape}")
    n_rows = a.shape[0]
    if not (0 < n_primary <= a.shape[1]):
        raise ValueError(f"n_primary={n_primary} out of range for {a.shape}")
    if not a[:, :n_primary].any(axis=1).all():
        raise ValueError("every row must cover at least one primary column")
    # int32 accumulation: a uint8 matmul wraps at 256 shared columns and
    # would silently drop that pair's conflict.
    conflict = (a.astype(np.int32) @ a.astype(np.int32).T) > 0
    np.fill_diagonal(conflict, False)
    return ExactCoverCSP(
        name=name,
        n_rows=n_rows,
        n_primary=n_primary,
        col_rows=_pack_bits(a[:, :n_primary].T),
        row_cols=_pack_bits(a[:, :n_primary]),
        elim=_pack_bits(conflict),
        max_sweeps=max_sweeps,
        incidence=_pack_bits(a),
        n_cols_full=a.shape[1],
    )


def sudoku_cover(geom, max_sweeps: int = 64) -> ExactCoverCSP:
    """Sudoku itself as exact cover: the cross-engine validation instance.

    Row r*n*n + c*n + (d-1) = "digit d in cell (r, c)"; primary columns are
    the 4n^2 classic constraints (cell filled, digit-in-row, digit-in-column,
    digit-in-box).  Solving this with the cover kernels must agree with the
    native Sudoku kernels (``models/sudoku.py``) — a strong mutual test of
    two independent propagation/branching implementations on one engine.
    Clue grids become root states via :meth:`ExactCoverCSP.state_with_rows_taken`
    with :func:`sudoku_clue_rows`.
    """
    n = geom.n
    a = np.zeros((n * n * n, 4 * n * n), dtype=bool)
    for r in range(n):
        for c in range(n):
            b = (r // geom.box_h) * geom.n_hboxes + (c // geom.box_w)
            for d in range(n):
                row = r * n * n + c * n + d
                a[row, r * n + c] = True  # cell (r, c) filled
                a[row, n * n + r * n + d] = True  # digit d in row r
                a[row, 2 * n * n + c * n + d] = True  # digit d in column c
                a[row, 3 * n * n + b * n + d] = True  # digit d in box b
    return build_cover(
        f"sudoku-cover{geom.box_h}x{geom.box_w}", a, 4 * n * n, max_sweeps=max_sweeps
    )


def sudoku_clue_rows(grid) -> list[int]:
    """Int clue grid [n, n] (0 = empty) -> cover row indices of the clues."""
    grid = np.asarray(grid)
    n = grid.shape[0]
    return [
        r * n * n + c * n + (int(grid[r, c]) - 1)
        for r in range(n)
        for c in range(n)
        if grid[r, c] > 0
    ]


def decode_sudoku_cover(problem: ExactCoverCSP, solution_state, n: int) -> np.ndarray:
    """Solved sudoku-cover state -> int grid [n, n]."""
    grid = np.zeros((n, n), dtype=np.int32)
    for row in problem.chosen_rows(solution_state):
        row = int(row)
        grid[row // (n * n), (row // n) % n] = row % n + 1
    return grid
