from distributed_sudoku_solver_tpu.cli import main

main()
