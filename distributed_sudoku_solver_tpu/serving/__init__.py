"""Serving: host-side job engine + reference-compatible HTTP API."""

from distributed_sudoku_solver_tpu.serving.engine import (  # noqa: F401
    Job,
    SolverEngine,
)
from distributed_sudoku_solver_tpu.serving.faults import (  # noqa: F401
    CircuitBreaker,
    FaultInjector,
    FaultSchedule,
    RecoveryPolicy,
)
from distributed_sudoku_solver_tpu.serving.portfolio import (  # noqa: F401
    DEFAULT_PORTFOLIO,
    PortfolioResult,
    race,
)
from distributed_sudoku_solver_tpu.serving.scheduler import (  # noqa: F401
    EngineSaturated,
    ResidentConfig,
    ResidentFlight,
)
