"""Continuous-batching resident flights: an admission scheduler that packs
live traffic into the long-lived fused (or composite) frontier.

The static-flight engine (``serving/engine.py``) launches one frontier per
admitted batch and tears it down when the batch drains: a request arriving
one chunk after launch waits for a whole flight to retire before it gets a
single lane, even while that flight's lanes go idle (the round-6
``fused_lane_occupancy`` histogram shows exactly this endgame starvation).
This module is the serving fix — the same shape as continuous batching in
LLM inference serving, and the keep-the-device-saturated discipline the
GPU-CP line of work argues for (PAPERS.md, "Experimenting with Constraint
Programming on GPU"):

* **One resident frontier per geometry**, shape-stable forever: fixed lane
  count ``L = job_slots * gang_lanes`` and a fixed pool of ``job_slots``
  job slots.  Every device program (init / attach / detach / poll /
  advance) compiles once and is reused for the life of the process.
* **Slot = gang of lanes.**  Slot ``j`` owns lanes ``[j*gang, (j+1)*gang)``
  and work stealing is scoped to the gang (``SolverConfig.steal_gang``),
  so a slot's lanes only ever hold its own job's subtrees — detaching the
  job provably frees the whole gang for the next tenant.  (Global stealing
  would leak other jobs' subtrees into the gang and make slot recycling
  unsound: a stack row's job identity is its lane's ``job`` tag.)
* **Admission between dispatches.**  Arriving jobs enter a bounded FIFO
  queue; between fused dispatches the scheduler detaches finished slots,
  recycles them, and attaches queued jobs in-graph
  (``ops/frontier.attach_roots`` / ``detach`` — jit-stable: K is a static
  shape, validity rides the data).  No teardown, no membership recompile.
* **One sync per chunk, one chunk behind (round 8).**  Each scheduler
  round consumes the PREVIOUS advance's packed status word
  (``ops/frontier.chunk_status``) in one host fetch, then detach / attach
  / the next advance are async dispatches against donated buffers — the
  old per-round ``_poll_jit`` five-array fetch, ``int(state.steps)``
  scalar fetch, and full-state ``block_until_ready`` are gone.  Verdicts
  and slot recycling therefore react one chunk late (sound: solved-slot
  rows freeze in-graph, and a workless gang cannot regrow work), and the
  host never stalls the device except for that single fetch.
* **Backpressure, deadlines, cancellation.**  A full queue rejects with a
  retry hint (the HTTP layer turns that into ``429`` + ``Retry-After``);
  every admitted job carries a deadline (expired jobs are detached and
  their slots recycled); a host ``cancel`` frees the slot in-graph at the
  next chunk boundary, exactly like the static path's purge.

Ownership: all device work happens on the engine's device-loop thread
(``ResidentFlight.step`` is called between static-flight chunks); the
admission queue is the only cross-thread surface.  Jobs ineligible for the
resident flight — per-job config overrides (portfolio racers), roots
resumes, ``count_all`` enumerations, fused-misfit geometries — keep using
the static flight path unchanged (``SolverEngine._route_resident``).
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import math
from collections import deque
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.obs import compilewatch, lockdep, trace
from distributed_sudoku_solver_tpu.obs.logctx import uuids_label
from distributed_sudoku_solver_tpu.ops.frontier import (
    Frontier,
    SolverConfig,
    attach_roots,
    detach,
    unpack_status,
)
from distributed_sudoku_solver_tpu.serving import engine as engine_mod
from distributed_sudoku_solver_tpu.serving import faults

_LOG = logging.getLogger(__name__)

# The resident frontier never retires, so the per-solve step budget is
# replaced by wall-clock deadlines; int32 max keeps run_frontier's
# steps-vs-max_steps guard permanently open (steps are rebased long before
# they could reach it, see _REBASE_STEPS).
_NO_STEP_BUDGET = (1 << 31) - 1
# Rebase the monotonically growing step counter well before int32 overflow
# (limits are relative: only steps-since-last-chunk matters).
_REBASE_STEPS = 1 << 30


class EngineSaturated(RuntimeError):
    """Resident admission queue is full; retry after ``retry_after_s``."""

    def __init__(self, retry_after_s: float):
        super().__init__(
            f"admission queue saturated; retry after {retry_after_s:.2f}s"
        )
        self.retry_after_s = retry_after_s


@dataclasses.dataclass(frozen=True)
class ResidentConfig:
    """Static shape of a resident flight (one per geometry)."""

    job_slots: int = 16  # J_max: concurrent jobs resident on the device
    gang_lanes: int = 8  # lanes per slot (per-job OR-parallel speculation
    #   width; a slot's gang is its fair share — FIFO admission plus fixed
    #   gangs is the fairness story, no job can starve another's lanes)
    queue_depth: int = 64  # admission queue bound; beyond it submits are
    #   rejected with a retry hint (HTTP: 429 + Retry-After) instead of
    #   queueing unboundedly
    attach_batch: int = 8  # max jobs attached per chunk boundary (the
    #   static K of the jit-stable attach program)
    chunk_steps: int = 64  # frontier rounds per resident dispatch — the
    #   admission/cancel/deadline reaction latency, same knob as the
    #   engine's static-flight chunk_steps
    default_deadline_s: float = 300.0  # wall-clock budget per job (the
    #   resident flight has no per-job step budget; deadlines bound it)
    mesh_devices: int = 0  # > 1: shard the resident flight's lane axis over
    #   a device mesh of this size (serving/mesh_scheduler.py) — job_slots
    #   becomes the PER-SHARD slot count, so capacity scales with the mesh.
    #   0/1 = the single-chip flight.  Engines fall back to single-chip
    #   when fewer devices are visible (SolverEngine._resident_for).

    def __post_init__(self) -> None:
        if self.job_slots < 1:
            raise ValueError(f"job_slots must be >= 1, got {self.job_slots}")
        if self.gang_lanes < 1:
            raise ValueError(f"gang_lanes must be >= 1, got {self.gang_lanes}")
        if self.queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {self.queue_depth}")
        if self.attach_batch < 1:
            raise ValueError(f"attach_batch must be >= 1, got {self.attach_batch}")
        if self.mesh_devices < 0:
            raise ValueError(
                f"mesh_devices must be >= 0, got {self.mesh_devices}"
            )


# -- jitted device programs (module-level: caches shared across engines) ------


@functools.partial(jax.jit, static_argnames=("geom", "config", "n_slots"))
def _init_resident(geom: Geometry, config: SolverConfig, n_slots: int) -> Frontier:
    from distributed_sudoku_solver_tpu.ops.frontier import init_frontier_roots

    lanes = config.lanes
    roots = jnp.zeros((lanes, geom.n, geom.n), jnp.uint32)
    return init_frontier_roots(
        roots, jnp.full(lanes, -1, jnp.int32), n_slots, config
    )


# The resident state is donated through every program that threads it
# (attach / detach / advance): the scheduler always rebinds
# ``self.state = ...``, so the long-lived frontier's buffers are reused
# in place instead of copied per dispatch (round 8).
@functools.partial(
    jax.jit, static_argnames=("geom", "gang"), donate_argnums=(0,)
)
def _attach_jit(
    state: Frontier, grids: jax.Array, slot_ids: jax.Array, geom: Geometry, gang: int
) -> Frontier:
    from distributed_sudoku_solver_tpu.ops.bitmask import encode_grid

    return attach_roots(state, encode_grid(grids, geom), slot_ids, gang)


@functools.partial(jax.jit, donate_argnums=(0,))
def _detach_jit(state: Frontier, slot_mask: jax.Array) -> Frontier:
    return detach(state, slot_mask)


@jax.jit
def _verdict_jit(state: Frontier):
    """Detach-time verdict payload, fetched ONLY on chunks where a slot
    actually leaves (an event fetch, not a per-round poll): per-slot node
    counts, model counts, overflow flags, and the decoded solution grids.
    The per-round poll itself is gone — its solved / has-work bits ride
    the packed status word the advance program returns
    (``ops/frontier.chunk_status``).  Ships the whole slot pool's rows
    (one stable compiled shape; ~83 KB at 256 9x9 slots — under one RPC
    floor); a static-K gather of just the leaving slots is the upgrade
    path for giant-geometry pools."""
    from distributed_sudoku_solver_tpu.ops.bitmask import decode_grid

    return (
        state.nodes,
        state.sol_count,
        state.overflowed,
        decode_grid(state.solution),
    )


def resident_solver_config(
    base: SolverConfig, geom: Geometry, rcfg: ResidentConfig
) -> SolverConfig:
    """The shape-stable SolverConfig a resident flight runs: fixed lanes,
    gang-scoped stealing, no step budget.

    For a fused base config the lane count must be kernel-valid
    (``pallas_step.fused_lanes``: whole-array <= 128 or a multiple of 128)
    while staying an exact multiple of ``job_slots`` — the gang is bumped
    to the smallest width satisfying both.  Raises ``ValueError`` when the
    fused kernel cannot serve the geometry/stack at all (the caller falls
    back to static flights — the resident path never downgrades silently).
    """
    gang = rcfg.gang_lanes
    lanes = gang * rcfg.job_slots
    if base.step_impl == "fused":
        from distributed_sudoku_solver_tpu.ops.pallas_step import fused_lanes

        if lanes > 128:
            # Beyond one whole-array tile Mosaic requires 128-multiples;
            # keep lanes = slots * gang exact by bumping the gang in steps
            # of 128 / gcd(slots, 128).
            step = 128 // math.gcd(rcfg.job_slots, 128)
            gang = -(-gang // step) * step
            lanes = gang * rcfg.job_slots
        fused_lanes(lanes, geom.n, base.stack_slots)  # raises on misfit
    return dataclasses.replace(
        base,
        lanes=lanes,
        min_lanes=lanes,
        steal_gang=gang,
        max_steps=_NO_STEP_BUDGET,
        count_all=False,
    )


class ResidentFlight:
    """One long-lived frontier + its slot allocator and admission queue.

    Thread contract: ``try_admit`` / ``retry_after_s`` / ``metrics`` may be
    called from any thread; ``step`` / ``fail`` / ``drain`` only from the
    engine's device-loop thread (single-owner device discipline).
    """

    def __init__(self, engine, geom: Geometry, rcfg: ResidentConfig):
        self.engine = engine
        self.geom = geom
        self.rcfg = rcfg
        self.config = self._solver_config(engine.config, geom, rcfg)
        self.gang = self.config.steal_gang
        # lanes = gang * slots by construction (resident_solver_config) —
        # derived here so the mesh subclass's total (per-shard * devices)
        # slot pool needs no second hook.
        self.n_slots = self.config.lanes // self.gang
        # Device-program bindings: the mesh flight
        # (serving/mesh_scheduler.py) rebinds these to its shard_map twins
        # and every hot-loop method below is shared verbatim — the one-sync
        # round structure is the contract, the programs are the strategy.
        self._init_fn = _init_resident
        self._attach_fn = _attach_jit
        self._detach_fn = _detach_jit
        self._verdict_fn = _verdict_jit
        self.state: Optional[Frontier] = None  # created lazily on the loop
        # Pipelined status plumbing (round 8): the un-fetched packed status
        # word of the most recent advance dispatch, and the host-side copy
        # of the last consumed one.  The scheduler round consumes the
        # previous chunk's status in ONE host sync, reacts, and dispatches
        # the next chunk without ever blocking on device state.
        self._pending_status = None
        self._status: Optional[dict] = None
        self._event_wall = 0.0  # last round's verdict-fetch sync wall
        self.slots: list = [None] * self.n_slots  # slot -> Job
        self._free: deque = deque(range(self.n_slots))  # slot recycler
        self._pending: deque = deque()  # FIFO admission queue
        self._lock = lockdep.named_lock("serving.scheduler")  # lockck: name(serving.scheduler)
        self._closed = False
        # Self-healing (serving/faults.py): a failed device program no
        # longer closes admission forever.  Transient failures rebuild the
        # flight after a cooldown with its jobs requeued; the breaker opens
        # after k consecutive rebuild failures (admission then deflects to
        # static flights) and half-opens after its own cooldown.  All time
        # comes from the policy clock (injectable for sleep-free tests).
        self.policy = engine.recovery
        self.breaker = faults.CircuitBreaker(self.policy)
        self._cooldown_until = 0.0
        self.rebuilds = 0  # flights torn down and requeued for rebuild
        self.rebuild_requeued = 0  # jobs put back on the admission queue
        self.requeued_static = 0  # jobs rerouted to static flights
        self.breaker_deflected = 0  # lockck: guard(_lock) — admissions deflected while open
        self.closed_deflected = 0  # lockck: guard(_lock) — admissions deflected by a closed flight
        # Counters (occupancy/queue read under the lock; the rest are
        # single-writer on the device loop, readers tolerate staleness).
        self.admitted = 0  # lockck: guard(_lock)
        self.rejected = 0  # lockck: guard(_lock)
        self.completed = 0
        self.cancelled = 0
        self.expired = 0
        self.chunks = 0
        from distributed_sudoku_solver_tpu.utils.profiling import StatWindow

        self.admission_wait = StatWindow()  # submit -> attach seconds
        self.chunk_wall = StatWindow()  # per-chunk status-sync wall: time
        #   blocked consuming the previous advance's packed status word
        #   (includes the simulated per-sync floor; device compute the
        #   host did not overlap shows up here and nowhere else)
        self.dispatch_wall = StatWindow()  # host time per round spent
        #   ENQUEUEING device work (collect/detach/attach/advance — all
        #   async); the gap to chunk_wall is the observable overlap,
        #   mirroring the engine's dispatch_wall_ms / sync_wall_ms split
        self.event_wall = StatWindow()  # detach-round verdict fetches —
        #   the round's SECOND sync (floor included), recorded so the
        #   split never hides it (same property as the engine's
        #   event_wall)
        # Running frontier-round / wall totals (single-writer: the device
        # loop) — the resident twin of the engine's _chunk_steps_total /
        # _chunk_wall_total, so the cost plane's device-efficiency gauge
        # (obs/compilewatch.py) stays live on a resident-serving node.
        # Wall here is the per-round sync wall: the dominant host-side
        # share of a resident round (dispatches are async-thin).
        self.rounds_total = 0
        self.round_wall_total = 0.0
        self._steps_seen = 0

    # -- strategy hooks (the mesh flight overrides these) --------------------
    def _solver_config(
        self, base: SolverConfig, geom: Geometry, rcfg: ResidentConfig
    ) -> SolverConfig:
        return resident_solver_config(base, geom, rcfg)

    def _unpack(self, raw) -> dict:
        """Host-side decode of the fetched status word (numpy only — the
        round's one sync already happened in ``_consume_status``)."""
        return unpack_status(raw, self.n_slots)

    def _advance_bound(self):
        """``(advance fn, compilewatch program name, extra static kwargs)``
        — the strategy half of ``_advance``; the dispatch/trace/cost-seam
        body stays shared."""
        if self.config.step_impl == "fused":
            from distributed_sudoku_solver_tpu.ops.pallas_step import (
                advance_frontier_fused_status as fn,
            )

            return fn, compilewatch.ADVANCE_FUSED_STATUS, {}
        from distributed_sudoku_solver_tpu.utils.checkpoint import (
            advance_frontier_status as fn,
        )

        return fn, compilewatch.ADVANCE_STATUS, {}

    # -- any-thread surface --------------------------------------------------
    #: admit() verdicts.  SATURATED is the only one a reject-mode caller
    #: may 429 on: the flight is healthy but full, so Retry-After is an
    #: honest hint.  DEFLECTED (breaker open/half-denied, or permanently
    #: closed) must fall back to static flights even under reject mode —
    #: the resident flight being broken is not client backpressure.
    ADMITTED = "admitted"
    SATURATED = "saturated"
    DEFLECTED = "deflected"

    def admit(self, job) -> str:
        """Queue ``job`` for attachment; returns an admission verdict."""
        if not self.breaker.allow():
            with self._lock:  # submit threads race here like admitted/rejected
                self.breaker_deflected += 1
            return self.DEFLECTED
        with self._lock:
            if self._closed:
                # Permanently closed (permanent fault / terminal fail()):
                # counted apart from breaker deflections so /metrics shows
                # WHY this geometry's traffic is bypassing the resident
                # path — a closed flight never reopens, a breaker does.
                self.closed_deflected += 1
                return self.DEFLECTED
            if len(self._pending) >= self.rcfg.queue_depth:
                self.rejected += 1
                return self.SATURATED
            if job.deadline is None:
                job.deadline = self.engine._clock() + self.rcfg.default_deadline_s
            self._pending.append(job)
            self.admitted += 1
            return self.ADMITTED

    def try_admit(self, job) -> bool:
        """Boolean convenience over :meth:`admit` (False = not admitted,
        whatever the reason)."""
        return self.admit(job) == self.ADMITTED

    def retry_after_s(self) -> float:
        """Backpressure hint: roughly how long until queue headroom opens —
        the backlog ahead of a retry, paced at the recent per-job latency
        over ``job_slots`` parallel servers."""
        lat = self.engine.latency.snapshot()
        per_job = lat["p50"] if lat else 0.5
        with self._lock:
            backlog = len(self._pending) + sum(
                1 for s in self.slots if s is not None
            )
        return float(min(30.0, max(0.1, per_job * backlog / self.n_slots)))

    def cooling(self) -> bool:
        """Rebuild cooldown after a transient failure still running."""
        return self.policy.clock() < self._cooldown_until

    def active(self) -> bool:
        # A flight cooling down after a failure holds its requeued jobs
        # but must not dispatch until the cooldown elapses — active() going
        # False lets the engine loop fall back to its 50 ms queue poll (no
        # busy-spin); the loop still step()s a cooling flight with queued
        # jobs so cancels/deadlines are swept during the cooldown.
        if self.cooling():
            return False
        with self._lock:
            return bool(self._pending) or any(
                s is not None for s in self.slots
            )

    def queued_depth(self) -> int:
        with self._lock:
            return len(self._pending) + sum(
                1 for s in self.slots if s is not None
            )

    def admission_pressure(self) -> tuple:
        """``(queue_fraction, admission_wait_p95_s)`` — the brownout
        controller's resident signals (``serving/brownout.py``): how full
        the bounded admission queue is (1.0 = the next reject-mode submit
        429s) and how long admitted jobs recently waited for a slot."""
        with self._lock:
            frac = len(self._pending) / float(self.rcfg.queue_depth)
        aw = self.admission_wait.snapshot()
        return frac, (aw["p95"] if aw else 0.0)

    def metrics(self) -> dict:
        with self._lock:
            occupied = sum(1 for s in self.slots if s is not None)
            queued = len(self._pending)
        out = {
            "slots": self.n_slots,
            "gang_lanes": self.gang,
            "occupied": occupied,
            "queued": queued,
            "admitted": int(self.admitted),
            "completed": int(self.completed),
            "rejected": int(self.rejected),
            "cancelled": int(self.cancelled),
            "deadline_expired": int(self.expired),
            "chunks": int(self.chunks),
        }
        aw = self.admission_wait.snapshot()
        if aw:
            out["admission_wait_ms"] = {
                "count": aw["count"],
                **{k: round(aw[k] * 1e3, 3) for k in ("p50", "p95", "p99")},
            }
        for name, win in (
            ("chunk_wall_ms", self.chunk_wall),  # per-round status sync
            ("dispatch_wall_ms", self.dispatch_wall),  # async enqueue time
            ("event_wall_ms", self.event_wall),  # detach-round verdicts
        ):
            snap = win.snapshot()
            if snap:
                out[name] = {
                    "count": snap["count"],
                    **{k: round(snap[k] * 1e3, 3) for k in ("p50", "p95")},
                }
        out["faults"] = {
            "rebuilds": int(self.rebuilds),
            "rebuild_requeued": int(self.rebuild_requeued),
            "requeued_static": int(self.requeued_static),
            "breaker_deflected": int(self.breaker_deflected),
            "closed_deflected": int(self.closed_deflected),
            "breaker": self.breaker.metrics(),
        }
        return out

    # -- device-loop surface -------------------------------------------------
    def step(self) -> None:
        """One scheduler round: sweep -> consume status -> collect ->
        detach -> attach -> advance.

        The round's ONE host sync is the status consumption; detach,
        attach, and the next advance are async dispatches, so the host
        returns to the engine loop (other flights, controls, admission)
        while the device crunches the chunk just enqueued.  Consequences
        of a chunk are therefore observed one chunk late — the same
        documented reaction lag as the static flight loop."""
        # Queue housekeeping first, even mid-cooldown: a cancelled or
        # deadline-expired job requeued on a cooling flight must resolve
        # now, not after the (operator-settable) cooldown elapses.
        self._sweep_pending()
        if self.cooling():
            return  # rebuilding after a failure: no device work yet
        self._consume_status()
        t0 = self.engine._clock()
        self._event_wall = 0.0
        self._collect_and_detach()
        self._attach_pending()
        self._advance()
        if self._pending_status is not None:  # a chunk was dispatched
            # Exclude the detach-round verdict fetch (a sync, recorded by
            # _collect_and_detach) so dispatch_wall stays what it claims:
            # async enqueue time.
            self.dispatch_wall.record(self.engine._clock() - t0 - self._event_wall)

    def _consume_status(self) -> None:
        """Fetch the previous advance's packed status word (the round's
        single host sync); no-op when no advance is outstanding."""
        if self._pending_status is None:
            return
        rec = trace.active()
        tr0 = rec.now() if rec is not None else 0.0
        t0 = self.engine._clock()
        raw = engine_mod.host_fetch(
            self._pending_status, floor_s=self.engine.handicap_s
        )
        self._pending_status = None
        self._status = self._unpack(raw)
        sync_s = self.engine._clock() - t0
        self.chunk_wall.record(sync_s)
        # The mergeable twin + the floor estimator (obs/hist.py): resident
        # chunk syncs share the engine-level histograms so cluster-scope
        # aggregation sees one distribution per phase, not one per
        # geometry object.
        self.engine.hist["chunk_wall_ms"].record(sync_s)
        self.engine.rpc_floor.record(sync_s)
        self.chunks += 1
        # Round/wall totals for the device-efficiency gauge.  A negative
        # delta is the _REBASE_STEPS reset — rebase the baseline, skip
        # the sample (limits are relative, so nothing is lost).
        steps = int(self._status["steps"])
        delta = steps - self._steps_seen
        self._steps_seen = steps
        if delta > 0:
            self.rounds_total += delta
            self.round_wall_total += sync_s
        # A consumed chunk is the breaker's definition of success: it
        # resets the consecutive-failure count and closes a half-open
        # breaker (the probe rebuild proved the device serves again).
        if rec is None:
            self.breaker.record_success()
        else:
            rec.record(
                None, "resident.sync", "fetch.status", tr0,
                node=self.engine.trace_node, chunk=self.chunks,
                geometry=f"{self.geom.n}x{self.geom.n}",
                uuids=[j.uuid for j in self.slots if j is not None],
            )
            before = self.breaker.state
            self.breaker.record_success()
            if self.breaker.state != before:
                rec.event(
                    None, "breaker", "resident.breaker",
                    node=self.engine.trace_node,
                    geometry=f"{self.geom.n}x{self.geom.n}",
                    attrs={"from": before, "to": self.breaker.state},
                )

    def _resolve_dead(self, job, cancelled: bool) -> None:
        """Resolve a job that leaves the scheduler with no verdict: either
        its cancel was consumed (``cancelled``) or its deadline passed.
        The single definition of that bookkeeping — every exit path
        (queue sweep, attach-time check, slot collection) goes through
        here so flags, counters, and latency accounting cannot diverge."""
        if cancelled:
            job.cancelled = True
            self.cancelled += 1
        else:
            job.error = "deadline expired"
            self.expired += 1
        self.engine._finish_job(job)

    def _sweep_pending(self) -> None:
        """Resolve cancelled/expired jobs still WAITING in the admission
        queue, independently of slot availability.

        Without this, dead queue entries would only drain when a slot
        freed: with every slot busy on long jobs, a burst of timed-out
        clients (HTTP 504 -> cancel) would keep the bounded queue full of
        dead work — 429-ing live traffic for minutes — and the cancelled
        jobs' done events would stay unset until a slot opened."""
        now = self.engine._clock()
        with self._lock:
            queued = list(self._pending)
        dead = []
        for job in queued:
            cancelled = self.engine._consume_cancel(job)
            expired = job.deadline is not None and now > job.deadline
            if cancelled or expired:
                dead.append((job, cancelled))
        if not dead:
            return
        with self._lock:
            for job, _ in dead:
                self._pending.remove(job)  # single-threaded pop: present
        for job, cancelled in dead:
            self._resolve_dead(job, cancelled)

    def _collect_and_detach(self) -> None:
        """Resolve finished/cancelled/expired slot jobs; recycle their slots.

        Solved / has-work bits come from the last consumed status word —
        one chunk stale by design, and sound: a solved slot's rows are
        frozen in-graph the round it resolves, and a slot with no live
        lanes cannot regrow work (stealing is gang-scoped), so the verdict
        payload read from the already-dispatched next chunk's state is
        exact.  The payload fetch (``_verdict_jit``) happens ONLY on
        rounds where a slot actually leaves."""
        if self.state is None or self._status is None or all(
            s is None for s in self.slots
        ):
            return
        solved = self._status["solved"]
        has_work = self._status["has_work"]
        now = self.engine._clock()
        detach_mask = np.zeros(self.n_slots, bool)
        leaving: list = []  # (slot, job, cancelled, expired)
        for slot, job in enumerate(self.slots):
            if job is None:
                continue
            cancelled = self.engine._consume_cancel(job)
            expired = job.deadline is not None and now > job.deadline
            if not (solved[slot] or not has_work[slot] or cancelled or expired):
                continue
            detach_mask[slot] = True
            leaving.append((slot, job, cancelled, expired))
        if not leaving:
            return
        # The event fetch: one sync for every leaving slot's verdict data —
        # skipped entirely when every leaver departs verdict-less (cancelled
        # or expired mid-search), since none of the payload would be read;
        # those jobs keep nodes=0 (best-effort) instead of paying an RPC
        # floor plus the in-flight chunk's wall for a discarded fetch.
        nodes = sol_counts = overflowed = solutions = None
        if any(
            solved[slot] or (not has_work[slot] and not cancelled)
            for slot, job, cancelled, expired in leaving
        ):
            rec = trace.active()
            tr_ev = rec.now() if rec is not None else 0.0
            t_ev = self.engine._clock()
            nodes, sol_counts, overflowed, solutions = engine_mod.host_fetch(
                self._verdict_fn(self.state),
                floor_s=self.engine.handicap_s,
                tag="event",
            )
            self._event_wall = self.engine._clock() - t_ev
            self.event_wall.record(self._event_wall)
            self.engine.hist["event_wall_ms"].record(self._event_wall)
            if rec is not None:
                rec.record(
                    None, "verdict.sync", "fetch.event", tr_ev,
                    node=self.engine.trace_node,
                    uuids=[j.uuid for _, j, _, _ in leaving],
                )
        for slot, job, cancelled, expired in leaving:
            if solved[slot]:
                job.solved = True
                job.solution = np.asarray(solutions[slot], np.int32)
                job.sol_count = int(sol_counts[slot])
            elif not has_work[slot] and not cancelled:
                # Space exhausted.  Resident jobs never shed, so exhaustion
                # IS a proof — unless an overflow dropped a subtree, which
                # downgrades the verdict to unknown exactly like the static
                # path's finalize.  A complete proof beats a same-chunk
                # deadline expiry: the client gets proven-unsat, not a
                # spurious "deadline expired".
                job.exhausted = not overflowed[slot]
                job.unsat = job.exhausted
            if nodes is not None:
                job.nodes = int(nodes[slot])
            self.slots[slot] = None
            with self._lock:
                self._free.append(slot)
            self.completed += 1
            if cancelled or (
                expired and not (job.solved or job.unsat or job.exhausted)
            ):
                # Leaving without a verdict (a found solution or a
                # completed exhaustion proof always beats same-chunk
                # expiry; a consumed cancel always marks the job).
                self._resolve_dead(job, cancelled)
            else:
                self.engine._finish_job(job)
        if faults.active() is not None:  # don't build uuid tuples per round
            faults.fire(
                "resident.detach",
                uuids=tuple(j.uuid for j in self.slots if j is not None),
            )
        self.state = self._detach_fn(self.state, jnp.asarray(detach_mask))

    def _attach_pending(self) -> None:
        """FIFO-drain the admission queue into free slots, one jit-stable
        attach batch per chunk boundary."""
        now = self.engine._clock()
        batch: list = []
        while len(batch) < self.rcfg.attach_batch:
            with self._lock:
                if not self._pending or not self._free:
                    break
                job = self._pending.popleft()
                slot = self._free.popleft()
            # Queued-side cancel/expiry: resolve without ever touching the
            # device; the slot goes straight back.
            cancelled = self.engine._consume_cancel(job)
            expired = job.deadline is not None and now > job.deadline
            if cancelled or expired:
                with self._lock:
                    self._free.appendleft(slot)
                self._resolve_dead(job, cancelled)
                continue
            # Record the slot BEFORE any device call: if the init/attach
            # program below raises (compile/OOM), fail() -> drain() sweeps
            # self.slots and resolves the job instead of leaving it
            # stranded in a popped limbo with its done event never set.
            self.slots[slot] = job
            batch.append((slot, job))
        if not batch:
            return
        rec = trace.active()
        if rec is not None:
            t1 = rec.now()
            for slot, job in batch:
                # Admission span: submit -> attach is the resident queue
                # wait, the per-job number the aggregate
                # admission_wait_ms window cannot attribute.
                rec.record(
                    job.uuid, "admission", "resident.attach",
                    t0=job.trace_t0 if job.trace_t0 is not None else t1,
                    t1=t1, node=self.engine.trace_node, route="resident",
                    slot=slot,
                )
        if faults.active() is not None:
            faults.fire(
                "resident.attach", uuids=tuple(job.uuid for _, job in batch)
            )
        if self.state is None:
            self.state = self._init_fn(self.geom, self.config, self.n_slots)
        n = self.geom.n
        k = self.rcfg.attach_batch
        grids = np.zeros((k, n, n), np.int32)
        slot_ids = np.full(k, -1, np.int32)
        for i, (slot, job) in enumerate(batch):
            grids[i] = job.grid
            slot_ids[i] = slot
            wait_s = now - job.submitted_at
            self.admission_wait.record(wait_s)
            self.engine.hist["admission_wait_ms"].record(wait_s)
        self.state = self._attach_fn(
            self.state, jnp.asarray(grids), jnp.asarray(slot_ids),
            self.geom, self.gang,
        )

    def _advance(self) -> None:
        """Dispatch one bounded-step chunk of the resident frontier (async
        — the chunk's status is consumed at the NEXT scheduler round).

        The step limit is computed in-graph from the frontier's own
        counter, so no host fetch is needed to dispatch; the old
        per-round ``int(state.steps)`` scalar fetch and full-state
        ``block_until_ready`` are gone (round 8)."""
        if self.state is None or all(s is None for s in self.slots):
            return
        if self._status is not None and self._status["steps"] > _REBASE_STEPS:
            # Rebase both monotone counters well before int32 overflow:
            # limits are relative, and the occupancy histogram is computed
            # from in-graph deltas, so zeroing lane_rounds (which a
            # never-retiring resident frontier grows forever — a latent
            # round-7 overflow) is invisible to every consumer.
            # deadck: allow(single-writer: ResidentFlight.state is only ever mutated on the device loop; solve_file's reach is a static over-approximation through the shared advance helpers)
            self.state = self.state._replace(
                steps=jnp.int32(0),
                lane_rounds=jnp.zeros_like(self.state.lane_rounds),
            )
        _advance_fn, _advance_prog, _statics = self._advance_bound()
        if faults.active() is not None:
            faults.fire(
                "resident.advance",
                uuids=tuple(j.uuid for j in self.slots if j is not None),
            )
        rec = trace.active()
        tr0 = rec.now() if rec is not None else 0.0
        self.state, self._pending_status = _advance_fn(
            self.state, jnp.int32(self.rcfg.chunk_steps), self.geom,
            self.config, **_statics,
        )
        if rec is not None:
            rec.record(
                None, "resident.chunk.dispatch", "resident.advance", tr0,
                node=self.engine.trace_node,
                uuids=[j.uuid for j in self.slots if j is not None],
            )
        cw = compilewatch.active()
        if cw is not None and self.chunks == 0:
            # Cost-plane seam (obs/compilewatch.py), the engine's twin:
            # once per (program, resident shape) — the chunks==0 guard
            # bounds even the key construction to the flight's first
            # round(s), and ``.lower()`` reads aval shapes only (no
            # device sync; the fetch-count guard runs with the watch
            # installed to prove it).
            # .shape is host-side metadata (a tuple of ints, no sync).
            lanes = self.state.has_top.shape[0]
            cw.capture_cost(
                _advance_prog,
                (self.geom.n, lanes, self.config.stack_slots,
                 self.config.step_impl, "resident"),
                lambda: _advance_fn.lower(
                    self.state, jnp.int32(self.rcfg.chunk_steps),
                    self.geom, self.config, **_statics,
                ),
                geometry=f"{self.geom.n}x{self.geom.n}",
                lanes=lanes,
                chunk_steps=self.rcfg.chunk_steps,
                resident=True,
            )

    def on_failure(self, exc: BaseException) -> None:
        """A device program died mid-round (attach/advance/status): recover
        instead of erroring every held job (the pre-round-9 behavior, now
        only the last resort).

        The donated frontier did not survive the failed program, so all
        device state is dropped; held jobs (slots AND admission queue) are
        charged one retry each — those out of budget fail, survivors are
        requeued.  A *transient* fault requeues them on this flight's own
        admission queue and schedules a rebuild after ``rebuild_cooldown_s``
        (a rebuilt flight re-attaches from job grids — sound, because no
        partial results were ever reported).  A *permanent* fault, or a
        circuit breaker driven OPEN by ``breaker_failures`` consecutive
        rebuild failures, reroutes survivors to static flights instead
        (they keep their deadlines, lose only the resident packing); a
        permanent fault additionally closes admission for good — this
        geometry's resident program is broken, not unlucky.
        """
        kind = faults.classify(exc)
        label = f"{type(exc).__name__}: {exc}"
        rec = trace.active()
        breaker_before = self.breaker.state
        self.breaker.record_failure()
        self.state = None
        self._pending_status = None
        self._status = None
        held = [j for j in self.slots if j is not None]
        self.slots = [None] * self.n_slots
        with self._lock:
            held.extend(self._pending)
            self._pending.clear()
            self._free = deque(range(self.n_slots))
        survivors = [
            job
            for job in held
            if not job.done.is_set()
            and self.engine._charge_retry(job, kind, label)
        ]
        if rec is not None and self.breaker.state != breaker_before:
            geometry = f"{self.geom.n}x{self.geom.n}"
            rec.event(
                None, "breaker", "resident.breaker",
                node=self.engine.trace_node, geometry=geometry,
                attrs={"from": breaker_before, "to": self.breaker.state},
            )
            if self.breaker.state == self.breaker.OPEN:
                # The other flight-recorder moment: admission is about to
                # deflect this geometry's traffic — dump the recent ring
                # and metrics so the opening is reconstructible.
                rec.dump("breaker_open", metrics=self.engine.metrics())
        if kind == faults.PERMANENT or self.breaker.state == self.breaker.OPEN:
            for job in survivors:
                self.engine._requeue(job)
            self.requeued_static += len(survivors)
            if kind == faults.PERMANENT:
                with self._lock:
                    self._closed = True
            _LOG.warning(
                "[resident %sx%s] %s failure: rerouted %d jobs to static "
                "flights (%s): %s",
                self.geom.n, self.geom.n, kind, len(survivors),
                uuids_label(survivors), label,
            )
            if rec is not None:
                rec.event(
                    None, "recovery.reroute", "resident.recovery",
                    node=self.engine.trace_node, kind=kind,
                    uuids=[j.uuid for j in survivors], error=label,
                )
        else:
            # Rebuild path: jobs go back to the front of the admission
            # queue in order; the cooldown keeps back-to-back failure
            # storms from monopolizing the device loop.
            with self._lock:
                self._pending.extendleft(reversed(survivors))
            self.rebuild_requeued += len(survivors)
            self.rebuilds += 1
            self._cooldown_until = (
                self.policy.clock() + self.policy.rebuild_cooldown_s
            )
            _LOG.warning(
                "[resident %sx%s] transient failure: rebuild scheduled, "
                "%d jobs requeued (%s): %s",
                self.geom.n, self.geom.n, len(survivors),
                uuids_label(survivors), label,
            )
            if rec is not None:
                rec.event(
                    None, "recovery.rebuild", "resident.recovery",
                    node=self.engine.trace_node, kind=kind,
                    uuids=[j.uuid for j in survivors], error=label,
                )

    def detach_pending(self) -> list:
        """Graceful drain (``SolverEngine.drain``): pop every queued job
        that never attached to a slot and hand it back to the engine's
        drain ladder (peer handoff or WAL replay).  Attached slots are
        NOT touched — those jobs finish on the device.  Admission stays
        open (``_closed`` untouched): the engine's drain gate already
        rejects new submits, and a restart reuses this flight."""
        with self._lock:
            out = [j for j in self._pending if not j.done.is_set()]
            self._pending.clear()
        return out

    def fail(self, exc: BaseException) -> None:
        """Terminal failure (no recovery): fail every job this flight
        holds and close admission — future submits fall back to static
        flights.  Kept for callers that need the pre-round-9 semantics;
        the engine loop itself now routes through :meth:`on_failure`."""
        self.drain(f"{type(exc).__name__}: {exc}")

    def drain(self, reason: str = "engine stopped") -> None:
        """Resolve everything still held at shutdown (nobody will ever
        service these jobs; an un-set event would hang its waiter)."""
        with self._lock:
            self._closed = True
            stranded = list(self._pending)
            self._pending.clear()
        stranded.extend(j for j in self.slots if j is not None)
        self.slots = [None] * self.n_slots
        self._pending_status = None  # nobody will consume it
        for job in stranded:
            if not job.done.is_set():
                job.error = reason
                job.done.set()
