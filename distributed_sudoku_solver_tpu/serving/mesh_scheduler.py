"""Pod-scale resident serving: the resident flight sharded over a device
mesh (ROADMAP #1).

``MeshResidentFlight`` IS a ``ResidentFlight`` — same admission queue, FIFO
fairness, deadlines, cancel, 429 backpressure, breaker-guarded rebuild, and
the round-8 one-sync-per-chunk loop, all inherited verbatim.  What changes
is the strategy underneath the hooks:

* the device programs are the shard_map twins
  (``parallel/mesh_resident.py``): lane axis sharded over a 1-D mesh,
  donated through every program, per-step psum solved merge, cross-shard
  ring steal with home lanes excluded from installs;
* ``job_slots`` becomes the PER-SHARD slot count — the flight's pool is
  ``job_slots * mesh_devices``, so admission capacity (and aggregate
  boards/s) scales with the mesh while the per-job gang width stays fixed;
* the status word carries mesh telemetry (ring-steal volume, per-shard
  live / foreign-live lanes) decoded by the ``_unpack`` hook into the
  ``metrics()["mesh"]`` section — still ONE ``host_fetch`` per chunk;
* shard loss surfaces as a failed collective in the advance/attach/detach
  program: ``ResidentFlight.on_failure`` classifies it transient, drops
  the donated state, requeues held jobs, and rebuilds through the round-9
  breaker — the ``mesh.*`` FaultSchedule sites below let tests inject the
  fault exactly at the collective seams.

Composite step only: the fused kernel has no sharded resident twins, so a
fused base config is downgraded to ``step_impl='xla'`` for the mesh flight
(the single-chip resident and the bulk fused-sharded tier are unaffected).

Slot placement: slot ``s`` lives on shard ``s // job_slots``; its gang is
shard-contained by construction.  With ``gang_lanes == 1`` every lane is a
home lane and cross-shard steal has no install capacity — allowed, but a
mesh flight wants ``gang_lanes >= 2`` to actually balance load.
"""

from __future__ import annotations

import dataclasses
import functools
import logging

import jax
import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.obs import compilewatch, lockdep
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.parallel.mesh import make_mesh
from distributed_sudoku_solver_tpu.parallel.mesh_resident import (
    mesh_advance_status,
    mesh_attach,
    mesh_detach,
    mesh_init_resident,
    unpack_mesh_status,
)
from distributed_sudoku_solver_tpu.serving import faults
from distributed_sudoku_solver_tpu.serving.scheduler import (
    ResidentConfig,
    ResidentFlight,
    resident_solver_config,
)

_LOG = logging.getLogger(__name__)


class MeshResidentFlight(ResidentFlight):
    """One long-lived MESH-resident frontier: ``ResidentFlight`` with the
    device programs swapped for the shard_map twins.

    Raises ``ValueError`` when ``rcfg.mesh_devices < 2`` or more devices
    are requested than visible — the engine degrades to the single-chip
    flight (``SolverEngine._resident_for``), never silently under-shards.
    """

    def __init__(self, engine, geom: Geometry, rcfg: ResidentConfig):
        n_dev = rcfg.mesh_devices
        if n_dev < 2:
            raise ValueError(
                f"mesh_devices must be >= 2 for a mesh flight, got {n_dev}"
            )
        devices = jax.devices()
        if len(devices) < n_dev:
            raise ValueError(
                f"mesh_devices={n_dev} but only {len(devices)} visible"
            )
        self.mesh = make_mesh(devices[:n_dev])
        self.mesh_devices = n_dev
        super().__init__(engine, geom, rcfg)
        self._attach_fn = self._mesh_attach
        self._detach_fn = self._mesh_detach
        self._init_fn = functools.partial(mesh_init_resident, mesh=self.mesh)
        # Mesh telemetry decoded from the chunk status word (_unpack runs
        # on the device loop; metrics() reads from any thread).
        self._mesh_lock = lockdep.named_lock("serving.mesh_scheduler")  # lockck: name(serving.mesh_scheduler)
        self.ring_shipped = 0  # lockck: guard(_mesh_lock) — rows stolen cross-shard
        self._shard_live = np.zeros(n_dev, np.int64)  # lockck: guard(_mesh_lock)
        self._shard_foreign = np.zeros(n_dev, np.int64)  # lockck: guard(_mesh_lock)

    # -- strategy hooks ------------------------------------------------------
    def _solver_config(
        self, base: SolverConfig, geom: Geometry, rcfg: ResidentConfig
    ) -> SolverConfig:
        if base.step_impl == "fused":
            base = dataclasses.replace(base, step_impl="xla")
        # Home lanes must never receive stolen rows on the mesh: ring steal
        # makes gangs tag-heterogeneous, and a foreign row relayed onto a
        # freed slot's home lane is destroyed by the next attach overwrite
        # (a false-unsat, no overflow flag).  See SolverConfig.
        base = dataclasses.replace(base, protect_home_lanes=True)
        total = dataclasses.replace(
            rcfg, job_slots=rcfg.job_slots * rcfg.mesh_devices
        )
        return resident_solver_config(base, geom, total)

    def _unpack(self, raw) -> dict:
        status = unpack_mesh_status(raw, self.n_slots, self.mesh_devices)
        with self._mesh_lock:
            self.ring_shipped += status["ring_shipped"]
            self._shard_live = status["shard_live"]
            self._shard_foreign = status["shard_foreign"]
        return status

    def _advance_bound(self):
        if faults.active() is not None:
            faults.fire(
                "mesh.advance",
                uuids=tuple(j.uuid for j in self.slots if j is not None),
            )
        return (
            mesh_advance_status,
            compilewatch.MESH_ADVANCE_STATUS,
            {"mesh": self.mesh},
        )

    def _mesh_attach(self, state, grids, slot_ids, geom, gang):
        if faults.active() is not None:
            faults.fire("mesh.attach")
        return mesh_attach(state, grids, slot_ids, geom, gang, mesh=self.mesh)

    def _mesh_detach(self, state, slot_mask):
        if faults.active() is not None:
            faults.fire("mesh.detach")
        return mesh_detach(state, slot_mask, mesh=self.mesh)

    # -- any-thread surface --------------------------------------------------
    def admission_pressure(self) -> tuple:
        """Mesh-aware brownout signal (``serving/brownout.py`` queue/wait
        closures): pending jobs that fit the mesh's FREE shard slots
        attach on the next chunk, so they exert no sustained queue
        pressure — subtract that headroom before normalizing.  A browning
        node with ``mesh_devices`` headroom therefore gets WIDER (keeps
        admitting into idle shards) before the controller sheds; a full
        pool reads identically to the single-chip flight."""
        with self._lock:
            pending = len(self._pending)
            free = sum(1 for s in self.slots if s is None)
        frac = max(0, pending - free) / float(self.rcfg.queue_depth)
        aw = self.admission_wait.snapshot()
        return frac, (aw["p95"] if aw else 0.0)

    def metrics(self) -> dict:
        out = super().metrics()
        per = self.rcfg.job_slots
        with self._lock:
            occupancy = [
                sum(
                    1
                    for s in self.slots[d * per : (d + 1) * per]
                    if s is not None
                )
                for d in range(self.mesh_devices)
            ]
        with self._mesh_lock:
            out["mesh"] = {
                "devices": self.mesh_devices,
                "slot_occupancy": occupancy,  # per-shard occupied slots
                "shard_live_lanes": [int(x) for x in self._shard_live],
                "shard_foreign_lanes": [int(x) for x in self._shard_foreign],
                "ring_shipped": int(self.ring_shipped),
                "rebuilds": int(self.rebuilds),
            }
        return out
