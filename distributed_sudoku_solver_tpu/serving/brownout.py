"""SLO-burn-driven brownout: the observability plane finally *drives* admission.

Rounds 11-15 built a complete observability plane — stitched traces,
mergeable histograms, a declarative SLO monitor whose burn-rate crossings
dump the flight recorder — but a crossing only ever produced *evidence*:
under sustained overload the node observed its own death in perfect
detail while admitting every request that killed it.  This module closes
the loop (ROADMAP #6): a :class:`BrownoutController` turns the signals
the plane already exports into an **edge-triggered, hysteresis-guarded
stage ladder** that sheds load *by value*, not at random — possible only
because the front door (``serving/frontdoor``) already classifies every
request into cache / propagation / native / device tiers at submit time,
and the easy tiers are cheap to serve natively or to refuse (PAPERS.md,
"A Study Of Sudoku Solving Algorithms": backtracking handles easy
instances without device help).

**The stage ladder** (each stage strictly contains the previous one's
restrictions; cache hits and propagation verdicts serve at EVERY stage —
they cost microseconds and no device work):

====== =====================================================================
stage  admission policy
====== =====================================================================
0      healthy: every tier serves normally.
1      easy boards route **native-only**: the ``race_native`` device
       shadow fallback is suppressed, reclaiming the device lanes the
       easy tier was hedging with.
2      the easy tier is **shed** with ``503 + Retry-After`` at the front
       door; the hard tail still reaches the device.
3      only cache/propagation answers are admitted: anything that would
       cost a dispatch — easy or hard — is refused with ``429``.
====== =====================================================================

**Signals** (each normalized so 1.0 = "at the configured limit"; the
controller's pressure is the max over whatever signals are bound):

* ``burn`` — the max per-objective SLO burn rate
  (:meth:`obs.slo.SloMonitor.burn_snapshot`; burn 1.0 = consuming the
  error budget exactly at the sustained allowable rate);
* ``queue`` — resident admission-queue fill fraction
  (``serving/scheduler.py`` :meth:`ResidentFlight.admission_pressure`);
* ``wait`` — resident admission-wait p95 over ``wait_budget_s``;
* ``floor`` — ``rpc_floor_ms`` drift: the recent-window floor over the
  lifetime floor, normalized by ``floor_drift`` (a link whose sync floor
  quadrupled is a degrading tunnel, not a code change).

**Hysteresis** is two-sided and edge-triggered: pressure at or above
``enter`` climbs one stage per evaluation (never faster than ``hold_s``);
de-escalation requires pressure at or below ``exit`` *continuously* for
``quiet_s`` — a reading between the thresholds resets nothing upward but
also accrues no calm, so the ladder neither flaps nor decays under
sustained borderline load.  Every transition is counted exactly once,
``[brownout]`` ctx-logged, trace-evented, and flight-recorder dumped.

**Hot-path contract** (the tracer's): the serving path reaches the
controller through the process-wide seam ``brownout.active()`` — ``None``
unless installed, so the disabled path is one global read + one branch
(explode-microcheck pinned in tests/test_brownout.py).  All time comes
from the injectable ``clock``; signal callables are read OUTSIDE the
controller lock, and transition side effects (log/trace/dump) fire after
it is released, so the controller's lock is a leaf that never holds
another lock (deadck rank ``serving.brownout``).

**Scope**: shedding happens only for ``saturation='reject'`` submits —
the serving boundary, where a refusal becomes an honest HTTP answer.
Quiet-fallback callers (cluster TASK re-execution, library users, bulk
stragglers) are internal work the node already accepted; at shed stages
they degrade to the native-only policy instead of erroring.

Import discipline: stdlib + ``obs`` only (closed layer in
``analysis/manifest.py``) — the engine binds its signals through
:func:`engine_signals` duck-typed closures, never an import back.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import time
from typing import Callable, Dict, Optional

from distributed_sudoku_solver_tpu.obs import lockdep, slo, trace
from distributed_sudoku_solver_tpu.obs.logctx import ctx_log

_LOG = logging.getLogger(__name__)

#: Admission verdicts from :meth:`BrownoutController.gate`.
SERVE = "serve"
NATIVE_ONLY = "native_only"
SHED = "shed"

#: The ladder's stage count (0..MAX_STAGE inclusive).
MAX_STAGE = 3

#: Shed tiers (the ``shed_tier`` field of every shed response).
TIERS = ("easy", "hard")


class BrownoutShed(RuntimeError):
    """A brownout stage refused this request at the front door.

    The HTTP layer turns it into the machine-readable shed response
    ``{stage, retry_after_s, shed_tier}`` — ``503`` at stage 2 (the easy
    tier is browned out, retry later), ``429`` at stage 3 (nothing that
    costs a dispatch is admitted).  Shed responses are recorded into the
    ``solve`` SLO stream as NON-errors: shedding exists to protect the
    error-rate objective, so it must not burn it.
    """

    def __init__(self, stage: int, retry_after_s: float, shed_tier: str,
                 uuid: Optional[str] = None):
        self.stage = int(stage)
        self.retry_after_s = float(retry_after_s)
        self.shed_tier = shed_tier
        self.status = 503 if self.stage == 2 else 429
        self.uuid = uuid
        super().__init__(
            f"browning out (stage {self.stage}): {shed_tier}-tier requests "
            f"are shed; retry after {self.retry_after_s:.1f}s"
        )


@dataclasses.dataclass(frozen=True)
class BrownoutConfig:
    """Knobs for the stage ladder (CLI: ``--brownout-enter`` /
    ``--brownout-exit``; the controller itself is on by default whenever
    ``--slo`` is set, ``--no-brownout`` disables it)."""

    #: Pressure at or above which the ladder climbs one stage.
    enter: float = 1.0
    #: Pressure at or below which calm accrues toward de-escalation.
    #: Must be strictly below ``enter`` (the hysteresis band).
    exit: float = 0.5
    #: Continuous calm (pressure <= exit) before stepping DOWN one stage.
    quiet_s: float = 15.0
    #: Minimum dwell between consecutive UPWARD transitions, so one
    #: pressure spike cannot leap 0 -> 3 in a single burst of reads.
    hold_s: float = 1.0
    #: Signal re-evaluation is rate-limited to once per this interval
    #: (every ``stage()`` read past the interval re-evaluates, so the
    #: ladder also recovers on /metrics reads when traffic stops).
    eval_interval_s: float = 0.25
    #: Admission-wait p95 that counts as pressure 1.0 on the ``wait``
    #: signal.
    wait_budget_s: float = 1.0
    #: ``rpc_floor_ms`` recent/lifetime ratio that counts as pressure 1.0
    #: on the ``floor`` signal (4.0 = the sync floor quadrupled).  The
    #: signal is normalized over the DRIFT, not the raw ratio — an
    #: undrifted floor (recent == lifetime min) reads 0.0, so a healthy
    #: node carries no structural floor pressure whatever the
    #: enter/exit thresholds are set to.  Must be > 1.
    floor_drift: float = 4.0
    #: Retry-After hint on shed responses; 0 derives it from ``quiet_s``
    #: (the soonest the ladder could possibly step down).
    retry_after_s: float = 0.0

    def __post_init__(self) -> None:
        if not self.exit < self.enter:
            raise ValueError(
                f"brownout exit threshold ({self.exit}) must be strictly "
                f"below enter ({self.enter}) — the hysteresis band"
            )
        if not self.floor_drift > 1.0:
            raise ValueError(
                f"floor_drift must be > 1 (got {self.floor_drift}): it is "
                "the recent/lifetime floor ratio that maps to pressure 1.0"
            )


class BrownoutController:
    """The stage ladder: signals in, admission verdicts out.

    ``signals`` maps signal names to zero-arg callables returning a
    normalized pressure (or ``None`` when the signal has no data yet);
    they are read with NO controller lock held — injected callables may
    acquire arbitrary observability locks (``engine_signals``).
    ``metrics_fn`` (optional, injected at wiring time) supplies the
    metrics snapshot embedded in transition dumps, exactly the SLO
    monitor's pattern.
    """

    SERVE, NATIVE_ONLY, SHED = SERVE, NATIVE_ONLY, SHED

    def __init__(
        self,
        config: Optional[BrownoutConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        signals: Optional[Dict[str, Callable[[], Optional[float]]]] = None,
        metrics_fn: Optional[Callable[[], dict]] = None,
    ):
        self.config = config or BrownoutConfig()
        self._clock = clock
        self._signals: Dict[str, Callable[[], Optional[float]]] = dict(
            signals or {}
        )
        self.metrics_fn = metrics_fn
        self._lock = lockdep.named_lock("serving.brownout")  # lockck: name(serving.brownout)
        now = clock()
        self._stage = 0  # lockck: guard(_lock)
        self._stage_since = now  # lockck: guard(_lock)
        self._last_eval: Optional[float] = None  # lockck: guard(_lock)
        self._last_up = now - self.config.hold_s  # lockck: guard(_lock)
        self._calm_since: Optional[float] = None  # lockck: guard(_lock)
        self._pressure: Dict[str, float] = {}  # lockck: guard(_lock) — last evaluated per-signal readings
        self.transitions = 0  # lockck: guard(_lock) — every stage change, exactly once
        self.escalations = 0  # lockck: guard(_lock)
        self.deescalations = 0  # lockck: guard(_lock)
        self.stage_entered = [0] * (MAX_STAGE + 1)  # lockck: guard(_lock)
        self._residency = [0.0] * (MAX_STAGE + 1)  # lockck: guard(_lock)
        self.shed_counts = {t: 0 for t in TIERS}  # lockck: guard(_lock)
        self.shed_by_stage = [0] * (MAX_STAGE + 1)  # lockck: guard(_lock)

    # -- signal wiring -------------------------------------------------------
    def set_signals(
        self, signals: Dict[str, Callable[[], Optional[float]]]
    ) -> None:
        """Replace the signal set (wiring time, before install)."""
        self._signals = dict(signals)

    # -- the admission surface ----------------------------------------------
    def stage(self) -> int:
        """Current stage; re-evaluates the signals at most once per
        ``eval_interval_s`` (the front door calls this per eligible
        submit, so under traffic the ladder tracks pressure closely, and
        /metrics reads keep it decaying when traffic stops)."""
        now = self._clock()
        with self._lock:
            due = (
                self._last_eval is None
                or now - self._last_eval >= self.config.eval_interval_s
            )
            if due:
                self._last_eval = now
        if due:
            return self.evaluate()
        with self._lock:
            return self._stage

    def gate(self, tier: str) -> tuple:
        """Admission verdict for a probed-open board of ``tier`` ('easy'
        or 'hard'): ``(SERVE | NATIVE_ONLY | SHED, stage)``.  Shedding
        callers raise :class:`BrownoutShed`; quiet callers downgrade a
        SHED verdict to the native-only policy themselves (module note).
        """
        s = self.stage()
        if tier == "easy":
            if s >= 2:
                return SHED, s
            if s == 1:
                return NATIVE_ONLY, s
        elif s >= 3:
            return SHED, s
        return SERVE, s

    def record_shed(self, tier: str, stage: int) -> None:
        """Count one shed response (called by whoever refused the
        request — the front door in production, a replay node's model)."""
        with self._lock:
            if tier in self.shed_counts:
                self.shed_counts[tier] += 1
            self.shed_by_stage[max(0, min(MAX_STAGE, int(stage)))] += 1

    def retry_after_s(self) -> float:
        """Retry-After for shed responses: configured, or the soonest a
        quiet window could walk the ladder down one stage."""
        if self.config.retry_after_s > 0:
            return self.config.retry_after_s
        return max(1.0, self.config.quiet_s)

    # -- evaluation ----------------------------------------------------------
    def evaluate(self) -> int:
        """Read every signal, apply the hysteresis ladder, fire the
        transition side effects; returns the (possibly new) stage.

        Signals are read and side effects fired with the lock RELEASED:
        the lock guards only the transition decision and counters, so
        ``serving.brownout`` stays a leaf in the deadck hierarchy no
        matter what the injected callables touch.
        """
        readings: Dict[str, float] = {}
        for name, fn in self._signals.items():
            try:
                v = fn()
            except Exception:  # noqa: BLE001 - a broken signal is silence, not an outage
                v = None
            if v is not None:
                readings[name] = float(v)
        pressure = max(readings.values(), default=0.0)
        now = self._clock()
        cfg = self.config
        event = None
        with self._lock:
            self._pressure = readings
            old = self._stage
            if pressure >= cfg.enter:
                self._calm_since = None
                if old < MAX_STAGE and now - self._last_up >= cfg.hold_s:
                    self._transition_locked(old + 1, now)
                    self._last_up = now
                    event = (old, old + 1, pressure, dict(readings))
            elif pressure <= cfg.exit:
                if self._calm_since is None:
                    self._calm_since = now
                elif old > 0 and now - self._calm_since >= cfg.quiet_s:
                    self._transition_locked(old - 1, now)
                    # The next step down needs its own full quiet window.
                    self._calm_since = now
                    event = (old, old - 1, pressure, dict(readings))
            else:
                # Inside the hysteresis band: no climb, no calm accrual.
                self._calm_since = None
            stage = self._stage
        if event is not None:
            self._announce(*event)
        return stage

    def _transition_locked(self, new: int, now: float) -> None:
        self._residency[self._stage] += now - self._stage_since
        self._stage = new
        self._stage_since = now
        self.transitions += 1
        self.stage_entered[new] += 1

    def _announce(self, old: int, new: int, pressure: float,
                  readings: Dict[str, float]) -> None:
        """Transition side effects, fired OUTSIDE the lock: the
        ``[brownout]`` log line, the trace event, and the flight-recorder
        dump (evidence of what the node looked like when admission
        changed)."""
        up = new > old
        with self._lock:
            if up:
                self.escalations += 1
            else:
                self.deescalations += 1
        log = ctx_log(_LOG, "brownout", f"{old}->{new}")
        if up:
            log.warning(
                "pressure %.2f >= enter %.2f: escalating to stage %d (%s)",
                pressure, self.config.enter, new,
                ", ".join(f"{k}={v:.2f}" for k, v in sorted(readings.items()))
                or "no signals",
            )
        else:
            log.info(
                "pressure %.2f quiet for %.0fs: de-escalating to stage %d",
                pressure, self.config.quiet_s, new,
            )
        rec = trace.active()
        if rec is None:
            return
        rec.event(
            None, "brownout", "brownout.stage",
            attrs={"from": old, "to": new},
            pressure=round(pressure, 4),
        )
        metrics = None
        if self.metrics_fn is not None:
            try:
                metrics = self.metrics_fn()
            except Exception:  # noqa: BLE001 - evidence is best-effort
                metrics = None
        rec.dump(
            "brownout",
            metrics={
                "from": old,
                "to": new,
                "pressure": round(pressure, 4),
                "signals": {k: round(v, 4) for k, v in readings.items()},
                "metrics": metrics,
            },
        )

    # -- read surface --------------------------------------------------------
    def metrics(self) -> dict:
        """The ``brownout`` section of ``/metrics`` (prom renders ``shed``
        as a ``tier``-labeled table; residency/entered label by index)."""
        stage = self.stage()  # an idle ladder must decay on reads
        now = self._clock()
        with self._lock:
            residency = list(self._residency)
            residency[self._stage] += now - self._stage_since
            return {
                "stage": stage,
                "enter": self.config.enter,
                "exit": self.config.exit,
                "quiet_s": self.config.quiet_s,
                "transitions": int(self.transitions),
                "escalations": int(self.escalations),
                "deescalations": int(self.deescalations),
                "stage_entered": [int(n) for n in self.stage_entered],
                "stage_residency_s": [round(r, 3) for r in residency],
                "shed_total": int(sum(self.shed_counts.values())),
                "shed": {t: int(n) for t, n in self.shed_counts.items()},
                "shed_by_stage": [int(n) for n in self.shed_by_stage],
                "pressure": {
                    k: round(v, 4) for k, v in sorted(self._pressure.items())
                },
            }


def max_burn(mon) -> Optional[float]:
    """The ONE burn-pressure formula: the max per-objective burn rate
    from a monitor's :meth:`~obs.slo.SloMonitor.burn_snapshot` (None =
    no objectives).  Shared by :func:`engine_signals` and the replay
    harness's virtual nodes (``benchmarks/replay.py``) so the replayed
    ladder can never drift onto a different signal than production."""
    snap = mon.burn_snapshot()
    rates = [o["burn_rate"] for o in snap.values()]
    return max(rates) if rates else None


def engine_signals(engine, config: Optional[BrownoutConfig] = None) -> dict:
    """The production signal set over one engine, as duck-typed closures
    (this module never imports the serving layers back): SLO burn,
    resident queue fill, admission-wait p95, and rpc-floor drift."""
    cfg = config or BrownoutConfig()

    # Names kept globally unique on purpose: deadck's call-graph resolver
    # is name-based, and a nested function named `wait` would alias
    # threading.Condition.wait and poison the static lock graph with
    # false edges (the frontdoor cache's get/put lesson, round 17).
    def _burn_signal() -> Optional[float]:
        mon = slo.active()
        if mon is None:
            return None
        return max_burn(mon)

    def _queue_signal() -> Optional[float]:
        best = None
        for rf in engine._resident_flights():
            frac, _wait_p95 = rf.admission_pressure()
            best = frac if best is None else max(best, frac)
        return best

    def _wait_signal() -> Optional[float]:
        best = None
        for rf in engine._resident_flights():
            _frac, wait_p95 = rf.admission_pressure()
            best = wait_p95 if best is None else max(best, wait_p95)
        if best is None:
            return None
        return best / cfg.wait_budget_s

    def _floor_signal() -> Optional[float]:
        d = engine.rpc_floor.to_dict()
        if not d or not d.get("min") or d["min"] <= 0:
            return None
        # Normalized over the DRIFT: recent == lifetime min -> 0.0 (a
        # healthy link exerts no pressure, whatever the thresholds),
        # recent == floor_drift x min -> 1.0.  A raw-ratio form had a
        # structural 1/drift baseline that made any --brownout-exit at
        # or below it an un-recoverable shed state (review finding).
        ratio = d.get("recent", d["min"]) / d["min"]
        return max(0.0, ratio - 1.0) / (cfg.floor_drift - 1.0)

    return {
        "burn": _burn_signal,
        "queue": _queue_signal,
        "wait": _wait_signal,
        "floor": _floor_signal,
    }


def bind_engine(ctrl: BrownoutController, engine) -> None:
    """Wire a controller to one engine: production signals + the metrics
    snapshot for transition dumps (cli.py calls this post-boot)."""
    ctrl.set_signals(engine_signals(engine, ctrl.config))
    ctrl.metrics_fn = engine.metrics


# -- the process-wide seam ----------------------------------------------------
#
# Mirrors obs/slo.py / obs/trace.py / serving/faults.py: production with
# no controller installed pays one global read + one branch at the front
# door's routing decision and at engine.metrics.

_active: Optional[BrownoutController] = None


def install(controller: Optional[BrownoutController]) -> None:
    global _active
    _active = controller


def active() -> Optional[BrownoutController]:
    return _active


@contextlib.contextmanager
def installed(controller: BrownoutController):
    """Scope a controller over a block (tests): always uninstalls."""
    install(controller)
    try:
        yield controller
    finally:
        install(None)
