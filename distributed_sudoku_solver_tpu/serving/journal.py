"""Crash-safe job journal (WAL): the durable half of the job lifecycle.

A serving node that crashes between ``201 Created`` and resolution
silently loses every accepted job — the reference's fault tolerance
(heartbeats, ring repair, re-execution) only covers *remote worker*
death, never the origin process itself.  This module closes that gap
with a write-ahead log over the job lifecycle:

* ``accepted`` — appended by ``SolverEngine.submit`` BEFORE the client
  sees 201: uuid, board, config overrides, deadline, trace id.
* ``resolved`` — appended when a job reaches a REAL verdict (solved /
  unsat / exhausted / cancelled).  Infra errors ("engine stopped", retry
  budget) deliberately do NOT resolve the WAL entry: a crash or drain
  leaves them ``accepted``-only, which is exactly what
  :meth:`Journal.unresolved` replays through the normal submit seam on
  the next boot.  At-least-once is safe because verdicts are
  deterministic and cache fills / cluster dedupe are idempotent by uuid.

Format: segmented JSONL, one self-describing event per line
(``{"kind": "accepted"|"resolved", "uuid": ...}``), torn-tail-tolerant
like ``obs/ordertrace.py`` — a crash mid-write loses at most the final
line, and recovery skips any line that does not parse.  Segments rotate
at ``segment_bytes``; a resolve-driven **compaction** rewrites the live
(unresolved) set into a fresh segment and unlinks the old ones, so disk
stays bounded by the in-flight job count plus one segment of slack.

Durability is *batched off the hot path*, asymmetrically by record
kind.  ``accepted`` is written+flushed synchronously under the journal
lock (microseconds; submit runs on HTTP/client threads, never the
device loop) so a 201 implies the record is at least in the page cache
— the daemon batcher thread (``Journal._fsync_loop``) fsyncs every
``fsync_interval_s``, the declared durability lag.  ``resolved`` MAY
fire from the device loop (``_finish_job``), so it only appends to an
in-memory pending buffer the batcher drains to disk — no file I/O ever
runs on the device loop thread.  A crash that loses a buffered resolve
merely replays an already-resolved job, which is idempotent by design.

Failure doctrine (the ``serving/faults.py`` sites ``journal.append`` /
``journal.fsync``): a full disk or dead file handle **degrades the
journal to non-durable** — a loud counter, one ``[journal]`` log line
per degrade, and every subsequent append dropped — but NEVER fails the
accept path.  Serving without durability beats not serving.

Like faults/brownout, production runs with no journal installed and the
engine's hook sites pay one global read + one branch
(:func:`active` / :func:`install` / :func:`installed`).  Stdlib +
obs.lockdep/logctx + the serving.faults sites only — no jax (the
lint.yml fast lane proves it at import time).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
from typing import Callable, List, Optional

from distributed_sudoku_solver_tpu.obs import lockdep
from distributed_sudoku_solver_tpu.obs.logctx import ctx_log
from distributed_sudoku_solver_tpu.serving import faults

_LOG = logging.getLogger(__name__)

#: Segment filenames sort lexically AND numerically: wal-00000042.jsonl.
_SEG_PREFIX = "wal-"
_SEG_SUFFIX = ".jsonl"

#: The front-door L1 hot set persists beside the WAL under this name
#: (graceful drain writes it; the next boot restores the cache warm).
FRONTDOOR_SNAPSHOT = "frontdoor_l1.json"


def _seg_name(index: int) -> str:
    return f"{_SEG_PREFIX}{index:08d}{_SEG_SUFFIX}"


def _seg_index(name: str) -> Optional[int]:
    if not (name.startswith(_SEG_PREFIX) and name.endswith(_SEG_SUFFIX)):
        return None
    try:
        return int(name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)])
    except ValueError:
        return None


def read_segment(path: str) -> List[dict]:
    """All events in one segment, skipping any torn final line (the
    ``obs/ordertrace.py`` recovery contract)."""
    out: List[dict] = []
    if not os.path.exists(path):
        return out
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue  # torn tail line from a crash mid-write
            if isinstance(ev, dict):
                out.append(ev)
    return out


class Journal:
    """One node's segmented write-ahead log of the job lifecycle.

    ``path`` is a directory (created if missing); segments live inside
    it so a crash-restart harness can kill the process and hand the SAME
    directory to the reborn node.  The injected ``clock`` feeds event
    timestamps (relative, diagnostic-only — recovery never orders by
    them); the batcher thread paces on its own stop event, so no bare
    wall-clock call runs anywhere in the hot path.
    """

    def __init__(
        self,
        path: str,
        segment_bytes: int = 1 << 20,
        fsync_interval_s: float = 0.05,
        compact_min_resolved: int = 64,
        clock: Callable[[], float] = None,
    ):
        self.path = path
        self.segment_bytes = max(4096, int(segment_bytes))
        self.fsync_interval_s = max(0.001, float(fsync_interval_s))
        self.compact_min_resolved = max(1, int(compact_min_resolved))
        self._clock = clock if clock is not None else (lambda: 0.0)
        os.makedirs(path, exist_ok=True)
        self._lock = lockdep.named_lock("serving.journal")  # lockck: name(serving.journal)
        self._fh = None  # lockck: guard(_lock) — active segment handle
        self._seg_index = 0  # lockck: guard(_lock)
        self._seg_bytes = 0  # lockck: guard(_lock)
        self._live = {}  # lockck: guard(_lock) — uuid -> accepted event
        self._pending = []  # lockck: guard(_lock) — resolve events awaiting
        #   the batcher (device-loop-safe buffering, see module docstring)
        self._resolved_since_compact = 0  # lockck: guard(_lock)
        self._durable = True  # lockck: guard(_lock)
        self._dirty = False  # lockck: guard(_lock) — unfsynced writes
        # Counters (all guarded): the journal/lifecycle metrics family.
        self.accepted = 0  # lockck: guard(_lock)
        self.resolved = 0  # lockck: guard(_lock)
        self.recovered = 0  # lockck: guard(_lock)
        self.append_failures = 0  # lockck: guard(_lock)
        self.fsync_failures = 0  # lockck: guard(_lock)
        self.dropped_non_durable = 0  # lockck: guard(_lock)
        self.compactions = 0  # lockck: guard(_lock)
        self.segments_removed = 0  # lockck: guard(_lock)
        with self._lock:
            self._recover_state_locked()
        self._stop = threading.Event()
        self._batcher = threading.Thread(
            target=self._fsync_loop, name="journal-fsync", daemon=True
        )
        self._batcher.start()

    # -- boot-time scan -------------------------------------------------------
    def _segments(self) -> List[str]:
        """Segment file names in append order (oldest first)."""
        try:
            names = os.listdir(self.path)
        except OSError:
            return []
        segs = [(i, n) for n in names if (i := _seg_index(n)) is not None]
        return [n for _, n in sorted(segs)]

    def _recover_state_locked(self) -> None:
        """Replay existing segments into the live map and open the next
        segment for appends (the old tail may be torn — never reopened)."""
        resolved: set = set()
        order: List[str] = []
        live: dict = {}
        last = -1
        for name in self._segments():
            last = max(last, _seg_index(name) or 0)
            for ev in read_segment(os.path.join(self.path, name)):
                kind = ev.get("kind")
                uuid = ev.get("uuid")
                if not uuid:
                    continue
                if kind == "accepted":
                    if uuid not in live:
                        order.append(uuid)
                    live[uuid] = ev
                elif kind == "resolved":
                    resolved.add(uuid)
        for uuid in order:
            if uuid not in resolved and uuid in live:
                self._live[uuid] = live[uuid]
        self._seg_index = last + 1
        self._open_segment_locked()

    def _open_segment_locked(self) -> None:
        path = os.path.join(self.path, _seg_name(self._seg_index))
        self._fh = open(path, "a", encoding="utf-8")
        self._seg_bytes = self._fh.tell()

    # -- the hot path ---------------------------------------------------------
    def _append_locked(self, event: dict) -> None:
        """Write one event (write+flush only; fsync rides the batcher).
        Degrades to non-durable on the first failure — the accept path
        NEVER sees an exception out of here."""
        if not self._durable:
            self.dropped_non_durable += 1
            return
        try:
            faults.fire("journal.append", uuids=(event.get("uuid", ""),))
            line = json.dumps(event, sort_keys=True) + "\n"
            self._fh.write(line)
            self._fh.flush()
            self._seg_bytes += len(line)
            self._dirty = True
            if self._seg_bytes >= self.segment_bytes:
                self._rotate_locked()
        except Exception as e:  # SimulatedFault, OSError (disk full), ...
            self.append_failures += 1
            self._durable = False
            ctx_log(_LOG, "journal", self.path).error(
                "append failed — journal DEGRADED to non-durable "
                "(accepted jobs are no longer crash-safe): %r", e
            )

    def _rotate_locked(self) -> None:
        try:
            self._fsync_locked()
            self._fh.close()
        except Exception:
            pass
        self._seg_index += 1
        self._open_segment_locked()

    def _fsync_locked(self) -> None:
        if not self._dirty or not self._durable:
            return
        try:
            faults.fire("journal.fsync")
            os.fsync(self._fh.fileno())
            self._dirty = False
        except Exception as e:
            self.fsync_failures += 1
            self._durable = False
            ctx_log(_LOG, "journal", self.path).error(
                "fsync failed — journal DEGRADED to non-durable: %r", e
            )

    def _drain_pending_locked(self) -> None:
        """Write out buffered resolve events (batcher/sync/shutdown only —
        never a caller thread)."""
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        for ev in pending:
            self._append_locked(ev)

    def _fsync_loop(self) -> None:
        """The batcher daemon: drain buffered resolves, then one fsync per
        interval covers every append since the last — durability off the
        hot path."""
        while not self._stop.wait(self.fsync_interval_s):
            with self._lock:
                self._drain_pending_locked()
                self._fsync_locked()
                if self._resolved_since_compact >= self.compact_min_resolved:
                    self._compact_locked()

    def sync_now(self) -> None:
        """Deterministic flush: drain the pending buffer and fsync NOW
        (drain/shutdown/tests — callers that cannot wait out the batcher
        interval)."""
        with self._lock:
            self._drain_pending_locked()
            self._fsync_locked()

    # -- the lifecycle records ------------------------------------------------
    def record_accepted(
        self,
        uuid: str,
        grid=None,
        config: Optional[dict] = None,
        deadline_s: Optional[float] = None,
        trace: Optional[str] = None,
        roots=None,
        geom: Optional[str] = None,
    ) -> None:
        """The WAL promise: appended before the client's 201.  ``grid`` is
        any nested-list-able board; ``roots`` covers subtask (row-frontier)
        jobs; ``config`` carries only the caller's overrides dict."""
        ev = {"kind": "accepted", "uuid": str(uuid), "t": round(self._clock(), 6)}
        if grid is not None:
            ev["grid"] = [[int(v) for v in row] for row in grid]
        if roots is not None:
            ev["roots"] = [[int(v) for v in row] for row in roots]
        if geom is not None:
            ev["geom"] = geom
        if config:
            ev["config"] = config
        if deadline_s is not None:
            ev["deadline_s"] = float(deadline_s)
        if trace:
            ev["trace"] = trace
        with self._lock:
            if str(uuid) not in self._live:
                self._live[str(uuid)] = ev
            self.accepted += 1
            self._append_locked(ev)

    def record_resolved(self, uuid: str, verdict: Optional[dict] = None) -> None:
        """A REAL verdict reached: the accepted entry is discharged and
        becomes compaction fodder.  Unknown uuids are fine (replays,
        remote parts).  Buffered, not written: this site may run on the
        device loop thread (``_finish_job``), so the disk write rides the
        batcher — a crash-lost buffered resolve only replays an
        already-resolved job, which is idempotent."""
        ev = {"kind": "resolved", "uuid": str(uuid), "t": round(self._clock(), 6)}
        if verdict:
            ev.update({k: verdict[k] for k in sorted(verdict)})
        with self._lock:
            if self._live.pop(str(uuid), None) is not None:
                self._resolved_since_compact += 1
            self.resolved += 1
            self._pending.append(ev)

    def mark_recovered(self, n: int) -> None:
        """Bookkeeping for the boot-time replay (the engine counts what it
        actually re-submitted)."""
        with self._lock:
            self.recovered += int(n)

    # -- recovery / compaction ------------------------------------------------
    def unresolved(self) -> List[dict]:
        """The replay set: every ``accepted`` with no ``resolved``, in
        original accept order — deterministic, so two recover() runs over
        the same directory are byte-identical."""
        with self._lock:
            return [dict(ev) for ev in self._live.values()]

    def compact(self) -> None:
        """Rewrite the live set into a fresh segment and unlink the old
        ones (also the drain-time final flush)."""
        with self._lock:
            self._drain_pending_locked()
            self._compact_locked()

    def _compact_locked(self) -> None:
        if not self._durable:
            self._resolved_since_compact = 0
            return
        old = self._segments()
        try:
            self._fh.close()
        except Exception:
            pass
        self._seg_index += 1
        try:
            self._open_segment_locked()
            for ev in self._live.values():
                line = json.dumps(ev, sort_keys=True) + "\n"
                self._fh.write(line)
                self._seg_bytes += len(line)
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._dirty = False
            for name in old:
                try:
                    os.unlink(os.path.join(self.path, name))
                    self.segments_removed += 1
                except OSError:
                    pass
            self.compactions += 1
        except Exception as e:
            self._durable = False
            ctx_log(_LOG, "journal", self.path).error(
                "compaction failed — journal DEGRADED to non-durable: %r", e
            )
        self._resolved_since_compact = 0

    # -- lifecycle ------------------------------------------------------------
    @property
    def durable(self) -> bool:
        with self._lock:
            return self._durable

    def metrics(self) -> dict:
        with self._lock:
            return {
                "durable": self._durable,
                "accepted": self.accepted,
                "resolved": self.resolved,
                "recovered": self.recovered,
                "unresolved": len(self._live),
                "pending": len(self._pending),
                "append_failures": self.append_failures,
                "fsync_failures": self.fsync_failures,
                "dropped_non_durable": self.dropped_non_durable,
                "compactions": self.compactions,
                "segments_removed": self.segments_removed,
                "segment_index": self._seg_index,
                "fsync_interval_s": self.fsync_interval_s,
            }

    def shutdown(self) -> None:
        """Final fsync + handle close; the directory stays for the next
        boot (that is the whole point).  Named ``shutdown`` (not
        ``close``) so deadck's name-based call resolver never binds other
        modules' file-handle ``close()`` calls to the journal lock."""
        self._stop.set()
        self._batcher.join(timeout=5)
        with self._lock:
            self._drain_pending_locked()
            self._fsync_locked()
            try:
                self._fh.close()
            except Exception:
                pass

    # -- the front-door hot-set sidecar ---------------------------------------
    def save_frontdoor(self, entries: list) -> None:
        """Persist the L1 hot set beside the WAL (graceful drain).  Atomic
        rename so a crash mid-dump leaves the previous snapshot intact."""
        path = os.path.join(self.path, FRONTDOOR_SNAPSHOT)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(entries, fh)
            os.replace(tmp, path)
        except OSError as e:
            ctx_log(_LOG, "journal", self.path).error(
                "front-door snapshot failed (cache restarts cold): %r", e
            )

    def load_frontdoor(self) -> list:
        path = os.path.join(self.path, FRONTDOOR_SNAPSHOT)
        try:
            with open(path, encoding="utf-8") as fh:
                out = json.load(fh)
            return out if isinstance(out, list) else []
        except (OSError, ValueError):
            return []


# -- the process-wide seam ----------------------------------------------------

_active: Optional[Journal] = None


def install(journal: Optional[Journal]) -> None:
    global _active
    _active = journal


def active() -> Optional[Journal]:
    return _active


@contextlib.contextmanager
def installed(journal: Journal):
    """Scope a journal over a block (tests): always uninstalls + closes."""
    install(journal)
    try:
        yield journal
    finally:
        install(None)
        journal.shutdown()
