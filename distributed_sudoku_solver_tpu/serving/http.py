"""HTTP API: the reference's three endpoints, JSON-shape compatible.

Endpoint contracts copied behaviorally from ``/root/reference/
DHT_Node.py:540-614`` (SudokuHandler):

* ``POST /solve``  {"sudoku": [[...]]} -> 201 {"solution": [[...]], "duration": s}
* ``GET /stats``   -> 200 {"all": {"solved": N, "validations": M},
                           "nodes": [{"address": "h:p", "validations": V}, ...]}
* ``GET /network`` -> 200 {"<addr>": ["<predecessor>", "<successor>"], ...}

Since round 17 a **front door** (``serving/frontdoor``) sits on the
engine's submit seam ahead of every plain ``POST /solve``: a
symmetry-canonical result cache (any of the ~3x10^6 equivalents of a
published puzzle answers from one entry, O(µs) after the host-side
canonicalization), a propagation-only difficulty probe that answers
propagation-solved boards and proven-contradictory boards (422) without
any dispatch, and difficulty routing (easy boards race the native DFS
via ``serving/portfolio.race_native``; the hard tail runs
resident/static flights exactly as before).  CLI knobs:
``--no-frontdoor`` restores the direct path, ``--cache-entries`` bounds
the result cache.  **Bypasses**: ``count_all``, ``portfolio``, and
``POST /solve_batch`` requests never touch the cache — enumeration and
bulk are not memoizable by a single canonical entry, and portfolio
racers carry per-job configs, which skip the seam by construction.

Superset endpoints (absent from the reference):

* ``GET /metrics`` — latency percentiles, batch sizes, fault/breaker
  counters, mergeable phase histograms (``hist`` section, obs/hist.py —
  including the front door's per-route ``frontdoor_*_ms`` latency
  histograms), the ``frontdoor`` section (cache hits/misses/evictions,
  canonical-dup counts, probe verdicts, per-route dispatch counts), the
  live ``rpc_floor_ms`` estimate, device info.  Since round 8 the
  flight-loop wall is split into ``dispatch_wall_ms`` (host time
  enqueueing device work — async, near zero), ``sync_wall_ms`` (host
  time blocked in the one per-chunk status fetch), and
  ``event_wall_ms`` (the rarer verdict/finalize fetches on chunks where
  a job resolved), so the always-ahead loop's host/device overlap is
  observable; the resident section's ``chunk_wall_ms`` is likewise the
  per-round status sync wall, with its own ``dispatch_wall_ms`` /
  ``event_wall_ms``.  Query params: ``?format=prometheus`` renders text
  exposition (obs/prom.py, linted by obs/promck.py);
  ``?scope=cluster`` fans a METRICS_PULL over the ring and returns the
  per-node breakdown plus a merged rollup (obs/agg.py) whose histogram
  counts are the vector sum of the members' — partitioned members are
  flagged ``unreachable``, never hung on.
* ``POST /solve?latency=1`` — the interactive hard-tail route (round 19,
  ``serving/megastep.py``): eligible boards (no per-job config, engine
  not enumerating) are served as ONE donated device dispatch whose
  in-graph ``lax.while_loop`` runs the whole chunk schedule with early
  exit on solved/all-dead, so the handler thread syncs with the device
  once per request instead of once per chunk — the round-trip floor
  (``rpc_floor_ms``) is paid ~once, not ~N times.  The front door still
  answers cache hits/easy boards first; a megastep that cannot serve the
  board (unfit geometry, in-graph budget exhausted, device fault)
  degrades silently to the chunked paths below.  Engines started with
  ``--latency-mode`` serve every eligible ``/solve`` this way without
  the query flag.  Per-route wall rides ``frontdoor_megastep_ms`` in the
  ``hist`` section; the ``megastep`` metrics section carries flight /
  verdict / degrade counters.
* ``POST /solve`` with ``"count_all": true`` — enumerate EVERY solution
  to exhaustion and return the exact model count plus the first solution
  found (the reference's DFS stops at one solution and cannot express
  this).
* ``POST /solve`` with ``"portfolio": true`` — race the default strategy
  portfolio (``serving/portfolio.DEFAULT_PORTFOLIO``) on the board; the
  first verdict wins and cancels the losers (on a cluster node the racers
  spread across members).  Response adds ``"strategy"``: the winning
  config's branch rule.
* ``POST /solve_batch`` — bulk solving over HTTP, routed through the
  ``ops/bulk`` one-dispatch pipeline.  Body either
  ``{"boards": [[[...]], ...]}`` (nested int grids) or
  ``{"lines": ["53..7....", ...], "size": 9}`` (puzzle strings, base-36
  digits); optional ``"rules"`` ('basic'|'extended'|'subsets') and ``"chunk"``.
  Response mirrors the input form: ``solutions`` as grids or as strings
  (zeros line = unsolved), plus per-board ``solved``/``unsat`` and counts.
  Chunks run on the engine's device-owner thread between flight chunks
  (``SolverEngine.run_exclusive``), so concurrent `/solve` jobs interleave
  at chunk granularity instead of waiting for the whole bulk call.

Differences are deliberate upgrades, not behavior drift:

* the reference busy-polls a shared field at 10 ms and can cross-talk between
  concurrent requests (it nulls ``solution`` globally, ``:542,563``); here
  each request waits on its own job event.
* **backpressure**: on an engine with resident flights enabled
  (``serving/scheduler.py``), a ``POST /solve`` that arrives while the slot
  pool and its bounded admission queue are both full is answered ``429``
  with a ``Retry-After`` header (and ``retry_after_s`` in the body) instead
  of queueing unboundedly — the reference would accept and stall forever.
* unsat boards: the reference would search forever; we return 422 with a
  proven-unsat body (the frontier exhausts the space).
* cancellation (a timed-out ``/solve`` cancels its job) and deadlines act
  at chunk granularity, and since round 8 one chunk LATE: the engine's
  always-ahead loop enqueues chunk k+1 before reading chunk k's status,
  so a cancel frees the device within two chunk boundaries instead of
  one — the price of never letting the host stall the device
  (``serving/engine.py``).
* ``/stats`` aggregation uses the cluster runtime's snapshot instead of a
  blind 1 s sleep window (``:571``).

Observability endpoints (rounds 11-12, ``obs/``) — the full endpoint set
served here is: ``POST /solve``, ``POST /solve_batch``, ``POST
/profile``, ``GET /stats``, ``GET /network`` (``?scope=dht``), ``GET
/metrics`` (``?format=prometheus``, ``?scope=cluster``, ``&sample=N``),
``GET /trace[/uuid]`` (``?format=perfetto``), ``GET /status``, ``GET
/slo``:

* ``GET /trace`` — recent flight-recorder spans (JSON);
  ``?format=perfetto`` exports the ring as Chrome-trace JSON (open in
  Perfetto / chrome://tracing; validated by ``obs/traceck.py``).  404
  unless a recorder is installed (``--trace``).
* ``GET /trace/<uuid>`` — one job's stitched trace (spans from every
  cluster node that touched it); ``?analyze=1`` adds the critical-path
  decomposition (``obs/critpath.py``): per-phase walls (queue /
  dispatch / sync / event / wire / recovery / other) that sum to the
  job's end-to-end wall, plus attribution shares.  Unknown uuids and
  malformed ``?limit``/``?analyze`` values answer structured 4xx JSON.
* ``GET /metrics?format=prometheus`` — the nested metrics dict flattened
  into Prometheus text exposition (``obs/prom.py``); with
  ``scope=cluster`` the federated form: the merged rollup plus per-node
  reachability gauges.
* ``GET /metrics?scope=cluster`` — the cluster-scope merge (see above);
  ``&sample=N`` bounds the fan-out to a deterministic stride sample of N
  members (the O(1)-per-scrape mode for large rings).
* ``GET /network?scope=dht`` — the DHT plane (round 20,
  ``cluster/dht/``): gossip membership view (per-member state /
  incarnation / brownout flag), consistent-hash ring summary, and this
  node's cluster-cache shard counters; ``&owner=<digest-hex>`` resolves
  a canonical digest to its owner and replica set.  Structured 400 on an
  unknown scope or malformed digest, 404 when the DHT plane is off —
  the bare ``GET /network`` ring shape is API-pinned and unchanged.
* ``GET /status`` — compact health: member reachability/staleness,
  cluster latency quantiles from the merged histograms, the
  ``rpc_floor_ms`` estimate, and the SLO plane's state (``obs/agg.py``).
* ``GET /slo`` — the SLO monitor's objectives, burn rates, and breach
  counters (``obs/slo.py``), plus the live per-objective ``burn``
  snapshot (burn rate / headroom / windowed totals — the exact numbers
  the brownout controller acts on); 404 unless the node runs with
  ``--slo``.

Since round 18 a **brownout controller** (``serving/brownout.py``, on by
default with ``--slo``) closes the loop from the SLO plane back to
admission: sustained burn / queue pressure walks an edge-triggered stage
ladder that suppresses the easy tier's device shadow (stage 1), sheds
the easy tier with ``503 + Retry-After`` (stage 2), and admits only
cache/propagation answers (stage 3, ``429``).  Every shed response
carries a machine-readable body ``{stage, retry_after_s, shed_tier}``
and is recorded into the ``solve`` SLO stream as a NON-error.  The
controller's stage/shed counters ride ``/metrics`` (``brownout``
section), turn ``/status`` amber (``brownout_members``), and roll up
cluster-wide via ``obs/agg.py``.
* ``POST /profile`` ``{"secs": 1.0, "logdir": "..."} `` — a bounded
  ``jax.profiler`` device-trace window (``utils/profiling.py``); one
  window at a time (409 while open).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs, urlsplit

from distributed_sudoku_solver_tpu.obs import agg, slo, trace
from distributed_sudoku_solver_tpu.serving.brownout import BrownoutShed
from distributed_sudoku_solver_tpu.serving.engine import EngineDraining, SolverEngine
from distributed_sudoku_solver_tpu.serving.scheduler import EngineSaturated

# Opt-in access log (--access-log): routed through logging, not the
# stdlib handler's bare stderr write, so deployments aggregate it like
# every other record.
_ACCESS_LOG = logging.getLogger(__name__ + ".access")


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def _now(self) -> float:
        """The server's injected wall clock (``ApiServer(clock=...)``):
        request durations and solve deadlines are client-visible wall
        time, timed through the one seam so clockck can prove no handler
        grows a bare ``time.time()`` back."""
        return self.server.clock()

    # Route table kept flat on purpose: few endpoints, like the reference.
    def do_POST(self):  # noqa: N802 (stdlib casing)
        url = urlsplit(self.path)
        if url.path == "/solve_batch":
            return self._solve_batch()
        if url.path == "/profile":
            return self._profile()
        if url.path == "/admin/drain":
            return self._admin_drain()
        if url.path != "/solve":
            return self._send(404, {"error": "not found"})
        # ``POST /solve?latency=1`` — the interactive hard-tail route
        # (serving/megastep.py): the whole advance loop fuses into ONE
        # donated device dispatch with in-graph early exit, resolving on
        # this handler thread with a single host sync.  Opt-in per
        # request; an engine started with ``latency_mode`` serves every
        # eligible /solve this way without the flag.
        latency = parse_qs(url.query).get("latency", ["0"])[0] not in (
            "", "0", "false",
        )
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length))
            grid = payload["sudoku"]
        except (ValueError, KeyError, TypeError):
            return self._send(400, {"error": "body must be JSON {'sudoku': [[...]]}"})
        node = self.server.solver_node
        import numpy as np

        # Validate the grid up front: the portfolio path submits straight to
        # the engine, which must never see a malformed body.
        try:
            g = np.asarray(grid)  # ragged lists raise ValueError here
        except ValueError as e:
            return self._send(400, {"error": f"bad sudoku grid: {e}"})
        if g.ndim != 2 or g.shape[0] != g.shape[1] or g.shape[0] < 1:
            return self._send(
                400, {"error": f"sudoku must be a square grid, got shape {g.shape}"}
            )
        # Optional client-supplied idempotency key (ISSUE 20): a resubmit
        # carrying the uuid a previous attempt returned (e.g. from a 504
        # body) answers with the existing in-flight/resolved job instead
        # of double-solving — and double-counting — it.
        client_uuid = payload.get("uuid")
        if client_uuid is not None and (
            not isinstance(client_uuid, str)
            or not client_uuid
            or len(client_uuid) > 120
        ):
            return self._send(
                400, {"error": "uuid must be a non-empty string (<=120 chars)"}
            )
        start = self._now()
        rec = trace.active()
        t_http = rec.now() if rec is not None else 0.0
        timeout = self.server.solve_timeout_s
        # Optional per-request branch-ordering override (ISSUE 19): the
        # payload may carry ``"branch": "head:cw-slack"`` etc.; it is
        # validated HERE (400 on an unknown rule, before anything is
        # enqueued) and rides the job as a per-job SolverConfig override —
        # on a cluster node it travels with the TASK.
        config = None
        branch = payload.get("branch")
        if branch is not None:
            import dataclasses

            from distributed_sudoku_solver_tpu.ops import ordering

            try:
                ordering.validate_branch(branch)
            except (TypeError, ValueError) as e:
                return self._send(400, {"error": str(e)})
            if payload.get("portfolio"):
                # The portfolio races its OWN per-racer configs; a single
                # branch override is ambiguous there.  Reject loudly, the
                # same contract as count_all+portfolio below.
                return self._send(
                    400, {"error": "branch and portfolio are mutually exclusive"}
                )
            engine = getattr(node, "engine", None)
            if engine is None:
                return self._send(500, {"error": "node has no engine"})
            config = dataclasses.replace(engine.config, branch=branch)
        if payload.get("count_all"):
            if payload.get("portfolio"):
                # Racing heterogeneous configs makes sense for find-one (first
                # verdict wins) but not for enumeration: every racer would run
                # the identical exhaustive count.  Reject loudly rather than
                # silently ignoring one of the two flags (ADVICE r3).
                return self._send(
                    400, {"error": "count_all and portfolio are mutually exclusive"}
                )
            return self._solve_count_all(node, g, start, timeout, config=config)
        strategy = None
        if payload.get("portfolio"):
            try:
                res = self._race(node, grid, timeout)
            except ValueError as e:
                return self._send(400, {"error": str(e)})
            if res.winner is None:
                if res.timed_out:
                    self._record_solve(node, self._now() - start, 504)
                    return self._send(504, {"error": "portfolio race timed out"})
                # Every racer resolved without a verdict: a permanent
                # budget/overflow failure, not a retryable timeout.
                err = next((j.error for j in res.jobs if j.error), None)
                self._record_solve(node, self._now() - start, 500)
                return self._send(500, {"error": err or "search budget exhausted"})
            job = res.winner
            strategy = res.strategy
        else:
            try:
                job = (
                    node.submit(
                        grid, config=config, latency=True,
                        job_uuid=client_uuid,
                    )
                    if latency
                    else node.submit(grid, config=config, job_uuid=client_uuid)
                )
            except ValueError as e:
                return self._send(400, {"error": str(e)})
            except EngineDraining as e:
                # Durable lifecycle (serving/engine.py drain ladder): the
                # node is draining/drained, admission is closed.  503 with
                # a machine-readable body — clients retry against another
                # member after Retry-After; recorded shed (an honest
                # refusal must not burn the error budget the drain is
                # protecting).
                self._record_solve(
                    node, self._now() - start, 503, shed=True
                )
                return self._send(
                    503,
                    {
                        "error": "draining",
                        "state": e.state,
                        "retry_after_s": round(e.retry_after_s, 3),
                    },
                    headers={
                        "Retry-After": str(max(1, int(-(-e.retry_after_s // 1))))
                    },
                )
            except BrownoutShed as e:
                # Brownout load shedding (serving/brownout.py): the stage
                # ladder refused this request's tier at the front door.
                # The body is machine-readable ({stage, retry_after_s,
                # shed_tier}), and the response is recorded into the
                # `solve` SLO stream as a NON-error — shedding protects
                # the error-rate objective, it must not burn it.
                self._trace_http(rec, t_http, e.uuid or "", e.status)
                self._record_solve(
                    node, self._now() - start, e.status, shed=True
                )
                return self._send(
                    e.status,
                    {
                        "error": str(e),
                        "stage": e.stage,
                        "retry_after_s": round(e.retry_after_s, 3),
                        "shed_tier": e.shed_tier,
                    },
                    headers={
                        "Retry-After": str(max(1, int(-(-e.retry_after_s // 1))))
                    },
                )
            except EngineSaturated as e:
                # Resident-flight admission control (serving/scheduler.py):
                # slot pool and bounded queue are full, so the node sheds
                # load loudly instead of queueing unboundedly.  Retry-After
                # is the scheduler's backlog-paced estimate.  Recorded into
                # the solve stream (429 < 500, so never an error): the SLO
                # plane should see refused requests, not pretend the wall
                # vanished.
                self._record_solve(
                    node, self._now() - start, 429, shed=True
                )
                return self._send(
                    429,
                    {
                        "error": "server saturated",
                        "retry_after_s": round(e.retry_after_s, 3),
                    },
                    headers={
                        "Retry-After": str(max(1, int(-(-e.retry_after_s // 1))))
                    },
                )
            if not job.wait(timeout):
                node.cancel(job.uuid)
                self._trace_http(rec, t_http, job.uuid, 504)
                self._record_solve(node, self._now() - start, 504)
                return self._send(504, {"error": "solve timed out", "uuid": job.uuid})
        duration = self._now() - start
        extra = {"strategy": strategy} if strategy is not None else {}
        if job.solved:
            status = 201
            body = {"solution": job.solution.tolist(), "duration": duration,
                    **extra}
        elif job.unsat:
            status = 422
            body = {"error": "puzzle is unsatisfiable", "duration": duration,
                    **extra}
        else:
            status = 500
            body = {
                "error": job.error or "search budget exhausted",
                "duration": duration,
            }
        self._trace_http(rec, t_http, job.uuid, status)
        self._record_solve(node, duration, status)
        return self._send(status, body)

    @staticmethod
    def _trace_http(rec, t0: float, job_uuid: str, status: int) -> None:
        """The trace's outermost span: HTTP accept -> response for one job
        (obs/trace.py; a no-op unless a recorder is installed)."""
        if rec is not None:
            rec.record(job_uuid, "http.solve", "http", t0, status=status)

    @staticmethod
    def _record_solve(node, duration: float, status: int,
                      shed: bool = False) -> None:
        """The http-solve wall (obs/hist.py ``solve_ms`` + the SLO
        ``solve`` stream): one sample per completed ``/solve`` whatever
        the status and whichever branch produced it (plain, portfolio,
        count_all) — the cluster-scope p95 over this phase is the
        serving-tier SLI the ``--slo`` grammar names
        (``solve_p95_ms<=...``).  5xx statuses — including a 504
        timeout, where the job merely got cancelled and carries no
        ``job.error`` — count as errors for ``error_rate``: the SLO
        plane watches what the CLIENT saw, not what the engine felt.

        ``shed=True`` marks deliberate load shedding (a brownout 503 or a
        saturation 429): the response counts toward the error-rate
        objective's totals but NEVER as an error — shedding exists to
        protect that objective, and a 503 storm of honest refusals
        burning the budget it was defending would make the controller
        self-sustaining — and is excluded from latency objectives
        outright, so a flood of ~1 ms refusals cannot dilute the latency
        window and flap the ladder (both pinned in
        tests/test_brownout.py).  The raw ``solve_ms`` histogram still
        records every response: it documents what clients experienced,
        shed answers included."""
        eng = getattr(node, "engine", None)
        if eng is not None:
            eng.hist["solve_ms"].record(duration)
        mon = slo.active()
        if mon is not None:
            mon.observe(
                duration, error=status >= 500 and not shed, stream="solve",
                shed=shed,
            )

    def _solve_count_all(self, node, grid, start, timeout, config=None):
        """``POST /solve`` with ``"count_all": true``: enumerate EVERY
        solution (``SolverConfig.count_all``); 200 with the exact model
        count, the first solution found (null if none), and whether the
        enumeration ran to completion.  A capability the reference cannot
        express at all — its search stops at the first solution
        (``/root/reference/DHT_Node.py:474-538``).

        Enumeration runs on the LOCAL engine only, even on a cluster node:
        shed NEEDWORK parts would be counted by the peer and aggregated
        nowhere, so enumeration flights never shed (``serving/engine.py
        _do_shed``) and the count needs no cross-node merge.  The response
        carries ``"scope": "local"`` to surface that (ADVICE r3)."""
        import dataclasses

        engine = getattr(node, "engine", None)
        if engine is None:
            return self._send(500, {"error": "node has no engine"})
        try:
            # Honor the engine's configured step_impl: the fused kernel
            # enumerates natively since round 4 (count-mode kernel,
            # ops/pallas_step.py), so no silent downgrade either way.
            job = engine.submit(
                grid,
                config=dataclasses.replace(
                    config if config is not None else engine.config, count_all=True
                ),
            )
        except ValueError as e:
            return self._send(400, {"error": str(e)})
        if not job.wait(timeout):
            engine.cancel(job.uuid)
            self._record_solve(node, self._now() - start, 504)
            return self._send(504, {"error": "enumeration timed out"})
        if job.error:
            self._record_solve(node, self._now() - start, 500)
            return self._send(500, {"error": job.error})
        body = {
            "count": int(job.sol_count),
            # unsat == search space exhausted == the count is complete
            # (unless a stack overflow dropped subtrees: then lower bound).
            "complete": bool(job.unsat and not job.cancelled),
            "solution": job.solution.tolist() if job.sol_count > 0 else None,
            "duration": self._now() - start,
            "scope": "local",  # enumeration never distributes (see docstring)
        }
        self._record_solve(node, body["duration"], 200)
        return self._send(200, body)

    @staticmethod
    def _race(node, grid, timeout):
        """Race the default portfolio (strategy/timed_out are filled in by
        the race itself, ``serving/portfolio.py``)."""
        from distributed_sudoku_solver_tpu.serving.portfolio import (
            DEFAULT_PORTFOLIO,
            race,
        )

        if hasattr(node, "race"):  # cluster node: racers spread over members
            return node.race(grid, DEFAULT_PORTFOLIO, timeout=timeout)
        return race(node.engine, grid, DEFAULT_PORTFOLIO, timeout=timeout)

    def _solve_batch(self):
        import time  # the waived backoff sleep below; clock reads go through _now()

        import numpy as np

        from distributed_sudoku_solver_tpu.models.geometry import geometry_for_size
        from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig, solve_bulk
        from distributed_sudoku_solver_tpu.utils.puzzles import parse_line, to_line

        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length))
            as_lines = "lines" in payload
            if as_lines:
                size = int(payload.get("size", 9))
                grids = np.stack(
                    [parse_line(s, size) for s in payload["lines"]]
                ).astype(np.int32)
            else:
                grids = np.asarray(payload["boards"], dtype=np.int32)
            if grids.ndim != 3 or grids.shape[1] != grids.shape[2]:
                raise ValueError(f"boards must be [B, n, n], got {grids.shape}")
            n = grids.shape[1]
            geom = geometry_for_size(n)
            # Bound the device occupancy of one exclusive slice: chunk width
            # scales down with board area and the first pass gets a small
            # step cap, so a single run_exclusive holds the device for
            # seconds, not minutes — interactive /solve flights interleave.
            default_chunk = max(64, (8192 * 81) // (n * n))
            cfg = BulkConfig(
                rules=payload.get("rules", "extended"),
                chunk=max(1, min(int(payload.get("chunk", default_chunk)), 32768)),
                first_pass_steps=512,
                rungs=(),  # stragglers go through the engine below
            )
        except (ValueError, KeyError, TypeError) as e:
            return self._send(400, {"error": f"bad solve_batch body: {e}"})

        engine = getattr(self.server.solver_node, "engine", None)
        if engine is None:
            return self._send(500, {"error": "node has no engine"})
        start = self._now()
        deadline = start + self.server.solve_timeout_s
        solved = np.zeros(len(grids), bool)
        unsat = np.zeros(len(grids), bool)
        solutions = np.zeros_like(grids)
        # Mass pass: one run_exclusive per chunk (rung-free, step-capped).
        # A chunk that fails with a TRANSIENT error (preemption, OOM,
        # runtime hiccup — serving/faults.py taxonomy) is re-dispatched
        # under the engine's recovery policy before the endpoint gives up;
        # permanent errors (and exhausted budgets) still answer 500.
        from distributed_sudoku_solver_tpu.serving import faults

        for lo in range(0, len(grids), cfg.chunk):
            sl = grids[lo : lo + cfg.chunk]
            attempts = 0
            while True:
                try:
                    res = engine.run_exclusive(
                        lambda sl=sl: solve_bulk(sl, geom, cfg),
                        timeout=max(1.0, deadline - self._now()),
                    )
                    break
                except RuntimeError as e:
                    if (
                        faults.classify_message(str(e)) == faults.TRANSIENT
                        and attempts < engine.recovery.max_retries
                        and self._now() < deadline
                    ):
                        attempts += 1
                        with engine._lock:  # handler threads race this bump
                            engine.fault_bulk_retries += 1
                        # Short exponential pause so one brief device
                        # outage doesn't burn the whole budget back-to-back
                        # (the engine path gets this implicitly via its
                        # requeue latency); capped by the request deadline.
                        # clockck: allow(bulk retry backoff on a real HTTP worker thread — socket lane only, deadline-capped)
                        time.sleep(
                            min(0.05 * 2**attempts, 1.0,
                                max(0.0, deadline - self._now()))
                        )
                        continue
                    return self._send(500, {"error": str(e), "done": int(lo)})
            if res is None:
                return self._send(
                    504, {"error": "bulk chunk timed out", "done": int(lo)}
                )
            solved[lo : lo + len(sl)] = res.solved
            unsat[lo : lo + len(sl)] = res.unsat
            solutions[lo : lo + len(sl)] = res.solution
        # Stragglers (step cap hit) become ordinary engine jobs: they share
        # the chunked flight loop fairly with interactive traffic and stay
        # individually cancellable, instead of monopolizing the device
        # inside one long exclusive section.  frontdoor=False: solve_batch
        # is documented to bypass the result cache wholesale (bulk is not
        # memoizable by a single canonical entry), so its stragglers must
        # not be the one path that quietly populates it.
        pending = [
            (int(i), engine.submit(grids[i], geom=geom, frontdoor=False))
            for i in np.flatnonzero(~solved & ~unsat)
        ]
        for i, job in pending:
            if not job.wait(max(1.0, deadline - self._now())):
                # All stragglers were submitted up front: cancel every one
                # still pending, not just the first timed-out job, or the
                # rest keep burning the engine with no waiter.
                for _, other in pending:
                    if not other.done.is_set():
                        engine.cancel(other.uuid)
                return self._send(
                    504, {"error": "straggler solve timed out", "done": int(i)}
                )
            solved[i] = job.solved
            unsat[i] = job.unsat
            if job.solved:
                solutions[i] = job.solution
        body = {
            "count": int(len(grids)),
            "solved": int(solved.sum()),
            "unsat": int(unsat.sum()),
            "solved_mask": solved.tolist(),
            "unsat_mask": unsat.tolist(),
            "duration": self._now() - start,
        }
        if as_lines:
            body["solutions"] = [to_line(s) for s in solutions]
        else:
            body["solutions"] = solutions.tolist()
        return self._send(200, body)

    def _profile(self):
        """``POST /profile``: a bounded jax.profiler device-trace window —
        ``utils/profiling.device_trace`` finally wired to serving.  One
        window at a time; the stop is a daemon timer, so a forgotten
        client can never leave a node tracing unboundedly."""
        import tempfile

        from distributed_sudoku_solver_tpu.utils import profiling

        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length)) if length else {}
            secs = float(payload.get("secs", 1.0))
            if not (0.05 <= secs <= 300.0):
                raise ValueError(f"secs must be in [0.05, 300], got {secs}")
            logdir = str(
                payload.get("logdir")
                or tempfile.mkdtemp(prefix="dsst-profile-")
            )
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            return self._send(400, {"error": f"bad profile body: {e}"})
        try:
            started = profiling.start_profile_window(logdir, secs)
        except Exception as e:  # noqa: BLE001 - profiler state is global
            # (e.g. a --profile-dir lifetime trace already running)
            return self._send(409, {"error": f"profiler unavailable: {e}"})
        if not started:
            return self._send(409, {"error": "a profile window is already open"})
        return self._send(200, {"logdir": logdir, "secs": secs})

    def _admin_drain(self):
        """``POST /admin/drain`` — walk the durable-lifecycle ladder
        (ISSUE 20): close admission, let in-flight work finish (bounded
        by ``timeout_s``, default 30), hand unstarted jobs to a healthy
        peer or journal them, persist the front-door hot set, fsync the
        WAL.  Runs synchronously on this handler thread (drain is bounded
        by construction) and answers 200 with the engine's machine-
        readable summary: ``{state, handoffs, journaled, finished,
        leftover}``.  A second call while draining answers the current
        state with ``already_draining`` — the ladder is idempotent."""
        node = self.server.solver_node
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length)) if length else {}
            timeout_s = float(payload.get("timeout_s", 30.0))
            if not (0.0 <= timeout_s <= 600.0):
                raise ValueError(f"timeout_s must be in [0, 600], got {timeout_s}")
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            return self._send(400, {"error": f"bad drain body: {e}"})
        drain = getattr(node, "drain", None)
        if drain is None:
            return self._send(500, {"error": "node cannot drain"})
        return self._send(200, drain(timeout=timeout_s))

    def do_GET(self):  # noqa: N802
        node = self.server.solver_node
        url = urlsplit(self.path)
        path, query = url.path, parse_qs(url.query)
        if path == "/stats":
            return self._send(200, node.stats_view())
        if path == "/network":
            return self._network_view(node, query)
        if path == "/metrics":
            # Superset endpoint (not in the reference): per-node latency
            # percentiles, batch sizes, device info — SURVEY.md §5.5.
            # ?format=prometheus flattens the nested dict into text
            # exposition lines (obs/prom.py) for direct scraping;
            # ?scope=cluster fans a METRICS_PULL over the ring and merges
            # (obs/agg.py) — partitioned members are flagged, never hung on.
            if query.get("scope", [""])[0] == "cluster":
                return self._cluster_metrics(node, query)
            if query.get("format", [""])[0] == "prometheus":
                from distributed_sudoku_solver_tpu.obs import prom

                return self._send_text(200, prom.render(self._metrics(node)))
            return self._send(200, self._metrics(node))
        if path == "/status":
            # Compact SLO/health plane: member reachability, cluster
            # quantiles from the merged histograms, floor, SLO state.
            return self._send(200, agg.status_from(self._cluster_view(node)))
        if path == "/slo":
            mon = slo.active()
            if mon is None:
                return self._send(
                    404,
                    {"error": "no SLO configured (start the node with --slo)"},
                )
            # `burn` is the public burn_snapshot read API (ISSUE 15): the
            # per-objective live burn/headroom the brownout controller
            # consumes — surfaced so operators see the same numbers the
            # admission policy acts on.
            return self._send(
                200, {**mon.state(), "burn": mon.burn_snapshot()}
            )
        if path == "/trace" or path.startswith("/trace/"):
            return self._trace_view(path, query)
        return self._send(404, {"error": "not found"})

    def _network_view(self, node, query: dict):
        """``GET /network`` — the pinned ring-view shape; ``?scope=dht``
        adds the gossip membership view, consistent-hash ring summary,
        and this node's cluster-cache shard counters (``cluster/dht``),
        with ``&owner=<digest-hex>`` resolving a canonical digest to its
        owner and replica set.  Hardened like ``/trace``: an unknown
        scope or malformed owner digest is a structured 400, a node
        running without the DHT plane answers a structured 404 — never a
        500 (API-pinned)."""
        scope = query.get("scope", [""])[0]
        if scope in ("", "ring"):
            return self._send(200, node.network_view())
        if scope != "dht":
            return self._send(
                400,
                {"error": f"scope must be 'dht', got {scope!r}"},
            )
        if getattr(node, "gossip", None) is None:
            return self._send(
                404,
                {
                    "error": "DHT disabled (single node, or started with"
                    " dht=False)"
                },
            )
        owner_of = query.get("owner", [""])[0] or None
        if owner_of is not None:
            try:
                int(owner_of, 16)
            except ValueError:
                return self._send(
                    400,
                    {
                        "error": "owner must be a hex canonical digest,"
                        f" got {owner_of!r}"
                    },
                )
        return self._send(200, node.dht_view(owner_of))

    @staticmethod
    def _cluster_view(node, sample: int = 0) -> dict:
        """The node's cluster-scope metrics view (single-node shape for a
        bare engine that predates the cluster surface)."""
        fn = getattr(node, "cluster_metrics_view", None)
        if fn is not None:
            return fn(sample=sample) if sample else fn()
        engine = getattr(node, "engine", None)
        m = engine.metrics() if engine is not None else {}
        addr = getattr(node, "address", "local:0")
        return {
            "scope": "cluster",
            "address": addr,
            "coordinator": addr,
            "view": [0, 0],
            "nodes": {
                addr: {
                    "unreachable": False,
                    "stale": False,
                    "view": [0, 0],
                    "metrics": m,
                }
            },
            "rollup": {**agg.rollup([m]), "nodes": 1, "unreachable": 0},
        }

    def _cluster_metrics(self, node, query: dict):
        """``GET /metrics?scope=cluster``: the per-node breakdown + merged
        rollup; ``&format=prometheus`` renders the federated form (the
        rollup's series plus per-node reachability gauges — per-node full
        bodies stay JSON-only, each member already serves its own
        exposition).  ``&sample=N`` pulls a deterministic stride sample
        of N members instead of all of them — the O(1)-per-scrape mode
        for 500-member rings (the rollup then carries
        ``members_total``/``members_sampled``)."""
        sample = 0
        if "sample" in query:
            try:
                sample = int(query["sample"][0])
            except ValueError:
                return self._send(
                    400, {"error": "sample must be an integer"}
                )
            if sample <= 0:
                return self._send(
                    400,
                    {"error": f"sample must be positive, got {sample}"},
                )
        cm = self._cluster_view(node, sample)
        if query.get("format", [""])[0] == "prometheus":
            from distributed_sudoku_solver_tpu.obs import prom

            doc = {
                "cluster_rollup": cm.get("rollup", {}),
                "cluster_nodes": {
                    addr: {
                        "unreachable": n.get("unreachable", False),
                        "stale": n.get("stale", False),
                    }
                    for addr, n in cm.get("nodes", {}).items()
                },
            }
            return self._send_text(200, prom.render(doc))
        return self._send(200, cm)

    def _trace_view(self, path: str, query: dict):
        """``GET /trace`` (recent ring; ``?format=perfetto`` for Chrome-
        trace JSON) and ``GET /trace/<uuid>`` (one job's stitched spans;
        ``?analyze=1`` adds the critical-path decomposition,
        ``obs/critpath.py``).  Hardened: an unknown uuid is a structured
        404 and a malformed ``?limit``/``?analyze`` value is a structured
        400 — never a 500 (API-pinned)."""
        rec = trace.active()
        if rec is None:
            return self._send(
                404, {"error": "tracing disabled (start the node with --trace)"}
            )
        raw_analyze = query.get("analyze", ["0"])[0].lower()
        if raw_analyze in ("1", "true", "yes"):
            analyze = True
        elif raw_analyze in ("0", "false", "no", ""):
            analyze = False
        else:
            return self._send(
                400,
                {"error": f"analyze must be 0 or 1, got {raw_analyze!r}"},
            )
        limit = None
        if "limit" in query:
            try:
                limit = int(query["limit"][0])
            except ValueError:
                return self._send(400, {"error": "limit must be an integer"})
            if limit <= 0:
                return self._send(
                    400, {"error": f"limit must be positive, got {limit}"}
                )
        if path.startswith("/trace/"):
            uuid = path[len("/trace/") :]
            spans = rec.spans(uuid)
            if not spans:
                return self._send(
                    404, {"error": "unknown trace uuid", "uuid": uuid}
                )
            body = {"uuid": uuid, "count": len(spans), "spans": spans}
            if analyze:
                from distributed_sudoku_solver_tpu.obs import critpath

                # Decompose over the FULL stitched trace, then apply the
                # limit to the echoed spans only — a truncated window
                # would silently break the phases-sum-to-wall contract.
                body["analysis"] = critpath.decompose(spans)
                body["analysis_tolerance"] = critpath.SUM_TOLERANCE
            if limit is not None:
                body["spans"] = body["spans"][-limit:]
            return self._send(200, body)
        if analyze:
            return self._send(
                400, {"error": "analyze requires a job: GET /trace/<uuid>?analyze=1"}
            )
        if query.get("format", [""])[0] == "perfetto":
            return self._send(200, rec.perfetto())
        spans = rec.spans(limit=limit if limit is not None else 1000)
        return self._send(200, {"count": len(spans), "spans": spans})

    @staticmethod
    def _metrics(node) -> dict:
        if hasattr(node, "metrics_view"):  # cluster node: + runtime counters
            body = node.metrics_view()
        else:
            engine = getattr(node, "engine", None)
            body = engine.metrics() if engine is not None else {}
        try:
            import jax

            dev = jax.devices()[0]
            body["device"] = {"kind": dev.device_kind, "platform": dev.platform}
        except Exception:  # pragma: no cover - no backend
            pass
        return body

    def _send(self, code: int, body: dict, headers: Optional[dict] = None) -> None:
        data = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _send_text(self, code: int, text: str) -> None:
        data = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, fmt, *args):
        # Access logging is OPT-IN (--access-log) and routed through
        # `logging` — the old `verbose` gate wrote to bare stderr via the
        # stdlib handler and was silently swallowed everywhere else.
        if getattr(self.server, "access_log", False):
            _ACCESS_LOG.info("%s %s", self.address_string(), fmt % args)


class ApiServer:
    """ThreadingHTTPServer wrapper bound to a solver node (or bare engine).

    ``access_log=True`` emits one INFO record per request on the
    ``...serving.http.access`` logger (``--access-log`` on the CLI);
    ``verbose`` is the deprecated alias it replaces.
    """

    def __init__(
        self,
        solver_node,
        host: str = "0.0.0.0",
        port: int = 8000,
        solve_timeout_s: float = 300.0,
        verbose: bool = False,
        access_log: bool = False,
        clock: Callable[[], float] = time.time,
    ):
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.httpd.solver_node = solver_node
        self.httpd.solve_timeout_s = solve_timeout_s
        self.httpd.access_log = access_log or verbose
        # Wall time on purpose (durations are client-visible); injectable
        # so the handlers stay clockck-clean — see _Handler._now.
        self.httpd.clock = clock
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> "ApiServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True, name="http-server"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(5)


class StandaloneNode:
    """Single-process node: engine + API, no cluster peers (v1 of serving).

    Presents the same surface the cluster node will: submit/cancel,
    stats_view, network_view.
    """

    def __init__(self, engine: Optional[SolverEngine] = None, address: str = "local:0"):
        if engine is None:
            # The front door is the default routing layer for a serving
            # node (ISSUE 14); callers supplying their own engine choose
            # their own frontdoor= policy.
            from distributed_sudoku_solver_tpu.serving.frontdoor.router import (
                FrontDoorConfig,
            )

            engine = SolverEngine(frontdoor=FrontDoorConfig()).start()
        self.engine = engine
        self.address = address

    def submit(self, grid, config=None, latency=None, job_uuid=None):
        import numpy as np

        g = np.asarray(grid, dtype=np.int32)
        if g.ndim != 2 or g.shape[0] != g.shape[1]:
            raise ValueError(f"grid must be square, got {g.shape}")
        # The serving node is where backpressure belongs: a saturated
        # resident admission queue raises EngineSaturated here and the
        # HTTP layer answers 429 + Retry-After.  Library callers using the
        # engine directly keep the quiet static-flight fallback.
        # ``job_uuid`` is the client idempotency key (ISSUE 20): the
        # engine's resubmit registry dedupes it.
        return self.engine.submit(
            g, saturation="reject", config=config, latency=latency,
            job_uuid=job_uuid,
        )

    def cancel(self, job_uuid: str) -> None:
        self.engine.cancel(job_uuid)

    def drain(self, timeout: float = 30.0) -> dict:
        """``POST /admin/drain`` on a standalone node: no peers, so every
        unstarted job journals for restart (handoff=None)."""
        return self.engine.drain(timeout=timeout)

    def recover(self) -> int:
        return self.engine.recover()

    def stats_view(self) -> dict:
        s = self.engine.stats()
        return {
            "all": {"solved": s["solved"], "validations": s["validations"]},
            "nodes": [{"address": self.address, "validations": s["validations"]}],
        }

    def network_view(self) -> dict:
        return {self.address: [self.address, self.address]}
