"""Host-side job engine: an async queue feeding chunked, batched device solves.

Replaces the reference's per-node `task_queue` + busy-poll `/solve` plumbing
(``/root/reference/DHT_Node.py:35,225-250,553-554``) with a single-owner
device loop (SURVEY.md §5.2: device state has exactly one driving thread, so
there is none of the reference's unlocked cross-thread mutation):

* **submit** enqueues a uuid-tagged job and returns immediately; callers wait
  on the job's event (no 10 ms busy-poll — a real `threading.Event`).
* **the device loop** drains the queue into *flights*: a flight is one
  geometry-grouped batch of jobs sharing one frontier.  Each flight advances
  in bounded-step chunks (``advance_frontier_status`` /
  ``advance_frontier_fused_status`` — buffer-donated, in-graph step limits),
  and multiple flights round-robin — a hard batch no longer
  head-of-line-blocks later jobs, the way the reference's single-threaded
  solve loop blocked its whole node until the next message poll.  Since
  round 8 the loop is **always one dispatch ahead**: chunk k+1 is enqueued
  before chunk k's packed status word — the chunk's ONE host sync — is
  consumed, so host scheduling overlaps device compute; cancels, deadlines,
  and resolution consequently react one chunk late (bounded by
  ``chunk_steps``, see ``_advance_flight``).
* **cancel** lands *mid-flight*: between chunks the loop purges cancelled
  jobs' lanes in-graph (``ops/frontier.purge_jobs``), freeing the device
  within one chunk — the chunked heir of the reference's once-per-recursion
  cancellation poll (``/root/reference/DHT_Node.py:481-488``).  In-graph
  cancellation *between* concurrent jobs of one flight is the frontier's own
  solved-mask purge (``ops/frontier.py``).
* **snapshot / shed**: between chunks the loop also services control
  requests — extracting a job's surviving subtree roots (its tops + stack
  rows) for progress checkpoints, or *removing* bottom stack rows to ship to
  an idle cluster peer (``ops/frontier.shed_rows``) — the live-range split
  of ``/root/reference/DHT_Node.py:491-510`` at host level.
* **stats** mirrors the reference's counters: ``validations`` = branch nodes
  expanded (``/root/reference/DHT_Node.py:512-513`` analog), ``solved_count``
  (``:37,428``).

An explicit ``solve_fn`` override (tests' oracle backends, the sharded
multi-chip path) keeps the legacy one-dispatch-per-batch behavior; the
default path is the chunked flight loop.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import queue
import threading
import time
import uuid as uuid_mod
from typing import Any, Callable, Optional

import jax
import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import Geometry, geometry_for_size
from distributed_sudoku_solver_tpu.obs import (
    compilewatch,
    critpath,
    lockdep,
    ordertrace,
    slo,
    trace,
)
from distributed_sudoku_solver_tpu.obs.hist import LatencyHistogram, MinEstimator
from distributed_sudoku_solver_tpu.obs.logctx import job_log, uuids_label
from distributed_sudoku_solver_tpu.ops.frontier import Frontier, SolverConfig
from distributed_sudoku_solver_tpu.ops.solve import solve_batch
from distributed_sudoku_solver_tpu.serving import brownout, faults
from distributed_sudoku_solver_tpu.serving import journal as journal_wal

# Diagnostics go through logging (stderr via the root handler / logging's
# lastResort), not print(): failure paths log at ERROR with the fault
# classification, policy decisions (downgrades, unfit configs) at WARNING.
# Message text is kept grep-compatible with the old prints ("[engine] ...").
_LOG = logging.getLogger(__name__)


def host_fetch(x, floor_s: float = 0.0, tag: str = "status"):
    """THE device->host value seam of the serving hot loops.

    Every value the engine's flight loop or the resident scheduler reads
    off the device goes through here — which is what makes "exactly one
    host sync per chunk" an enforceable contract instead of a comment: the
    fetch-count guard test wraps this function and fails CI if a chunk
    syncs more than once (a stray ``np.asarray`` in the hot loop used to
    silently re-add ~100 ms/chunk through a tunneled device).

    ``floor_s`` simulates the per-sync RPC floor of a tunneled device (the
    engine's ``handicap_s`` slow-link simulator): the sleep happens HERE,
    at the sync, because that is where a real tunnel pays it — and because
    the loops dispatch ahead, the device computes straight through the
    simulated floor exactly as it would through a real one.  ``tag``
    classifies the sync for the guard: ``'status'`` (the one per-chunk
    fetch), ``'event'`` (solve/detach verdict data, only on chunks where a
    job resolved), ``'finalize'`` (terminal flight drain), ``'control'``
    (rare snapshot/shed control requests — batched to one sync each, and
    under the always-ahead loop they also wait out the in-flight chunk).
    ``x`` may be a pytree; the result is the matching numpy tree.
    """
    faults.fire("fetch." + tag)
    if floor_s:
        # clockck: allow(simulated RPC floor: sleeping at the sync IS this seam's documented behavior)
        time.sleep(floor_s)
    return jax.device_get(x)


@dataclasses.dataclass
class Job:
    """One `/solve` request travelling through the engine."""

    uuid: str
    grid: np.ndarray
    geom: Geometry
    # A resumed/offloaded job re-enters as subtree roots (uint32 candidate
    # rows [R, h, w]) instead of a clue grid; `grid` is then unused.
    roots: Optional[np.ndarray] = None
    # Per-job solver-config override (portfolio racing, serving/portfolio.py):
    # jobs group into flights by (geometry, config), so R configs of the same
    # board race as R concurrent flights.  None = the engine default.
    config: Optional[SolverConfig] = None
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    solution: Optional[np.ndarray] = None
    solved: bool = False
    unsat: bool = False
    nodes: int = 0
    sol_count: int = 0  # solutions found (exact model count under a
    #   config with count_all=True, where `unsat` means "enumeration
    #   complete" and `solution` holds the first one found)
    cancelled: bool = False
    # Mid-job offload bookkeeping: rows shed to a peer leave the local search
    # space incomplete, so "local space exhausted" (`exhausted`) is no longer
    # a proof of unsatisfiability (`unsat`) — the cluster layer aggregates
    # exhaustion across all shipped parts before claiming unsat.
    shed_parts: int = 0
    exhausted: bool = False
    error: Optional[str] = None
    # Absolute monotonic wall-clock budget, enforced at chunk granularity
    # on both flight paths (resident scheduler AND static flights — a job
    # that falls back from a saturated resident queue keeps its guarantee).
    # None = no deadline on the static path, the default deadline on
    # resident admission.  The legacy solve_fn path ignores it (one
    # uninterruptible dispatch).
    deadline: Optional[float] = None
    submitted_at: float = dataclasses.field(default_factory=time.monotonic)
    # Self-healing bookkeeping (serving/faults.py): transient re-dispatches
    # consumed from the per-job retry budget, the classification of the
    # last fault that requeued this job, and the bisection group token —
    # requeued halves of a permanently-failing batch must NOT re-merge at
    # the (geometry, config) grouping, or the poison-job isolation search
    # would never converge.
    fault_retries: int = 0
    last_fault: Optional[str] = None
    bisect_token: Optional[int] = None
    # Trace-clock submit time (obs/trace.py): set only when a recorder is
    # installed, read by the admission span so the queue wait is measured
    # on the RECORDER's clock (virtual in simnet tests) — `submitted_at`
    # stays on the wall clock for latency/deadline semantics.
    trace_t0: Optional[float] = None
    # Front-door routing (serving/frontdoor): which tier answered —
    # 'cache' | 'propagation' | 'native' | 'device' — or None for jobs
    # that never crossed the front door.
    route: Optional[str] = None
    # Difficulty-probe observations (serving/frontdoor/router.py), set
    # when the job crossed the front door's probe: the branching-slack
    # score and empty-cell count.  -1 = never probed.  The opt-in
    # ordering trace (obs/ordertrace.py) journals these with the route
    # outcome so the easy/hard threshold can be learned offline.
    probe_score: int = -1
    probe_empties: int = -1
    # Resolution hook: called by _finish_job with the verdict fields set,
    # BEFORE the done event (the front door's cache fill — a waiter that
    # resubmits the moment it wakes must see the entry).  Exceptions are
    # logged, never propagated; the hook fires at most once.
    on_resolve: Optional[Callable[["Job"], None]] = None
    # Shadow jobs are accounting-invisible: _finish_job still resolves
    # them (verdict fields, trace event, hooks, done) but skips every
    # counter/histogram/SLO sample.  The portfolio native race submits
    # its device FALLBACK as a shadow — the one user request is accounted
    # exactly once, by the race's own verdict hook, whichever entrant
    # wins (a non-shadow fallback double-counted the request the moment
    # the native entrant won after the fallback had been submitted).
    shadow: bool = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)


def _bucket(n: int, max_batch: int) -> int:
    """Smallest power-of-two >= n (capped): one jit entry per bucket, not per J."""
    b = 1
    while b < n and b < max_batch:
        b *= 2
    return b


@dataclasses.dataclass
class _Flight:
    """One in-progress device batch: jobs sharing a frontier, advanced in chunks."""

    geom: Geometry
    config: SolverConfig
    jobs: list  # list[Job]; index in this list == in-graph job id
    state: Frontier
    started: float = dataclasses.field(default_factory=time.monotonic)
    chunks: int = 0
    # Always-ahead dispatch bookkeeping: the un-fetched packed status word
    # of the most recently dispatched chunk (the device may still be
    # computing it), and the host's view of the absolute step counter as of
    # the last CONSUMED status — the authoritative value rides the status
    # word, so the loop never fetches the ``steps`` scalar.
    pending_status: Any = None
    steps_seen: int = 0


@dataclasses.dataclass
class _Control:
    """A cross-thread request the device loop services between chunks.

    The abandon handshake closes a work-loss hole: if the waiter times out
    before the loop services a *shed* (a long compile or handicapped chunk),
    the rows must NOT be removed — nobody would ship them, and the job's
    later exhaustion would read as a false unsat proof.  Waiter and servicer
    both take ``lock``; whoever wins decides (abandoned -> no-op, serviced
    -> waiter returns the result even after its timeout raced).
    """

    kind: str  # 'snapshot' | 'shed' | 'exec'
    uuid: Optional[str] = None
    k: int = 8
    fn: Any = None  # 'exec': zero-arg callable run on the device-owner thread
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    lock: Any = dataclasses.field(  # lockck: name(serving.control)
        default_factory=lambda: lockdep.named_lock("serving.control")
    )
    abandoned: bool = False
    claimed: bool = False  # servicer took it; abandon is no longer possible
    result: Any = None
    error: Optional[str] = None  # servicer-side exception, for exec callers


class EngineDraining(RuntimeError):
    """Raised by ``submit`` once the drain ladder has left the ``serving``
    state: admission is closed for NEW work (duplicate resubmits of
    already-accepted uuids still answer from the idempotency registry).
    The HTTP layer turns this into 503 + Retry-After with a machine body
    — the rolling-restart client contract."""

    def __init__(self, state: str, retry_after_s: float = 5.0):
        super().__init__(f"engine {state}: admission closed")
        self.state = state
        self.retry_after_s = retry_after_s


class SolverEngine:
    """Single-owner device loop consuming a thread-safe job queue."""

    def __init__(
        self,
        config: SolverConfig = SolverConfig(),
        max_batch: int = 256,
        batch_window_s: float = 0.002,
        solve_fn=None,
        chunk_steps: int = 64,
        max_flights: int = 4,
        handicap_s: float = 0.0,
        resident=None,  # Optional[serving.scheduler.ResidentConfig]
        recovery: Optional[faults.RecoveryPolicy] = None,
        clock: Callable[[], float] = time.monotonic,
        frontdoor=None,  # Optional[serving.frontdoor.FrontDoorConfig]
        latency_mode: bool = False,
        megastep=None,  # Optional[serving.megastep.MegastepConfig]
        journal=None,  # Optional[serving.journal.Journal]
    ):
        self.config = config
        self.max_batch = max_batch
        self.batch_window_s = batch_window_s
        self.chunk_steps = max(1, chunk_steps)
        self.max_flights = max(1, max_flights)
        # Slow-node simulator (the reference's per-guess sleep, `-d`,
        # ``DHT_Node.py:38,524``): charged per HOST SYNC at the fetch seam
        # (``host_fetch``) — one per flight chunk under the round-8
        # one-fetch contract, so per-sync == per-chunk, but the device
        # computes through the simulated floor exactly as it would through
        # a real tunnel because the loops dispatch ahead.  The legacy
        # solve_fn path sleeps per batch.
        self.handicap_s = handicap_s
        # The engine's time source for latency windows, batch windows, and
        # deadline math.  The DEFAULT binds the real monotonic clock at
        # class-definition time (a parameter default, i.e. clockck's
        # injection-seam shape), which also makes default-clock engines
        # immune to the simnet purity guard's time.monotonic monkeypatch —
        # engine device loops live outside the virtual clock by design
        # (cluster/simnet.py `wait_until` pacing note).
        self._clock = clock
        self._solve_fn = solve_fn or (
            lambda grids, geom, cfg: solve_batch(grids, geom, cfg)
        )
        self._use_flights = solve_fn is None
        from distributed_sudoku_solver_tpu.utils.profiling import StatWindow

        self.latency = StatWindow()  # seconds per job
        self.batch_sizes = StatWindow()  # jobs per device batch
        self.chunk_wall = StatWindow()  # seconds per flight-loop pass
        #   (dispatch + sync) per chunk consumed
        # The overlap split (round 8): dispatch wall is host time spent
        # ENQUEUEING device work (async — near zero, and it must stay
        # there), sync wall is host time blocked in the one per-chunk
        # status fetch, which through a tunnel includes the RPC floor and
        # on any backend includes waiting out device compute the host did
        # not overlap.  sync >> dispatch is the pipelined loop working as
        # designed; dispatch creeping up means something in the hot loop
        # started blocking.
        self.dispatch_wall = StatWindow()
        self.sync_wall = StatWindow()
        # Event/finalize fetch wall: the loop's only OTHER blocking reads
        # — solved-job verdict data (blocks on the just-dispatched chunk's
        # completion, so it can cost a chunk wall + floor) and terminal
        # flight drains.  Rare by construction (resolution chunks only),
        # but recorded so the dispatch/sync split never hides them.
        self.event_wall = StatWindow()
        # Mergeable log2-bucket histograms (obs/hist.py) recorded beside
        # the StatWindows at the same phase seams — the StatWindows answer
        # "this node's p95", the histograms vector-add across nodes into
        # cluster-scope distributions (GET /metrics?scope=cluster, via
        # obs/agg.py).  Keys: latency_ms (submit->resolve), solve_ms
        # (HTTP accept->response, fed by serving/http.py), dispatch/sync/
        # event walls (static flight loop), admission_wait_ms +
        # chunk_wall_ms (the resident scheduler's seams — shared across
        # geometries, serving/scheduler.py records into these).
        self.hist = {
            k: LatencyHistogram()
            for k in (
                "latency_ms",
                "solve_ms",
                "dispatch_wall_ms",
                "sync_wall_ms",
                "event_wall_ms",
                "admission_wait_ms",
                "chunk_wall_ms",
                # Per-route front-door latencies (serving/frontdoor):
                # empty (and therefore absent from /metrics and the
                # cluster rollup) unless a front door is installed.
                "frontdoor_cache_ms",
                "frontdoor_propagation_ms",
                "frontdoor_native_ms",
                "frontdoor_device_ms",
                # Latency-mode megastep flights (serving/megastep.py):
                # whole-flight walls — attach through the ONE status
                # sync.  Deliberately NOT recorded into the per-chunk
                # chunk_wall_ms / sync_wall_ms seams: one megastep sync
                # covers N in-graph chunks, so a per-chunk histogram
                # would double-count it N-fold (round-16 sweep).
                "frontdoor_megastep_ms",
            )
        }
        # Live RPC-floor estimate from the chunk.sync samples (both serving
        # loops): the per-sync minimum IS the dispatch floor a tunneled
        # device pays — the baseline number ROADMAP #2 attacks.
        self.rpc_floor = MinEstimator()
        # Running totals for the device-step rate (single-writer: the device
        # loop).  On an attached host sync wall bounds device step time;
        # through a tunneled device it includes the per-sync RPC overhead —
        # the /metrics field is named for what it measures, not a guess
        # (VERDICT r3 #8: bench.py derives the device-only number with a
        # measured RPC-floor subtraction, BENCHMARKS.md "Device-only
        # latency").
        self._chunk_wall_total = 0.0
        self._chunk_steps_total = 0
        self._queue: "queue.Queue[Job]" = queue.Queue()
        self._control: "queue.Queue[_Control]" = queue.Queue()
        self._flights: list[_Flight] = []  # owned by the device loop
        # Continuous batching (serving/scheduler.py): one long-lived
        # resident flight per geometry, admitting jobs between dispatches.
        # Eligible submits route there; everything else (portfolio config
        # overrides, roots resumes, count_all, fused-misfit geometries)
        # keeps the static flight path.  Device work still happens only on
        # the device loop; the dict itself is guarded by _lock.
        self.resident_config = resident
        self._resident: dict = {}  # Geometry -> ResidentFlight
        self.resident_unfit = 0  # lockck: guard(_lock) — geometries the resident fused shape
        #   cannot serve (fell back to static flights at submit time)
        self.mesh_unfit = 0  # lockck: guard(_lock) — mesh-resident flights that
        #   degraded to single-chip (too few devices / indivisible shapes)
        # Latency-mode serving megastep (serving/megastep.py, ISSUE 16):
        # single hard boards fuse their whole advance loop into ONE
        # donated dispatch with in-graph early exit — one host sync per
        # flight instead of one per chunk.  Opt-in per engine
        # (latency_mode=True) or per submit (latency=True); a failed or
        # budget-exhausted megastep degrades to the chunked paths below.
        # The dict is guarded by _lock; flights own their rank-36 lock.
        self.latency_mode = bool(latency_mode)
        self.megastep_config = megastep
        self._megasteps: dict = {}  # Geometry -> MegastepFlight | None
        self.megastep_unfit = 0  # lockck: guard(_lock) — geometries the megastep
        #   gang shape cannot serve (degraded to chunked paths at submit time)
        # Insertion-ordered so stale entries (cancels for jobs that already
        # finished or never arrive) can be pruned oldest-first.
        self._cancelled: "dict[str, None]" = {}
        self._lock = lockdep.named_lock("serving.engine")  # lockck: name(serving.engine)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Job-outcome counters (readers tolerate staleness).  Guarded
        # since round 19: megastep flights resolve jobs on submit
        # threads, so the device loop is no longer the single writer.
        self.validations = 0  # lockck: guard(_lock)
        self.solved_count = 0  # lockck: guard(_lock)
        self.jobs_done = 0  # lockck: guard(_lock)
        # Fused flights downgraded to the composite step at launch because
        # the config's (geometry, stack depth, lane width) sits outside the
        # kernel's measured compile boundary (see _fit_fused).
        self.fused_downgrades = 0
        # Self-healing recovery (serving/faults.py): transient device-side
        # failures requeue their jobs under a per-job retry budget with
        # degraded fallbacks; permanent failures bisect multi-job batches
        # until the poison job is isolated.  All counters below are
        # single-writer on the device loop (except fault_bulk_retries,
        # bumped by HTTP bulk threads — readers tolerate staleness) and
        # exported as the /metrics "faults" section.
        self.recovery = recovery or faults.RecoveryPolicy()
        self.fault_retries_total = 0  # transient re-dispatches granted
        self.fault_requeues = 0  # jobs put back on the queue by recovery
        self.fault_downgrades_fused = 0  # fused -> composite retry rung
        self.fault_lane_halvings = 0  # OOM retry rung: halved flight width
        self.fault_bisections = 0  # permanently-failing batches split
        self.fault_budget_exhausted = 0  # jobs failed out of retries
        self.fault_permanent = 0  # jobs failed on an isolated permanent fault
        self.fault_bulk_retries = 0  # lockck: guard(_lock) — transient bulk-chunk re-dispatches, bumped by HTTP handler threads
        self._bisect_seq = 0  # bisection group token source
        # Per-dispatch lane-occupancy histogram for fused flights (ROADMAP
        # 4b evidence): the kernel counts, per lane, how many in-kernel
        # rounds it held live work (Frontier.lane_rounds); the advance
        # program buckets each lane's live-rounds / rounds-advanced
        # fraction into 10 deciles IN-GRAPH and ships the bins in the
        # packed status word (round 8 — previously a host-side bincount
        # over two full lane_rounds fetches per chunk, paid even when
        # /metrics was never read).  Lanes stuck idle INSIDE a fused_steps
        # dispatch — the starvation an in-kernel tile-local steal would
        # fix — show up as mass in the low buckets.  Single-writer: the
        # device loop.
        self._occ_hist = np.zeros(10, np.int64)
        self._occ_frac_sum = 0.0
        self._occ_chunks = 0
        # Durable job lifecycle (serving/journal.py, ISSUE 20).  The WAL
        # records `accepted` before the client's 201 and discharges it on
        # REAL verdicts only; `recover()` replays the difference on boot.
        # An explicit ctor journal wins; otherwise the process-wide seam
        # (journal_wal.active()) is consulted per record — one global
        # read + one branch when nothing is installed, like faults/slo.
        self.journal = journal
        # The drain ladder: 'serving' -> 'draining' -> 'drained'.  submit
        # rejects new work (EngineDraining -> HTTP 503 + Retry-After) the
        # moment the state leaves 'serving'; duplicate resubmits of known
        # uuids still answer.
        self._lifecycle = "serving"  # lockck: guard(_lock)
        self.drain_handoffs = 0  # lockck: guard(_lock) — unstarted jobs shipped to a peer
        self.drain_journaled = 0  # lockck: guard(_lock) — unstarted jobs left to WAL replay
        self.drain_finished = 0  # lockck: guard(_lock) — in-flight jobs finished during drain
        self.recovered_jobs = 0  # lockck: guard(_lock) — journal entries replayed on boot
        self._drain_wait = threading.Event()  # never set: drain's pacing timer
        # Idempotent-resubmit registry (insertion-ordered, bounded): every
        # non-shadow submit parks its Job here so a client retry with the
        # same uuid — the retry-after-crash story — returns the SAME job
        # (in-flight) or its real verdict (resolved) instead of
        # double-solving and double-counting stats/SLO.  Error terminals
        # are evicted at lookup so a genuine retry runs fresh.
        self._jobs_by_uuid: "dict[str, Job]" = {}  # lockck: guard(_lock)
        # Node identity for trace spans (obs/trace.py): a cluster node sets
        # this to its wire address so a stitched multi-node trace
        # attributes each engine span to the host that recorded it.
        self.trace_node: Optional[str] = None
        # The front door (serving/frontdoor, ISSUE 14): symmetry-canonical
        # result cache + difficulty-probed routing ahead of every eligible
        # submit.  Built last so it sees a fully-wired engine; lazy import
        # keeps the frontdoor package out of engine-only deployments.
        self.frontdoor = None
        if frontdoor is not None:
            from distributed_sudoku_solver_tpu.serving.frontdoor.router import (
                FrontDoor,
            )

            self.frontdoor = FrontDoor(self, frontdoor)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "SolverEngine":
        self._thread = threading.Thread(target=self._run, daemon=True, name="device-loop")
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        # Close the submit-vs-stop window: a producer that won the lock
        # before this drain gets swept here; one that arrives after saw
        # _stop (set before we took the lock) and raised in submit().
        with self._lock:
            self._drain_on_stop()

    # -- durable lifecycle (serving/journal.py, ISSUE 20) ---------------------
    def _journal(self):
        """The engine's journal: the ctor-injected one, else the
        process-wide seam — one global read + one branch when nothing is
        installed (the faults/slo pattern)."""
        return self.journal if self.journal is not None else journal_wal.active()

    def lifecycle(self) -> str:
        with self._lock:
            return self._lifecycle

    def _dup_job(self, job_uuid: str) -> Optional[Job]:
        """Idempotency lookup: the live or real-verdict Job for a
        resubmitted uuid, or None.  An ERROR terminal is evicted here —
        the client's retry gets a fresh solve, not the stale failure."""
        with self._lock:
            prev = self._jobs_by_uuid.get(job_uuid)
            if prev is None:
                return None
            if prev.done.is_set() and prev.error is not None:
                self._jobs_by_uuid.pop(job_uuid, None)
                return None
            return prev

    def _journal_resolved(self, job: Job) -> None:
        """Terminal-site hook (every resolution path): discharge the
        job's WAL entry on a REAL verdict (solved/unsat/exhausted/
        cancelled).  Infra-error terminals keep the entry accepted-only
        — exactly the set ``recover()`` replays on the next boot — and
        drop out of the idempotency registry so a retry runs fresh.
        Safe on the device loop: ``record_resolved`` only buffers (the
        journal's batcher thread does the disk write)."""
        if job.shadow:
            return
        real = job.error is None
        if not real:
            with self._lock:
                self._jobs_by_uuid.pop(job.uuid, None)
            return
        jr = self._journal()
        if jr is not None:
            jr.record_resolved(
                job.uuid,
                {
                    "solved": bool(job.solved),
                    "unsat": bool(job.unsat),
                    "cancelled": bool(job.cancelled),
                    "exhausted": bool(job.exhausted),
                    "nodes": int(job.nodes),
                },
            )

    def recover(self) -> int:
        """Boot-time journal replay: re-submit every ``accepted`` entry
        with no ``resolved`` through the NORMAL submit seam (front door,
        resident routing, megastep — a replayed job is just a job), and
        warm the front-door L1 from the drain-time snapshot.  At-least-
        once is safe: verdicts are deterministic and cache fills /
        cluster dedupe are idempotent by uuid.  Returns the number of
        jobs replayed."""
        jr = self._journal()
        if jr is None:
            return 0
        if self.frontdoor is not None:
            warmed = self.frontdoor.cache.import_hot(jr.load_frontdoor())
            if warmed:
                _LOG.info(
                    "[engine] front-door cache restored warm: %d entries",
                    warmed,
                )
        entries = jr.unresolved()
        n = 0
        for ev in entries:
            grid = ev.get("grid")
            if grid is None:
                continue  # nothing replayable without a board
            cfg = None
            try:
                if ev.get("config"):
                    cfg = SolverConfig(**ev["config"])
                self.submit(
                    grid,
                    job_uuid=ev.get("uuid"),
                    config=cfg,
                    deadline_s=ev.get("deadline_s"),
                )
                n += 1
            except Exception as e:  # noqa: BLE001 — one bad entry must not sink the rest
                _LOG.error(
                    "[engine] journal replay failed for %s: %r",
                    ev.get("uuid"), e,
                )
        if n:
            with self._lock:
                self.recovered_jobs += n
            jr.mark_recovered(n)
            rec = trace.active()
            if rec is not None:
                rec.event(
                    None, "journal.recover", "engine.lifecycle",
                    node=self.trace_node, jobs=n,
                )
                # The flight-recorder moment: a reborn node just replayed
                # its WAL — dump the ring + a metrics snapshot so the
                # post-crash forensics start from the recovery point.
                rec.dump("journal_recovery", metrics=self.metrics())
        return n

    def drain(self, timeout: float = 30.0, handoff=None) -> dict:
        """Graceful drain, the ladder's middle rung: serving -> draining
        -> drained.  New admission starts failing with
        :class:`EngineDraining` (HTTP: 503 + Retry-After) the moment the
        state flips; then

        1. unstarted work (static queue + resident admission queues) is
           DETACHED: each job is offered to ``handoff`` (the cluster
           layer ships it to a gossip-healthy ring peer via the existing
           TASK frames) — shipped jobs discharge their WAL entry, the
           rest stay ``accepted``-only so the restart replays them;
        2. in-flight flights FINISH (bounded by ``timeout``) — the
           device loop keeps running until :meth:`stop`;
        3. the front-door L1 hot set persists beside the WAL and the
           journal syncs to disk.

        Idempotent: a second call reports the current state.  Returns a
        machine-readable summary (the ``/admin/drain`` response body).
        """
        with self._lock:
            if self._lifecycle != "serving":
                return {"state": self._lifecycle, "already_draining": True}
            self._lifecycle = "draining"
        started = self.busy_depth()
        rec = trace.active()
        if rec is not None:
            rec.event(
                None, "drain.begin", "engine.lifecycle",
                node=self.trace_node, busy=started,
            )
        jr = self._journal()
        # 1. Detach unstarted work.
        detached: list[Job] = []
        while True:
            try:
                j = self._queue.get_nowait()
            except queue.Empty:
                break
            if not j.done.is_set():
                detached.append(j)
        for rf in self._resident_flights():
            detached.extend(rf.detach_pending())
        handoffs = journaled = 0
        for j in detached:
            shipped = False
            if handoff is not None and not j.shadow and j.roots is None:
                try:
                    shipped = bool(handoff(j))
                except Exception:  # noqa: BLE001 — a dead peer must not sink the drain
                    _LOG.exception(
                        "[engine] drain handoff failed for %s", j.uuid
                    )
            if shipped:
                handoffs += 1
                if jr is not None and not j.shadow:
                    # The peer owns it now (and journals its own accept);
                    # discharge ours so the restart does not double-run it.
                    jr.record_resolved(j.uuid, {"handoff": True})
                j.error = "draining: handed off to peer"
            else:
                journaled += 1
                # WAL entry stays accepted-only -> replayed on restart
                # (root parts have no entry; their origin re-executes).
                j.error = "draining: journaled for restart"
            j.done.set()
        # 2. Wait out the in-flight work.  Spin-count pacing (not clock
        # math) so an injected virtual clock cannot hang the drain.
        spins = max(1, int(timeout / 0.02))
        while spins > 0 and self.busy_depth() > 0:
            spins -= 1
            self._drain_wait.wait(0.02)
        leftover = self.busy_depth()
        # 3. Persist the warm state beside the WAL.
        if jr is not None:
            if self.frontdoor is not None:
                jr.save_frontdoor(self.frontdoor.cache.export_hot())
            jr.sync_now()
        finished = max(0, started - len(detached) - leftover)
        with self._lock:
            self._lifecycle = "drained"
            self.drain_handoffs += handoffs
            self.drain_journaled += journaled
            self.drain_finished += finished
        if rec is not None:
            rec.event(
                None, "drain.done", "engine.lifecycle",
                node=self.trace_node, handoffs=handoffs,
                journaled=journaled, finished=finished, leftover=leftover,
            )
        return {
            "state": "drained",
            "handoffs": handoffs,
            "journaled": journaled,
            "finished": finished,
            "leftover": leftover,
        }

    # -- client API ----------------------------------------------------------
    def submit(
        self,
        grid,
        geom: Optional[Geometry] = None,
        job_uuid: Optional[str] = None,
        config: Optional[SolverConfig] = None,
        deadline_s: Optional[float] = None,
        saturation: str = "fallback",
        frontdoor: bool = True,
        shadow: bool = False,
        latency: Optional[bool] = None,
    ) -> Job:
        """Enqueue one job.  With a front door installed
        (``SolverEngine(frontdoor=...)``), eligible jobs cross it first:
        canonical-cache hits and propagation-solved/unsat boards come
        back already resolved, easy boards race the native DFS
        (``serving/portfolio.race_native``), and only the hard tail
        reaches a device flight — ``frontdoor=False`` is the per-call
        bypass (the racer's own fallback submit, bulk stragglers, tests
        pinning the direct path).  Per-job configs (portfolio racers,
        ``count_all``) skip the seam by construction.

        Eligible device-path jobs (no per-job config, no roots, engine
        not enumerating) route into the geometry's resident flight when
        one is configured (``serving/scheduler.py``); the rest take the
        static flight path.  ``saturation`` picks the policy when the
        resident admission queue is full: ``'fallback'`` (default) quietly
        uses a static flight, ``'reject'`` raises ``EngineSaturated`` — the
        HTTP layer's 429 + Retry-After backpressure.

        ``latency`` opts this submit into the serving megastep
        (serving/megastep.py): the whole advance loop fuses into ONE
        donated dispatch with in-graph early exit, resolving the job on
        the caller's thread with a single host sync.  ``None`` defers to
        the engine-wide ``latency_mode`` flag; a megastep that cannot
        serve the board (unfit geometry, budget exhausted, device fault)
        quietly degrades to the chunked resident/static paths below."""
        g = np.asarray(grid, dtype=np.int32)  # syncck: allow(client input coercion at submit time — list/ndarray host data, not the hot loop)
        geom = geom or geometry_for_size(g.shape[0])
        if g.shape != (geom.n, geom.n):
            raise ValueError(f"grid shape {g.shape} does not match geometry {geom}")
        if job_uuid is not None and not shadow:
            # Idempotent resubmit: a duplicate of an in-flight/resolved
            # uuid returns the existing job (its verdict, once done)
            # instead of double-solving — no stats/SLO stream counts the
            # request twice.  Checked BEFORE the drain gate so clients
            # polling by resubmit still get answers while draining.
            prev = self._dup_job(job_uuid)
            if prev is not None:
                return prev
        if not shadow:
            with self._lock:
                if self._lifecycle != "serving":
                    raise EngineDraining(self._lifecycle)
        job = Job(
            uuid=job_uuid or str(uuid_mod.uuid4()), grid=g, geom=geom,
            config=config, shadow=shadow,
        )
        # Re-stamp on the ENGINE clock: the dataclass default factory is
        # the real monotonic clock, which is only the same time source
        # when no custom clock was injected.
        job.submitted_at = self._clock()
        rec = trace.active()
        if rec is not None:
            job.trace_t0 = rec.now()
        if deadline_s is not None:
            job.deadline = job.submitted_at + deadline_s
        # The WAL promise (serving/journal.py): `accepted` is on record
        # BEFORE any routing — and therefore before the client's 201.  A
        # rejected placement (saturation 429, brownout/drain shed) never
        # answered 201, so the except arm discharges the entry; a crash
        # mid-routing leaves it accepted-only, and the replay of a job
        # whose client saw an error is idempotent by design.
        jr = None if shadow else self._journal()
        if jr is not None:
            jr.record_accepted(
                job.uuid, grid=g,
                config=dataclasses.asdict(config) if config is not None else None,
                deadline_s=deadline_s,
                geom=f"{geom.n}x{geom.n}",
            )
        if not shadow:
            with self._lock:
                self._jobs_by_uuid[job.uuid] = job
                while len(self._jobs_by_uuid) > 8192:  # stale-entry bound
                    self._jobs_by_uuid.pop(next(iter(self._jobs_by_uuid)))
        try:
            fd_token = None
            fd_routed = False
            if (
                frontdoor
                and self.frontdoor is not None
                and config is None
                and not self.config.count_all
                and not shadow  # the race's fallback must not re-enter the door
            ):
                # The front door owns cache/propagation/native verdicts;
                # owned=False means "hard tail" — fall through to the device
                # paths below, then COMMIT the routing decision (counters,
                # cache-fill registration) only once placement succeeded, so
                # an EngineSaturated 429 never inflates the device-route
                # counters or parks a dead cache-fill entry.  ``saturation``
                # rides along for the brownout gate (serving/brownout.py):
                # only reject-mode submits — the serving boundary — may be
                # shed with a BrownoutShed raise; quiet callers degrade.
                owned, fd_token = self.frontdoor.route(job, saturation=saturation)
                if owned:
                    return job
                fd_routed = True
            if self._megastep_eligible(job, latency):
                # Commit the front-door routing decision BEFORE the flight:
                # the megastep resolves synchronously on this thread, and the
                # cache-fill hook (frontdoor.commit_device installs
                # job.on_resolve) must be registered when _finish_job fires.
                if fd_routed:
                    self.frontdoor.commit_device(job, fd_token)
                    fd_routed = False
                if self._route_megastep(job):
                    return job
            if not self._route_resident(job, saturation):
                self._enqueue(job)
            if fd_routed:
                self.frontdoor.commit_device(job, fd_token)
            return job
        except BaseException:
            # Placement failed — the client gets an error, not a 201, so
            # the uuid must not look in-flight (registry) or replayable
            # (WAL): discharge both before re-raising.
            if not shadow:
                with self._lock:
                    self._jobs_by_uuid.pop(job.uuid, None)
            if jr is not None:
                jr.record_resolved(job.uuid, {"cancelled": True, "rejected": True})
            raise

    def _route_resident(self, job: Job, saturation: str) -> bool:
        """True if the job was admitted to a resident flight."""
        if (
            self.resident_config is None
            or not self._use_flights
            or job.config is not None
            or job.roots is not None
            or self.config.count_all
        ):
            return False
        rf = self._resident_for(job.geom)
        if rf is None:
            return False
        verdict = rf.admit(job)
        if verdict == rf.ADMITTED:
            return True
        if verdict == rf.SATURATED and saturation == "reject":
            # Only genuine backpressure may 429: a healthy-but-full queue.
            from distributed_sudoku_solver_tpu.serving.scheduler import (
                EngineSaturated,
            )

            raise EngineSaturated(rf.retry_after_s())
        # Saturated with quiet fallback, or DEFLECTED (breaker open /
        # flight permanently closed — a broken resident program must not
        # read as client backpressure): serve on a static flight.
        return False

    def _megastep_eligible(self, job: Job, latency: Optional[bool]) -> bool:
        """Whether this submit may take the latency-mode megastep: the
        caller (or the engine default) asked for it, and the job is a
        plain single-board solve — per-job configs, roots resumes and
        enumeration keep the chunked paths, same gate as the resident."""
        want = self.latency_mode if latency is None else bool(latency)
        return (
            want
            and self._use_flights
            and job.config is None
            and job.roots is None
            and not self.config.count_all
        )

    def _route_megastep(self, job: Job) -> bool:
        """True if the megastep resolved the job (on THIS thread — the
        flight is synchronous).  False degrades to the chunked paths:
        unfit geometry, open breaker, in-graph budget exhausted, device
        fault — all counted on the flight (round-9 taxonomy)."""
        mf = self._megastep_for(job.geom)
        if mf is None:
            return False
        # solve() runs outside the engine lock: it blocks on device work
        # and acquires the flight's own rank-36 lock.
        return mf.solve(job)

    def _megastep_for(self, geom: Geometry):
        """The geometry's megastep flight, created on first eligible
        latency submit.  None = geometry unservable (gang shape misfit):
        cached so the derivation isn't repaid per submit."""
        with self._lock:
            if self._stop.is_set():
                return None
            if geom in self._megasteps:
                return self._megasteps[geom]
            from distributed_sudoku_solver_tpu.serving.megastep import (
                MegastepConfig,
                MegastepFlight,
            )

            cfg = self.megastep_config or MegastepConfig()
            try:
                mf = MegastepFlight(self, geom, cfg)
            except ValueError as e:
                self.megastep_unfit += 1
                self._megasteps[geom] = None  # don't re-derive per submit
                _LOG.warning("[engine] megastep flight unfit for %s: %s", geom, e)
                return None
            self._megasteps[geom] = mf
            return mf

    def _resident_for(self, geom: Geometry):
        """The geometry's resident flight, created on first eligible submit
        (host-side shape math only — device state appears lazily on the
        device loop).  None = geometry unservable (fused misfit): the
        caller falls back to static flights, which downgrade per-flight."""
        with self._lock:
            if self._stop.is_set():
                return None
            if geom in self._resident:
                return self._resident[geom]
            from distributed_sudoku_solver_tpu.serving.scheduler import (
                ResidentFlight,
            )

            try:
                rf = None
                if self.resident_config.mesh_devices > 1:
                    # Pod-scale serving (serving/mesh_scheduler.py): shard
                    # the flight over a device mesh.  A misfit (too few
                    # visible devices, indivisible shapes) degrades to the
                    # single-chip flight, never to an error — the mesh is
                    # capacity, not correctness.
                    from distributed_sudoku_solver_tpu.serving.mesh_scheduler import (
                        MeshResidentFlight,
                    )

                    try:
                        rf = MeshResidentFlight(self, geom, self.resident_config)
                    except ValueError as e:
                        self.mesh_unfit += 1
                        _LOG.warning(
                            "[engine] mesh-resident flight unfit for %s "
                            "(single-chip fallback): %s", geom, e,
                        )
                if rf is None:
                    rf = ResidentFlight(self, geom, self.resident_config)
            except ValueError as e:
                self.resident_unfit += 1
                self._resident[geom] = None  # don't re-derive per submit
                _LOG.warning("[engine] resident flight unfit for %s: %s", geom, e)
                return None
            self._resident[geom] = rf
            return rf

    def job_is_resident(self, job_uuid: str) -> bool:
        """Whether a job is queued/running in a resident flight (resident
        jobs have no snapshot/shed surface — the cluster's progress loop
        skips them instead of polling a permanent None)."""
        with self._lock:
            flights = [rf for rf in self._resident.values() if rf is not None]
        for rf in flights:
            with rf._lock:
                if any(j.uuid == job_uuid for j in rf._pending):
                    return True
            if any(j is not None and j.uuid == job_uuid for j in rf.slots):
                return True
        return False

    def _enqueue(self, job: Job) -> None:
        # Lock-ordered with stop()'s final drain: either this put happens
        # before the drain (and is swept by it), or _stop is already
        # visible here and we fail fast instead of stranding the caller.
        with self._lock:
            if self._stop.is_set():
                raise RuntimeError("engine stopped")
            self._queue.put(job)

    def submit_roots(
        self,
        roots,
        geom: Geometry,
        job_uuid: Optional[str] = None,
        config: Optional[SolverConfig] = None,
    ) -> Job:
        """Submit a job whose search space is given subtree roots (candidate
        rows uint32[R, h, w]) rather than a clue grid — the entry point for
        checkpoint resume and cluster mid-job offload."""
        r = np.ascontiguousarray(np.asarray(roots, dtype=np.uint32))  # syncck: allow(resume payload coercion at submit time — wire-decoded host rows, not the hot loop)
        if r.ndim != 3 or r.shape[1:] != (geom.n, geom.n):
            raise ValueError(f"roots shape {r.shape} does not match geometry {geom}")
        if r.shape[0] == 0:
            raise ValueError("roots must contain at least one row")
        job = Job(
            uuid=job_uuid or str(uuid_mod.uuid4()),
            grid=np.zeros((geom.n, geom.n), np.int32),
            geom=geom,
            roots=r,
            config=config,
        )
        with self._lock:
            if self._lifecycle != "serving":
                # Root parts are re-executed by their ORIGIN on failure —
                # rejecting here routes them to a healthy peer; no local
                # WAL entry is taken for them (the origin keeps the
                # parent job journaled).
                raise EngineDraining(self._lifecycle)
        job.submitted_at = self._clock()  # engine-clock stamp, as in submit()
        rec = trace.active()
        if rec is not None:
            job.trace_t0 = rec.now()
        self._enqueue(job)
        return job

    def cancel(self, job_uuid: str) -> None:
        with self._lock:
            self._cancelled[job_uuid] = None
            while len(self._cancelled) > 4096:  # stale-cancel bound
                self._cancelled.pop(next(iter(self._cancelled)))

    def _request(self, req: _Control, timeout: float):
        with self._lock:
            if self._stop.is_set():
                return None  # nobody will service it; fail fast, don't strand
            self._control.put(req)
        if not req.done.wait(timeout):
            with req.lock:
                if not req.done.is_set() and not req.claimed:
                    req.abandoned = True  # servicer will no-op
                    return None
            # Claimed (running) or finished between the wait timing out and
            # us taking the lock.  A running exec/snapshot is simply given
            # up on — its result is discardable; a running *shed* has
            # already pulled rows out of a frontier, so wait it out (it is
            # one short jitted call) rather than drop work on the floor.
            if req.kind != "shed" and not req.done.is_set():
                return None
            req.done.wait()
        if req.error is not None and req.kind == "exec":
            # exec callers must distinguish "fn raised" from "timed out":
            # a 504-style retry against a deterministic failure loops forever.
            raise RuntimeError(req.error)
        return req.result

    def snapshot_rows(self, job_uuid: str, timeout: float = 10.0):
        """Current surviving subtree roots of an in-flight job.

        Returns ``(rows uint32[R, h, w], nodes int, shed_parts int,
        config dict)`` or None (job unknown / already resolved / engine
        stopped).  ``config`` is the job's effective SolverConfig as a dict,
        so a resume reconstructs the exact same search.  Serviced by the
        device loop between chunks, so the result is a consistent frontier
        cut — and because sheds are serviced by the same thread,
        ``shed_parts == 0`` proves no rows had left this job before the cut,
        i.e. the rows are a *complete* cover of its remaining space.
        """
        return self._request(_Control(kind="snapshot", uuid=job_uuid), timeout)

    def shed_work(self, k: int = 8, timeout: float = 10.0):
        """Remove up to ``k`` bottom stack rows from the neediest in-flight
        job; returns ``(job_uuid, rows uint32[<=k, h, w], config dict)`` or
        None.

        The donor half of cluster mid-job offload: the caller ships rows +
        config to an idle peer, which re-enters them via
        :meth:`submit_roots` under the same solver config (a portfolio
        racer's heterogeneity survives the hop).
        """
        return self._request(_Control(kind="shed", k=max(1, k)), timeout)

    def run_exclusive(self, fn, timeout: float = 600.0):
        """Run ``fn()`` on the device-owner thread, between flight chunks.

        The single-owner escape hatch for non-engine device work (the HTTP
        bulk endpoint's ``ops/bulk`` dispatches): no second thread ever
        talks to the device, and in-flight interactive jobs resume at the
        next chunk boundary.  Returns ``fn()``'s result; returns None if the
        engine never got to it within ``timeout`` (the abandoned request is
        skipped, never run late); raises RuntimeError if ``fn`` itself
        raised on the device loop."""
        return self._request(_Control(kind="exec", fn=fn), timeout)

    def busy_depth(self) -> int:
        """Queued jobs + unresolved jobs across active flights (approximate —
        flights list is read without the device loop's coordination)."""
        n = self._queue.qsize()
        for fl in list(self._flights):
            n += sum(0 if j.done.is_set() else 1 for j in fl.jobs)
        for rf in self._resident_flights():
            n += rf.queued_depth()
        return n

    def _resident_flights(self) -> list:
        with self._lock:
            return [rf for rf in self._resident.values() if rf is not None]

    def _megastep_flights(self) -> list:
        with self._lock:
            return [mf for mf in self._megasteps.values() if mf is not None]

    def stats(self) -> dict:
        s = {
            "validations": int(self.validations),
            "solved": int(self.solved_count),
            "jobs_done": int(self.jobs_done),
        }
        if self.frontdoor is not None:
            # Jobs the front door answered without a device flight still
            # count as this node's work (native-racer nodes land in
            # `validations`, matching the reference's counter semantics).
            s = self.frontdoor.merge_stats(s)
        return s

    def metrics(self) -> dict:
        """Extended observability (GET /metrics): latency percentiles over
        the last ~1k jobs, batch sizes, and the base counters."""
        out = dict(self.stats())
        lat = self.latency.snapshot()
        if lat:
            out["job_latency_ms"] = {
                "count": lat["count"],
                **{k: round(lat[k] * 1e3, 3) for k in ("p50", "p95", "p99")},
            }
        bs = self.batch_sizes.snapshot()
        if bs:
            out["batch_jobs"] = {
                "count": bs["count"],
                **{k: round(bs[k], 1) for k in ("p50", "p95")},
            }
        cw = self.chunk_wall.snapshot()
        if cw:
            out["chunk_wall_ms"] = {
                "count": cw["count"],
                **{k: round(cw[k] * 1e3, 3) for k in ("p50", "p95")},
            }
        # The overlap split (round 8): dispatch wall = host time enqueueing
        # device work (async, should stay near zero), sync wall = host time
        # blocked in the one per-chunk status fetch.  Their gap is the
        # observable proof that scheduling/admission work overlaps device
        # compute instead of serializing with it (see __init__).
        for name, win in (
            ("dispatch_wall_ms", self.dispatch_wall),
            ("sync_wall_ms", self.sync_wall),
            ("event_wall_ms", self.event_wall),
        ):
            snap = win.snapshot()
            if snap:
                out[name] = {
                    "count": snap["count"],
                    **{k: round(snap[k] * 1e3, 3) for k in ("p50", "p95")},
                }
        if self._chunk_steps_total > 0:
            # Per-frontier-round advance wall: device step time on attached
            # hosts, device + per-sync RPC through a tunnel (see
            # __init__).  The denominator counts frontier rounds actually
            # advanced, so compile-time outliers only dilute, never inflate.
            out["step_wall_ms_avg"] = round(
                self._chunk_wall_total / self._chunk_steps_total * 1e3, 4
            )
        out["active_flights"] = len(self._flights)
        out["fused_downgrades"] = int(self.fused_downgrades)
        resident_flights = self._resident_flights()
        if resident_flights:
            # Slot occupancy, admission waits, and rejects per geometry —
            # the continuous-batching observability (cluster nodes export
            # this section verbatim through metrics_view).
            out["resident"] = {
                f"{rf.geom.n}x{rf.geom.n}": rf.metrics()
                for rf in resident_flights
            }
        if self.resident_unfit:
            out["resident_unfit"] = int(self.resident_unfit)
        if self.mesh_unfit:
            out["mesh_unfit"] = int(self.mesh_unfit)
        megastep_flights = self._megastep_flights()
        if megastep_flights:
            # Latency-mode megastep observability (serving/megastep.py):
            # flight/verdict counters, degrade taxonomy, chunk totals and
            # whole-flight walls per geometry.  The matching
            # frontdoor_megastep_ms histogram rides `hist` below.
            out["megastep"] = {
                f"{mf.geom.n}x{mf.geom.n}": mf.metrics()
                for mf in megastep_flights
            }
        if self.megastep_unfit:
            out["megastep_unfit"] = int(self.megastep_unfit)
        if self.frontdoor is not None:
            # The routing layer's own observability (serving/frontdoor):
            # cache hit/miss/eviction/canonical-dup counters, probe
            # verdicts, per-route dispatch counts.  The matching per-route
            # latency histograms ride the `hist` section below, so the
            # cluster rollup merges them for free.
            out["frontdoor"] = self.frontdoor.metrics()
        # Self-healing observability (serving/faults.py): retry/requeue/
        # downgrade/bisection counters, per-geometry breaker state, and —
        # when a fault injector is installed — what it injected where.
        fa = {
            "retries": int(self.fault_retries_total),
            "requeues": int(self.fault_requeues),
            "downgrades": {
                "fused_to_composite": int(self.fault_downgrades_fused),
                "lanes_halved": int(self.fault_lane_halvings),
            },
            "bisections": int(self.fault_bisections),
            "budget_exhausted": int(self.fault_budget_exhausted),
            "permanent_failures": int(self.fault_permanent),
            "bulk_retries": int(self.fault_bulk_retries),
        }
        breaker = {
            f"{rf.geom.n}x{rf.geom.n}": rf.breaker.metrics()
            for rf in resident_flights
        }
        if breaker:
            fa["breaker"] = breaker
        inj = faults.active()
        if inj is not None:
            fa["injector"] = inj.metrics()
        out["faults"] = fa
        rec = trace.active()
        if rec is not None:
            # Flight-recorder health: ring fill, links, dumps written,
            # spans stitched in from remote nodes (obs/trace.py).
            out["trace"] = rec.metrics()
        # The mergeable plane (obs/hist.py): phase-decomposed log2
        # histograms (cluster-scope aggregation vector-adds these across
        # members) and the live RPC-floor estimate from chunk.sync walls.
        hist_sec = {k: h.to_dict() for k, h in self.hist.items() if len(h)}
        cp = critpath.active()
        if cp is not None:
            # Per-phase critical-path histograms ride the same ``hist``
            # keyspace (``critpath_<phase>_ms``), so the cluster rollup
            # vector-adds them with zero extra aggregation code; the
            # shares/watchdog counters get their own section below.
            hist_sec.update(cp.hist_dicts())
            out["critpath"] = cp.metrics()
        if hist_sec:
            out["hist"] = hist_sec
        cw = compilewatch.active()
        if cw is not None:
            # The compile/recompile watch (obs/compilewatch.py): per-
            # program compile counts and walls, warmup/alarm state, and
            # — when the serving loops captured a cost model — the cost
            # plane with the live device-efficiency gauge (measured
            # rounds/s priced by the per-round HLO cost analysis).
            out["compile"] = cw.metrics()
            cost = cw.cost_metrics()
            if cost is not None:
                # Frontier rounds + chunk walls from BOTH serving loops
                # (the resident scheduler is the default path, and a
                # resident-only node must still light the gauge).
                rounds = self._chunk_steps_total
                wall = self._chunk_wall_total
                for rf in resident_flights:
                    rounds += rf.rounds_total
                    wall += rf.round_wall_total
                for mf in megastep_flights:
                    # Megastep flights advance rounds too (a latency-only
                    # node must still light the gauge); their wall is the
                    # whole-flight wall — the only wall the one-sync
                    # design observes.
                    rounds += mf.rounds_total
                    wall += mf.round_wall_total
                eff = cw.efficiency(
                    compilewatch.ADVANCE_FUSED_STATUS
                    if self.config.step_impl == "fused"
                    else compilewatch.ADVANCE_STATUS,
                    rounds,
                    wall,
                )
                if eff is not None:
                    cost["efficiency"] = eff
                out["cost"] = cost
        floor = self.rpc_floor.to_dict()
        if floor is not None:
            out["rpc_floor_ms"] = floor
        mon = slo.active()
        if mon is not None:
            # SLO plane health (obs/slo.py): burn rates, breaches, dumps.
            out["slo"] = mon.metrics()
        bo = brownout.active()
        if bo is not None:
            # The brownout controller (serving/brownout.py): current
            # stage, transition counters, per-tier shed counts, stage
            # residency, and the last evaluated pressure readings — the
            # section obs/agg.py rolls up cluster-wide and /status scans
            # for browning-out members.
            out["brownout"] = bo.metrics()
        jr = self._journal()
        if jr is not None:
            # Durability plane (serving/journal.py): WAL depth, degrade
            # counters, compaction totals — the families promck validates.
            out["journal"] = jr.metrics()
        # The drain ladder + recovery counters, read lock-free like every
        # other guarded counter here (readers tolerate staleness).
        # `state` is numeric for the Prometheus plane (0=serving
        # 1=draining 2=drained); /status carries the string.
        out["lifecycle"] = {
            "state": ("serving", "draining", "drained").index(
                self._lifecycle
            ),
            "drain_handoffs": int(self.drain_handoffs),
            "drain_journaled": int(self.drain_journaled),
            "drain_finished": int(self.drain_finished),
            "recovered_jobs": int(self.recovered_jobs),
            "resubmit_registry": len(self._jobs_by_uuid),
        }
        if self._occ_chunks > 0:
            # Lane-occupancy inside fused dispatches: counts[k] = lanes
            # observed live for [10k, 10(k+1))% of the rounds their chunk
            # advanced (last bucket closed at 100%).  The data that settles
            # ROADMAP 4b's in-kernel steal question (BENCHMARKS.md).
            out["fused_lane_occupancy"] = {
                "bucket_pct": 10,
                "counts": [int(c) for c in self._occ_hist],
                "mean_pct": round(
                    100.0 * self._occ_frac_sum / self._occ_chunks, 2
                ),
                "chunks": int(self._occ_chunks),
            }
        return out

    # -- device loop ---------------------------------------------------------
    def _take_batch(self, wait: bool) -> list[Job]:
        try:
            first = self._queue.get(timeout=0.05 if wait else 0)
        except queue.Empty:
            return []
        jobs = [first]
        deadline = self._clock() + self.batch_window_s
        while len(jobs) < self.max_batch:
            remaining = deadline - self._clock()
            if remaining <= 0:
                break
            try:
                jobs.append(self._queue.get(timeout=remaining))
            except queue.Empty:
                break
        return jobs

    def _consume_cancel(self, job: Job) -> bool:
        with self._lock:
            return self._cancelled.pop(job.uuid, "absent") is None

    def _peek_cancels(self, jobs: list[Job]) -> list[int]:
        with self._lock:
            return [
                i
                for i, j in enumerate(jobs)
                if not j.done.is_set() and j.uuid in self._cancelled
            ]

    def _run(self) -> None:
        while not self._stop.is_set():
            resident = [rf for rf in self._resident_flights() if rf.active()]
            # Admit new work (non-blocking while flights are active so a
            # running chunk never starves the queue check); the flight cap
            # bounds concurrent device frontiers — excess jobs wait queued.
            jobs = (
                self._take_batch(wait=not self._flights and not resident)
                if len(self._flights) < self.max_flights
                else []
            )
            live: list[Job] = []
            for job in jobs:
                if self._consume_cancel(job):
                    job.cancelled = True
                    self._journal_resolved(job)  # cancel IS a real verdict
                    job.done.set()
                else:
                    live.append(job)
            by_key: dict[tuple, list[Job]] = {}
            for job in live:
                # bisect_token keeps requeued halves of a permanently-
                # failing batch apart; it is None for every healthy job.
                by_key.setdefault(
                    (job.geom, job.config or self.config, job.bisect_token), []
                ).append(job)
            for (geom, cfg, _token), group in by_key.items():
                # The device loop must survive anything a batch throws
                # (compile error, bad config, OOM): recover the batch's
                # jobs (serving/faults.py — transient faults requeue under
                # a retry budget, permanent ones bisect/fail), keep
                # serving — a dead loop would strand every later job.
                try:
                    if self._use_flights:
                        self._launch_flights(geom, cfg, group)
                    else:
                        self._solve_group(geom, group, cfg)
                except Exception as e:  # noqa: BLE001
                    _LOG.error(
                        "[engine] batch failed (%s, %s): %r [%s]",
                        geom, uuids_label(group), e, faults.classify(e),
                    )
                    self._recover_group(group, cfg, e)
            self._service_controls()
            # Resident flights advance one chunk each, interleaved with the
            # static flights below (same chunk-granularity fairness).  A
            # COOLING flight with queued jobs is stepped too: step() only
            # sweeps its pending queue (cancels/deadlines) mid-cooldown —
            # active() stays False so the wait logic above still sleeps.
            stepable = list(resident)
            for rf in self._resident_flights():
                if rf not in stepable and rf.cooling() and rf.queued_depth():
                    stepable.append(rf)
            for rf in stepable:
                try:
                    rf.step()
                except Exception as e:  # noqa: BLE001
                    # A resident device program died: classify and recover
                    # (serving/scheduler.py) — a transient fault rebuilds
                    # the flight after a cooldown with its jobs requeued, a
                    # permanent one (or a tripped circuit breaker) routes
                    # them to static flights; the loop keeps serving.
                    _LOG.error(
                        "[engine] resident flight failed (%s, %s): %r [%s]",
                        rf.geom,
                        uuids_label([j for j in rf.slots if j is not None]),
                        e, faults.classify(e),
                    )
                    rf.on_failure(e)
            # Round-robin: advance every active flight by one chunk.
            for fl in list(self._flights):
                try:
                    finished = self._advance_flight(fl)
                except Exception as e:  # noqa: BLE001
                    self._flights.remove(fl)
                    _LOG.error(
                        "[engine] flight failed (%s, %s): %r [%s]",
                        fl.geom, uuids_label(fl.jobs), e, faults.classify(e),
                    )
                    self._recover_jobs(
                        [j for j in fl.jobs if not j.done.is_set()],
                        fl.config,
                        e,
                    )
                    continue
                if finished:
                    self._flights.remove(fl)
        self._drain_on_stop()

    # -- fault recovery (serving/faults.py) -----------------------------------
    def _recover_group(self, group: list[Job], cfg, exc) -> None:
        """A batch failed at launch: recover every job not already owned by
        a flight (``_launch_flights`` may have launched some of the group
        before the raise — those flights are live and keep their jobs)."""
        owned = {id(j) for fl in self._flights for j in fl.jobs}
        self._recover_jobs(
            [j for j in group if id(j) not in owned and not j.done.is_set()],
            cfg,
            exc,
        )

    def _recover_jobs(self, jobs: list[Job], cfg: SolverConfig, exc) -> None:
        """Classify-and-recover for a failed dispatch's unresolved jobs.

        Transient: every job re-enters the queue under its retry budget,
        with the degraded fallback config for the fault's shape (fused ->
        composite; OOM -> halved lanes).  Permanent: a multi-job batch is
        BISECTED — both halves requeue under fresh group tokens, so
        repeated failures converge on the one poison job, which then fails
        alone instead of taking its batchmates down.  The device state is
        gone either way (donated buffers do not survive a failed program),
        so a recovered job restarts from its grid/roots — sound, since
        neither path ever reported partial results.
        """
        if not jobs:
            return
        kind = faults.classify(exc)
        label = f"{type(exc).__name__}: {exc}"
        rec = trace.active()
        if kind == faults.PERMANENT:
            if len(jobs) > 1:
                self.fault_bisections += 1
                if rec is not None:
                    rec.event(
                        None, "recovery.bisect", "engine.recovery",
                        node=self.trace_node,
                        uuids=[j.uuid for j in jobs], error=label,
                    )
                mid = len(jobs) // 2
                for half in (jobs[:mid], jobs[mid:]):
                    self._bisect_seq += 1
                    for job in half:
                        job.bisect_token = self._bisect_seq
                        job.last_fault = kind
                        self._requeue(job)
                _LOG.error(
                    "[engine] permanent batch failure (%s): bisecting %d "
                    "jobs to isolate the poison dispatch",
                    uuids_label(jobs), len(jobs),
                )
            else:
                for job in jobs:
                    job.error = label
                    self.fault_permanent += 1
                    job_log(_LOG, job.uuid).error(
                        "[engine] permanent failure: %s", label
                    )
                    # Span BEFORE the done event (_finish_job's contract):
                    # setting done releases the cluster waiter that ships
                    # the SOLUTION, and a reader stitching the trace at
                    # resolve time must already see the fault.
                    if rec is not None:
                        rec.event(
                            job.uuid, "fault.permanent", "engine.recovery",
                            node=self.trace_node, error=label,
                        )
                    job.done.set()
                if rec is not None:
                    # The flight-recorder moment: an isolated permanent
                    # fault just failed a paying job — dump the recent
                    # ring + a metrics snapshot for the post-mortem.
                    rec.dump("permanent_fault", metrics=self.metrics())
            return
        degraded = self._degrade(cfg, exc)
        for job in jobs:
            if not self._charge_retry(job, kind, label):
                continue
            if rec is not None:
                rec.event(
                    job.uuid, "recovery.requeue", "engine.recovery",
                    node=self.trace_node, kind=kind,
                    retry=job.fault_retries,
                )
            # Pin the (possibly degraded) config on the job: the requeue
            # must not re-enter the resident path (that flight has its own
            # breaker) and must group under the degraded config.
            job.config = degraded
            self._requeue(job)

    def _charge_retry(self, job: Job, kind: str, label: str) -> bool:
        """Charge one transient retry against ``job``'s budget.  False =
        budget exhausted: the job is failed AND resolved here (the error
        text is load-bearing — cluster ``_on_solution`` classifies it via
        ``classify_message`` and tests assert on it).  Shared by the static
        recovery above and ``ResidentFlight.on_failure``."""
        job.fault_retries += 1
        job.last_fault = kind
        if job.fault_retries > self.recovery.max_retries:
            job.error = (
                f"retry budget exhausted after "
                f"{job.fault_retries - 1} retries: {label}"
            )
            job.done.set()
            self.fault_budget_exhausted += 1
            job_log(_LOG, job.uuid).error("[engine] %s", job.error)
            rec = trace.active()
            if rec is not None:
                rec.event(
                    job.uuid, "recovery.budget_exhausted", "engine.recovery",
                    node=self.trace_node, error=job.error,
                )
            return False
        self.fault_retries_total += 1
        return True

    def _requeue(self, job: Job) -> None:
        # Device-loop thread only.  Straight to the queue (not _enqueue):
        # recovery during stop() is fine — _drain_on_stop sweeps the queue
        # after the loop exits, so a requeued job still resolves.
        self._queue.put(job)
        self.fault_requeues += 1

    def _degrade(self, cfg: SolverConfig, exc) -> SolverConfig:
        """One rung down the fallback ladder for a transient retry: an OOM
        halves the flight's lane width (attacking the allocation that
        failed), any other fault on a fused config downgrades to the
        composite step (the slower, always-correct path) — mirroring
        ``_fit_fused``'s launch-time policy of degrading instead of
        erroring paying jobs.

        The halved width is PINNED (even for auto-width configs): a pinned
        width is a per-flight cap — ``_launch_flights`` splits oversized
        groups at ``cap=lanes`` and ``_start_flight`` shrinks the bucket to
        it — so the retry really allocates half the frontier PER PROGRAM,
        and ``resolve_lanes`` can never see more jobs than lanes.  Scope
        honestly stated: this rung attacks per-program peaks (fused VMEM
        admission, XLA temp buffers — the dominant OOM mode on this
        stack); a multi-job group split into more flights keeps roughly
        the same AGGREGATE persistent frontier HBM, which no width cap can
        shrink — only the retry budget bounds that failure mode."""
        rec = trace.active()
        if faults.is_oom(exc):
            lanes = cfg.lanes if cfg.lanes > 0 else cfg.min_lanes
            halved = max(1, lanes // 2)
            self.fault_lane_halvings += 1
            if rec is not None:
                rec.event(
                    None, "recovery.downgrade", "engine.recovery",
                    node=self.trace_node, rung="lanes_halved", lanes=halved,
                )
            new = dataclasses.replace(
                cfg, lanes=halved, min_lanes=min(cfg.min_lanes, halved)
            )
            if new.steal_gang > 0 and halved % new.steal_gang:
                # Gang-scoped stealing needs gang | lanes; a halved width
                # that breaks divisibility drops to global pairing.
                new = dataclasses.replace(new, steal_gang=0)
            return new
        if cfg.step_impl == "fused":
            self.fault_downgrades_fused += 1
            if rec is not None:
                rec.event(
                    None, "recovery.downgrade", "engine.recovery",
                    node=self.trace_node, rung="fused_to_composite",
                )
            return dataclasses.replace(cfg, step_impl="xla")
        return cfg

    def _drain_on_stop(self) -> None:
        """Resolve everything still pending when the loop exits: nobody else
        will ever touch these jobs/controls, and an un-set event would hang
        any caller waiting without a timeout."""
        leftovers: list[Job] = []
        while True:
            try:
                leftovers.append(self._queue.get_nowait())
            except queue.Empty:
                break
        for fl in self._flights:
            leftovers.extend(j for j in fl.jobs if not j.done.is_set())
        self._flights.clear()
        # No _resident_flights() here: stop() calls this with _lock held
        # (non-reentrant), and a raw dict-values read is safe under the GIL.
        for rf in list(self._resident.values()):
            if rf is not None:
                rf.drain()
        for job in leftovers:
            if not job.done.is_set():
                job.error = "engine stopped"
                job.done.set()
        while True:
            try:
                req = self._control.get_nowait()
            except queue.Empty:
                break
            req.done.set()  # result stays None: caller sees "not serviced"

    # -- flight path (default) ----------------------------------------------
    def _fit_fused(self, geom: Geometry, cfg: SolverConfig, would_be_lanes: int):
        """Pin a fused flight's lane count to a kernel-valid width, or
        downgrade the flight to the composite step when no width fits.

        The fused kernel tiles lanes at 128 (``ops/pallas_step.fused_lanes``:
        counts beyond 128 round up to a multiple, and the tile must fit the
        measured scoped-VMEM compile boundary for the geometry + stack
        depth).  When it cannot, a correct, slower path exists — the
        composite ``step_impl='xla'`` flight — so a tuning misfit downgrades
        (logged, counted on ``/metrics`` as ``fused_downgrades``) instead of
        erroring the batch's jobs (VERDICT r4 #5: erroring paying jobs on a
        config misfit is a policy the serving tier shouldn't impose).  The
        composite path has no such constraint and keeps ``cfg`` untouched."""
        if cfg.step_impl != "fused":
            return cfg
        from distributed_sudoku_solver_tpu.ops.pallas_step import fused_lanes

        try:
            return dataclasses.replace(
                cfg, lanes=fused_lanes(would_be_lanes, geom.n, cfg.stack_slots)
            )
        except ValueError as e:
            self.fused_downgrades += 1
            _LOG.warning(
                "[engine] fused config unfit, downgrading to composite: %s", e
            )
            return dataclasses.replace(cfg, step_impl="xla")

    def _launch_flights(
        self, geom: Geometry, cfg: SolverConfig, group: list[Job]
    ) -> None:
        cap = cfg.lanes if cfg.lanes > 0 else self.max_batch
        if cfg.step_impl == "fused":
            # Split the group at the widest width the kernel serves (e.g.
            # 9x9 at S=32: whole-array tiles compile to 128 lanes while the
            # gridded 128-lane tile does not) — a 256-job fused group then
            # launches as two 128-lane fused flights instead of one
            # composite-downgraded one.  cap=0 falls through: _fit_fused
            # downgrades the flight at launch.
            from distributed_sudoku_solver_tpu.ops.pallas_step import max_fused_lanes

            mfl = max_fused_lanes(geom.n, cfg.stack_slots)
            if mfl > 0:
                cap = min(cap, mfl)
                if cfg.lanes > mfl or cfg.min_lanes > mfl:
                    # A pinned width above the serving cap would make
                    # resolve_lanes ignore the smaller bucket and the flight
                    # would downgrade anyway — clamp the width too: fused at
                    # mfl lanes beats composite at the requested width.
                    cfg = dataclasses.replace(
                        cfg,
                        lanes=min(cfg.lanes, mfl) if cfg.lanes > 0 else 0,
                        min_lanes=min(cfg.min_lanes, mfl),
                    )
        # Roots jobs (resume / offloaded subtrees) fly solo with *packed*
        # seeding: their rows deal round-robin onto the configured lane
        # width, so a resume runs at the same width — and the same
        # speculative-expansion budget — as the original search.  They get
        # the clamped cfg too: a pinned width above the fused serving cap
        # should clamp-and-stay-fused for a resume exactly as for grid jobs.
        for job in group:
            if job.roots is not None:
                self._start_packed_flight(geom, cfg, job)
        group = [j for j in group if j.roots is None]
        for i in range(0, len(group), cap):
            self._start_flight(geom, cfg, group[i : i + cap])

    def _start_packed_flight(self, geom: Geometry, cfg: SolverConfig, job: Job) -> None:
        import jax.numpy as jnp

        r = job.roots
        bucket = _bucket(len(r), 1 << 30)
        if cfg.lanes > 0:
            # Cap padding at frontier capacity: the capacity check counts the
            # padded bucket, and a resume of R valid rows must not fail just
            # because the next power of two overshoots (R itself still fits).
            capacity = cfg.lanes * (1 + cfg.stack_slots)
            bucket = min(bucket, max(capacity, len(r)))
        roots = np.zeros((bucket, geom.n, geom.n), np.uint32)
        roots[: len(r)] = r
        valid = np.arange(bucket) < len(r)
        cfg = self._fit_fused(geom, cfg, cfg.resolve_lanes_packed(bucket))
        rec = trace.active()
        if rec is not None:
            # Admission span: submit -> launch is the static queue wait.
            rec.record(
                job.uuid, "admission", "engine.launch",
                t0=job.trace_t0 if job.trace_t0 is not None else rec.now(),
                node=self.trace_node, route="static", roots=len(r),
            )
        if faults.active() is not None:
            faults.fire("engine.launch", uuids=(job.uuid,))
        state = _start_packed(jnp.asarray(roots), jnp.asarray(valid), cfg)
        self._flights.append(_Flight(geom=geom, config=cfg, jobs=[job], state=state))

    def _start_flight(self, geom: Geometry, cfg: SolverConfig, jobs: list[Job]) -> None:
        """Grid jobs only (roots jobs fly packed): one root per job."""
        import jax.numpy as jnp

        from distributed_sudoku_solver_tpu.ops.bitmask import encode_grid

        n = geom.n
        bucket = _bucket(len(jobs), max(self.max_batch, len(jobs)))
        if cfg.lanes > 0:
            # A fixed (possibly non-power-of-two) lane count is a hard cap:
            # resolve_lanes rejects more roots than lanes.
            bucket = min(bucket, cfg.lanes)
        roots = np.zeros((bucket, n, n), np.uint32)
        job_of_root = np.full(bucket, -1, np.int32)
        grids = np.stack([job.grid for job in jobs])
        roots[: len(jobs)] = np.asarray(  # syncck: allow(launch-time frontier seeding: one encode fetch at flight birth, outside the chunk loop)
            encode_grid(jnp.asarray(grids), geom), np.uint32
        )
        job_of_root[: len(jobs)] = np.arange(len(jobs), dtype=np.int32)
        cfg = self._fit_fused(geom, cfg, cfg.resolve_lanes(bucket))
        rec = trace.active()
        if rec is not None:
            now = rec.now()
            for job in jobs:
                rec.record(
                    job.uuid, "admission", "engine.launch",
                    t0=job.trace_t0 if job.trace_t0 is not None else now,
                    t1=now, node=self.trace_node, route="static",
                    config_override=job.config is not None,
                )
        if faults.active() is not None:
            faults.fire("engine.launch", uuids=tuple(j.uuid for j in jobs))
        state = _start_roots(
            jnp.asarray(roots), jnp.asarray(job_of_root), bucket, cfg
        )
        self._flights.append(_Flight(geom=geom, config=cfg, jobs=jobs, state=state))

    def _advance_flight(self, fl: _Flight) -> bool:
        """One pipelined flight-loop pass; returns True when the flight is done.

        The always-ahead contract (round 8): every pass DISPATCHES chunk
        k+1 (async — the in-graph step limit means the host needs nothing
        from chunk k to do so) and then consumes chunk k's packed status
        word in ONE host sync (``host_fetch``).  The device therefore
        always has the next chunk enqueued while the host reads, reacts,
        and schedules — host work overlaps device compute instead of
        serializing with it.  The cost is a one-chunk reaction lag:
        cancels, deadlines, solved-job resolution, and flight retirement
        act on chunk k's status while chunk k+1 already runs (the same
        granularity spirit as the chunk-boundary purge — bounded by
        ``chunk_steps``, and the wasted trailing dispatch on a finished
        frontier is an in-graph no-op because its while-loop condition is
        already false).
        """
        import jax.numpy as jnp

        from distributed_sudoku_solver_tpu.ops.frontier import unpack_status

        # Tracing guard (obs/trace.py): disabled = this one read + branches
        # on `rec is not None` — no clock reads, no uuid tuples, no span
        # dicts.  Enabled, every span is built from host-side values the
        # loop already holds: tracing adds ZERO host syncs, which the
        # fetch-count guard enforces by running with tracing on.
        rec = trace.active()
        tr0 = rec.now() if rec is not None else 0.0
        live_uuids = ()  # the shared empty tuple: no per-chunk allocation
        t_pass = self._clock()
        # Mid-flight cancellation + deadline expiry: purge the jobs' lanes
        # in-graph (async dispatch — the purge rides the device queue ahead
        # of the next chunk).  Deadlines are engine-wide wall-clock
        # semantics (a job that falls back from a saturated resident flight
        # keeps its guarantee here), enforced at chunk granularity like
        # cancels; both need only host-side data, so they never wait on a
        # status fetch.
        now = self._clock()
        cancel_idx = self._peek_cancels(fl.jobs)
        expire_idx = [
            i
            for i, j in enumerate(fl.jobs)
            if not j.done.is_set()
            and i not in cancel_idx
            and j.deadline is not None
            and now > j.deadline
        ]
        if cancel_idx or expire_idx:
            # The frontier's job dimension is the padded power-of-two
            # bucket (see _start_flight), not len(fl.jobs).
            dead = np.zeros(fl.state.solved.shape[0], bool)
            dead[cancel_idx + expire_idx] = True
            fl.state = _purge(fl.state, jnp.asarray(dead))
            for i in cancel_idx:
                job = fl.jobs[i]
                if self._consume_cancel(job):
                    job.cancelled = True
                self._finish_job(job)
            for i in expire_idx:
                job = fl.jobs[i]
                job.error = "deadline expired"
                self._finish_job(job)
        # Dispatch chunk k+1 BEFORE consuming chunk k's status.  Both
        # advance programs donate the input frontier (zero state copies)
        # and compute their step limit in-graph, so this call returns as
        # soon as the work is enqueued.
        if fl.config.step_impl == "fused":
            # The whole-round VMEM kernel advances the same Frontier in
            # fused_steps-quantized chunks; purge/cancel/shed and the
            # finalize below are impl-agnostic (VERDICT r3 #1).
            from distributed_sudoku_solver_tpu.ops.pallas_step import (
                advance_frontier_fused_status as _advance,
            )
        else:
            from distributed_sudoku_solver_tpu.utils.checkpoint import (
                advance_frontier_status as _advance,
            )

        if faults.active() is not None:  # don't build uuid tuples per chunk
            faults.fire(
                "engine.advance",
                uuids=tuple(j.uuid for j in fl.jobs if not j.done.is_set()),
            )
        fl.state, status_dev = _advance(
            fl.state, jnp.int32(self.chunk_steps), fl.geom, fl.config
        )
        fl.chunks += 1
        prev_status = fl.pending_status
        fl.pending_status = status_dev
        dispatch_s = self._clock() - t_pass
        self.dispatch_wall.record(dispatch_s)
        self.hist["dispatch_wall_ms"].record(dispatch_s)
        if rec is not None:
            live_uuids = [j.uuid for j in fl.jobs if not j.done.is_set()]
            rec.record(
                None, "chunk.dispatch", "engine.advance", tr0,
                node=self.trace_node, uuids=live_uuids, chunk=fl.chunks,
                geometry=f"{fl.geom.n}x{fl.geom.n}",
            )
        cw = compilewatch.active()
        if cw is not None and fl.chunks == 1:
            # The cost-plane seam (obs/compilewatch.py): once per
            # (program, shape) EVER — the dedupe key bounds the lowering,
            # and the flight-birth guard bounds even the key construction
            # to one per flight, never per chunk.  ``.lower()`` re-traces
            # on the host (aval shapes only — it reads no device buffer,
            # so the one-sync-per-chunk guard stays green) and prices the
            # program via HLO cost analysis; no backend compile runs, so
            # the watch's own compile listener hears nothing.
            prog = (
                compilewatch.ADVANCE_FUSED_STATUS
                if fl.config.step_impl == "fused"
                else compilewatch.ADVANCE_STATUS
            )
            # .shape is host-side metadata (a tuple of ints, no sync).
            lanes = fl.state.has_top.shape[0]
            cw.capture_cost(
                prog,
                (fl.geom.n, lanes, fl.config.stack_slots, fl.config.step_impl),
                lambda: _advance.lower(
                    fl.state, jnp.int32(self.chunk_steps), fl.geom, fl.config
                ),
                geometry=f"{fl.geom.n}x{fl.geom.n}",
                lanes=lanes,
                chunk_steps=self.chunk_steps,
            )
        if prev_status is None:
            # Newborn flight: chunk 0 is in the device queue and the loop
            # moves on — the flight is a full dispatch ahead from birth.
            return False
        # The chunk's single host sync.  The status word is sized by the
        # frontier's padded job dimension (the bucket), not len(fl.jobs) —
        # padding rows are never seeded, so their bits stay False.
        tr1 = rec.now() if rec is not None else 0.0
        t_sync = self._clock()
        info = unpack_status(
            host_fetch(prev_status, floor_s=self.handicap_s),
            fl.state.solved.shape[0],
        )
        sync_s = self._clock() - t_sync
        self.sync_wall.record(sync_s)
        self.hist["sync_wall_ms"].record(sync_s)
        self.rpc_floor.record(sync_s)
        if rec is not None:
            rec.record(
                None, "chunk.sync", "fetch.status", tr1,
                node=self.trace_node, uuids=live_uuids,
                steps=int(info["steps"]),
            )
        wall = self._clock() - t_pass
        self.chunk_wall.record(wall)
        self._chunk_wall_total += wall
        steps_delta = info["steps"] - fl.steps_seen
        fl.steps_seen = info["steps"]
        self._chunk_steps_total += steps_delta
        if fl.config.step_impl == "fused" and steps_delta > 0:
            # The in-graph occupancy histogram rides the status word — the
            # old host-side bincount over two full lane_rounds fetches per
            # chunk is gone (round 8 satellite).
            self._occ_hist += info["hist"]
            lanes = fl.state.has_top.shape[0]
            self._occ_frac_sum += info["live_sum"] / float(lanes * steps_delta)
            self._occ_chunks += 1
        out_of_budget = info["steps"] >= fl.config.max_steps
        if info["has_work"].any() and not out_of_budget:
            # Early per-job resolution: a solved job's waiter unblocks at
            # the next status consumption, not when the whole flight
            # drains.  Solved-job rows are frozen in-graph (the lanes are
            # purged the round the job resolves), so reading them from the
            # already-dispatched chunk k+1 state is exact.
            solved = info["solved"]
            newly = [
                i
                for i, job in enumerate(fl.jobs)
                if solved[i] and not job.done.is_set()
            ]
            if newly:
                self._resolve_solved(fl, newly)
            return False
        res = _finalize_jit(fl.state)
        fl.state = None
        fl.pending_status = None
        tr_ev = rec.now() if rec is not None else 0.0
        t_ev = self._clock()
        solutions, unsat, nodes, solved, sol_counts = host_fetch(
            (res.solution, res.unsat, res.nodes, res.solved, res.sol_count),
            floor_s=self.handicap_s,
            tag="finalize",
        )
        fin_s = self._clock() - t_ev
        self.event_wall.record(fin_s)
        self.hist["event_wall_ms"].record(fin_s)
        if rec is not None:
            rec.record(
                None, "finalize.sync", "fetch.finalize", tr_ev,
                node=self.trace_node, uuids=live_uuids,
            )
        for i, job in enumerate(fl.jobs):
            if job.done.is_set():
                continue
            job.solved = bool(solved[i])
            job.exhausted = bool(unsat[i])
            job.unsat = job.exhausted and job.shed_parts == 0
            job.nodes = int(nodes[i])
            job.sol_count = int(sol_counts[i])
            if job.solved or job.sol_count > 0:
                # count_all enumerations keep `solved` False by design but
                # still carry the first-found solution.
                job.solution = solutions[i]
            if self._consume_cancel(job):
                job.cancelled = True
            self._finish_job(job)
        self.batch_sizes.record(float(len(fl.jobs)))
        return True

    def _resolve_solved(self, fl: _Flight, idx: list) -> None:
        """ONE batched event fetch for every job that solved this chunk —
        ten jobs solving together must not pay ten serialized RPC floors
        (the resident path's ``_verdict_jit`` is the same shape).

        Two deliberate trade-offs, both bounded to resolution chunks:
        ``fl.state`` here is the chunk dispatched THIS pass, so the fetch
        waits out that chunk's device wall (solved rows are frozen
        in-graph, so the values are exact; the device is busy on exactly
        the awaited chunk, never idle) — recorded in ``event_wall`` so the
        dispatch/sync split cannot hide it.  And the payload ships the
        whole padded bucket's decoded grids rather than a gather of the
        solved rows: one stable compiled shape, ~83 KB at a full 256-job
        9x9 bucket (under one RPC floor through the tunnel); a static-K
        in-graph gather is the upgrade path if giant-geometry buckets
        ever serve interactively."""
        rec = trace.active()
        tr_ev = rec.now() if rec is not None else 0.0
        t_ev = self._clock()
        solutions, nodes = host_fetch(
            _flight_verdict_jit(fl.state),
            floor_s=self.handicap_s,
            tag="event",
        )
        ev = self._clock() - t_ev
        self.event_wall.record(ev)
        self.hist["event_wall_ms"].record(ev)
        if rec is not None:
            rec.record(
                None, "verdict.sync", "fetch.event", tr_ev,
                node=self.trace_node,
                uuids=[fl.jobs[i].uuid for i in idx],
            )
        # This fetch blocked out chunk k+1's device wall; without this the
        # step_wall_ms_avg numerator misses exactly the chunks that
        # resolved jobs (their steps still land in _chunk_steps_total at
        # the next status consumption) and reads the device too fast.
        self._chunk_wall_total += ev
        for i in idx:
            job = fl.jobs[i]
            job.solved = True
            job.solution = np.asarray(solutions[i], np.int32)
            job.nodes = int(nodes[i])
            self._finish_job(job)

    def _finish_job(self, job: Job) -> None:
        if job.shadow:
            # Accounting-invisible resolution (see Job.shadow): verdict
            # fields are already set; fire the hook and release waiters,
            # touch no counter/histogram/SLO — the race that submitted
            # this job accounts the user's ONE request itself.
            cb = job.on_resolve
            if cb is not None:
                job.on_resolve = None
                try:
                    cb(job)
                except Exception:  # noqa: BLE001
                    _LOG.exception(
                        "[engine] on_resolve hook failed for %s", job.uuid
                    )
            job.done.set()
            return
        wall = self._clock() - job.submitted_at
        self.latency.record(wall)
        # Guarded since round 19: the megastep flight resolves jobs on
        # submit/handler threads, so these counters are no longer
        # single-writer on the device loop.  _finish_job runs with no
        # lock held (both callers' contract), so taking rank 30 here
        # nests under nothing.
        with self._lock:
            if job.solved:
                self.solved_count += 1
            self.validations += job.nodes
            self.jobs_done += 1
        rec = trace.active()
        # Histogram exemplar (the uuid linking a slow bucket to its
        # stitched trace) only when a recorder is installed — the
        # untraced path passes the None default, allocating nothing.
        self.hist["latency_ms"].record(
            wall, exemplar=job.uuid if rec is not None else None
        )
        # SLO observation seam (obs/slo.py): one global read + branch
        # when no --slo monitor is installed, like the tracer.
        mon = slo.active()
        if mon is not None:
            mon.observe(wall, error=job.error is not None, stream="job")
        if rec is not None:
            rec.event(
                job.uuid, "resolve", "engine.resolve", node=self.trace_node,
                solved=job.solved, unsat=job.unsat, cancelled=job.cancelled,
                nodes=job.nodes, error=job.error,
            )
            # Critical-path attribution (obs/critpath.py): decompose the
            # job's stitched spans into phase walls and run the slow-job
            # watchdog.  Inside the traced branch on purpose — untraced
            # serving pays nothing, and without spans there is nothing to
            # decompose.  Host-side ring scan only: zero device syncs.
            cp = critpath.active()
            if cp is not None:
                cp.observe_job(job.uuid, wall)
        ot = ordertrace.active()
        if ot is not None:
            # Device-tier outcome + sampled grid for the offline ordering
            # trainers (obs/ordertrace.py).  Front-door-owned routes
            # (cache / propagation / native race) journal at their own
            # resolution sites — this is the one place every DEVICE job
            # passes through, front-doored or not.
            ot.route(
                job.uuid, job.probe_score, job.probe_empties,
                job.route or "direct", wall * 1000.0,
                job.solved, job.unsat, job.nodes,
            )
            if job.roots is None and job.grid is not None:
                ot.grid(job.grid, job.geom.n)
        cb = job.on_resolve
        if cb is not None:
            # Front-door cache fill (serving/frontdoor): runs with the
            # verdict fields set but BEFORE the done event, so a waiter
            # that resubmits immediately sees the entry.  At most once,
            # and never allowed to kill resolution.
            job.on_resolve = None
            try:
                cb(job)
            except Exception:  # noqa: BLE001
                _LOG.exception(
                    "[engine] on_resolve hook failed for %s", job.uuid
                )
        # WAL discharge (serving/journal.py): buffered, so safe on the
        # device loop; real verdicts only (errors stay replayable).
        self._journal_resolved(job)
        job.done.set()

    # -- control requests (snapshot / shed) ----------------------------------
    def _service_controls(self) -> None:
        while True:
            try:
                req = self._control.get_nowait()
            except queue.Empty:
                return
            with req.lock:
                if req.abandoned:
                    req.done.set()
                    continue  # waiter gave up; must not mutate state for it
                req.claimed = True
            # Run OUTSIDE the lock: a long exec (bulk chunk) must not block
            # a timed-out waiter that is merely trying to record its abandon.
            try:
                if req.kind == "snapshot":
                    req.result = self._do_snapshot(req.uuid)
                elif req.kind == "shed":
                    req.result = self._do_shed(req.k)
                elif req.kind == "exec":
                    req.result = req.fn()
            except Exception as e:  # noqa: BLE001
                req.result = None
                req.error = f"{type(e).__name__}: {e}"
                _LOG.error(
                    "[engine] control %s failed: %r [%s]",
                    req.kind, e, faults.classify(e),
                )
            finally:
                req.done.set()

    def _find_flight(self, job_uuid: str):
        for fl in self._flights:
            for i, job in enumerate(fl.jobs):
                if job.uuid == job_uuid:
                    return fl, i
        return None, -1

    def _do_snapshot(self, job_uuid: str):
        fl, i = self._find_flight(job_uuid)
        if fl is None or fl.jobs[i].done.is_set():
            return None
        # One control sync for the whole frontier (a few MB at engine
        # scale): under the always-ahead loop this blocks on the in-flight
        # chunk too, so batch it and charge it at the seam rather than
        # paying ~7 stray per-array syncs outside the contract.
        st = host_fetch(fl.state, floor_s=self.handicap_s, tag="control")
        rows = _rows_of_job_host(st, i)
        if rows.shape[0] == 0:
            return None
        return (
            rows,
            int(st.nodes[i]),
            fl.jobs[i].shed_parts,
            dataclasses.asdict(fl.config),
        )

    def _do_shed(self, k: int):
        import jax.numpy as jnp

        # Neediest job: most deferred stack rows across lanes (host-side scan
        # of the small [L] vectors); shedding is rare, one sync per flight
        # is fine — but it goes through the seam (batched, tagged) because
        # under the always-ahead loop it also waits out the in-flight chunk.
        best = None  # (stack_rows, flight, job index)
        for fl in self._flights:
            if fl.config.count_all:
                # An enumeration's shed rows would be counted by the PEER
                # and aggregated nowhere — the returned model count would
                # silently miss those subtrees.  Enumerations never shed.
                continue
            jobv, countv, solvedv = host_fetch(
                (fl.state.job, fl.state.count, fl.state.solved),
                floor_s=self.handicap_s,
                tag="control",
            )
            for i, job in enumerate(fl.jobs):
                if job.done.is_set() or solvedv[i]:
                    continue
                depth = int(countv[jobv == i].sum())
                if depth >= 1 and (best is None or depth > best[0]):
                    best = (depth, fl, i)
        if best is None:
            return None
        _, fl, i = best
        new_state, rows, valid = _shed_jit(fl.state, jnp.int32(i), k)
        fl.state = new_state
        rows, valid = host_fetch(
            (rows, valid), floor_s=self.handicap_s, tag="control"
        )
        rows = rows[valid]
        if rows.shape[0] == 0:
            return None
        fl.jobs[i].shed_parts += 1
        return fl.jobs[i].uuid, rows, dataclasses.asdict(fl.config)

    # -- legacy one-dispatch path (solve_fn overrides) ------------------------
    def _solve_group(  # syncck: allow(legacy one-dispatch path: solve_fn overrides return device values and blocking fetches are its documented semantics)
        self, geom: Geometry, group: list[Job], cfg: Optional[SolverConfig] = None
    ) -> None:
        cfg = cfg or self.config
        # Respect an explicit lane cap: a fixed-lanes config can only take
        # batches up to that many jobs per compiled call.
        if cfg.lanes > 0 and len(group) > cfg.lanes:
            for i in range(0, len(group), cfg.lanes):
                self._solve_group(geom, group[i : i + cfg.lanes], cfg)
            return
        if self.handicap_s:
            # clockck: allow(slow-node simulator: the legacy solve_fn path charges its handicap per batch, by design)
            time.sleep(self.handicap_s)
        for job in group:
            if job.roots is not None:
                job.error = "roots jobs require the flight path (no solve_fn override)"
                job.done.set()
        group = [j for j in group if not j.done.is_set()]
        if not group:
            return
        n = geom.n
        bucket = _bucket(len(group), self.max_batch)
        if cfg.lanes > 0:
            bucket = min(bucket, cfg.lanes)
        grids = np.zeros((bucket, n, n), dtype=np.int32)
        for i, job in enumerate(group):
            grids[i] = job.grid
        # Padding rows hold a pre-solved board: their lanes resolve on step
        # one and immediately join the steal pool as thieves for the real
        # jobs (a replicated real grid would instead re-search it).  Masked
        # out of all stats below.
        from distributed_sudoku_solver_tpu.utils.puzzles import solved_board

        grids[len(group) :] = solved_board(geom)

        res = self._solve_fn(grids, geom, cfg)
        solved = np.asarray(res.solved)
        unsat = np.asarray(res.unsat)
        solutions = np.asarray(res.solution)
        nodes = np.asarray(res.nodes)

        # Optional field: oracle-backed test solve_fns don't produce it.
        sol_counts = np.asarray(getattr(res, "sol_count", solved.astype(np.int32)))

        now = self._clock()
        rec = trace.active()
        mon = slo.active()
        for i, job in enumerate(group):
            job.solved = bool(solved[i])
            job.unsat = bool(unsat[i])
            job.nodes = int(nodes[i])
            job.sol_count = int(sol_counts[i])
            if job.solved or job.sol_count > 0:
                job.solution = solutions[i]
            if self._consume_cancel(job):
                job.cancelled = True
            wall = now - job.submitted_at
            self.latency.record(wall)
            self.hist["latency_ms"].record(
                wall, exemplar=job.uuid if rec is not None else None
            )
            if mon is not None:
                mon.observe(wall, error=job.error is not None, stream="job")
            if rec is not None:
                rec.event(
                    job.uuid, "resolve", "engine.resolve",
                    node=self.trace_node, solved=job.solved,
                    unsat=job.unsat, cancelled=job.cancelled,
                    nodes=job.nodes, error=job.error,
                )
                cp = critpath.active()
                if cp is not None:
                    cp.observe_job(job.uuid, wall)
            # Same hook contract as _finish_job: verdict fields set, fired
            # at most once, BEFORE the done event (a waiter that resubmits
            # immediately must see the front-door cache fill), and never
            # allowed to kill resolution.  Without this, solve_fn engines
            # (the whole simnet/oracle lane) silently skip every
            # device-route cache fill the flight path performs.
            cb = job.on_resolve
            if cb is not None:
                job.on_resolve = None
                try:
                    cb(job)
                except Exception:  # noqa: BLE001
                    _LOG.exception(
                        "[engine] on_resolve hook failed for %s", job.uuid
                    )
            self._journal_resolved(job)  # WAL discharge, as in _finish_job
            job.done.set()
        self.batch_sizes.record(float(len(group)))
        with self._lock:  # shared with megastep-thread resolutions since round 19
            self.validations += int(nodes[: len(group)].sum())
            self.solved_count += int(solved[: len(group)].sum())
            self.jobs_done += len(group)


# -- jitted helpers (module-level so the cache is shared across engines) ------
@functools.partial(jax.jit, static_argnames=("n_jobs", "config"))
def _start_roots(roots, job_of_root, n_jobs: int, config: SolverConfig) -> Frontier:
    from distributed_sudoku_solver_tpu.ops.frontier import init_frontier_roots

    return init_frontier_roots(roots, job_of_root, n_jobs, config)


@functools.partial(jax.jit, static_argnames=("config",))
def _start_packed(roots, valid, config: SolverConfig) -> Frontier:
    from distributed_sudoku_solver_tpu.ops.frontier import init_frontier_packed

    return init_frontier_packed(roots, valid, config)


# Every frontier-threading program donates its input state (round 8): the
# engine always rebinds (`fl.state = _purge(fl.state, ...)`), so the old
# buffers alias the new ones instead of costing a full-frontier HBM copy
# per dispatch.  Donation never changes values (pinned by the donated-vs-
# undonated A/B tests), only buffer ownership.
@functools.partial(jax.jit, donate_argnums=(0,))
def _purge(state: Frontier, dead) -> Frontier:
    from distributed_sudoku_solver_tpu.ops.frontier import purge_jobs

    return purge_jobs(state, dead)


@functools.partial(jax.jit, static_argnames=("k",), donate_argnums=(0,))
def _shed_jit(state: Frontier, job_id, k: int):
    from distributed_sudoku_solver_tpu.ops.frontier import shed_rows

    return shed_rows(state, job_id, k)


@jax.jit
def _flight_verdict_jit(state: Frontier):
    """Resolution-chunk verdict payload (decoded grids + node counts) as
    one compiled program — the static-flight twin of the scheduler's
    ``_verdict_jit``.  NOT donated: the flight state lives on."""
    from distributed_sudoku_solver_tpu.ops.bitmask import decode_grid

    return decode_grid(state.solution), state.nodes


@functools.partial(jax.jit, donate_argnums=(0,))
def _finalize_jit(state: Frontier):
    """Terminal drain — the caller drops the flight state right after."""
    from distributed_sudoku_solver_tpu.ops.solve import _finalize

    return _finalize(state)


def _rows_of_job_host(state: Frontier, job_index: int) -> np.ndarray:  # syncck: allow(callers pass a host_fetch-ed frontier; the asarray calls are numpy no-ops on host data)
    """All surviving subtree roots of one job: its lanes' tops + stack rows.

    Host-side numpy gather (engine-scale frontiers are a few MB); the result
    re-seeds an equivalent search via ``init_frontier_roots`` — this is both
    the progress-checkpoint payload and the offload wire format.
    """
    top = np.asarray(state.top)
    has_top = np.asarray(state.has_top)
    stack = np.asarray(state.stack)
    base = np.asarray(state.base)
    count = np.asarray(state.count)
    job = np.asarray(state.job)
    s = stack.shape[1]
    rows = []
    for lane in np.nonzero(job == job_index)[0]:
        if has_top[lane]:
            rows.append(top[lane])
        for i in range(int(count[lane])):
            rows.append(stack[lane, (int(base[lane]) + i) % s])
    if not rows:
        return np.zeros((0,) + top.shape[1:], np.uint32)
    return np.stack(rows).astype(np.uint32)
