"""Host-side job engine: an async queue feeding batched device solves.

Replaces the reference's per-node `task_queue` + busy-poll `/solve` plumbing
(``/root/reference/DHT_Node.py:35,225-250,553-554``) with a single-owner
device loop (SURVEY.md §5.2: device state has exactly one driving thread, so
there is none of the reference's unlocked cross-thread mutation):

* **submit** enqueues a uuid-tagged job and returns immediately; callers wait
  on the job's event (no 10 ms busy-poll — a real `threading.Event`).
* **the device loop** drains the queue, groups jobs by geometry, pads each
  group to a bucketed batch size (bounding jit cache growth), and runs the
  compiled frontier solve; results resolve each job's event.
* **cancel** is the SOLUTION_FOUND purge at host level: a cancelled uuid is
  dropped from the queue, or its result discarded if already in flight
  (in-graph cancellation between concurrent jobs lives in the frontier
  itself, ``ops/frontier.py``).
* **stats** mirrors the reference's counters: ``validations`` = branch nodes
  expanded (``/root/reference/DHT_Node.py:512-513`` analog), ``solved_count``
  (``:37,428``).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
import uuid as uuid_mod
from typing import Optional

import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import Geometry, geometry_for_size
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.ops.solve import solve_batch


@dataclasses.dataclass
class Job:
    """One `/solve` request travelling through the engine."""

    uuid: str
    grid: np.ndarray
    geom: Geometry
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    solution: Optional[np.ndarray] = None
    solved: bool = False
    unsat: bool = False
    nodes: int = 0
    cancelled: bool = False
    error: Optional[str] = None
    submitted_at: float = dataclasses.field(default_factory=time.monotonic)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)


def _bucket(n: int, max_batch: int) -> int:
    """Smallest power-of-two >= n (capped): one jit entry per bucket, not per J."""
    b = 1
    while b < n and b < max_batch:
        b *= 2
    return b


class SolverEngine:
    """Single-owner device loop consuming a thread-safe job queue."""

    def __init__(
        self,
        config: SolverConfig = SolverConfig(),
        max_batch: int = 256,
        batch_window_s: float = 0.002,
        solve_fn=None,
    ):
        self.config = config
        self.max_batch = max_batch
        self.batch_window_s = batch_window_s
        self._solve_fn = solve_fn or (
            lambda grids, geom, cfg: solve_batch(grids, geom, cfg)
        )
        from distributed_sudoku_solver_tpu.utils.profiling import StatWindow

        self.latency = StatWindow()  # seconds per job
        self.batch_sizes = StatWindow()  # jobs per device batch
        self._queue: "queue.Queue[Job]" = queue.Queue()
        # Insertion-ordered so stale entries (cancels for jobs that already
        # finished or never arrive) can be pruned oldest-first.
        self._cancelled: "dict[str, None]" = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Counters (single-writer: the device loop; readers tolerate staleness).
        self.validations = 0
        self.solved_count = 0
        self.jobs_done = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "SolverEngine":
        self._thread = threading.Thread(target=self._run, daemon=True, name="device-loop")
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    # -- client API ----------------------------------------------------------
    def submit(self, grid, geom: Optional[Geometry] = None, job_uuid: Optional[str] = None) -> Job:
        g = np.asarray(grid, dtype=np.int32)
        geom = geom or geometry_for_size(g.shape[0])
        if g.shape != (geom.n, geom.n):
            raise ValueError(f"grid shape {g.shape} does not match geometry {geom}")
        job = Job(uuid=job_uuid or str(uuid_mod.uuid4()), grid=g, geom=geom)
        self._queue.put(job)
        return job

    def cancel(self, job_uuid: str) -> None:
        with self._lock:
            self._cancelled[job_uuid] = None
            while len(self._cancelled) > 4096:  # stale-cancel bound
                self._cancelled.pop(next(iter(self._cancelled)))

    def stats(self) -> dict:
        return {
            "validations": int(self.validations),
            "solved": int(self.solved_count),
            "jobs_done": int(self.jobs_done),
        }

    def metrics(self) -> dict:
        """Extended observability (GET /metrics): latency percentiles over
        the last ~1k jobs, batch sizes, and the base counters."""
        out = dict(self.stats())
        lat = self.latency.snapshot()
        if lat:
            out["job_latency_ms"] = {
                "count": lat["count"],
                **{k: round(lat[k] * 1e3, 3) for k in ("p50", "p95", "p99")},
            }
        bs = self.batch_sizes.snapshot()
        if bs:
            out["batch_jobs"] = {
                "count": bs["count"],
                **{k: round(bs[k], 1) for k in ("p50", "p95")},
            }
        return out

    # -- device loop ---------------------------------------------------------
    def _take_batch(self) -> list[Job]:
        try:
            first = self._queue.get(timeout=0.05)
        except queue.Empty:
            return []
        jobs = [first]
        deadline = time.monotonic() + self.batch_window_s
        while len(jobs) < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                jobs.append(self._queue.get(timeout=remaining))
            except queue.Empty:
                break
        return jobs

    def _consume_cancel(self, job: Job) -> bool:
        with self._lock:
            return self._cancelled.pop(job.uuid, "absent") is None

    def _run(self) -> None:
        while not self._stop.is_set():
            jobs = self._take_batch()
            if not jobs:
                continue
            live: list[Job] = []
            for job in jobs:
                if self._consume_cancel(job):
                    job.cancelled = True
                    job.done.set()
                else:
                    live.append(job)
            # Group by geometry: one compiled program per (bucket, geometry).
            by_geom: dict[Geometry, list[Job]] = {}
            for job in live:
                by_geom.setdefault(job.geom, []).append(job)
            for geom, group in by_geom.items():
                # The device loop must survive anything a batch throws
                # (compile error, bad config, OOM): fail the batch's jobs,
                # keep serving — a dead loop would strand every later job.
                try:
                    self._solve_group(geom, group)
                except Exception as e:  # noqa: BLE001
                    for job in group:
                        if not job.done.is_set():
                            job.error = f"{type(e).__name__}: {e}"
                            job.done.set()
                    print(f"[engine] batch failed ({geom}): {e!r}")

    def _solve_group(self, geom: Geometry, group: list[Job]) -> None:
        # Respect an explicit lane cap: a fixed-lanes config can only take
        # batches up to that many jobs per compiled call.
        if self.config.lanes > 0 and len(group) > self.config.lanes:
            for i in range(0, len(group), self.config.lanes):
                self._solve_group(geom, group[i : i + self.config.lanes])
            return
        n = geom.n
        bucket = _bucket(len(group), self.max_batch)
        if self.config.lanes > 0:
            bucket = min(bucket, self.config.lanes)
        grids = np.zeros((bucket, n, n), dtype=np.int32)
        for i, job in enumerate(group):
            grids[i] = job.grid
        # Padding rows hold a pre-solved board: their lanes resolve on step
        # one and immediately join the steal pool as thieves for the real
        # jobs (a replicated real grid would instead re-search it).  Masked
        # out of all stats below.
        from distributed_sudoku_solver_tpu.utils.puzzles import solved_board

        grids[len(group) :] = solved_board(geom)

        res = self._solve_fn(grids, geom, self.config)
        solved = np.asarray(res.solved)
        unsat = np.asarray(res.unsat)
        solutions = np.asarray(res.solution)
        nodes = np.asarray(res.nodes)

        now = time.monotonic()
        for i, job in enumerate(group):
            job.solved = bool(solved[i])
            job.unsat = bool(unsat[i])
            job.nodes = int(nodes[i])
            if job.solved:
                job.solution = solutions[i]
            if self._consume_cancel(job):
                job.cancelled = True
            self.latency.record(now - job.submitted_at)
            job.done.set()
        self.batch_sizes.record(float(len(group)))
        self.validations += int(nodes[: len(group)].sum())
        self.solved_count += int(solved[: len(group)].sum())
        self.jobs_done += len(group)
