"""Portfolio racing: R solver configs per job, first verdict wins.

The full expert-parallel analog (SURVEY.md §2.2 EP row; VERDICT r1 #10):
where the reference can only ever run its one recursive strategy, a job
here races heterogeneous strategies — branch heuristics (MRV vs reference
order), digit order (ascending vs descending), propagation strength — as
concurrent flights on one engine.  The engine's round-robin chunk loop is
the scheduler; the first racer to reach a *verdict* (solved or proven
unsat — all configs are sound, so any verdict is final) cancels the rest,
exactly the SOLUTION_FOUND purge between racers
(``/root/reference/DHT_Node.py:348-387``) instead of between peers.

DFS order is a classic heavy-tailed lottery: a unique solution living in
high digits is reached orders of magnitude faster descending than
ascending.  min-over-configs of a heavy-tailed cost beats every fixed
config over a board family, which ``tests/test_portfolio.py`` pins down.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Sequence

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.serving.engine import Job, SolverEngine

#: A sensible default portfolio: the two digit orders hedge each other's
#: worst case; the reference-order racer adds cell-choice diversity; the
#: fused racer (round 4 — engine flights accept step_impl='fused') adds a
#: step-engine axis: it advances rounds ~2.4x faster per chunk where the
#: geometry + stack fit the kernel's measured compile boundaries (9x9 at
#: these settings — whole-array tiles compile to S=48 there; at 16x16
#: the measured whole-array cap is S=20, outside this racer's S=32,
#: matching an observed scoped-VMEM compile OOM at exactly that shape),
#: while the composite racers keep exact per-round purge/steal
#: reactivity.  Wherever the kernel cannot serve,
#: the engine downgrades the fused racer's flight to the composite step at
#: launch (counted as ``fused_downgrades`` on ``/metrics``) and it races on
#: as a fourth composite entrant — never erroring, never blocking a winner
#: (tests/test_portfolio.py).
DEFAULT_PORTFOLIO: tuple[SolverConfig, ...] = (
    SolverConfig(branch="minrem"),
    SolverConfig(branch="minrem-desc"),
    SolverConfig(branch="first"),
    SolverConfig(branch="minrem", step_impl="fused", fused_steps=4, stack_slots=32),
)


@dataclasses.dataclass
class PortfolioResult:
    winner: Optional[Job]  # first racer with a verdict; None if none got one
    winner_index: int  # index into `configs` (-1 if no winner)
    jobs: list  # every racer's Job, same order as `configs`
    duration_s: float
    # With winner=None these disambiguate: timed_out=True means the deadline
    # expired with racers still running (retryable); False means every racer
    # resolved without a verdict (permanent budget/overflow failure).
    timed_out: bool = False
    strategy: Optional[str] = None  # winning config's branch rule


def race_jobs(
    jobs: list,
    cancel,
    timeout: Optional[float] = None,
    start: Optional[float] = None,
) -> PortfolioResult:
    """First-verdict-wins over already-submitted racer jobs.

    ``cancel(uuid)`` is called for every loser still running — on an engine
    that is :meth:`SolverEngine.cancel` (mid-flight purge within one chunk),
    on a cluster node it is :meth:`ClusterNode.cancel` (local purge + CANCEL
    to the executing member, which also fans out to any shed parts).

    Short-interval poll over the racers' events: verdicts arrive at chunk
    granularity (>= ms), so a 10 ms poll adds no meaningful latency and no
    per-race thread churn.
    """
    start = time.monotonic() if start is None else start
    deadline = None if timeout is None else start + timeout
    winner, winner_index = None, -1
    timed_out = False
    while winner is None:
        if deadline is not None and time.monotonic() >= deadline:
            timed_out = True
            break
        for i, job in enumerate(jobs):
            if job.done.is_set() and (job.solved or job.unsat):
                winner, winner_index = job, i
                break
        if winner is None:
            if all(j.done.is_set() for j in jobs):
                break  # every racer resolved without a verdict (budget/overflow)
            time.sleep(0.01)
    for job in jobs:
        if job is not winner and not job.done.is_set():
            cancel(job.uuid)
    return PortfolioResult(
        winner=winner,
        winner_index=winner_index,
        jobs=jobs,
        duration_s=time.monotonic() - start,
        timed_out=timed_out,
    )


def race(
    engine: SolverEngine,
    grid,
    configs: Sequence[SolverConfig] = DEFAULT_PORTFOLIO,
    geom: Optional[Geometry] = None,
    timeout: Optional[float] = None,
) -> PortfolioResult:
    """Race ``configs`` on one board; cancel the losers on the first verdict.

    Every racer is an ordinary engine job with a per-job config override, so
    races interleave with regular traffic and inherit mid-flight
    cancellation: losers release the device within one chunk.
    """
    if not configs:
        raise ValueError("portfolio needs at least one config")
    start = time.monotonic()
    jobs = []
    try:
        for cfg in configs:
            jobs.append(engine.submit(grid, geom=geom, config=cfg, job_uuid=None))
    except BaseException:
        # A mid-list rejection (e.g. a config the engine refuses) must not
        # strand the already-submitted racers searching with no waiter.
        for j in jobs:
            engine.cancel(j.uuid)
        raise
    res = race_jobs(jobs, cancel=engine.cancel, timeout=timeout, start=start)
    if res.winner is not None:
        res.strategy = configs[res.winner_index].branch
    return res
