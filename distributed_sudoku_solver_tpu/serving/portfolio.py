"""Portfolio racing: R solver configs per job, first verdict wins.

The full expert-parallel analog (SURVEY.md §2.2 EP row; VERDICT r1 #10):
where the reference can only ever run its one recursive strategy, a job
here races heterogeneous strategies — branch heuristics (MRV vs reference
order), digit order (ascending vs descending), propagation strength — as
concurrent flights on one engine.  The engine's round-robin chunk loop is
the scheduler; the first racer to reach a *verdict* (solved or proven
unsat — all configs are sound, so any verdict is final) cancels the rest,
exactly the SOLUTION_FOUND purge between racers
(``/root/reference/DHT_Node.py:348-387``) instead of between peers.

DFS order is a classic heavy-tailed lottery: a unique solution living in
high digits is reached orders of magnitude faster descending than
ascending.  min-over-configs of a heavy-tailed cost beats every fixed
config over a board family, which ``tests/test_portfolio.py`` pins down.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import threading
import time
from typing import Callable, Optional, Sequence

import jax

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.obs import lockdep
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.serving.engine import Job, SolverEngine

#: A sensible default portfolio: the two digit orders hedge each other's
#: worst case; the reference-order racer adds cell-choice diversity; the
#: fused racer (round 4 — engine flights accept step_impl='fused') adds a
#: step-engine axis: it advances rounds ~2.4x faster per chunk where the
#: geometry + stack fit the kernel's measured compile boundaries (9x9 at
#: these settings — whole-array tiles compile to S=48 there; at 16x16
#: the measured whole-array cap is S=20, outside this racer's S=32,
#: matching an observed scoped-VMEM compile OOM at exactly that shape),
#: while the composite racers keep exact per-round purge/steal
#: reactivity.  Wherever the kernel cannot serve,
#: the engine downgrades the fused racer's flight to the composite step at
#: launch (counted as ``fused_downgrades`` on ``/metrics``) and it races on
#: as a fourth composite entrant — never erroring, never blocking a winner
#: (tests/test_portfolio.py).
DEFAULT_PORTFOLIO: tuple[SolverConfig, ...] = (
    SolverConfig(branch="minrem"),
    SolverConfig(branch="minrem-desc"),
    SolverConfig(branch="first"),
    SolverConfig(branch="minrem", step_impl="fused", fused_steps=4, stack_slots=32),
)


@dataclasses.dataclass
class PortfolioResult:
    winner: Optional[Job]  # first racer with a verdict; None if none got one
    winner_index: int  # index into `configs` (-1 if no winner)
    jobs: list  # every racer's Job, same order as `configs`
    duration_s: float
    # With winner=None these disambiguate: timed_out=True means the deadline
    # expired with racers still running (retryable); False means every racer
    # resolved without a verdict (permanent budget/overflow failure).
    timed_out: bool = False
    strategy: Optional[str] = None  # winning config's branch rule


def race_jobs(
    jobs: list,
    cancel,
    timeout: Optional[float] = None,
    start: Optional[float] = None,
    clock: Callable[[], float] = time.monotonic,
) -> PortfolioResult:
    """First-verdict-wins over already-submitted racer jobs.

    ``cancel(uuid)`` is called for every loser still running — on an engine
    that is :meth:`SolverEngine.cancel` (mid-flight purge within one chunk),
    on a cluster node it is :meth:`ClusterNode.cancel` (local purge + CANCEL
    to the executing member, which also fans out to any shed parts).

    Short-interval poll over the racers' events: verdicts arrive at chunk
    granularity (>= ms), so a 10 ms poll adds no meaningful latency and no
    per-race thread churn.  The poll pace is a bounded Event.wait yield
    (the simnet lane's blessed idiom), never ``time.sleep``; ``clock``
    (injected; default = real monotonic, bound at import) times the
    deadline and the result, and ``start`` — when the caller began
    submitting — must be a reading of the SAME clock.
    """
    start = clock() if start is None else start
    deadline = None if timeout is None else start + timeout
    winner, winner_index = None, -1
    timed_out = False
    pacer = threading.Event()  # never set: wait() is a bounded real yield
    while winner is None:
        if deadline is not None and clock() >= deadline:
            timed_out = True
            break
        for i, job in enumerate(jobs):
            if job.done.is_set() and (job.solved or job.unsat):
                winner, winner_index = job, i
                break
        if winner is None:
            if all(j.done.is_set() for j in jobs):
                break  # every racer resolved without a verdict (budget/overflow)
            pacer.wait(0.01)
    for job in jobs:
        if job is not winner and not job.done.is_set():
            cancel(job.uuid)
    return PortfolioResult(
        winner=winner,
        winner_index=winner_index,
        jobs=jobs,
        duration_s=clock() - start,
        timed_out=timed_out,
    )


_LOG = logging.getLogger(__name__)


def race_native(
    engine: SolverEngine,
    job: Job,
    head_start_s: float = 0.5,
    on_verdict: Optional[Callable[[Job], None]] = None,
    device_fallback: bool = True,
) -> Job:
    """Race the native C++ DFS against a *delayed* device fallback on one
    pre-built job — the find-one twin of :func:`race_cover`, and the seam
    the front door's easy tier routes through (``serving/frontdoor``).

    First verdict wins, same contract as :func:`race_jobs`:

    * **native**: ``native.solve`` on a daemon thread — the measured
      winner on easy boards, where the device path's dispatch floor
      dwarfs the search itself.  A native *decline* (no compiler,
      malformed grid) releases the fallback immediately.  Like the cover
      race, a losing native entrant cannot be interrupted mid-recursion:
      it finishes in the background and its verdict is discarded.
    * **device fallback**: waits ``head_start_s`` (or the native
      entrant's settle, whichever first), then submits the board to the
      engine as a *shadow* job (accounting-invisible — the race's hook
      counts the one user request exactly once whichever entrant wins)
      under the SAME uuid — so a caller-side ``engine.cancel(job.uuid)``
      (the HTTP timeout path) reaches the fallback flight — bypassing
      the front door (``frontdoor=False``: the race IS the front door's
      native tier; re-entering it would loop) and inheriting the outer
      job's absolute deadline.  A native win mid-flight cancels the
      fallback; a fallback resolution (including a cancellation or an
      expired deadline) resolves the job if the native entrant has not
      already.

    ``on_verdict`` (the front door's accounting + cache-fill hook) runs
    on the winning entrant's thread for EVERY resolution, with the
    verdict fields set, BEFORE the job's done event — a waiter that
    resubmits the moment it wakes sees the cache already filled.
    ``job.route`` tells it which entrant won ('native' or 'device').  No
    clock reads here: the deadline/latency math belongs to the caller,
    and the head start is a bounded ``Event.wait`` yield (the
    simnet-blessed idiom).

    ``device_fallback=False`` (brownout stage 1, ``serving/brownout.py``)
    runs the race **native-only**: the device shadow is never submitted,
    reclaiming its device lanes for the hard tail.  A backstop thread
    still settles the job if the native entrant declines or dies (a
    500-able error — rare by construction, since the front door only
    suppresses the fallback when ``native.available()`` held at boot).
    """
    # The settle lock guards ONLY the winner claim: the claiming thread
    # then fills the job, runs the verdict hook, and sets the done event
    # lock-free (single writer; the event's set is the release barrier),
    # so no other lock is ever acquired under it — it stays a leaf in the
    # deadck hierarchy whatever the hook touches.
    settle = lockdep.named_lock("frontdoor.race")  # lockck: name(frontdoor.race)
    claimed = [False]
    native_settled = threading.Event()
    device_submitted = threading.Event()

    def _finish(route, solved=False, solution=None, unsat=False, nodes=0,
                error=None, cancelled=False) -> bool:
        import numpy as np

        with settle:
            if claimed[0]:
                return False
            claimed[0] = True
        job.route = route
        job.solved = bool(solved)
        job.unsat = bool(unsat)
        # unsat here is always a COMPLETE proof (the native DFS ran its
        # space dry, or the device fallback's own exhaustion): mirror it
        # on `exhausted`, the field cluster finalization actually reads.
        job.exhausted = bool(unsat)
        job.cancelled = bool(cancelled)
        job.solution = (
            None if solution is None
            else np.asarray(solution, np.int32)  # syncck: allow(native DFS result — ctypes host array, no device value)
        )
        job.nodes = int(nodes)
        job.error = error
        if on_verdict is not None:
            # EVERY resolution fires the hook — the fallback runs as an
            # accounting-invisible shadow job, so this call is the one
            # place the request gets counted (the hook's cache fill
            # guards cancels/errors itself).
            try:
                on_verdict(job)
            except Exception:  # noqa: BLE001 - cache fill must not kill the race
                _LOG.exception(
                    "[portfolio] race_native verdict hook failed (%s)",
                    job.uuid,
                )
        job.done.set()
        return True

    def native_entrant() -> None:
        won = False
        try:
            try:
                from distributed_sudoku_solver_tpu import native

                if not native.available():
                    return  # decline: the fallback covers it
                sol, nodes = native.solve(job.grid, job.geom)
            except Exception:  # noqa: BLE001 - any native failure is a decline
                return
            won = _finish(
                "native", solved=sol is not None, solution=sol,
                unsat=sol is None, nodes=nodes,
            )
        finally:
            native_settled.set()
        if won and device_submitted.is_set():
            engine.cancel(job.uuid)  # release the fallback flight

    def device_entrant() -> None:
        native_settled.wait(head_start_s)
        if job.done.is_set():
            return  # native already answered inside its head start
        try:
            # shadow=True: the fallback is accounting-invisible in the
            # engine (the race's verdict hook counts the ONE request);
            # sharing the outer uuid lets caller-side cancels reach it.
            inner = engine.submit(
                job.grid, geom=job.geom, job_uuid=job.uuid,
                frontdoor=False, shadow=True,
            )
        except Exception as e:  # noqa: BLE001 - engine stopped/rejecting
            native_settled.wait()  # the native entrant always settles
            if not job.done.is_set():
                _finish("device", error=f"device fallback unavailable: {e}")
            return
        # The caller's wall-clock budget survives the hop: the fallback
        # inherits the outer job's absolute deadline (chunk-granularity
        # enforcement reads it per pass, so setting it post-submit is at
        # worst one chunk late — the documented reaction lag).  The
        # native entrant itself is uninterruptible; an expired fallback
        # resolving "deadline expired" is what bounds the caller's wait.
        if job.deadline is not None:
            inner.deadline = job.deadline
        device_submitted.set()
        if job.done.is_set():
            engine.cancel(job.uuid)  # native won during our submit window
        inner.done.wait()
        _finish(
            "device", solved=inner.solved, solution=inner.solution,
            unsat=inner.unsat, nodes=inner.nodes, error=inner.error,
            cancelled=inner.cancelled,
        )

    def backstop() -> None:
        # Native-only mode: no device shadow exists, so a native decline
        # (native_entrant returning without claiming) must still resolve
        # the job — an unresolved done event would hang its waiter.
        native_settled.wait()
        if not job.done.is_set():
            _finish(
                "native",
                error="native engine declined (device fallback suppressed "
                "by brownout stage 1)",
            )

    threading.Thread(
        target=native_entrant, daemon=True, name="frontdoor-native"
    ).start()
    threading.Thread(
        target=device_entrant if device_fallback else backstop,
        daemon=True, name="frontdoor-native-fallback",
    ).start()
    return job


#: Include the native C++ DFS as a cover-race entrant only below this row
#: count.  The measured crossover (BENCHMARKS.md round-5 cover table): the
#: native MRV DFS wins small trees outright (n-queens-12: 0.108 s native vs
#: 0.409 s device — dispatch floors dominate under ~1M nodes) and loses from
#: n-queens-13 up; every shipped small instance sits far below 4,096 rows
#: (q12: 144) while the racer costs one daemon thread when it loses.
NATIVE_COVER_MAX_ROWS = 4096


@functools.partial(jax.jit, static_argnames=("problem", "config"))
def _advance_cover(state, limit, problem, config):
    """Module-level jitted advance for the cover-race device entrant: one
    compile per (problem, config) across every race, not per call (the jit
    cache is shared, cf. the engine's module-level jitted helpers)."""
    from distributed_sudoku_solver_tpu.ops.frontier import run_frontier

    return run_frontier(state, problem, config, step_limit=limit)


@dataclasses.dataclass
class CoverRaceResult:
    count: int  # exact model count from the winning engine
    winner: str  # 'native' | 'device'
    nodes: int  # winner's expanded nodes
    duration_s: float
    complete: bool  # enumeration ran to exhaustion (False: budget/overflow)


def race_cover(
    problem,
    config: Optional[SolverConfig] = None,
    timeout: Optional[float] = None,
    dispatch_steps: int = 256,
    native_head_start_s: float = 2.0,
    provisional_grace_s: float = 60.0,
    clock: Callable[[], float] = time.monotonic,
) -> CoverRaceResult:
    """Race exact-cover enumeration: device frontier vs the native C++ DFS.

    The round-6 close of VERDICT r5 missing #2: small cover jobs used to be
    served by the measured-losing engine (`native.cover_count` sat in-tree
    but was never a racer).  Both entrants count the IDENTICAL packed
    matrix, so any completed count is final — first finisher wins, same
    first-verdict-wins contract as :func:`race`:

    * **native** (small instances only, ``NATIVE_COVER_MAX_ROWS``): the
      recursive MRV DFS in ``native/src/solver.cc`` on a daemon thread.
      It cannot be interrupted mid-recursion, so a losing native entrant
      finishes in the background and is discarded.  The row gate is a
      heuristic, not a tree-size bound — an adversarial few-row instance
      with a huge tree leaves the daemon burning a core until process
      exit; serving callers therefore pass ``timeout``, which bounds THEIR
      wait unconditionally (the orphan thread is the accepted cost of an
      uninterruptible C recursion).
    * **device**: step-bounded enumeration dispatches (the watchdog
      discipline) that poll the race between dispatches, so a native win
      releases the device within one ``dispatch_steps`` chunk.

    Returns the first COMPLETE count.  A device result whose enumeration
    was cut short (step budget / stack overflow: ``complete=False``, the
    count is a lower bound) does not end the race while the native
    entrant is still running — it is held as the provisional answer and
    returned only if nothing better arrives.  With ``timeout=None`` the
    wait for that better answer is still bounded by
    ``provisional_grace_s`` once a provisional is in hand (the native
    entrant is uninterruptible, and "hold a finished lower bound hostage
    to a DFS that may run for days" is not a behavior any caller wants).
    Raises TimeoutError if no engine produced anything inside ``timeout``.
    """
    import queue as queue_mod

    cfg = config or SolverConfig(
        min_lanes=256, stack_slots=64, count_all=True
    )
    if not cfg.count_all:
        cfg = dataclasses.replace(cfg, count_all=True)
    # Every entrant posts exactly once — a CoverRaceResult on a win, None
    # on any decline/failure path — so the consumer below can distinguish
    # "still racing" from "every entrant is out" and never blocks forever
    # on a silent double failure.
    results: "queue_mod.Queue[Optional[CoverRaceResult]]" = queue_mod.Queue()
    start = clock()
    done = threading.Event()  # a WINNING result exists
    native_settled = threading.Event()  # the native entrant is out of the
    #   race, win or decline — releases the device head-start early
    native_racer = problem.n_rows <= NATIVE_COVER_MAX_ROWS

    def native_entrant() -> None:
        try:
            try:
                from distributed_sudoku_solver_tpu import native

                if not native.available():
                    results.put(None)  # no compiler: device covers it
                    return
                count, nodes = native.cover_count(problem)
            except Exception:
                results.put(None)  # malformed/compile failure: ditto
                return
            done.set()
            results.put(
                CoverRaceResult(
                    count=count, winner="native", nodes=nodes,
                    duration_s=clock() - start, complete=True,
                )
            )
        finally:
            native_settled.set()

    def device_entrant() -> None:
        # Where a native racer runs, give it a short head start before
        # paying the device path's jit compile: on instances the DFS wins
        # it returns well inside this window and the doomed compile never
        # starts (so a losing device entrant doesn't burn the host — or
        # crash interpreter teardown mid-compile).  No thumb on the scale:
        # the device entrant's own warm-up exceeds this on every backend —
        # and a native DECLINE (no compiler) releases the wait immediately
        # via native_settled.
        if native_racer:
            native_settled.wait(native_head_start_s)
            if done.is_set():
                results.put(None)  # native already won; never compile
                return
        try:
            import jax.numpy as jnp
            import numpy as np

            from distributed_sudoku_solver_tpu.ops.frontier import (
                frontier_live,
                init_frontier,
            )
            from distributed_sudoku_solver_tpu.ops.solve import (
                finalize_frontier,
            )

            state = init_frontier(
                jnp.asarray(problem.initial_state()[None]), cfg
            )
            limit = 0
            while limit < cfg.max_steps and not done.is_set():
                limit = min(limit + dispatch_steps, cfg.max_steps)
                state = _advance_cover(state, jnp.int32(limit), problem, cfg)
                # syncck: allow(the between-dispatch liveness poll — the watchdog discipline's one deliberate sync per chunk)
                if not bool(np.asarray(frontier_live(state)).any()):
                    break
            if done.is_set():
                results.put(None)  # lost the race; release the device
                return
            res = finalize_frontier(state)
            # syncck: allow(terminal verdict fetch — the race is over for this entrant, nothing left to overlap)
            complete = bool(np.asarray(res.unsat[0]))
            if complete:
                # Only a COMPLETE count ends the race: an exhausted step
                # budget or overflow yields a lower bound, and a live
                # native entrant may still deliver the exact count.
                done.set()
            results.put(
                CoverRaceResult(
                    count=int(np.asarray(res.sol_count[0])),  # syncck: allow(terminal result scalar — post-race)
                    winner="device",
                    nodes=int(np.asarray(res.nodes[0])),  # syncck: allow(terminal result scalar — post-race)
                    duration_s=clock() - start,
                    complete=complete,
                )
            )
        except Exception:
            results.put(None)  # out of the race; consumer accounts for it

    threads = [threading.Thread(target=device_entrant, daemon=True)]
    if native_racer:
        threads.append(threading.Thread(target=native_entrant, daemon=True))
    for t in threads:
        t.start()
    deadline = None if timeout is None else start + timeout
    pending = len(threads)
    provisional: Optional[CoverRaceResult] = None  # incomplete device count
    while pending:
        remaining = (
            None if deadline is None
            else max(0.0, deadline - clock())
        )
        if remaining is None and provisional is not None:
            # No overall deadline, but a usable lower bound is in hand:
            # bound the wait for a strictly better answer (see docstring).
            remaining = provisional_grace_s
        try:
            res = results.get(timeout=remaining)
        except queue_mod.Empty:
            done.set()  # stop the survivors at their next poll
            if provisional is not None:
                return provisional  # a lower bound beats a timeout error
            raise TimeoutError(
                f"cover race finished no engine within {timeout}s"
            ) from None
        pending -= 1
        if res is not None and res.complete:
            return res
        if res is not None:
            provisional = res  # hold: a live entrant may still do better
    if provisional is not None:
        return provisional
    raise RuntimeError(
        "every cover-race entrant failed (native unavailable or declined, "
        "and the device enumeration raised)"
    )


def race(
    engine: SolverEngine,
    grid,
    configs: Sequence[SolverConfig] = DEFAULT_PORTFOLIO,
    geom: Optional[Geometry] = None,
    timeout: Optional[float] = None,
    clock: Callable[[], float] = time.monotonic,
) -> PortfolioResult:
    """Race ``configs`` on one board; cancel the losers on the first verdict.

    Every racer is an ordinary engine job with a per-job config override, so
    races interleave with regular traffic and inherit mid-flight
    cancellation: losers release the device within one chunk.
    """
    if not configs:
        raise ValueError("portfolio needs at least one config")
    start = clock()
    jobs = []
    try:
        for cfg in configs:
            jobs.append(engine.submit(grid, geom=geom, config=cfg, job_uuid=None))
    except BaseException:
        # A mid-list rejection (e.g. a config the engine refuses) must not
        # strand the already-submitted racers searching with no waiter.
        for j in jobs:
            engine.cancel(j.uuid)
        raise
    res = race_jobs(jobs, cancel=engine.cancel, timeout=timeout, start=start, clock=clock)
    if res.winner is not None:
        res.strategy = configs[res.winner_index].branch
    return res
