"""Latency-mode serving megastep: one donated dispatch, one sync, per JOB.

BENCH_r05 (TPU) pinned the interactive problem: a hard 9x9 board is
~1 ms of device time but ~79 ms end-to-end, because the chunked serving
loops pay the host round-trip floor once per CHUNK — ``rpc_floor_ms`` is
~99% of the p50.  The front door (ISSUE 14) already answers repeats and
easy boards without a device; the hard tail is the only slow tier left,
and its cost is the dispatch loop itself, not the kernel.

This module kills that floor for single hard boards.  A
:class:`MegastepFlight` holds a small device-resident mailbox — a
one-slot resident frontier (``serving/scheduler._init_resident``) whose
slot is written by the scheduler's donated attach program — and serves a
job as ONE in-graph flight:

    attach (donated, async)
      -> ``ops/frontier.advance_megastep`` (or the fused twin in
         ``ops/pallas_step``): an in-graph ``lax.while_loop`` over
         advance chunks that re-uses the round-8 packed status word per
         inner chunk and EARLY-EXITS when the board solves or its
         search space drains (all-dead), emitting the final status plus
         the chunk count actually run
      -> verdict program (async, non-donated)
      -> ONE ``host_fetch`` for status + chunk count + verdict payload
      -> detach (donated, async)

The host therefore syncs once per *flight* instead of once per chunk:
under a simulated 50 ms floor an N-chunk hard board pays ~1 floor, not
~N.  The loop is pure device dataflow — NO host callbacks close the
mailbox (the jaxck callback carve-out table in ``analysis/manifest.py``
is deliberately empty; see ``JAXCK_CALLBACK_CARVEOUTS``).

Degrade-to-chunked contract (round-9 taxonomy): a flight that exhausts
``max_chunks`` with work left, overflows a lane stack, trips the fused
shape validator, or dies in a device program does NOT error the job —
``solve`` returns False and the engine falls through to the chunked
resident/static paths, which own retries, shedding, and recovery.
Sound because a degraded megastep never reports partial results: the
slot is detached and the chunked path re-solves from the clue grid.
Failures feed the flight's circuit breaker (``serving/faults``), so a
broken device program deflects future latency-mode submits in O(1).

Accounting contract (the round-19 double-count sweep): the megastep's
single sync is recorded in ``frontdoor_megastep_ms`` (whole-flight wall)
and NOWHERE else — it must not land in the per-chunk ``chunk_wall_ms``/
``sync_wall_ms`` seams, whose samples mean "one chunk's sync", nor in
the ``rpc_floor`` estimator, whose samples mean "one floor".  For the
same reason the flight's trace spans classify its in-graph loop as
dispatch-overlapped device time, not host sync: the flight-wide span
carries site ``megastep.advance`` (a ``critpath`` dispatch site) and the
fetch span carries site ``megastep.fetch.status``, which critpath treats
as a marker (the fetch wall IS the device loop's wall; calling it
``sync`` would tell the operator to attack a floor that is already paid
exactly once).  The fetch-count guard still counts the fetch itself: the
``host_fetch`` tag stays ``status``.

Thread contract: ``solve`` runs on the CALLER's thread (the submit /
HTTP handler thread) — the lowest-latency path has no queue hop and no
device-loop round-trip — serialized per flight by the rank-36
``serving.megastep`` lock, which is acquired holding at most the
rank-30 engine lock and released before ``engine._finish_job`` (the SLO
plane's rank-24 RLock must never be entered above rank 36).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

import jax.numpy as jnp
import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.obs import compilewatch, lockdep, trace
from distributed_sudoku_solver_tpu.ops.frontier import unpack_status
from distributed_sudoku_solver_tpu.serving import engine as engine_mod
from distributed_sudoku_solver_tpu.serving import faults
from distributed_sudoku_solver_tpu.serving.scheduler import (
    _REBASE_STEPS,
    ResidentConfig,
    _attach_jit,
    _detach_jit,
    _init_resident,
    _verdict_jit,
    resident_solver_config,
)

_LOG = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class MegastepConfig:
    """Static shape of a latency-mode flight (one per geometry).

    ``chunk_steps * max_chunks`` is the flight's total step budget: a
    board still holding work past it degrades to the chunked resident
    path (which has no step budget, only deadlines).  ``chunk_steps``
    is the inner early-exit granularity — smaller reacts faster to a
    solve inside the loop, larger amortizes the per-chunk status pack;
    neither changes the verdict (the search order is chunk-invariant,
    pinned by the bit-identity test)."""

    gang_lanes: int = 8  # lanes speculating on the one board
    chunk_steps: int = 64  # frontier rounds per inner in-graph chunk
    max_chunks: int = 64  # in-graph loop bound: the flight step budget

    def __post_init__(self) -> None:
        if self.gang_lanes < 1:
            raise ValueError(f"gang_lanes must be >= 1, got {self.gang_lanes}")
        if self.chunk_steps < 1:
            raise ValueError(f"chunk_steps must be >= 1, got {self.chunk_steps}")
        if self.max_chunks < 1:
            raise ValueError(f"max_chunks must be >= 1, got {self.max_chunks}")


class MegastepFlight:
    """One geometry's latency-mode mailbox: a single-slot resident
    frontier served synchronously, one donated megastep per job.

    Raises ``ValueError`` from the constructor when the fused kernel
    cannot serve the geometry at the gang width (same contract as the
    resident scheduler — the engine counts the geometry unfit and
    latency-mode submits fall through to the chunked paths)."""

    def __init__(self, engine, geom: Geometry, cfg: MegastepConfig):
        self.engine = engine
        self.geom = geom
        self.cfg = cfg
        # The mailbox re-uses the resident seams end to end: the same
        # shape-stable config derivation (fused-validated, no step
        # budget — the in-graph loop bound is max_chunks, not
        # max_steps), the same init/attach/detach/verdict programs.
        self.config = resident_solver_config(
            engine.config, geom,
            ResidentConfig(
                job_slots=1, gang_lanes=cfg.gang_lanes,
                chunk_steps=cfg.chunk_steps,
            ),
        )
        self.gang = self.config.steal_gang
        if self.config.step_impl == "fused":
            from distributed_sudoku_solver_tpu.ops.pallas_step import (
                advance_megastep_fused,
            )

            self._advance_fn = advance_megastep_fused
            self._advance_prog = compilewatch.ADVANCE_MEGASTEP_FUSED
        else:
            from distributed_sudoku_solver_tpu.ops.frontier import (
                advance_megastep,
            )

            self._advance_fn = advance_megastep
            self._advance_prog = compilewatch.ADVANCE_MEGASTEP
        self.mailbox = None  # lockck: guard(_lock) — the device-resident frontier (lazy)
        self._steps_seen = 0  # lockck: guard(_lock) — host copy of the frontier step counter
        self._lock = lockdep.named_lock("serving.megastep")  # lockck: name(serving.megastep)
        self.breaker = faults.CircuitBreaker(engine.recovery)
        # Counters: flight outcomes (guarded — solve runs on arbitrary
        # submit/handler threads, serialized only by _lock).
        self.flights = 0  # lockck: guard(_lock)
        self.flights_solved = 0  # lockck: guard(_lock)
        self.flights_unsat = 0  # lockck: guard(_lock)
        self.degraded_budget = 0  # lockck: guard(_lock) — max_chunks hit with work left
        self.degraded_overflow = 0  # lockck: guard(_lock) — stack overflow: verdict untrusted
        self.degraded_fault = 0  # lockck: guard(_lock) — device program failed (classified)
        self.breaker_deflected = 0  # lockck: guard(_lock)
        self.chunks_total = 0  # lockck: guard(_lock) — in-graph chunks across flights
        # Round/wall totals for the device-efficiency gauge (the
        # engine's cost-plane loop adds these like the resident ones).
        self.rounds_total = 0  # lockck: guard(_lock)
        self.round_wall_total = 0.0  # lockck: guard(_lock)
        from distributed_sudoku_solver_tpu.utils.profiling import StatWindow

        self.flight_wall = StatWindow()  # whole-flight seconds (the one sync included)

    # -- the one serving surface ----------------------------------------------
    def solve(self, job) -> bool:
        """Serve ``job`` as one megastep flight on the calling thread.

        True  -> the job is RESOLVED (solved or proven unsat) and
                 ``engine._finish_job`` has run.
        False -> degrade: the job was not touched (no partial results) —
                 the caller must route it to the chunked paths.
        """
        if not self.breaker.allow():
            with self._lock:  # submit threads race on the counter
                self.breaker_deflected += 1
            return False
        verdict: Optional[tuple] = None
        wall = 0.0
        # Resolve the obs-plane singletons BEFORE taking the flight
        # lock: the lookups acquire nothing, and keeping every
        # cross-module call out of the locked region keeps the static
        # lock graph exact (deadck resolves bare ``active`` by name).
        rec = trace.active()
        cw = compilewatch.active()
        inj = faults.active()
        with self._lock:
            try:
                verdict = self._fly_locked(job, rec, cw, inj)
            except Exception as exc:  # noqa: BLE001 - degrade, never error the job
                kind = faults.classify(exc)
                self.degraded_fault += 1
                self.breaker.record_failure()
                # The donated mailbox did not survive the failed program:
                # drop it (rebuilt lazily on the next flight).
                self.mailbox = None
                self._steps_seen = 0
                _LOG.warning(
                    "[megastep] flight failed for %s (%s: %r) — degrading "
                    "to the chunked path", job.uuid, kind, exc,
                )
                return False
            self.breaker.record_success()
            self.flights += 1
            info, chunks, nodes, sol_counts, overflowed, solutions, wall = verdict
            self.chunks_total += chunks
            delta = int(info["steps"]) - self._steps_seen  # syncck: allow(info is the unpack_status dict fetched in _fly_locked — host data across the return)
            self._steps_seen = int(info["steps"])  # syncck: allow(same host dict — the one flight fetch already happened)
            if delta > 0:
                self.rounds_total += delta
                self.round_wall_total += wall
            if bool(info["solved"][0]):
                self.flights_solved += 1
            elif not bool(info["has_work"][0]) and not bool(overflowed[0]):
                self.flights_unsat += 1
            elif bool(info["has_work"][0]):
                self.degraded_budget += 1
                return False
            else:
                self.degraded_overflow += 1
                return False
        # Outside the flight lock: _finish_job enters the SLO plane's
        # rank-24 RLock, which must never nest above our rank 36.
        self.flight_wall.record(wall)
        self.engine.hist["frontdoor_megastep_ms"].record(wall)
        if bool(info["solved"][0]):
            job.solved = True
            job.solution = np.asarray(solutions[0], np.int32)  # syncck: allow(host_fetch-ed in _fly_locked — numpy no-op on host data)
            job.sol_count = int(sol_counts[0])  # syncck: allow(host_fetch-ed in _fly_locked)
        else:
            # Space exhausted, no overflow: a complete proof (the
            # megastep never sheds), same verdict rule as the resident
            # collect path.
            job.exhausted = True
            job.unsat = True
        job.nodes = int(nodes[0])  # syncck: allow(host_fetch-ed in _fly_locked)
        self.engine._finish_job(job)
        return True

    def _fly_locked(self, job, rec, cw, inj) -> tuple:
        """One flight under the lock: attach -> megastep -> verdict ->
        the ONE host fetch -> detach.  Returns the host-side payload.
        ``rec``/``cw``/``inj`` are the caller's pre-lock obs-plane
        lookups (trace recorder, compile watch, fault injector)."""
        t0 = self.engine._clock()
        geom, config = self.geom, self.config
        # Rebase the monotone step counter well before int32 overflow
        # (the scheduler's trick: limits and status baselines are
        # relative, so a reset between flights is invisible).
        if self.mailbox is not None and self._steps_seen > _REBASE_STEPS:
            self.mailbox = self.mailbox._replace(
                steps=jnp.int32(0),
                lane_rounds=jnp.zeros_like(self.mailbox.lane_rounds),
            )
            self._steps_seen = 0
        if self.mailbox is None:
            self.mailbox = _init_resident(geom, config, 1)
            self._steps_seen = 0
        if rec is not None:
            t_att = rec.now()
            rec.record(
                job.uuid, "admission", "megastep.attach",
                t0=job.trace_t0 if job.trace_t0 is not None else t_att,
                t1=t_att, node=self.engine.trace_node, route="megastep",
            )
        if inj is not None:
            faults.fire("megastep.advance", uuids=(job.uuid,))
        tr0 = rec.now() if rec is not None else 0.0
        # The donated attach is the mailbox write; the megastep is the
        # whole flight as one dispatch.  Scalars are jnp-pinned (jaxck's
        # weak-type rule) and TRACED, so retuning chunk_steps/max_chunks
        # never recompiles.
        self.mailbox = _attach_jit(
            self.mailbox, jnp.asarray(job.grid[None], jnp.int32),
            jnp.zeros(1, jnp.int32), geom, self.gang,
        )
        self.mailbox, status_dev, chunks_dev = self._advance_fn(
            self.mailbox, jnp.int32(self.cfg.chunk_steps),
            jnp.int32(self.cfg.max_chunks), geom, config,
        )
        verdict_dev = _verdict_jit(self.mailbox)
        if cw is not None and self.flights == 0:
            # Cost-plane seam (obs/compilewatch.py), the serving loops'
            # twin: once per (program, shape) — ``.lower()`` re-traces on
            # the host (aval shapes only, no device sync; the fetch-count
            # guard runs with the watch installed to prove it).
            lanes = self.config.lanes
            cw.capture_cost(
                self._advance_prog,
                (geom.n, lanes, config.stack_slots, config.step_impl,
                 "megastep"),
                lambda: self._advance_fn.lower(
                    self.mailbox, jnp.int32(self.cfg.chunk_steps),
                    jnp.int32(self.cfg.max_chunks), geom, config,
                ),
                geometry=f"{geom.n}x{geom.n}", lanes=lanes,
                chunk_steps=self.cfg.chunk_steps,
                max_chunks=self.cfg.max_chunks,
            )
        # The flight's ONE host sync: status word + early-exit chunk
        # count + the verdict payload, one batched fetch (tag "status" —
        # the fetch-count guard's megastep lane counts exactly one per
        # flight).  Blocking here waits out the in-graph loop: that wall
        # is device compute plus ONE floor, recorded whole-flight in
        # frontdoor_megastep_ms (never the per-chunk seams — see the
        # module docstring's accounting contract).
        tr1 = rec.now() if rec is not None else 0.0
        raw_status, chunks, nodes, sol_counts, overflowed, solutions = (
            engine_mod.host_fetch(
                (status_dev, chunks_dev) + verdict_dev,
                floor_s=self.engine.handicap_s,
                tag="status",
            )
        )
        wall = self.engine._clock() - t0
        if rec is not None:
            # Site megastep.fetch.status is a critpath MARKER, and the
            # flight-wide span below is a DISPATCH site: the in-graph
            # loop decomposes as dispatch-overlapped device time, not
            # host sync (the round-19 decompose pin).
            rec.record(
                None, "megastep.sync", "megastep.fetch.status", tr1,
                node=self.engine.trace_node, uuids=[job.uuid],
                chunks=int(chunks),
            )
            rec.record(
                None, "megastep.chunk.dispatch", "megastep.advance", tr0,
                node=self.engine.trace_node, uuids=[job.uuid],
                chunks=int(chunks), geometry=f"{geom.n}x{geom.n}",
            )
        info = unpack_status(raw_status, 1)
        # Async teardown: the slot is recycled without another sync.
        self.mailbox = _detach_jit(self.mailbox, jnp.ones(1, bool))
        return (
            info, int(chunks), nodes, sol_counts, overflowed, solutions,
            wall,
        )

    # -- reads ----------------------------------------------------------------
    def metrics(self) -> dict:
        with self._lock:
            out = {
                "gang_lanes": int(self.gang),
                "chunk_steps": int(self.cfg.chunk_steps),
                "max_chunks": int(self.cfg.max_chunks),
                "flights": int(self.flights),
                "solved": int(self.flights_solved),
                "unsat": int(self.flights_unsat),
                "degraded": {
                    "budget": int(self.degraded_budget),
                    "overflow": int(self.degraded_overflow),
                    "fault": int(self.degraded_fault),
                    "breaker": int(self.breaker_deflected),
                },
                "chunks_total": int(self.chunks_total),
            }
            if self.flights > 0:
                out["chunks_per_flight"] = round(
                    self.chunks_total / self.flights, 2
                )
        fw = self.flight_wall.snapshot()
        if fw:
            out["flight_wall_ms"] = {
                "count": fw["count"],
                **{k: round(fw[k] * 1e3, 3) for k in ("p50", "p95")},
            }
        out["breaker"] = self.breaker.metrics()
        return out
