"""Self-healing serving, layer by layer: fault taxonomy, deterministic
injection, recovery policy, and the resident-flight circuit breaker.

The reference system's headline capability is fault tolerance — heartbeats,
failure detection, task re-execution (``/root/reference/DHT_Node.py``) —
and the cluster layer reproduces it at node granularity.  This module
brings the same discipline INSIDE one node's serving stack, where until
round 9 every device-side failure was terminal: a dispatch exception
failed its whole batch, and a failed resident flight closed admission
forever.  Real accelerator fleets see transient faults (preemption,
co-tenant OOM, runtime hiccups) as routine events, not fatal ones.

Four pieces, all host-side (no shared-op HLO changes — the tier-1 XLA
cache stays warm):

* **Taxonomy** (:func:`classify` / :func:`classify_message` /
  :func:`is_oom`): transient vs permanent.  Transient errors (OOM,
  preemption, runtime aborts, tripped RPC deadlines, anything unknown)
  are worth a bounded retry; permanent ones (``ValueError``-shaped
  programming/config errors, anything tagged ``[permanent]``) fail fast.
  Unknown errors default to *transient* — the per-job retry budget bounds
  the optimism, and retrying a deterministic failure three times is
  cheaper than failing a recoverable job once.
* **Deterministic injection plane** (:class:`FaultSchedule` /
  :class:`FaultInjector`): a seeded, schedule-driven injector wrapping the
  serving dispatch/fetch seams (``faults.fire(site)`` calls in
  ``serving/engine.py``, ``serving/scheduler.py``, ``ops/bulk.py``, and
  the cluster's ``_send``).  Faults are chosen purely by ``(site,
  per-site dispatch index)`` — independent of thread interleaving — so a
  schedule is bit-reproducible from its seed.  No sleeps, no sockets: a
  "delay" fault is simulated by its observable consequence (the per-sync
  RPC deadline trips) instead of wall-clock time.
* **Recovery policy** (:class:`RecoveryPolicy`): the knobs — per-job
  retry budget, rebuild cooldown, breaker thresholds — plus an injectable
  ``clock`` so breaker/cooldown transitions are testable without sleeping.
* **Circuit breaker** (:class:`CircuitBreaker`): closed → open after k
  consecutive resident-rebuild failures (admission then falls back to
  static flights), half-open after a cooldown (one rebuild attempt
  probes), closed again on the first successfully consumed chunk.

Import discipline: stdlib plus the (itself stdlib-only) ``obs.lockdep``
named-lock factory — the declared carve-out in ``manifest.LAYERS`` that
puts this module's two locks in the one deadck/lockdep hierarchy.
Engine, scheduler, bulk, and cluster all import this module; it must
never import them back.
"""

from __future__ import annotations

import contextlib
import dataclasses
import random
import re
import time
import zlib
from typing import Callable, Iterable, Optional

from distributed_sudoku_solver_tpu.obs import lockdep

# -- taxonomy -----------------------------------------------------------------

TRANSIENT = "transient"
PERMANENT = "permanent"

#: Injectable fault kinds and the wire-style status each simulates.  The
#: last two are *network link* kinds, consumed by ``cluster/simnet.py``'s
#: per-link schedules rather than the serving dispatch seams: ``drop``
#: (frame lost after bytes were written — the sender sees an
#: ambiguous-delivery WireError and its retry implies at-least-once),
#: ``dup`` (the frame is delivered twice — a redelivery the sender never
#: learns about).  ``delay`` does double duty: at a serving seam it is
#: simulated by its consequence (tripped RPC deadline), on a simnet link
#: it is a real bounded *virtual* delay, i.e. reordering.
FAULT_KINDS = ("oom", "preempt", "runtime", "delay", "permanent", "drop", "dup")

_MESSAGES = {
    # RESOURCE_EXHAUSTED-style OOM: a co-tenant ate the HBM headroom.
    "oom": "RESOURCE_EXHAUSTED: out of memory while trying to allocate "
    "frontier buffers (simulated co-tenant OOM)",
    # Preemption: the runtime revoked the device mid-dispatch.
    "preempt": "UNAVAILABLE: device preempted by a higher-priority job "
    "(simulated preemption)",
    # Runtime hiccup: the program aborted for no reason of ours.
    "runtime": "INTERNAL: device program aborted (simulated runtime error)",
    # Delay: simulated by its consequence — the per-sync RPC deadline
    # trips — because a real sleep would make tests wall-clock-bound.
    "delay": "DEADLINE_EXCEEDED: dispatch exceeded the RPC deadline "
    "(simulated slow link)",
    # Poison: a deterministic failure retries cannot cure.
    "permanent": "INVALID_ARGUMENT: poisoned dispatch (simulated) [permanent]",
    # Link kinds (cluster/simnet.py): frame lost after the connect
    # succeeded — delivery is ambiguous at the sender — and duplicate
    # delivery of a frame the sender believes it sent once.
    "drop": "UNAVAILABLE: connection reset mid-frame (simulated loss after "
    "connect; delivery ambiguous)",
    "dup": "UNAVAILABLE: frame redelivered (simulated at-least-once "
    "duplicate)",
}


class SimulatedFault(RuntimeError):
    """An injected device/wire fault.  ``transient`` drives classification
    directly; real-world exceptions go through the message heuristics."""

    def __init__(self, kind: str, site: str, index: int):
        super().__init__(f"{_MESSAGES[kind]} [site={site} #{index}]")
        self.kind = kind
        self.site = site
        self.index = index
        self.transient = kind != "permanent"


# Exception types that mean "the program/inputs are wrong", not "the world
# hiccuped": retrying cannot change the outcome.
_PERMANENT_TYPES = (
    ValueError,
    TypeError,
    KeyError,
    IndexError,
    AssertionError,
    NotImplementedError,
)
# Error-string prefixes for the same judgement once an exception has been
# flattened to ``f"{type(e).__name__}: {e}"`` (engine job errors, cluster
# SOLUTION payloads).
_PERMANENT_PREFIXES = tuple(t.__name__ for t in _PERMANENT_TYPES)
# Bare "OOM" needs word boundaries: "headroom"/"zoom" must not route a
# non-allocation fault onto the lane-halving rung.
_OOM_RE = re.compile(r"RESOURCE_EXHAUSTED|OUT OF MEMORY|\bOOM\b")


def classify(exc: BaseException) -> str:
    """``'transient'`` or ``'permanent'``.  Unknown errors are transient:
    the retry budget bounds the optimism (see module docstring)."""
    if isinstance(exc, SimulatedFault):
        return TRANSIENT if exc.transient else PERMANENT
    if isinstance(exc, _PERMANENT_TYPES):
        return PERMANENT
    return classify_message(str(exc))


def classify_message(msg: Optional[str]) -> str:
    """Classify an error already flattened to a string (cluster SOLUTION
    payloads, ``run_exclusive``'s re-raised control errors)."""
    if not msg:
        return TRANSIENT
    if "[permanent]" in msg:
        return PERMANENT
    head = msg.split(":", 1)[0].strip()
    if head in _PERMANENT_PREFIXES:
        return PERMANENT
    if "INVALID_ARGUMENT" in msg:
        return PERMANENT
    return TRANSIENT


def is_oom(exc_or_msg) -> bool:
    """OOM-shaped failures get the lane-halving rung of the downgrade
    ladder: half the flight width is the one retry that attacks the cause."""
    if isinstance(exc_or_msg, SimulatedFault):
        return exc_or_msg.kind == "oom"
    return _OOM_RE.search(str(exc_or_msg).upper()) is not None


# -- recovery policy ----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RecoveryPolicy:
    """Host-side recovery knobs (engine + resident scheduler + breaker).

    ``clock`` exists so every time-based transition (rebuild cooldown,
    breaker open → half-open) is testable deterministically: tests inject
    a manually-advanced clock and never sleep.
    """

    max_retries: int = 3  # transient re-dispatches per job before it fails
    rebuild_cooldown_s: float = 0.25  # wait before rebuilding a failed
    #   resident flight (back-to-back rebuild storms burn the device loop)
    breaker_failures: int = 3  # consecutive rebuild failures that open the
    #   breaker (admission then deflects to static flights)
    breaker_cooldown_s: float = 2.0  # open -> half-open wait; the first
    #   admission after it is the probe rebuild
    clock: Callable[[], float] = time.monotonic

    def __post_init__(self) -> None:
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {self.max_retries}")
        if self.breaker_failures < 1:
            raise ValueError(
                f"breaker_failures must be >= 1, got {self.breaker_failures}"
            )


# -- circuit breaker ----------------------------------------------------------


class CircuitBreaker:
    """closed -> open after k consecutive failures -> half-open after a
    cooldown -> closed on the next success (or back open on failure).

    Thread contract: any thread may call any method (``allow`` runs on
    submit threads, record_* on the device loop); a single internal lock
    keeps transitions atomic.  Time comes from the policy clock only.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, policy: RecoveryPolicy):
        self.policy = policy
        self._lock = lockdep.named_lock("serving.breaker")  # lockck: name(serving.breaker)
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self.transitions = 0  # state changes, for observability/tests
        self._opened_at = 0.0
        self._probe_at = 0.0  # last half-open probe grant

    def allow(self) -> bool:
        """May work be admitted?  Flips open -> half-open when the cooldown
        has elapsed — the ONE admission that sees the flip is the probe;
        later callers are denied until the probe resolves the state
        (record_success -> closed, record_failure -> back open), so a
        concurrent submit burst cannot pile jobs onto an unproven rebuild.
        A probe can die resolving NEITHER way (cancelled or
        deadline-expired before its flight consumes a chunk, or rejected
        by the admission checks after this flip) — so half-open re-grants
        one probe per cooldown window instead of wedging forever."""
        with self._lock:
            now = self.policy.clock()
            if self.state == self.OPEN:
                if now - self._opened_at >= self.policy.breaker_cooldown_s:
                    self.state = self.HALF_OPEN
                    self.transitions += 1
                    self._probe_at = now
                    return True
                return False
            if self.state == self.HALF_OPEN:
                if now - self._probe_at >= self.policy.breaker_cooldown_s:
                    self._probe_at = now
                    return True
                return False
            return True

    def record_failure(self) -> None:
        with self._lock:
            self.consecutive_failures += 1
            if (
                self.state == self.HALF_OPEN
                or self.consecutive_failures >= self.policy.breaker_failures
            ):
                if self.state != self.OPEN:
                    self.transitions += 1
                self.state = self.OPEN
                self._opened_at = self.policy.clock()

    def record_success(self) -> None:
        with self._lock:
            self.consecutive_failures = 0
            if self.state != self.CLOSED:
                self.state = self.CLOSED
                self.transitions += 1

    def metrics(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "transitions": self.transitions,
            }


# -- deterministic fault schedules --------------------------------------------


class FaultSchedule:
    """Pure function ``(site, per-site dispatch index) -> fault kind | None``.

    Two constructors: :meth:`at` pins exact faults to exact dispatch
    indices (unit tests, poison scenarios), :meth:`seeded` draws a
    per-(site, index) Bernoulli from a seed (chaos soaks).  Both are
    independent of call interleaving: the decision for dispatch #7 of
    ``engine.advance`` is the same whatever other sites did in between,
    so a multi-threaded run is as reproducible as a serial one.
    """

    def __init__(self, fn: Callable[[str, int], Optional[str]]):
        self._fn = fn

    @classmethod
    def at(cls, plan: dict) -> "FaultSchedule":
        """``plan``: ``{site: {index: kind}}`` — explicit, exact."""
        for site, hits in plan.items():
            for idx, kind in hits.items():
                if kind not in FAULT_KINDS:
                    raise ValueError(f"unknown fault kind {kind!r} at {site}#{idx}")
        return cls(lambda site, idx: plan.get(site, {}).get(idx))

    @classmethod
    def seeded(
        cls,
        seed: int,
        rate: float,
        kinds: Iterable[str] = ("oom", "preempt", "runtime", "delay"),
        sites: Optional[Iterable[str]] = None,
    ) -> "FaultSchedule":
        """Bernoulli(rate) per (site, index), kind drawn uniformly from
        ``kinds``; ``sites`` restricts injection to those seams.  The draw
        is keyed on (seed, crc32(site), index) packed into one integer
        seed for a stdlib ``random.Random`` — order-independent,
        bit-reproducible, and free of hash randomization (ints only)."""
        kinds = tuple(kinds)
        for k in kinds:
            if k not in FAULT_KINDS:
                raise ValueError(f"unknown fault kind {k!r}")
        site_set = None if sites is None else frozenset(sites)

        def fn(site: str, idx: int) -> Optional[str]:
            if site_set is not None and site not in site_set:
                return None
            key = (
                ((seed & 0xFFFFFFFF) << 96)
                | (zlib.crc32(site.encode()) << 64)
                | idx
            )
            rng = random.Random(key)
            if rng.random() >= rate:
                return None
            return kinds[rng.randrange(len(kinds))]

        return cls(fn)

    def lookup(self, site: str, index: int) -> Optional[str]:
        return self._fn(site, index)


class FaultInjector:
    """Counts dispatches per site and raises the scheduled fault, if any.

    ``poison_jobs`` makes a *job* (not a dispatch index) the fault: any
    seam fired with a poisoned uuid raises a permanent fault — the
    deterministic way to exercise batch bisection, because the fault
    follows the job through every requeue and split.
    """

    def __init__(
        self,
        schedule: Optional[FaultSchedule] = None,
        poison_jobs: Iterable[str] = (),
    ):
        self.schedule = schedule
        self.poison_jobs = frozenset(poison_jobs)
        self._lock = lockdep.named_lock("serving.injector")  # lockck: name(serving.injector)
        self._idx: dict = {}  # site -> next dispatch index
        self.injected: dict = {}  # (site, kind) -> count

    def fire(self, site: str, uuids: Iterable[str] = ()) -> None:
        with self._lock:
            idx = self._idx.get(site, 0)
            self._idx[site] = idx + 1
        if self.poison_jobs:
            for u in uuids:
                if u in self.poison_jobs:
                    self._count(site, "permanent")
                    raise SimulatedFault("permanent", site, idx)
        kind = self.schedule.lookup(site, idx) if self.schedule else None
        if kind is not None:
            self._count(site, kind)
            raise SimulatedFault(kind, site, idx)

    def _count(self, site: str, kind: str) -> None:
        with self._lock:
            key = f"{site}:{kind}"
            self.injected[key] = self.injected.get(key, 0) + 1

    def dispatches(self, site: Optional[str] = None) -> int:
        with self._lock:
            if site is not None:
                return self._idx.get(site, 0)
            return sum(self._idx.values())

    def metrics(self) -> dict:
        with self._lock:
            return {
                "dispatches": dict(self._idx),
                "injected": dict(self.injected),
            }


# -- the process-wide seam ----------------------------------------------------
#
# Production runs have no injector installed and pay one global read per
# dispatch.  Tests install one around an engine/cluster lifetime; the
# serving stack never threads injector objects through its layers.

_active: Optional[FaultInjector] = None


def install(injector: Optional[FaultInjector]) -> None:
    global _active
    _active = injector


def active() -> Optional[FaultInjector]:
    return _active


@contextlib.contextmanager
def injected(injector: FaultInjector):
    """Scope an injector over a block (tests): always uninstalls."""
    install(injector)
    try:
        yield injector
    finally:
        install(None)


def fire(site: str, uuids: Iterable[str] = ()) -> None:
    """The seam: a no-op unless an injector is installed.  Call sites are
    the serving dispatch/fetch boundaries — engine launch/advance/fetch,
    resident attach/detach/advance, bulk rung dispatches, cluster sends."""
    inj = _active
    if inj is not None:
        inj.fire(site, uuids)
