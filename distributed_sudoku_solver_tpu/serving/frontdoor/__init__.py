"""Front door: the three tiers a board crosses before it may cost a device
dispatch (ISSUE 14; ROADMAP #3).

At millions-of-users scale, `/solve` traffic is overwhelmingly *easy*
(42040/65536 of the corpus solves by propagation alone) and heavily
*repeated* (published puzzles are shared), yet before this layer every
request paid a device dispatch.  The front door sits on the engine's
submit seam (``SolverEngine(frontdoor=...)``) and routes each board
through, in order:

1. **Result cache** (``cache.py``): a bounded content-addressed store
   keyed on the board's symmetry-canonical form (``canonical.py`` — digit
   relabeling + row/column permutations within bands/stacks + band/stack
   permutation + transpose), so any of the ~3x10^6 equivalents of a
   published puzzle keys to ONE entry.  Hits are O(µs) host lookups; the
   stored canonical solution is mapped back to the submitted frame via
   the request's own inverse transform.  Proven-unsat boards are cached
   as negative entries.
2. **Difficulty probe** (``router.py``): one bounded propagation-only
   pass (host numpy, no jax, no dispatch).  Boards it solves outright
   answer immediately; boards it proves contradictory answer 422; the
   rest are scored by remaining branching slack.
3. **Router**: easy boards go to the native C++ DFS via the
   ``serving/portfolio.py`` racer seam (``race_native`` — first verdict
   wins, with a delayed device fallback so a misjudged board never
   hangs); the hard tail goes to resident/static flights exactly as
   before.

Every tier is observable: per-route ``LatencyHistogram``s ride the
engine's ``hist`` keyspace (cluster rollup via ``obs/agg.py`` for free),
hit/dup/route counters export as the ``/metrics`` ``frontdoor`` section,
and ``frontdoor.cache``/``frontdoor.probe``/``frontdoor.route`` trace
spans ride the PR-8 recorder.  ``--no-frontdoor`` restores the direct
path; ``count_all``/portfolio/``solve_batch`` requests bypass the cache
by construction (per-job configs skip the seam — enumeration and bulk
are not memoizable by a single canonical entry).
"""

from distributed_sudoku_solver_tpu.serving.frontdoor.cache import ResultCache
from distributed_sudoku_solver_tpu.serving.frontdoor.canonical import (
    Transform,
    apply_transform,
    canonicalize,
    restore_solution,
)
from distributed_sudoku_solver_tpu.serving.frontdoor.router import (
    FrontDoor,
    FrontDoorConfig,
    probe_propagate,
)

__all__ = [
    "FrontDoor",
    "FrontDoorConfig",
    "ResultCache",
    "Transform",
    "apply_transform",
    "canonicalize",
    "probe_propagate",
    "restore_solution",
]
