"""Difficulty probe + route selection: the front door's decision tier.

``probe_propagate`` is one bounded propagation-only pass — eliminations
plus hidden singles to a fixpoint, host-side numpy, zero device work.  It
is a *sound under-approximation* of the device kernels' propagation: a
board it completes is solved by forced deductions alone (the grid is THE
unique solution), and a contradiction it derives is a proof of
unsatisfiability — both verdicts are final whatever the engine's
configured rule tier.  Boards it leaves open are scored by remaining
branching slack (sum of ``candidates - 1`` over undecided cells), the
quantity DFS cost actually tracks.

:class:`FrontDoor` wires the three tiers onto the engine's submit seam:
canonical-cache lookup, then the probe, then the route — easy boards to
the native C++ DFS via :func:`serving.portfolio.race_native` (first
verdict wins; a delayed device fallback covers a misjudged board), the
hard tail to resident/static flights untouched.  Device-routed jobs
carry a resolution hook that fills the cache when their verdict lands,
so a hard board is paid for once per orbit.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
from collections import OrderedDict
from typing import Optional

import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.obs import lockdep, ordertrace, slo, trace
from distributed_sudoku_solver_tpu.serving import brownout
from distributed_sudoku_solver_tpu.serving.frontdoor import cache as cache_mod
from distributed_sudoku_solver_tpu.serving.frontdoor import canonical as canon_mod

_LOG = logging.getLogger(__name__)

#: Device-routed jobs awaiting a verdict for cache fill: bound the map so
#: abandoned uuids (errors, overflows) can never grow it without limit.
_PENDING_BOUND = 4096


@dataclasses.dataclass(frozen=True)
class ProbeResult:
    status: str  # 'solved' | 'unsat' | 'open'
    solution: Optional[np.ndarray]  # int32[n, n] when solved
    empties: int  # undecided cells after propagation
    score: int  # sum of (candidates - 1) over undecided cells
    sweeps: int  # propagation sweeps consumed


def _popcounts(m: np.ndarray, digits: np.ndarray) -> np.ndarray:
    return ((m[..., None] >> digits) & 1).sum(-1)


def probe_propagate(grid, geom: Geometry, max_sweeps: int = 64) -> ProbeResult:
    """Eliminations + hidden singles to a fixpoint (bounded by
    ``max_sweeps``).  See the module docstring for the soundness
    contract; out-of-range cell values make the board 'open' (the device
    path keeps whatever behavior it has for malformed values)."""
    n = geom.n
    g = np.asarray(grid, dtype=np.int64)
    if g.shape != (n, n) or g.min() < 0 or g.max() > n:
        return ProbeResult("open", None, n * n, n * n * (n - 1), 0)
    full = (1 << n) - 1
    m = np.full((n, n), full, dtype=np.int64)
    nz = g > 0
    m[nz] = np.int64(1) << (g[nz] - 1)
    digits = np.arange(n, dtype=np.int64)
    weights = np.int64(1) << digits
    vb, hb, bh, bw = geom.n_vboxes, geom.n_hboxes, geom.box_h, geom.box_w

    def duplicate_assigned(mm: np.ndarray) -> bool:
        pc = _popcounts(mm, digits)
        singles = np.where(pc == 1, mm, 0)
        sb = (singles[..., None] >> digits) & 1
        if (sb.sum(axis=1) > 1).any() or (sb.sum(axis=0) > 1).any():
            return True
        return bool((sb.reshape(vb, bh, hb, bw, n).sum(axis=(1, 3)) > 1).any())

    sweeps = 0
    for sweeps in range(1, max_sweeps + 1):
        prev = m
        if duplicate_assigned(m):
            return ProbeResult("unsat", None, 0, 0, sweeps)
        pc = _popcounts(m, digits)
        singles = np.where(pc == 1, m, 0)
        row_or = np.bitwise_or.reduce(singles, axis=1)
        col_or = np.bitwise_or.reduce(singles, axis=0)
        box_or = np.bitwise_or.reduce(
            np.bitwise_or.reduce(singles.reshape(vb, bh, hb, bw), axis=3), axis=1
        )
        box_exp = np.repeat(np.repeat(box_or, bh, axis=0), bw, axis=1)
        elim = (row_or[:, None] | col_or[None, :] | box_exp) & ~singles
        m = m & ~elim
        if (m == 0).any():
            return ProbeResult("unsat", None, 0, 0, sweeps)
        # Hidden singles: a digit confined to one cell of a unit pins that
        # cell.  Two distinct pinned digits meeting in one cell is a proof
        # of contradiction (the cell cannot be both).
        bits = (m[..., None] >> digits) & 1
        row_u = bits.sum(axis=1) == 1  # (n, d)
        col_u = bits.sum(axis=0) == 1
        box_u = bits.reshape(vb, bh, hb, bw, n).sum(axis=(1, 3)) == 1
        box_u_exp = np.repeat(np.repeat(box_u, bh, axis=0), bw, axis=1)
        uniq = row_u[:, None, :] | col_u[None, :, :] | box_u_exp
        hid = m & (uniq * weights).sum(-1)
        if (_popcounts(hid, digits) > 1).any():
            return ProbeResult("unsat", None, 0, 0, sweeps)
        m = np.where(hid != 0, hid, m)
        if (m == 0).any():  # pragma: no cover - hid is a subset of m
            return ProbeResult("unsat", None, 0, 0, sweeps)
        if np.array_equal(m, prev):
            break
    pc = _popcounts(m, digits)
    if (pc == 1).all():
        if duplicate_assigned(m):
            return ProbeResult("unsat", None, 0, 0, sweeps)
        sol = (((m[..., None] >> digits) & 1).argmax(-1) + 1).astype(np.int32)
        return ProbeResult("solved", sol, 0, 0, sweeps)
    open_cells = pc > 1
    return ProbeResult(
        "open",
        None,
        int(open_cells.sum()),
        int((pc[open_cells] - 1).sum()),
        sweeps,
    )


@dataclasses.dataclass(frozen=True)
class FrontDoorConfig:
    """Knobs for the routing layer (CLI: ``--cache-entries``; the whole
    layer is bypassed with ``--no-frontdoor``)."""

    cache_entries: int = 65536
    #: Boards whose post-propagation branching slack is at or below this
    #: route to the native DFS; above it, to resident/static flights.
    #: The default keeps genuinely hard published boards (AI Escargot
    #: scores in the hundreds) on the device path while the easy mass —
    #: a few undecided cells with 2-3 candidates — stays native.
    easy_score: int = 64
    probe_sweeps: int = 64
    #: Head start the native racer gets before the device fallback is
    #: submitted (serving/portfolio.race_native).
    native_head_start_s: float = 0.5
    canonical_max_states: int = canon_mod.MAX_STATES


class FrontDoor:
    """The routing layer, bound to one engine's submit seam."""

    def __init__(self, engine, config: Optional[FrontDoorConfig] = None):
        self.engine = engine
        self.config = config or FrontDoorConfig()
        self.cache = cache_mod.ResultCache(self.config.cache_entries)
        # Cluster-wide cache seam (ISSUE 17): an injected second level
        # behind the local LRU.  Duck-typed — ``lookup(digest, raw) ->
        # Optional[CacheEntry]`` and ``store(digest, entry)`` — so this
        # layer never imports cluster; ``cluster/node.py`` installs its
        # adapter at start().  None = single-node, zero behavior change.
        self.l2 = None
        self._lock = lockdep.named_lock("frontdoor.router")  # lockck: name(frontdoor.router)
        self.route_counts = {  # lockck: guard(_lock)
            "cache": 0, "propagation": 0, "native": 0, "device": 0,
        }
        self.probe_counts = {  # lockck: guard(_lock)
            "solved": 0, "unsat": 0, "easy": 0, "hard": 0,
        }
        self.uncacheable = 0  # lockck: guard(_lock) — boards with no canonical form
        self.cluster_hits = 0  # lockck: guard(_lock) — L1 misses answered by the L2 seam
        self.native_fallback_wins = 0  # lockck: guard(_lock) — device fallback beat the native racer
        self.answered = 0  # lockck: guard(_lock) — jobs resolved by the front door itself
        self.answered_solved = 0  # lockck: guard(_lock)
        self.answered_nodes = 0  # lockck: guard(_lock) — native racer nodes (stats parity)
        self._pending: "OrderedDict[str, tuple]" = OrderedDict()  # lockck: guard(_lock)
        # Probe availability ONCE, at construction: native.available()
        # may build the shared library (a bounded g++ run) and must never
        # do so on a request thread.
        try:
            from distributed_sudoku_solver_tpu import native

            self.native_available = bool(native.available())
        except Exception:  # pragma: no cover - import/abi failure
            self.native_available = False

    # -- the submit seam -----------------------------------------------------
    def route(self, job, saturation: str = "fallback"):
        """Route one eligible job.  Returns ``(owned, token)``:
        ``owned=True`` means the front door resolved it (cache /
        propagation) or the native race will; ``owned=False`` means hard
        tail — the caller places the job on its device paths and, once
        placement SUCCEEDED, hands ``token`` to :meth:`commit_device`
        (which does the device-route bookkeeping; deferring it keeps a
        saturation 429 from inflating counters or parking a dead
        cache-fill entry).

        With a brownout controller installed (``serving/brownout.py``)
        the routing decision is also the SHEDDING point — the one place
        in the system where a request's cost tier is known before any of
        that cost is paid.  Cache hits and propagation verdicts serve at
        every stage; probed-open boards consult the stage ladder, and a
        shed verdict raises :class:`serving.brownout.BrownoutShed` for
        ``saturation='reject'`` submits (the HTTP boundary turns it into
        503/429 + Retry-After) while quiet-fallback callers — internal
        work the node already accepted — degrade to the native-only
        policy instead of erroring."""
        rec = trace.active()
        t0 = rec.now() if rec is not None else 0.0
        raw = self._raw_digest(job)
        try:
            cf = canon_mod.canonicalize(
                job.grid, job.geom, self.config.canonical_max_states
            )
        except ValueError:
            # Out-of-range cell values: not an orbit.  The seam stays
            # transparent — the board is uncacheable and the device path
            # keeps whatever semantics it has for malformed values.
            cf = None
        entry = None
        if cf is None:
            with self._lock:
                self.uncacheable += 1
        else:
            entry = self.cache.lookup_entry(cf.digest, raw)
            if entry is None and self.l2 is not None:
                # L1 miss -> ask the cluster cache (digest owner).  Any
                # wire trouble is just a miss.  A hit is promoted into
                # the local LRU (read-through) so the next job in this
                # orbit answers wire-free.
                entry = self.l2.lookup(cf.digest, raw)
                if entry is not None:
                    self.cache.store_entry(cf.digest, entry)
                    with self._lock:
                        self.cluster_hits += 1
        if rec is not None:
            rec.record(
                job.uuid, "cache.lookup", "frontdoor.cache", t0,
                node=self.engine.trace_node,
                hit=entry is not None, cacheable=cf is not None,
            )
        if entry is not None:
            self._resolve(
                job, "cache",
                solved=entry.verdict == cache_mod.SOLVED,
                solution=None if entry.solution is None
                else canon_mod.restore_solution(
                    entry.solution, cf.transform
                ).astype(np.int32),
                unsat=entry.verdict == cache_mod.UNSAT,
                nodes=0,
            )
            return True, None

        t1 = rec.now() if rec is not None else 0.0
        pr = probe_propagate(job.grid, job.geom, self.config.probe_sweeps)
        # Journaled with the route outcome by the ordering trace
        # (obs/ordertrace.py) — the offline threshold learner's features.
        job.probe_score = int(pr.score)
        job.probe_empties = int(pr.empties)
        if rec is not None:
            rec.record(
                job.uuid, "probe", "frontdoor.probe", t1,
                node=self.engine.trace_node,
                status=pr.status, score=pr.score, sweeps=pr.sweeps,
            )
        if pr.status == "solved":
            with self._lock:
                self.probe_counts["solved"] += 1
            self._resolve(job, "propagation", solved=True,
                          solution=pr.solution, nodes=0)
            self._fill_cache(cf, raw, job)
            return True, None
        if pr.status == "unsat":
            with self._lock:
                self.probe_counts["unsat"] += 1
            self._resolve(job, "propagation", solved=False, unsat=True, nodes=0)
            self._fill_cache(cf, raw, job)
            return True, None

        # Brownout gate (serving/brownout.py): the stage ladder decides
        # whether this tier is admitted at all, and whether the easy
        # tier's device shadow is suppressed.  Disabled path = one global
        # read + one branch (explode-microcheck pinned).
        easy_tier = pr.score <= self.config.easy_score
        ctrl = brownout.active()
        action, bo_stage = (
            ctrl.gate("easy" if easy_tier else "hard")
            if ctrl is not None
            else (brownout.SERVE, 0)
        )
        if action == brownout.SHED:
            if saturation == "reject":
                tier = "easy" if easy_tier else "hard"
                ctrl.record_shed(tier, bo_stage)
                if rec is not None:
                    rec.record(
                        job.uuid, "route", "frontdoor.route",
                        rec.now(), node=self.engine.trace_node,
                        route="shed", stage=bo_stage, tier=tier,
                        score=pr.score,
                    )
                raise brownout.BrownoutShed(
                    bo_stage, ctrl.retry_after_s(), tier, uuid=job.uuid
                )
            # Quiet callers degrade, never error: internal work the node
            # already accepted serves at the stage-1 policy.
            action = brownout.NATIVE_ONLY if easy_tier else brownout.SERVE
        easy = easy_tier and self.native_available
        t2 = rec.now() if rec is not None else 0.0
        if rec is not None:
            rec.record(
                job.uuid, "route", "frontdoor.route", t2,
                node=self.engine.trace_node,
                route="native" if easy else "device", score=pr.score,
            )
        if easy:
            with self._lock:
                self.probe_counts["easy"] += 1
                self.route_counts["native"] += 1
            from distributed_sudoku_solver_tpu.serving.portfolio import race_native

            job.route = "native"
            race_native(
                self.engine, job,
                head_start_s=self.config.native_head_start_s,
                on_verdict=lambda j, cf=cf, raw=raw: self._native_verdict(
                    j, cf, raw
                ),
                # Stage >= 1: native-only — the device shadow's lanes go
                # back to the hard tail.
                device_fallback=action != brownout.NATIVE_ONLY,
            )
            return True, None
        job.route = "device"
        return False, (cf, raw)

    def commit_device(self, job, token) -> None:
        """Device-route bookkeeping, called by the engine AFTER the job
        landed on a flight path: counters bump and the cache-fill hook
        attaches only for jobs that will actually run (a rejected
        saturation submit commits nothing).  A job that resolved in the
        sub-millisecond window before this commit simply misses its
        cache fill — a bounded miss, never a wrong answer."""
        cf, raw = token
        with self._lock:
            self.probe_counts["hard"] += 1
            self.route_counts["device"] += 1
            if cf is not None:
                self._pending[job.uuid] = (cf, raw)
                while len(self._pending) > _PENDING_BOUND:
                    self._pending.popitem(last=False)
        if cf is not None:
            job.on_resolve = self._device_resolved

    # -- resolution paths ----------------------------------------------------
    def _resolve(self, job, route, solved, solution=None, unsat=False, nodes=0):
        """Resolve a job the front door answered itself (cache hit or
        propagation verdict) with the engine's usual accounting."""
        eng = self.engine
        job.route = route
        job.solved = bool(solved)
        job.unsat = bool(unsat)
        # The engine's verdict convention: unsat is derived from a
        # COMPLETE refutation of the search space, which downstream
        # consumers (cluster _Exec finalization) read off `exhausted` —
        # a propagation contradiction or cached negative entry is exactly
        # such a proof.  Without this, a cluster node finalizes a
        # front-door 422 as a verdictless 500 (found by live /verify).
        job.exhausted = bool(unsat)
        job.solution = solution
        job.nodes = int(nodes)
        wall = eng._clock() - job.submitted_at
        eng.latency.record(wall)
        eng.hist["latency_ms"].record(wall)
        eng.hist[f"frontdoor_{route}_ms"].record(wall)
        mon = slo.active()
        if mon is not None:
            mon.observe(wall, error=False, stream="job")
        with self._lock:
            if route in ("cache", "propagation"):  # native/device count at dispatch
                self.route_counts[route] += 1
            self.answered += 1
            if job.solved:
                self.answered_solved += 1
        rec = trace.active()
        if rec is not None:
            rec.event(
                job.uuid, "resolve", "frontdoor.resolve",
                node=eng.trace_node, route=route,
                solved=job.solved, unsat=job.unsat,
            )
        ot = ordertrace.active()
        if ot is not None:
            ot.route(
                job.uuid, job.probe_score, job.probe_empties, route,
                wall * 1000.0, job.solved, job.unsat, job.nodes,
            )
        # Front-door-owned verdicts never cross _finish_job, so the WAL
        # discharge (serving/journal.py) happens here.
        eng._journal_resolved(job)
        job.done.set()

    def _native_verdict(self, job, cf, raw) -> None:
        """race_native's resolution callback (runs on the winning
        entrant's thread, before the job's done-event is set, for EVERY
        resolution — verdicts, cancels, errors).  The race's device
        fallback is a *shadow* job (engine accounting skips it), so this
        is the ONE place the user's request is counted, whichever
        entrant won: ``job.route`` says which ('native' or 'device'), and
        the wall lands in that route's histogram."""
        eng = self.engine
        wall = eng._clock() - job.submitted_at
        route = job.route if job.route in ("native", "device") else "native"
        eng.hist[f"frontdoor_{route}_ms"].record(wall)
        eng.latency.record(wall)
        eng.hist["latency_ms"].record(wall)
        mon = slo.active()
        if mon is not None:
            mon.observe(wall, error=job.error is not None, stream="job")
        with self._lock:
            self.answered += 1
            if job.solved:
                self.answered_solved += 1
            self.answered_nodes += int(job.nodes)
            if route == "device":
                self.native_fallback_wins += 1
        ot = ordertrace.active()
        if ot is not None:
            ot.route(
                job.uuid, job.probe_score, job.probe_empties, route,
                wall * 1000.0, job.solved, job.unsat, job.nodes,
            )
        self._fill_cache(cf, raw, job)
        # The race's primary job resolves here (its device fallback is a
        # shadow _finish_job skips): discharge the WAL entry.
        eng._journal_resolved(job)

    def _device_resolved(self, job) -> None:
        """Job.on_resolve hook: runs inside engine._finish_job (device
        loop) for device-routed jobs that carried a canonical form."""
        with self._lock:
            pending = self._pending.pop(job.uuid, None)
        self.engine.hist["frontdoor_device_ms"].record(
            self.engine._clock() - job.submitted_at
        )
        if pending is not None:
            cf, raw = pending
            self._fill_cache(cf, raw, job)

    def _fill_cache(self, cf, raw: str, job) -> None:
        """Insert a finished job's verdict under its canonical digest.
        Only real verdicts are cacheable: solved with a solution, or a
        completed unsat proof — cancelled/errored/overflowed jobs leave
        no entry."""
        if cf is None:
            return
        if job.error is not None or job.cancelled:
            return
        if job.solved and job.solution is not None:
            entry = cache_mod.CacheEntry(
                verdict=cache_mod.SOLVED,
                solution=canon_mod.apply_transform(
                    np.asarray(job.solution), cf.transform
                ).astype(np.int8),
                nodes=int(job.nodes),
                raw_digest=raw,
                route=job.route or "device",
            )
        elif job.unsat:
            entry = cache_mod.CacheEntry(
                verdict=cache_mod.UNSAT, solution=None, nodes=int(job.nodes),
                raw_digest=raw, route=job.route or "device",
            )
        else:
            return
        self.cache.store_entry(cf.digest, entry)
        if self.l2 is not None:
            # Async on the adapter's side for remote owners: the filling
            # thread is often the device loop, which must never wait on
            # the wire.
            self.l2.store(cf.digest, entry)

    # -- plumbing ------------------------------------------------------------
    @staticmethod
    def _raw_digest(job) -> str:
        h = hashlib.sha256()
        h.update(f"{job.geom.box_h}x{job.geom.box_w}:".encode())
        h.update(np.ascontiguousarray(job.grid, dtype=np.int32).tobytes())
        return h.hexdigest()

    def merge_stats(self, stats: dict) -> dict:
        """Fold front-door-answered jobs into the engine's stats triple
        (the /stats and /metrics base counters keep meaning 'jobs this
        node answered', whichever tier answered them)."""
        with self._lock:
            stats["jobs_done"] += self.answered
            stats["solved"] += self.answered_solved
            stats["validations"] += self.answered_nodes
        return stats

    def metrics(self) -> dict:
        with self._lock:
            out = {
                "routes": dict(self.route_counts),
                "probe": dict(self.probe_counts),
                "uncacheable": int(self.uncacheable),
                "cluster_hits": int(self.cluster_hits),
                "native_available": bool(self.native_available),
                "native_fallback_wins": int(self.native_fallback_wins),
                "pending_fills": len(self._pending),
            }
        out["cache"] = self.cache.metrics()
        return out
