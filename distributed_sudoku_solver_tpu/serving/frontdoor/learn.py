"""Learn the front door's ``easy_score`` threshold from recorded outcomes.

``FrontDoorConfig.easy_score`` decides which probed-open boards race the
native DFS (easy tier) and which go straight to device flights (hard
tail).  The shipped default (64) is a hand-picked constant; this module
replaces it with a threshold **fit to this deployment's own traffic**:
the opt-in ordering trace (``obs/ordertrace.py``) journals every resolved
job's probe score, route, and wall time, and :func:`fit_easy_score`
replays those outcomes to pick the score cut that minimizes total
estimated wall.

The model is deliberately tiny — a 1-D threshold over an integer score,
chosen by exhaustive scan.  Per candidate threshold ``t``, each recorded
job is charged the *observed* mean wall of its would-be tier (native-tier
mean for ``score <= t``, device-tier mean for ``score > t``), estimated
from the jobs that actually took that route in the journal.  Scores only
ever observed on one route contribute their own wall either way; the scan
therefore reduces to choosing where the per-score mean-wall curves cross,
robust to a handful of outliers because means pool across the whole
journal.  No dependencies beyond the stdlib — this runs in the no-jax
fast lane (``benchmarks/train_ordering.py fit-threshold`` is the CLI).
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple

#: Routes whose wall time measures the native/easy path.
_NATIVE_ROUTES = ("native",)
#: Routes whose wall time measures the device/hard path.
_DEVICE_ROUTES = ("device", "direct")


def fit_easy_score(
    events: Iterable[dict],
    default: int = 64,
    min_samples: int = 8,
) -> Tuple[int, dict]:
    """Pick the easy/hard threshold minimizing estimated total wall.

    ``events`` are ordering-trace dicts (``kind == 'route'`` rows are
    read, others skipped).  Returns ``(threshold, report)``; with fewer
    than ``min_samples`` jobs on either route the journal cannot price
    one of the tiers and the ``default`` comes back unchanged (report
    says why) — a cold deployment keeps the shipped constant until it
    has seen real traffic.
    """
    native: list = []  # (score, wall_ms)
    device: list = []
    for ev in events:
        if ev.get("kind") != "route":
            continue
        score = int(ev.get("score", -1))
        if score < 0:  # cache hits / never-probed jobs carry no signal
            continue
        wall = float(ev.get("wall_ms", 0.0))
        route = ev.get("route")
        if route in _NATIVE_ROUTES:
            native.append((score, wall))
        elif route in _DEVICE_ROUTES:
            device.append((score, wall))
    report = {
        "native_samples": len(native),
        "device_samples": len(device),
        "default": int(default),
    }
    if len(native) < min_samples or len(device) < min_samples:
        report["fitted"] = False
        report["reason"] = (
            f"needs >= {min_samples} samples per route "
            f"(native={len(native)}, device={len(device)})"
        )
        return int(default), report

    def mean_wall_by_score(rows):
        acc: dict = {}
        for score, wall in rows:
            tot, cnt = acc.get(score, (0.0, 0))
            acc[score] = (tot + wall, cnt + 1)
        return {s: tot / cnt for s, (tot, cnt) in acc.items()}

    nat_mean = mean_wall_by_score(native)
    dev_mean = mean_wall_by_score(device)
    nat_global = sum(w for _, w in native) / len(native)
    dev_global = sum(w for _, w in device) / len(device)
    scores = sorted(set(nat_mean) | set(dev_mean))

    def cost(threshold: int) -> float:
        total = 0.0
        for s in scores:
            n_nat = sum(1 for sc, _ in native if sc == s)
            n_dev = sum(1 for sc, _ in device if sc == s)
            count = n_nat + n_dev
            if s <= threshold:
                # This score's jobs would race native: price them at the
                # observed native wall for the score, falling back to the
                # global native mean where that route was never sampled.
                total += count * nat_mean.get(s, nat_global)
            else:
                total += count * dev_mean.get(s, dev_global)
        return total

    candidates = sorted({default, *scores, max(scores) + 1})
    best = min(candidates, key=lambda t: (cost(t), abs(t - default)))
    report["fitted"] = True
    report["cost_default"] = round(cost(default), 3)
    report["cost_best"] = round(cost(best), 3)
    report["scores_seen"] = len(scores)
    return int(best), report


def learned_easy_score(
    path: str, default: int = 64, min_samples: int = 8
) -> Tuple[int, dict]:
    """Convenience: fit from an ordering-trace JSONL file on disk."""
    from distributed_sudoku_solver_tpu.obs import ordertrace

    return fit_easy_score(
        ordertrace.read_events(path), default=default, min_samples=min_samples
    )
