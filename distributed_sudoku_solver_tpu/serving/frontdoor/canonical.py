"""Symmetry-canonical forms under the sudoku equivalence group.

Two boards are *equivalent* when one maps to the other by a composition of
the standard validity-preserving symmetries:

* digit relabeling (any permutation of 1..n; empty cells stay empty),
* row permutations within a band, and band permutations,
* column permutations within a stack, and stack permutations,
* transpose (square-box geometries only — transposing a 2x3-box board
  yields the conjugate 3x2 geometry, a different board family).

For 9x9 that is 2 * (3! * 3!^3)^2 cell transforms (~3.36 million) times
9! relabelings — the ~3x10^6 published-puzzle aliasing the result cache
collapses.  :func:`canonicalize` returns the *orbit minimum*: the
lexicographically least grid (row-major, empty=0 sorting first, digits
relabeled by first appearance) over the full group, plus the transform
that maps the submitted board onto it.  Equivalent boards therefore
produce byte-identical canonical forms, and the transform's inverse maps
a cached canonical solution back to the submitted frame bit-exactly
(:func:`restore_solution`).

Pure host-side stdlib + numpy — no jax, no device.  The search is exact,
not heuristic, and fully vectorized: a frontier of partial candidates
(one per surviving column-transform/row-prefix/relabel-map combination)
advances one canonical row per step, keeping only prefix-minimal states
— every state proposes its legal next rows, all proposals are relabeled
under their states' partial digit maps in one batched numpy pass, and
only proposals matching the minimal relabeled row survive.  States whose
futures are provably identical (same partial map, same remaining row
content) deduplicate through an ``np.unique`` over integer key rows.
The walk's shape is conjugation-invariant, so the ``max_states`` safety
cap — which declares a pathologically symmetric board *uncacheable*
rather than burning CPU on it — triggers identically for every
representative of an orbit (the cache stays consistent).

Geometries whose column-transform count exceeds ``_MAX_COL_TRANSFORMS``
(16x16 and up: 24 * 24^4 per side) are uncacheable by policy: the exact
minimization is no longer enumerable host-side, and interactive repeat
traffic is 9x9-and-below in practice.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import itertools
from typing import Optional

import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import Geometry

#: Column-transform enumeration bound: 9x9 (3! * 3!^3 = 1296) is in,
#: 12x12 (3! * 4!^3 = 82944) and 16x16 (24 * 24^4) are out.
_MAX_COL_TRANSFORMS = 1500

#: Frontier-walk safety cap (see module docstring): orbit-invariant, so
#: "too symmetric to canonicalize cheaply" is a property of the board's
#: orbit, never of which representative arrived.  Measured frontiers on
#: real boards stay well under 100 states after deduplication.
MAX_STATES = 4096

#: Sorts after every real packed row in the dedupe keys (packed rows use
#: at most 62 bits, all non-negative).
_SENTINEL = np.int64(1) << 62


@dataclasses.dataclass(frozen=True)
class Transform:
    """A group element mapping a submitted board onto its canonical form.

    ``canonical[r, c] = relabel[g[row_perm[r], col_perm[c]]]`` where ``g``
    is the submitted grid, transposed first when ``transpose`` is set.
    ``relabel`` has length n+1 with ``relabel[0] == 0`` (empty is fixed);
    it is the greedy first-appearance map of the canonical scan, completed
    deterministically (unseen digits take the remaining labels in
    ascending digit order) so a full solution grid round-trips.
    """

    transpose: bool
    row_perm: tuple
    col_perm: tuple
    relabel: tuple


@dataclasses.dataclass(frozen=True)
class CanonicalForm:
    grid: np.ndarray  # int8[n, n], the orbit-minimal representative
    transform: Transform  # submitted frame -> canonical frame
    geom: Geometry

    @property
    def digest(self) -> str:
        """Content address of the orbit: sha256 over geometry + canonical
        bytes.  Distinct orbits collide only if sha256 does."""
        h = hashlib.sha256()
        h.update(f"{self.geom.box_h}x{self.geom.box_w}:".encode())
        h.update(np.ascontiguousarray(self.grid, dtype=np.uint8).tobytes())
        return h.hexdigest()


def apply_transform(grid, tr: Transform) -> np.ndarray:
    """Apply ``tr`` to a grid (puzzle or full solution) — submitted frame
    into the canonical frame."""
    g = np.asarray(grid)
    if tr.transpose:
        g = g.T
    rel = np.asarray(tr.relabel, dtype=g.dtype)
    return rel[g[np.ix_(np.asarray(tr.row_perm), np.asarray(tr.col_perm))]]


def restore_solution(canon_grid, tr: Transform) -> np.ndarray:
    """Invert ``tr``: map a canonical-frame grid (typically the cached
    solution) back to the submitted frame, bit-exactly."""
    c = np.asarray(canon_grid)
    n = c.shape[0]
    inv_rel = np.zeros(n + 1, dtype=c.dtype)
    for v, lab in enumerate(tr.relabel):
        inv_rel[lab] = v
    out = np.zeros_like(c)
    out[np.ix_(np.asarray(tr.row_perm), np.asarray(tr.col_perm))] = inv_rel[c]
    if tr.transpose:
        out = out.T
    return out


def random_transform(geom: Geometry, rng: np.random.Generator) -> Transform:
    """A uniformly random group element (the generator-composition tests
    and the bench's symmetry-transformed repeats both draw from here)."""
    n, bh, bw = geom.n, geom.box_h, geom.box_w
    row_perm = np.concatenate(
        [band * bh + rng.permutation(bh) for band in rng.permutation(geom.n_vboxes)]
    )
    col_perm = np.concatenate(
        [stack * bw + rng.permutation(bw) for stack in rng.permutation(geom.n_hboxes)]
    )
    relabel = np.concatenate([[0], rng.permutation(n) + 1])
    transpose = bool(bh == bw and rng.integers(2))
    return Transform(
        transpose=transpose,
        row_perm=tuple(int(r) for r in row_perm),
        col_perm=tuple(int(c) for c in col_perm),
        relabel=tuple(int(v) for v in relabel),
    )


@functools.lru_cache(maxsize=8)
def _col_transforms(box_w: int, n_stacks: int) -> Optional[np.ndarray]:
    """Every stack-respecting column order as an index array [C, n], or
    None when C exceeds the enumeration bound."""
    count = 1
    for k in range(2, n_stacks + 1):
        count *= k
    inner = 1
    for k in range(2, box_w + 1):
        inner *= k
    count *= inner**n_stacks
    if count > _MAX_COL_TRANSFORMS:
        return None
    stack_perms = list(itertools.permutations(range(n_stacks)))
    within = list(itertools.permutations(range(box_w)))
    orders = []
    for sp in stack_perms:
        for combo in itertools.product(within, repeat=n_stacks):
            order = []
            for pos, stack in enumerate(sp):
                order.extend(stack * box_w + w for w in combo[pos])
            orders.append(order)
    return np.asarray(orders, dtype=np.int64)


def _relabel_rows(rows: np.ndarray) -> np.ndarray:
    """First-appearance relabel of each row independently (vectorized):
    zeros stay zero; the j-th distinct nonzero value becomes j+1."""
    m, n = rows.shape
    eq = rows[:, None, :] == rows[:, :, None]  # eq[b, j, k]: rows[b,k]==rows[b,j]
    first = eq.argmax(axis=2)  # first index holding this value
    nz = rows != 0
    is_first = (first == np.arange(n)) & nz
    ranks = np.cumsum(is_first, axis=1)
    labels = np.take_along_axis(ranks, first, axis=1)
    return np.where(nz, labels, 0)


def _pack(rows: np.ndarray, n: int, bits: int) -> np.ndarray:
    shifts = (bits * (n - 1 - np.arange(n))).astype(np.int64)
    return (rows.astype(np.int64) << shifts).sum(axis=-1)


def canonicalize(
    grid, geom: Geometry, max_states: int = MAX_STATES
) -> Optional[CanonicalForm]:
    """The orbit-minimal form of ``grid`` under the full equivalence
    group, or None when the board is uncacheable (geometry beyond the
    enumeration bound, or a pathologically symmetric orbit tripping the
    conjugation-invariant ``max_states`` cap)."""
    n, bh, bw = geom.n, geom.box_h, geom.box_w
    nb = geom.n_vboxes
    bits = max(1, int(n).bit_length())
    if n * bits > 62:  # packed-row comparison must fit one int64
        return None
    ci = _col_transforms(bw, geom.n_hboxes)
    if ci is None:
        return None
    g = np.asarray(grid, dtype=np.int64)
    if g.shape != (n, n) or g.min() < 0 or g.max() > n:
        raise ValueError(f"grid must be int[{n},{n}] in 0..{n}, got {g.shape}")

    # Candidate-row tensor: aa[s, r] = source row r under the column
    # order of flat state s (transpose frame stacked after the plain one
    # — transpose is only in the group for square boxes; a non-square-box
    # transpose belongs to the conjugate geometry).
    c_count = ci.shape[0]
    g8 = g.astype(np.int8)  # n <= 25: int8 keeps the transform tensor small
    frames = [g8] if bh != bw else [g8, g8.T.copy()]
    aa = np.concatenate(
        [gf[:, ci].transpose(1, 0, 2) for gf in frames]
    )  # (S0, n, n) int8 with S0 = len(frames) * C
    s0 = aa.shape[0]
    band_of_row = np.repeat(np.arange(nb), bh)
    one_bit_weights = np.int64(1) << np.arange(n - 1, -1, -1, dtype=np.int64)
    # Level-0 skeleton scan: every candidate first row's empty/filled
    # pattern, packed one bit per cell (matmul: one pass, no int64
    # temporaries).  Computed on the whole transform set — everything
    # heavier below only ever touches the tiny surviving slice.
    patt0 = (aa != 0) @ one_bit_weights  # (S0, n)

    # Frontier state (one row per surviving partial candidate):
    fc = np.arange(s0)  # flat column-transform/frame id
    used = np.zeros((s0, n), dtype=bool)  # source rows consumed
    maps = np.zeros((s0, n + 1), dtype=np.int64)  # digit -> label (0 = unset)
    sizes = np.zeros(s0, dtype=np.int64)  # labels assigned so far
    last_band = np.full(s0, -1, dtype=np.int64)
    row_hist = np.zeros((s0, 0), dtype=np.int64)  # chosen source rows, in order

    canon_rows = []
    for _level in range(n):
        s = fc.shape[0]
        if _level == 0:
            # Every row of every transform is legal; the skeleton scan is
            # the exact prefilter (0 sorts before any label, so only
            # pattern-minimal rows can win the relabeled comparison).
            sidx, rsel = np.nonzero(patt0 == patt0.min())
        else:
            # Legal next rows: the current band's remaining rows while it
            # is incomplete, else any row of an untouched band.
            band_counts = used.reshape(s, nb, bh).sum(axis=2)
            lb_count = np.take_along_axis(
                band_counts, last_band[:, None], axis=1
            )[:, 0]
            in_cur = lb_count < bh
            allowed_cur = (band_of_row[None, :] == last_band[:, None]) & ~used
            allowed_new = (band_counts[:, band_of_row] == 0) & ~used
            allowed = np.where(in_cur[:, None], allowed_cur, allowed_new)
            sidx, rsel = np.nonzero(allowed)
        vals = aa[fc[sidx], rsel]  # (P, n) raw row values
        # Same exact skeleton prefilter on the in-walk proposals.
        patt = (vals != 0) @ one_bit_weights
        pre = np.flatnonzero(patt == patt.min())
        sidx, rsel, vals = sidx[pre], rsel[pre], vals[pre]
        # Batched greedy relabel under each proposal's partial map: mapped
        # digits read their label, unmapped nonzero digits get fresh
        # labels in first-appearance order starting at the map's size.
        base = np.take_along_axis(maps[sidx], vals, axis=1)
        unm = (vals > 0) & (base == 0)
        fresh = _relabel_rows(np.where(unm, vals, 0))
        final = base + np.where(fresh > 0, fresh + sizes[sidx, None], 0)

        packed = _pack(final, n, bits)
        best = packed.min()
        surv = np.flatnonzero(packed == best)
        canon_rows.append(np.asarray(final[surv[0]], dtype=np.int8))

        # Advance the surviving proposals into the next frontier.
        sidx_s, r_s = sidx[surv], rsel[surv]
        fc = fc[sidx_s]
        used = used[sidx_s].copy()
        used[np.arange(surv.size), r_s] = True
        maps = maps[sidx_s].copy()
        u0, u1 = np.nonzero(unm[surv])
        maps[u0, vals[surv][u0, u1]] = final[surv][u0, u1]
        sizes = sizes[sidx_s] + fresh[surv].max(axis=1)
        last_band = band_of_row[r_s]
        row_hist = np.concatenate([row_hist[sidx_s], r_s[:, None]], axis=1)

        # Dedupe states with provably identical futures: same partial
        # map, same remaining rows of the current band, same multiset of
        # untouched-band contents (sorted; consumed slots -> sentinel).
        # Pure pruning — skipping it on an already-tiny frontier is
        # cheaper than running it.
        k = fc.shape[0]
        if k <= 4:
            if k > max_states:  # pragma: no cover - k <= 4 here
                return None
            continue
        band_counts = used.reshape(k, nb, bh).sum(axis=2)
        band_rows = last_band[:, None] * bh + np.arange(bh)[None, :]
        # Packed raw rows of just the surviving states (k is tiny after
        # level 0 — packing all S0 transforms up front would dominate).
        rawp = _pack(aa[fc].reshape(-1, n), n, bits).reshape(k, n)
        band_sorted = np.sort(rawp.reshape(k, nb, bh), axis=2)
        in_band = np.take_along_axis(rawp, band_rows, axis=1)
        in_band = np.where(
            np.take_along_axis(used, band_rows, axis=1), _SENTINEL, in_band
        )
        in_band.sort(axis=1)
        other = np.where(
            (band_counts > 0)[:, :, None], _SENTINEL, band_sorted
        )
        other = np.ascontiguousarray(other)
        # Structured view: sorts the nb band-triples of each state
        # lexicographically without leaving numpy.
        view = other.view([(f"b{i}", np.int64) for i in range(bh)]).reshape(k, nb)
        view.sort(axis=1)
        key = np.concatenate(
            [maps, in_band, other.reshape(k, nb * bh)], axis=1
        )
        _, keep = np.unique(key, axis=0, return_index=True)
        keep.sort()
        fc, used, maps = fc[keep], used[keep], maps[keep]
        sizes, last_band, row_hist = sizes[keep], last_band[keep], row_hist[keep]
        if fc.shape[0] > max_states:
            return None

    # Any surviving state realizes the canonical grid; take the first.
    mapping = {d: int(maps[0, d]) for d in range(1, n + 1) if maps[0, d]}
    for d in range(1, n + 1):  # complete deterministically (see Transform)
        if d not in mapping:
            mapping[d] = len(mapping) + 1
    relabel = [0] * (n + 1)
    for d, lab in mapping.items():
        relabel[d] = lab
    tr = Transform(
        transpose=bool(fc[0] >= c_count),
        row_perm=tuple(int(r) for r in row_hist[0]),
        col_perm=tuple(int(c) for c in ci[int(fc[0]) % c_count]),
        relabel=tuple(relabel),
    )
    canon = np.asarray(canon_rows, dtype=np.int8)
    # The walk and the direct application must agree cell for cell; this
    # is the internal consistency check the round-trip contract rests on.
    check = apply_transform(g, tr).astype(np.int8)
    if not np.array_equal(check, canon):  # pragma: no cover - invariant
        raise AssertionError("canonical walk and transform disagree")
    return CanonicalForm(grid=canon, transform=tr, geom=geom)
