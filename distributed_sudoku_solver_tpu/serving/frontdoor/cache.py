"""Bounded content-addressed result cache keyed on canonical digests.

Values are verdicts in the CANONICAL frame: a solved entry stores the
canonical solution (mapped back to each requester's frame via that
request's own inverse transform — the entry itself is frame-free), an
unsat entry stores the negative verdict (proven unsatisfiability is an
orbit property, so one proof answers every equivalent board).  Overflowed
or errored searches are never cached: no verdict, no entry.

``lookup_entry``/``store_entry`` (named to stay unique in the repo's
method vocabulary — deadck resolves cross-module calls by name) do LRU
bookkeeping under a single deadck-ranked lock (``frontdoor.cache``,
acquired by HTTP handler threads at lookup, the device loop at device-
verdict insert, and the portfolio native-racer threads at native-verdict
insert; it nests inside the engine/scheduler locks rank-upward and holds
nothing further).  All counters are lockck-guarded.  Stdlib + numpy only.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Optional

import numpy as np

from distributed_sudoku_solver_tpu.obs import lockdep

#: Verdicts an entry may carry (``unsat`` entries are the negative form).
SOLVED, UNSAT = "solved", "unsat"


@dataclasses.dataclass(frozen=True)
class CacheEntry:
    verdict: str  # SOLVED | UNSAT
    solution: Optional[np.ndarray]  # int8[n, n] canonical frame; None for UNSAT
    nodes: int  # the original search's expanded nodes (stats parity)
    raw_digest: str  # digest of the submitted board that FILLED the entry
    #   (a later hit from a different representative is a canonical dup)
    route: str  # which tier produced the verdict (propagation/native/device)


class ResultCache:
    """Bounded LRU store: canonical digest -> :class:`CacheEntry`."""

    def __init__(self, capacity: int = 65536):
        self.capacity = max(1, int(capacity))
        self._lock = lockdep.named_lock("frontdoor.cache")  # lockck: name(frontdoor.cache)
        self._entries: "OrderedDict[str, CacheEntry]" = OrderedDict()
        self.hits = 0  # lockck: guard(_lock)
        self.negative_hits = 0  # lockck: guard(_lock) — hits answered from an UNSAT entry
        self.misses = 0  # lockck: guard(_lock)
        self.evictions = 0  # lockck: guard(_lock)
        self.insertions = 0  # lockck: guard(_lock)
        self.canonical_dups = 0  # lockck: guard(_lock) — hits whose submitted
        #   board differed from the entry's filler (a symmetry-transformed repeat)

    def lookup_entry(self, digest: str, raw_digest: str) -> Optional[CacheEntry]:
        with self._lock:
            entry = self._entries.get(digest)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(digest)
            self.hits += 1
            if entry.verdict == UNSAT:
                self.negative_hits += 1
            if entry.raw_digest != raw_digest:
                self.canonical_dups += 1
            return entry

    def store_entry(self, digest: str, entry: CacheEntry) -> None:
        with self._lock:
            if digest not in self._entries and len(self._entries) >= self.capacity:
                self._entries.popitem(last=False)
                self.evictions += 1
            # Last write wins on a racing double-fill of the same orbit:
            # both verdicts are correct (solutions of a unique puzzle are
            # equal in any frame), so there is nothing to reconcile.
            self._entries[digest] = entry
            self._entries.move_to_end(digest)
            self.insertions += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def metrics(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "capacity": self.capacity,
                "hits": int(self.hits),
                "negative_hits": int(self.negative_hits),
                "misses": int(self.misses),
                "evictions": int(self.evictions),
                "insertions": int(self.insertions),
                "canonical_dups": int(self.canonical_dups),
            }
