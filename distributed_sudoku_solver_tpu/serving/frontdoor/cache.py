"""Bounded content-addressed result cache keyed on canonical digests.

Values are verdicts in the CANONICAL frame: a solved entry stores the
canonical solution (mapped back to each requester's frame via that
request's own inverse transform — the entry itself is frame-free), an
unsat entry stores the negative verdict (proven unsatisfiability is an
orbit property, so one proof answers every equivalent board).  Overflowed
or errored searches are never cached: no verdict, no entry.

``lookup_entry``/``store_entry`` (named to stay unique in the repo's
method vocabulary — deadck resolves cross-module calls by name) do LRU
bookkeeping under a single deadck-ranked lock (``frontdoor.cache``,
acquired by HTTP handler threads at lookup, the device loop at device-
verdict insert, and the portfolio native-racer threads at native-verdict
insert; it nests inside the engine/scheduler locks rank-upward and holds
nothing further).  All counters are lockck-guarded.  Stdlib + numpy only.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Optional

import numpy as np

from distributed_sudoku_solver_tpu.obs import lockdep

#: Verdicts an entry may carry (``unsat`` entries are the negative form).
SOLVED, UNSAT = "solved", "unsat"


@dataclasses.dataclass(frozen=True)
class CacheEntry:
    verdict: str  # SOLVED | UNSAT
    solution: Optional[np.ndarray]  # int8[n, n] canonical frame; None for UNSAT
    nodes: int  # the original search's expanded nodes (stats parity)
    raw_digest: str  # digest of the submitted board that FILLED the entry
    #   (a later hit from a different representative is a canonical dup)
    route: str  # which tier produced the verdict (propagation/native/device)


class ResultCache:
    """Bounded LRU store: canonical digest -> :class:`CacheEntry`."""

    def __init__(self, capacity: int = 65536):
        self.capacity = max(1, int(capacity))
        self._lock = lockdep.named_lock("frontdoor.cache")  # lockck: name(frontdoor.cache)
        self._entries: "OrderedDict[str, CacheEntry]" = OrderedDict()
        self.hits = 0  # lockck: guard(_lock)
        self.negative_hits = 0  # lockck: guard(_lock) — hits answered from an UNSAT entry
        self.misses = 0  # lockck: guard(_lock)
        self.evictions = 0  # lockck: guard(_lock)
        self.insertions = 0  # lockck: guard(_lock)
        self.canonical_dups = 0  # lockck: guard(_lock) — hits whose submitted
        #   board differed from the entry's filler (a symmetry-transformed repeat)

    def lookup_entry(self, digest: str, raw_digest: str) -> Optional[CacheEntry]:
        with self._lock:
            entry = self._entries.get(digest)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(digest)
            self.hits += 1
            if entry.verdict == UNSAT:
                self.negative_hits += 1
            if entry.raw_digest != raw_digest:
                self.canonical_dups += 1
            return entry

    def store_entry(self, digest: str, entry: CacheEntry) -> None:
        with self._lock:
            if digest not in self._entries and len(self._entries) >= self.capacity:
                self._entries.popitem(last=False)
                self.evictions += 1
            # Last write wins on a racing double-fill of the same orbit:
            # both verdicts are correct (solutions of a unique puzzle are
            # equal in any frame), so there is nothing to reconcile.
            self._entries[digest] = entry
            self._entries.move_to_end(digest)
            self.insertions += 1

    def export_hot(self, limit: int = 1024) -> list:
        """Drain-time snapshot (serving/journal.py sidecar): the hottest
        ``limit`` entries as JSON-able dicts, hottest LAST so re-importing
        in order restores the LRU recency ranking."""
        with self._lock:
            items = list(self._entries.items())[-max(1, int(limit)):]
        return [
            {
                "digest": digest,
                "verdict": e.verdict,
                "solution": None if e.solution is None
                else [[int(v) for v in row] for row in e.solution],
                "nodes": int(e.nodes),
                "raw_digest": e.raw_digest,
                "route": e.route,
            }
            for digest, e in items
        ]

    def import_hot(self, entries: list) -> int:
        """Restore a drain-time snapshot on boot (the cache-warm half of
        journal recovery).  Malformed entries are skipped — a stale or
        truncated snapshot degrades to a colder cache, never an error."""
        n = 0
        for d in entries:
            if not isinstance(d, dict):
                continue
            try:
                sol = d.get("solution")
                entry = CacheEntry(
                    verdict=str(d["verdict"]),
                    solution=None if sol is None else np.asarray(sol, np.int8),
                    nodes=int(d.get("nodes", 0)),
                    raw_digest=str(d.get("raw_digest", "")),
                    route=str(d.get("route", "restored")),
                )
            except (KeyError, TypeError, ValueError):
                continue
            self.store_entry(str(d["digest"]), entry)
            n += 1
        return n

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def metrics(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "capacity": self.capacity,
                "hits": int(self.hits),
                "negative_hits": int(self.negative_hits),
                "misses": int(self.misses),
                "evictions": int(self.evictions),
                "insertions": int(self.insertions),
                "canonical_dups": int(self.canonical_dups),
            }
