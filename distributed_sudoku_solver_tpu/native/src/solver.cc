// Native CPU oracle: geometry-generic bitmask DFS solver + validator.
//
// Role (SURVEY.md §4): the framework's *test authority* and host-side
// reference, replacing the reference repo's only kernel — the pure-Python
// recursive `solve_sudoku` (/root/reference/utils.py:14-55,
// /root/reference/DHT_Node.py:474-538, ~185k recursions/s) — with a compiled
// implementation of the same observable search semantics:
//
//   * branch on the first empty cell in row-major order
//     (the reference's `find_next_empty`, /root/reference/utils.py:14-25),
//   * try digits in ascending order (/root/reference/DHT_Node.py:522),
//
// so the first solution found is the lexicographically-least completion —
// bit-exact with both the Python oracle (utils/oracle.py) and, on
// unique-solution boards, the TPU frontier solver.  No code or structure is
// shared with the reference: this is bitmask row/col/box state, not list
// scans.
//
// Built as a plain shared library; bound via ctypes (no pybind11 in image).

#include <cstdint>

namespace {

struct Geom {
  int n, box_h, box_w, n_hboxes;
};

// All-digits mask; `1u << 32` is UB, so n == 32 (kMaxN) is special-cased.
inline uint32_t full_mask(int n) {
  return (n >= 32) ? 0xffffffffu : ((1u << n) - 1u);
}

inline int box_of(const Geom& g, int r, int c) {
  return (r / g.box_h) * g.n_hboxes + (c / g.box_w);
}

// DFS over empty cells in row-major order, ascending digit order.
// `limit` caps the number of solutions counted; the first solution found is
// copied into `out` (if non-null).  Returns the number of solutions found
// (saturated at `limit`).  `nodes` counts cell-assignment attempts — the
// analog of the reference's `validations` counter
// (/root/reference/DHT_Node.py:512-513).
struct Searcher {
  Geom g;
  const int* empties;  // flat indices of empty cells, row-major ascending
  int n_empty;
  uint32_t* rows;
  uint32_t* cols;
  uint32_t* boxes;
  int32_t* grid;  // working copy, n*n
  int32_t* out;   // first solution, n*n (nullable)
  int limit;
  int found = 0;
  int64_t nodes = 0;

  void dfs(int depth) {
    if (found >= limit) return;
    if (depth == n_empty) {
      ++found;
      if (found == 1 && out != nullptr) {
        for (int i = 0; i < g.n * g.n; ++i) out[i] = grid[i];
      }
      return;
    }
    const int idx = empties[depth];
    const int r = idx / g.n, c = idx % g.n, b = box_of(g, r, c);
    uint32_t avail = ~(rows[r] | cols[c] | boxes[b]) & full_mask(g.n);
    while (avail != 0) {
      const uint32_t bit = avail & (~avail + 1u);  // lowest set bit: ascending
      avail &= avail - 1u;
      ++nodes;
      rows[r] |= bit;
      cols[c] |= bit;
      boxes[b] |= bit;
      grid[idx] = __builtin_ctz(bit) + 1;
      dfs(depth + 1);
      rows[r] &= ~bit;
      cols[c] &= ~bit;
      boxes[b] &= ~bit;
      grid[idx] = 0;
      if (found >= limit) return;
    }
  }
};

// Shared setup: returns 0 on success, -1 on malformed input, -2 on an
// immediate clue conflict (caller reports unsat with 0 solutions).
int setup(const int32_t* in, const Geom& g, uint32_t* rows, uint32_t* cols,
          uint32_t* boxes, int32_t* grid, int* empties, int* n_empty) {
  const int n = g.n;
  for (int i = 0; i < n; ++i) rows[i] = cols[i] = boxes[i] = 0;
  *n_empty = 0;
  for (int idx = 0; idx < n * n; ++idx) {
    const int v = in[idx];
    if (v < 0 || v > n) return -1;
    grid[idx] = v;
    if (v == 0) {
      empties[(*n_empty)++] = idx;
      continue;
    }
    const int r = idx / n, c = idx % n, b = box_of(g, r, c);
    const uint32_t bit = 1u << (v - 1);
    if ((rows[r] | cols[c] | boxes[b]) & bit) return -2;
    rows[r] |= bit;
    cols[c] |= bit;
    boxes[b] |= bit;
  }
  return 0;
}

constexpr int kMaxN = 32;

}  // namespace

extern "C" {

// Count solutions up to `limit`; fill `out` (nullable) with the first one.
// Returns: >=0 number of solutions found (saturated), -1 malformed input.
int csp_count_solutions(const int32_t* in, int n, int box_h, int box_w,
                        int limit, int32_t* out, int64_t* nodes_out) {
  if (n < 1 || n > kMaxN || box_h < 1 || box_w < 1 || box_h * box_w != n) {
    return -1;
  }
  Geom g{n, box_h, box_w, n / box_w};
  uint32_t rows[kMaxN], cols[kMaxN], boxes[kMaxN];
  int32_t grid[kMaxN * kMaxN];
  int empties[kMaxN * kMaxN];
  int n_empty = 0;
  const int rc = setup(in, g, rows, cols, boxes, grid, empties, &n_empty);
  if (rc == -1) return -1;
  if (rc == -2) {
    if (nodes_out != nullptr) *nodes_out = 0;
    return 0;
  }
  Searcher s{g, empties, n_empty, rows, cols, boxes, grid, out, limit};
  s.dfs(0);
  if (nodes_out != nullptr) *nodes_out = s.nodes;
  return s.found;
}

// Solve in place toward the lexicographically-least completion.
// Returns 1 solved (grid overwritten), 0 proven unsat, -1 malformed input.
int csp_solve(int32_t* grid, int n, int box_h, int box_w, int64_t* nodes_out) {
  int32_t out[kMaxN * kMaxN];
  const int found =
      csp_count_solutions(grid, n, box_h, box_w, 1, out, nodes_out);
  if (found < 0) return -1;
  if (found == 0) return 0;
  for (int i = 0; i < n * n; ++i) grid[i] = out[i];
  return 1;
}

// Validate a complete board: every unit contains each digit exactly once.
// Returns 1 valid, 0 invalid.  (The reference's `Sudoku.check` intends this
// but NameErrors on any valid grid — /root/reference/sudoku.py:68,
// SURVEY.md §2.5 #1; this is the corrected capability.)
int csp_is_valid_solution(const int32_t* grid, int n, int box_h, int box_w) {
  if (n < 1 || n > kMaxN || box_h < 1 || box_w < 1 || box_h * box_w != n) {
    return 0;
  }
  Geom g{n, box_h, box_w, n / box_w};
  const uint32_t full = full_mask(n);
  uint32_t rows[kMaxN] = {0}, cols[kMaxN] = {0}, boxes[kMaxN] = {0};
  for (int idx = 0; idx < n * n; ++idx) {
    const int v = grid[idx];
    if (v < 1 || v > n) return 0;
    const int r = idx / n, c = idx % n, b = box_of(g, r, c);
    const uint32_t bit = 1u << (v - 1);
    if ((rows[r] & bit) || (cols[c] & bit) || (boxes[b] & bit)) return 0;
    rows[r] |= bit;
    cols[c] |= bit;
    boxes[b] |= bit;
  }
  for (int i = 0; i < n; ++i) {
    if (rows[i] != full || cols[i] != full || boxes[i] != full) return 0;
  }
  return 1;
}

// Batch solve: `grids` is count contiguous n*n boards, solved in place.
// results[i]: 1 solved, 0 unsat, -1 malformed.  nodes[i] (nullable): per-board
// node counts.  Returns number solved.
int csp_solve_batch(int32_t* grids, int count, int n, int box_h, int box_w,
                    int32_t* results, int64_t* nodes) {
  int solved = 0;
  for (int i = 0; i < count; ++i) {
    int64_t nd = 0;
    const int r = csp_solve(grids + (int64_t)i * n * n, n, box_h, box_w, &nd);
    if (results != nullptr) results[i] = r;
    if (nodes != nullptr) nodes[i] = nd;
    if (r == 1) ++solved;
  }
  return solved;
}

}  // extern "C"

namespace {

// Generalized exact cover, counting all solutions.  Operates on the exact
// arrays models/cover.py::ExactCoverCSP carries (col_rows / row_cols /
// elim as packed uint32 words), so the native baseline and the TPU engine
// search the *identical* matrix — the benchmark contract of
// benchmarks/bench_cover.py.  MRV column choice (fewest available rows),
// ascending row order within a column: the same heuristic family as the
// device kernels, recursion instead of lane stacks.
struct CoverSearcher {
  const uint32_t* col_rows;  // [n_primary][w_rows]
  const uint32_t* row_cols;  // [n_rows][w_cols]
  const uint32_t* elim;      // [n_rows][w_rows]
  int n_rows, n_primary, w_rows, w_cols;
  int64_t limit;
  int64_t found = 0;
  int64_t nodes = 0;

  static int popcount_and(const uint32_t* a, const uint32_t* b, int w) {
    int c = 0;
    for (int i = 0; i < w; ++i) c += __builtin_popcount(a[i] & b[i]);
    return c;
  }

  void dfs(uint32_t* avail, uint32_t* covered) {
    if (limit >= 0 && found >= limit) return;
    // MRV: the uncovered primary column with the fewest available rows.
    int best_col = -1, best_cnt = INT32_MAX;
    for (int c = 0; c < n_primary; ++c) {
      if ((covered[c >> 5] >> (c & 31)) & 1u) continue;
      const int cnt = popcount_and(col_rows + c * w_rows, avail, w_rows);
      if (cnt < best_cnt) {
        best_cnt = cnt;
        best_col = c;
        if (cnt == 0) break;
      }
    }
    if (best_col == -1) {  // every primary column covered: one solution
      ++found;
      return;
    }
    if (best_cnt == 0) return;  // dead end
    const uint32_t* crow = col_rows + best_col * w_rows;
    uint32_t navail[128], ncovered[128];  // w_rows, w_cols <= 128 words each
    for (int r = 0; r < n_rows; ++r) {
      if (!((crow[r >> 5] >> (r & 31)) & (avail[r >> 5] >> (r & 31)) & 1u)) {
        continue;
      }
      ++nodes;
      const uint32_t* el = elim + r * w_rows;
      for (int i = 0; i < w_rows; ++i) navail[i] = avail[i] & ~el[i];
      navail[r >> 5] &= ~(1u << (r & 31));
      const uint32_t* rc = row_cols + r * w_cols;
      for (int i = 0; i < w_cols; ++i) ncovered[i] = covered[i] | rc[i];
      dfs(navail, ncovered);
      if (limit >= 0 && found >= limit) return;
    }
  }
};

}  // namespace

extern "C" {

// Count exact-cover solutions up to `limit` (< 0 = unlimited).
// Returns the count, or -1 on malformed sizes.
int64_t cover_count_solutions(const uint32_t* col_rows,
                              const uint32_t* row_cols, const uint32_t* elim,
                              int n_rows, int n_primary, int w_rows,
                              int w_cols, int64_t limit, int64_t* nodes_out) {
  if (n_rows < 1 || n_primary < 1 || w_rows < 1 || w_rows > 128 ||
      w_cols < 1 || w_cols > 128 || n_rows > 32 * w_rows ||
      n_primary > 32 * w_cols) {
    return -1;
  }
  CoverSearcher s{col_rows, row_cols, elim, n_rows, n_primary, w_rows,
                  w_cols, limit};
  uint32_t avail[128], covered[128];
  for (int i = 0; i < w_rows; ++i) {
    avail[i] = 0xffffffffu;
  }
  const int tail = n_rows & 31;
  if (tail) avail[w_rows - 1] = (1u << tail) - 1u;
  for (int i = 0; i < w_cols; ++i) covered[i] = 0u;
  s.dfs(avail, covered);
  if (nodes_out != nullptr) *nodes_out = s.nodes;
  return s.found;
}

}  // extern "C"
