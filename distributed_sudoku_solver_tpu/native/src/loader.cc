// Native bulk puzzle loader: newline-separated board strings -> int32 batches.
//
// The data-plane feeder for the bulk solver (ops/bulk.py).  The reference has
// no dataset path at all — each puzzle arrives as one HTTP POST body parsed
// in Python (/root/reference/DHT_Node.py:546-549); at 10^5-10^6 boards/s of
// solver throughput, Python-side string parsing (~10^5 boards/s single
// thread) would be the pipeline bottleneck, so ingestion is native and
// multithreaded here.
//
// Format, per line: the first field (up to ',', for Kaggle-style CSVs) must
// hold exactly n*n board characters: '.' or '0' = empty, digits then
// lowercase base-36 letters for values (matches utils/puzzles.py parse_line).
// Lines not matching are an error, reported by line index; empty lines and a
// leading header line (detected: first field not n*n board chars) are
// skipped.

#include <cctype>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Board-character value: '.'/'0' -> 0, '1'-'9' -> 1-9, letters -> 10-35
// (base 36 either case, matching Python's int(ch, 36) in
// utils/puzzles.py parse_line); -1 if invalid.
inline int char_value(char ch) {
  if (ch == '.' || ch == '0') return 0;
  if (ch >= '1' && ch <= '9') return ch - '0';
  if (ch >= 'a' && ch <= 'z') return ch - 'a' + 10;
  if (ch >= 'A' && ch <= 'Z') return ch - 'A' + 10;
  return -1;
}

struct LineSpan {
  const char* begin;
  int64_t len;  // excluding newline, outer whitespace trimmed
};

// First comma-separated field of the (pre-trimmed) line, with whitespace
// adjacent to the comma trimmed — byte-for-byte the Python fallback's
// raw.split(',')[0].strip().
inline int64_t field_len(const LineSpan& line) {
  int64_t end = line.len;
  for (int64_t i = 0; i < line.len; ++i) {
    if (line.begin[i] == ',') {
      end = i;
      break;
    }
  }
  while (end > 0 &&
         std::isspace(static_cast<unsigned char>(line.begin[end - 1]))) {
    --end;
  }
  return end;
}

// Parse one line's first field into out[n*n]; returns true on success.
bool parse_line(const LineSpan& line, int n, int32_t* out) {
  const int cells = n * n;
  if (field_len(line) != cells) return false;
  for (int i = 0; i < cells; ++i) {
    const int v = char_value(line.begin[i]);
    if (v < 0 || v > n) return false;
    out[i] = v;
  }
  return true;
}

void split_lines(const char* buf, int64_t len, std::vector<LineSpan>* lines) {
  int64_t start = 0;
  for (int64_t i = 0; i <= len; ++i) {
    if (i == len || buf[i] == '\n') {
      // Trim outer whitespace (editors/CSV exports pad lines; the Python
      // fallback .strip()s, and the two must agree byte-for-byte on which
      // lines exist) — whitespace-only lines count as empty.
      int64_t b = start, e = i;
      while (e > b && std::isspace(static_cast<unsigned char>(buf[e - 1]))) --e;
      while (b < e && std::isspace(static_cast<unsigned char>(buf[b]))) ++b;
      if (e > b) lines->push_back({buf + b, e - b});
      start = i + 1;
    }
  }
}

}  // namespace

extern "C" {

// Parse up to `max_boards` boards out of `buf[0:len]`.
// Returns the number of boards written to `out` (row-major int32 n*n each),
// or -(line_index+1) on the first malformed line (0-based index into the
// non-empty lines, after optional header skip).
// `allow_header` != 0 permits skipping line 0 iff it does not parse as a
// board (Kaggle-style CSV headers); with 0, every line must parse or the
// call errors — callers streaming chunk 2+ of a file use this.
// `n_threads` <= 0 means auto (hardware concurrency).
int64_t csp_parse_boards(const char* buf, int64_t len, int n, int32_t* out,
                         int64_t max_boards, int allow_header, int n_threads) {
  if (n < 1 || n > 35 || len < 0) return -1;
  std::vector<LineSpan> lines;
  split_lines(buf, len, &lines);
  if (lines.empty()) return 0;

  // Header detection: only a first line whose *field length* differs from
  // n*n can be a header (e.g. "quizzes,solutions").  A right-length line
  // with a bad character is a malformed board and errors like any other —
  // silently skipping it would shift every output line by one.
  int64_t first = 0;
  if (allow_header != 0 && field_len(lines[0]) != static_cast<int64_t>(n) * n) {
    first = 1;
  }
  const int64_t count =
      std::min<int64_t>(max_boards, static_cast<int64_t>(lines.size()) - first);
  if (count <= 0) return 0;

  int hw = static_cast<int>(std::thread::hardware_concurrency());
  if (n_threads <= 0) n_threads = hw > 0 ? hw : 4;
  if (n_threads > count) n_threads = static_cast<int>(count);

  std::vector<int64_t> bad(n_threads, -1);
  std::vector<std::thread> threads;
  const int cells = n * n;
  for (int t = 0; t < n_threads; ++t) {
    threads.emplace_back([&, t]() {
      const int64_t lo = count * t / n_threads;
      const int64_t hi = count * (t + 1) / n_threads;
      for (int64_t i = lo; i < hi; ++i) {
        if (!parse_line(lines[first + i], n, out + i * cells)) {
          bad[t] = i;
          return;
        }
      }
    });
  }
  for (auto& th : threads) th.join();
  for (int t = 0; t < n_threads; ++t) {
    if (bad[t] >= 0) return -(bad[t] + 1);
  }
  return count;
}

// Render boards back to text lines (inverse of csp_parse_boards; no commas).
// Each line is n*n chars + '\n'.  Returns bytes written.
int64_t csp_format_boards(const int32_t* boards, int64_t count, int n,
                          char* out) {
  static const char digits[] = "0123456789abcdefghijklmnopqrstuvwxyz";
  const int cells = n * n;
  int64_t pos = 0;
  for (int64_t b = 0; b < count; ++b) {
    const int32_t* g = boards + b * cells;
    for (int i = 0; i < cells; ++i) {
      const int32_t v = g[i];
      out[pos++] = (v >= 0 && v <= 35) ? digits[v] : '?';
    }
    out[pos++] = '\n';
  }
  return pos;
}

}  // extern "C"
