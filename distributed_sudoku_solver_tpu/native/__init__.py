"""ctypes bindings for the native CPU oracle (``src/solver.cc``).

The shared library is built on demand with g++ (no pybind11 in the image —
plain C ABI + ctypes, per the environment constraints).  If no compiler is
available the callers fall back to the pure-Python oracle in
``utils/oracle.py``; everything here is an accelerator, not a dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import Geometry, geometry_for_size
from distributed_sudoku_solver_tpu.obs import lockdep

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_LIB_PATH = os.path.join(os.path.dirname(__file__), "_libcsp.so")
_lock = lockdep.named_lock("native.build")  # lockck: name(native.build)
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _sources() -> list[str]:
    return sorted(
        os.path.join(_SRC_DIR, f)
        for f in os.listdir(_SRC_DIR)
        if f.endswith(".cc")
    )


def _build() -> bool:
    cmd = [
        "g++",
        "-O3",
        "-march=native",
        "-shared",
        "-fPIC",
        "-pthread",
        "-o",
        _LIB_PATH,
        *_sources(),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def load() -> Optional[ctypes.CDLL]:
    """The bound library, building it if needed; None if unavailable."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        stale = not os.path.exists(_LIB_PATH) or any(
            os.path.getmtime(_LIB_PATH) < os.path.getmtime(src)
            for src in _sources()
        )
        if stale and not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _build_failed = True
            return None
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.csp_solve.argtypes = [i32p, ctypes.c_int, ctypes.c_int, ctypes.c_int, i64p]
        lib.csp_solve.restype = ctypes.c_int
        lib.csp_count_solutions.argtypes = [
            i32p, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_void_p, i64p,
        ]
        lib.csp_count_solutions.restype = ctypes.c_int
        lib.csp_is_valid_solution.argtypes = [
            i32p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.csp_is_valid_solution.restype = ctypes.c_int
        lib.csp_solve_batch.argtypes = [
            i32p, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.csp_solve_batch.restype = ctypes.c_int
        lib.csp_parse_boards.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, i32p,
            ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ]
        lib.csp_parse_boards.restype = ctypes.c_int64
        lib.csp_format_boards.argtypes = [
            i32p, ctypes.c_int64, ctypes.c_int, ctypes.c_char_p,
        ]
        lib.csp_format_boards.restype = ctypes.c_int64
        u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
        lib.cover_count_solutions.argtypes = [
            u32p, u32p, u32p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int64, i64p,
        ]
        lib.cover_count_solutions.restype = ctypes.c_int64
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def solve(grid, geom: Optional[Geometry] = None) -> Tuple[Optional[np.ndarray], int]:
    """(lexicographically-least solution | None, nodes expanded)."""
    lib = load()
    if lib is None:
        raise RuntimeError("native solver unavailable (no compiler?)")
    g = np.ascontiguousarray(np.asarray(grid), dtype=np.int32).copy()
    n = g.shape[0]
    geom = geom or geometry_for_size(n)
    nodes = ctypes.c_int64(0)
    rc = lib.csp_solve(g.reshape(-1), n, geom.box_h, geom.box_w, ctypes.byref(nodes))
    if rc < 0:
        raise ValueError("malformed grid")
    return (g if rc == 1 else None), int(nodes.value)


def count_solutions(grid, geom: Optional[Geometry] = None, limit: int = 2) -> int:
    lib = load()
    if lib is None:
        raise RuntimeError("native solver unavailable (no compiler?)")
    g = np.ascontiguousarray(np.asarray(grid), dtype=np.int32)
    n = g.shape[0]
    geom = geom or geometry_for_size(n)
    rc = lib.csp_count_solutions(
        g.reshape(-1), n, geom.box_h, geom.box_w, limit, None, None
    )
    if rc < 0:
        raise ValueError("malformed grid")
    return rc


def is_valid_solution(grid, geom: Optional[Geometry] = None) -> bool:
    lib = load()
    if lib is None:
        raise RuntimeError("native solver unavailable (no compiler?)")
    g = np.ascontiguousarray(np.asarray(grid), dtype=np.int32)
    n = g.shape[0]
    geom = geom or geometry_for_size(n)
    return bool(lib.csp_is_valid_solution(g.reshape(-1), n, geom.box_h, geom.box_w))


def solve_batch(grids, geom: Optional[Geometry] = None):
    """Solve count boards in place; returns (solutions, results, nodes)."""
    lib = load()
    if lib is None:
        raise RuntimeError("native solver unavailable (no compiler?)")
    g = np.ascontiguousarray(np.asarray(grids), dtype=np.int32).copy()
    count, n = g.shape[0], g.shape[1]
    geom = geom or geometry_for_size(n)
    results = np.zeros(count, dtype=np.int32)
    nodes = np.zeros(count, dtype=np.int64)
    lib.csp_solve_batch(
        g.reshape(-1),
        count,
        n,
        geom.box_h,
        geom.box_w,
        results.ctypes.data_as(ctypes.c_void_p),
        nodes.ctypes.data_as(ctypes.c_void_p),
    )
    return g, results, nodes


def parse_boards(data: bytes, n: int, max_boards: Optional[int] = None,
                 allow_header: bool = True, n_threads: int = 0) -> np.ndarray:
    """Parse newline-separated board lines (first CSV field) -> int32[B, n, n].

    Raises ValueError naming the first malformed line.  Blank/whitespace
    lines are skipped; with ``allow_header`` an unparseable *first* line is
    treated as a CSV header, otherwise it is an error (see src/loader.cc).
    """
    lib = load()
    if lib is None:
        raise RuntimeError("native loader unavailable (no compiler?)")
    # Newline count is a free upper bound on board lines (bytes.count is a
    # single memchr pass in C); exact sizing comes from the parse's return.
    upper = data.count(b"\n") + 1
    if max_boards is not None:
        upper = min(upper, int(max_boards))
    out = np.empty((max(upper, 1), n, n), dtype=np.int32)
    got = int(
        lib.csp_parse_boards(
            data, len(data), n, out.reshape(-1), upper, int(allow_header), n_threads
        )
    )
    if got < 0:
        raise ValueError(f"malformed board at data line {-got - 1}")
    return out[:got]


def format_boards(boards) -> bytes:
    """int[B, n, n] -> newline-separated board lines (inverse of parse)."""
    lib = load()
    if lib is None:
        raise RuntimeError("native loader unavailable (no compiler?)")
    g = np.ascontiguousarray(np.asarray(boards), dtype=np.int32)
    if g.ndim != 3 or g.shape[1] != g.shape[2]:
        raise ValueError(f"expected [B, n, n] boards, got shape {g.shape}")
    count, n = g.shape[0], g.shape[1]
    if count == 0:
        return b""
    buf = ctypes.create_string_buffer(count * (n * n + 1))
    written = int(lib.csp_format_boards(g.reshape(-1), count, n, buf))
    return buf.raw[:written]


def cover_count(problem, limit: int = -1) -> Tuple[int, int]:
    """Count exact-cover solutions of an ``ExactCoverCSP`` natively.

    Runs the recursive MRV DFS in ``src/solver.cc`` over the *identical*
    packed matrix the device engine searches (``col_rows``/``row_cols``/
    ``elim``), so device-vs-native rows in ``benchmarks/bench_cover.py``
    compare search engines, not encodings.  Returns ``(count, nodes)``;
    ``limit < 0`` enumerates everything.
    """
    lib = load()
    if lib is None:
        raise RuntimeError("native solver unavailable (no compiler?)")
    col_rows = np.ascontiguousarray(problem.col_rows, dtype=np.uint32)
    row_cols = np.ascontiguousarray(problem.row_cols, dtype=np.uint32)
    elim = np.ascontiguousarray(problem.elim, dtype=np.uint32)
    nodes = ctypes.c_int64(0)
    rc = lib.cover_count_solutions(
        col_rows.reshape(-1),
        row_cols.reshape(-1),
        elim.reshape(-1),
        problem.n_rows,
        problem.n_primary,
        elim.shape[1],
        row_cols.shape[1],
        limit,
        ctypes.byref(nodes),
    )
    if rc < 0:
        raise ValueError("malformed cover instance")
    return int(rc), int(nodes.value)
