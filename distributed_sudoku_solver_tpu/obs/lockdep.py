"""Runtime lockdep witness: the dynamic half of the deadck contract.

``analysis/deadck.py`` proves the lock-acquisition graph *statically*
(every edge the source can take, checked against the declared hierarchy
in ``analysis/manifest.py``); this module is the runtime twin — the same
split as layerck/simnet and jaxck/the retrace guard.  Every lock in the
repo is created through the factories here (``named_lock`` /
``named_rlock`` / ``named_condition``) with its manifest identity
(``manifest.LOCK_RANKS``), and when a :class:`LockWitness` is installed:

* each thread's acquisition stack is tracked (re-entrant RLock
  acquisitions are recognized and excluded — re-entry is not ordering);
* every *new* ordered pair (held -> acquired) lands in one process-wide
  observed graph, dumpable as a ``--json`` artifact that tier-1
  cross-checks against deadck's predicted graph (an observed edge the
  static half didn't predict is a deadck bug — jaxck's golden
  discipline applied to concurrency);
* an acquisition that **violates the declared hierarchy** (rank order +
  ``manifest.LOCK_EDGE_DECLARED`` exceptions) or that **forms a cycle**
  with the edges already observed raises :class:`LockOrderError` at the
  moment it happens — in the thread that would have deadlocked, with
  both stacks' names in the message — and is recorded on
  ``violations`` so a raise swallowed by a daemon thread's catch-all
  still fails the test at teardown (the simnet purity-guard pattern).

**Hot-path contract** (the faults/trace/slo seam, pinned by the
explode-microcheck in tests/test_deadck.py): with no witness installed,
``acquire``/``release`` on a named lock cost ONE module-global read and
one branch over the raw ``threading`` primitive — no allocation, no
thread-local touch, no clock read.  Production never pays for the
witness it is not running.

Import discipline: stdlib only at module import.  The manifest hierarchy
is read lazily inside :func:`install` (the declared
``analysis.manifest`` carve-out in ``manifest.LAYERS``, mirroring
``obs.compilewatch``) so importing this module never drags the analysis
package into the serving hot path.
"""

from __future__ import annotations

import contextlib
import json
import threading
from typing import Dict, List, Optional, Tuple


class LockOrderError(RuntimeError):
    """An acquisition that the declared lock hierarchy forbids (or that
    closes a cycle in the observed order graph) — raised *before* the
    offending acquire blocks, in the thread that would have deadlocked."""


class _Named:
    """Proxy over a raw ``threading`` lock carrying its manifest name.

    The disabled path is the contract: ``_WITNESS`` is read once; when
    ``None`` the call forwards straight to the raw primitive."""

    __slots__ = ("_real", "name", "reentrant")

    def __init__(self, real, name: str, reentrant: bool = False):
        self._real = real
        self.name = name
        self.reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1):
        w = _WITNESS
        if w is None:
            return self._real.acquire(blocking, timeout)
        return w.acquire(self, blocking, timeout)

    def release(self) -> None:
        w = _WITNESS
        self._real.release()
        if w is not None:
            w.released(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        return self._real.locked()

    # -- threading.Condition integration ------------------------------------
    # Condition(lock) picks these up by hasattr at construction; without
    # them a re-entrantly-held RLock would be released one level instead
    # of fully around a wait().  The witness bookkeeping mirrors the real
    # state: a fully-released lock leaves the held stack, the re-acquire
    # after the wait re-enters it (no edge recording on the restore — a
    # condition re-acquire is wait protocol, not a new ordering decision,
    # and the original acquisition already recorded the edges).
    def _release_save(self):
        w = _WITNESS
        depth = w.release_all(self) if w is not None else 0
        if hasattr(self._real, "_release_save"):
            return (self._real._release_save(), depth)
        self._real.release()
        return (None, depth)

    def _acquire_restore(self, state) -> None:
        real_state, depth = state if isinstance(state, tuple) else (state, 1)
        if real_state is not None and hasattr(self._real, "_acquire_restore"):
            self._real._acquire_restore(real_state)
        else:
            self._real.acquire()
        w = _WITNESS
        if w is not None:
            # Re-push the pre-wait depth (a witness armed mid-wait saw no
            # release_all; max(1, 0) keeps the stack at least honest).
            w.restored(self, depth)

    def _is_owned(self) -> bool:
        if hasattr(self._real, "_is_owned"):
            return self._real._is_owned()
        if self._real.acquire(False):
            self._real.release()
            return False
        return True

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<named-{type(self._real).__name__} {self.name!r}>"


def named_lock(name: str) -> _Named:
    """A ``threading.Lock`` carrying its ``manifest.LOCK_RANKS`` identity."""
    return _Named(threading.Lock(), name)


def named_rlock(name: str) -> _Named:
    """A ``threading.RLock`` twin; re-entrant acquisitions are recognized
    by the witness and never recorded as ordering edges."""
    return _Named(threading.RLock(), name, reentrant=True)


def named_condition(name: str) -> threading.Condition:
    """A ``threading.Condition`` whose underlying (R)Lock is named —
    ``wait``'s release/re-acquire round-trips keep the witness stack
    honest through the ``_release_save``/``_acquire_restore`` seam."""
    return threading.Condition(named_rlock(name))


class LockWitness:
    """Process-wide acquisition recorder + hierarchy referee.

    ``ranks`` maps lock name -> hierarchy level (acquire strictly
    *upward*: holding A you may take B iff rank[A] < rank[B]);
    ``declared`` maps (held, acquired) -> reason for the blessed
    exceptions (the slo burn-dump re-entrancy family).  Both default to
    the manifest via :func:`install`.  ``strict`` raises on violations
    (they are *always* recorded)."""

    def __init__(
        self,
        ranks: Optional[Dict[str, int]] = None,
        declared: Optional[Dict[Tuple[str, str], str]] = None,
        strict: bool = True,
    ):
        self.ranks = dict(ranks or {})
        self.declared = dict(declared or {})
        self.strict = strict
        self._tls = threading.local()
        # Bookkeeping lock: a RAW primitive on purpose — the witness must
        # never recurse into itself, and it calls nothing while held.
        self._mu = threading.Lock()
        self._edges: set = set()  # (held, acquired) pairs observed
        self._succ: Dict[str, set] = {}  # adjacency over _edges
        self.violations: List[dict] = []
        self.acquisitions = 0  # distinct (non-reentrant) lock entries seen

    # -- per-thread stack ----------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    # -- the acquisition referee --------------------------------------------
    def acquire(self, lk: _Named, blocking: bool, timeout: float):
        st = self._stack()
        reentrant = any(e is lk for e in st)
        if reentrant and not lk.reentrant:
            # Re-acquiring a plain Lock this thread already holds: a
            # guaranteed self-deadlock the hierarchy cannot see (the
            # edge would be a self-edge).  Raise BEFORE blocking forever.
            rec = {
                "edge": [lk.name, lk.name],
                "problem": "self-deadlock: re-acquiring a non-reentrant "
                "lock already held by this thread",
            }
            with self._mu:
                self.violations.append(rec)
            if self.strict:
                raise LockOrderError(
                    f"self-deadlock acquiring {lk.name!r}: this thread "
                    "already holds it and it is not an RLock"
                )
        if not reentrant:
            held = {e.name for e in st}
            held.discard(lk.name)
            for h in held:
                self._check_edge(h, lk.name)
        ok = self._real_acquire(lk, blocking, timeout)
        if ok:
            st.append(lk)
            if not reentrant:
                with self._mu:
                    self.acquisitions += 1
        return ok

    @staticmethod
    def _real_acquire(lk: _Named, blocking: bool, timeout: float):
        return lk._real.acquire(blocking, timeout)

    def released(self, lk: _Named) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] is lk:
                del st[i]
                return
        # Acquired before install (or on another witness): tolerated.

    def release_all(self, lk: _Named) -> int:
        st = self._stack()
        n = sum(1 for e in st if e is lk)
        st[:] = [e for e in st if e is not lk]
        return n

    def restored(self, lk: _Named, n: int = 1) -> None:
        st = self._stack()
        for _ in range(max(1, n)):
            st.append(lk)

    # -- graph maintenance ---------------------------------------------------
    def _check_edge(self, a: str, b: str) -> None:
        if (a, b) in self._edges:  # the hot de-dupe: one set lookup
            return
        with self._mu:
            if (a, b) in self._edges:
                return
            problem = self._problem_locked(a, b)
            self._edges.add((a, b))
            self._succ.setdefault(a, set()).add(b)
        if problem is not None:
            rec = {"edge": [a, b], "problem": problem}
            with self._mu:
                self.violations.append(rec)
            if self.strict:
                raise LockOrderError(
                    f"lock-order violation acquiring {b!r} while holding "
                    f"{a!r}: {problem} (declare the edge in "
                    "analysis/manifest.LOCK_EDGE_DECLARED with a reason, "
                    "or fix the nesting)"
                )

    def _problem_locked(self, a: str, b: str) -> Optional[str]:
        # Cycle first: a->b closes one iff b already reaches a.
        if self._reaches_locked(b, a):
            return "closes a cycle in the observed acquisition graph"
        if (a, b) in self.declared:
            return None
        ra, rb = self.ranks.get(a), self.ranks.get(b)
        if ra is None or rb is None:
            unknown = a if ra is None else b
            return f"lock {unknown!r} is not in manifest.LOCK_RANKS"
        if ra >= rb:
            return (
                f"hierarchy violation: rank[{a}]={ra} >= rank[{b}]={rb} "
                "(locks must be acquired strictly rank-upward)"
            )
        return None

    def _reaches_locked(self, src: str, dst: str) -> bool:
        seen = set()
        frontier = [src]
        while frontier:
            n = frontier.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            frontier.extend(self._succ.get(n, ()))
        return False

    # -- read surface --------------------------------------------------------
    def graph(self) -> List[Tuple[str, str]]:
        with self._mu:
            return sorted(self._edges)

    def report(self) -> dict:
        with self._mu:
            return {
                "edges": [list(e) for e in sorted(self._edges)],
                "violations": list(self.violations),
                "acquisitions": int(self.acquisitions),
            }

    def dump_json(self, path: str) -> None:
        """The cross-check artifact: deterministic (sorted) JSON of the
        observed graph, for diffing against deadck's predicted edges."""
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.report(), f, indent=2, sort_keys=True)


# -- the process-wide seam ----------------------------------------------------
#
# Mirrors faults/trace/slo/compilewatch: one module global, read once per
# acquire.  Tests arm a witness around the whole tier-1 session (autouse
# conftest hook); production runs with None installed.

_WITNESS: Optional[LockWitness] = None


def manifest_witness(strict: bool = True) -> LockWitness:
    """A witness loaded with the manifest hierarchy (lazy import — the
    declared obs -> analysis.manifest carve-out)."""
    from distributed_sudoku_solver_tpu.analysis import manifest

    return LockWitness(
        ranks=dict(manifest.LOCK_RANKS),
        declared=dict(manifest.LOCK_EDGE_DECLARED),
        strict=strict,
    )


def install(witness: Optional[LockWitness]) -> None:
    global _WITNESS
    _WITNESS = witness


def active() -> Optional[LockWitness]:
    return _WITNESS


@contextlib.contextmanager
def installed(witness: LockWitness):
    """Scope a witness over a block (tests): always restores the previous
    one — tier-1 runs a session-wide witness, and a test scoping its own
    must not disarm the session on exit."""
    prev = _WITNESS
    install(witness)
    try:
        yield witness
    finally:
        install(prev)
