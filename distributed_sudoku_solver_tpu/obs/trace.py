"""Per-job flight-recorder tracing: spans from HTTP accept to device chunk.

A :class:`TraceRecorder` is a process-wide, clock-injectable span recorder
over a bounded ring — a *flight recorder*: always cheap enough to leave
on, always holding the recent past when something goes wrong.  Spans are
plain dicts (JSON-safe by construction) recording one job's lifecycle:

* ``http.solve`` — HTTP accept to response;
* ``admission`` — submit to flight launch / resident attach (the queue
  wait), with the route taken (``static`` / ``resident``);
* ``chunk.dispatch`` / ``chunk.sync`` and the resident twins — each device
  chunk's async enqueue vs its ONE status sync, sited on the fault plane's
  existing vocabulary (``engine.advance``, ``fetch.status``, ...), so the
  trace and fault planes name the world identically;
* recovery events — ``recovery.requeue`` / ``recovery.downgrade`` /
  ``recovery.bisect`` / ``recovery.rebuild`` / ``recovery.rehome`` /
  ``breaker`` transitions / ``fault.permanent``;
* ``resolve`` — the job's terminal verdict;
* ``send.<METHOD>`` / ``recv.<METHOD>`` — cluster wire egress/ingress for
  uuid-bearing frames (TASK / SUBTASK / SOLUTION / PART_RESULT / ...).

**The contract with the serving hot loops** (the same one the fault plane
honors): recording is reached through the process-wide seam
``trace.active()`` — ``None`` in production unless installed — so the
disabled path is one attribute read and one branch, with zero allocation
(no uuid tuples, no span dicts, no clock reads); and span payloads are
built exclusively from values the loop already holds on the host, so
tracing adds **zero host syncs** (the round-8 one-sync-per-chunk guard in
``tests/test_status_pipeline.py`` runs with tracing enabled to enforce
it).

**Cluster stitching**: trace context (the root job uuid) rides
TASK / SUBTASK / SOLUTION / PART_RESULT frames as a ``"trace"`` field;
receivers :meth:`TraceRecorder.link` derived uuids (shed part uuids) to
the root trace, and result-bearing replies ship the executor's spans back
(bounded) for the origin to :meth:`TraceRecorder.ingest` — so a
distributed solve reconstructs as ONE trace on the origin, each span
tagged with the node that recorded it.  Ingest dedupes by span id, which
makes the ship-back a no-op when nodes share a recorder (the simnet
lane's single process).

Timestamps come from the injectable ``clock`` only, so the simnet lane
asserts multi-node stitching on its virtual clock with no sleeps.

Import discipline: stdlib only (like ``serving/faults.py``).  Everything
imports this module; it imports nothing of the system back.
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import numbers
import os
from distributed_sudoku_solver_tpu.obs import lockdep
import time
from typing import Callable, Iterable, Optional

_LOG = logging.getLogger(__name__)

# Bound on spans shipped back per result-bearing frame (SOLUTION /
# PART_RESULT): ~200 B/span keeps the frame far under wire.MAX_FRAME.
EXPORT_SPAN_CAP = 256
# Bound on spans accepted per ingest call (a forged frame must not be able
# to flush the whole ring with garbage).
INGEST_SPAN_CAP = 1024


def _json_safe(v):
    """Coerce an attr value to a JSON-native type (numpy scalars arrive
    from unpacked status words; anything else degrades to ``str``)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, numbers.Integral):
        return int(v)
    if isinstance(v, numbers.Real):
        return float(v)
    return str(v)


class TraceRecorder:
    """Bounded-ring span recorder; every method is thread-safe.

    ``clock`` is the single time source for every span (inject the simnet
    virtual clock for deterministic tests).  The default is **wall time**
    (``time.time``), not monotonic: spans stitched across cluster nodes
    come from different processes, and per-process monotonic origins are
    arbitrary — wall clocks agree to NTP accuracy, which is what makes a
    multi-node Perfetto timeline readable.  (perfetto() sorts events, so
    a rare NTP step cannot produce a non-monotone export.)  ``node``
    labels spans recorded without an explicit node (cluster nodes pass
    their address); ``dump_dir`` enables the automatic flight-recorder
    dump — permanent faults and breaker-open transitions write the last
    ``dump_spans`` spans plus a metrics snapshot to a JSON logfile there.
    """

    def __init__(
        self,
        ring: int = 4096,
        clock: Callable[[], float] = time.time,
        node: str = "local",
        dump_dir: Optional[str] = None,
        dump_spans: int = 512,
    ):
        self._clock = clock
        self.node = node
        self.dump_dir = dump_dir
        self.dump_spans = max(1, dump_spans)
        self._lock = lockdep.named_lock("obs.trace")  # lockck: name(obs.trace)
        self._ring: collections.deque = collections.deque(maxlen=max(16, ring))
        # child uuid -> root trace id (shed parts under their job), bounded
        # like the engine's stale-cancel ledger.
        self._links: collections.OrderedDict = collections.OrderedDict()
        # span ids already recorded/ingested: makes ingest idempotent under
        # at-least-once delivery AND a no-op for spans this recorder itself
        # produced (nodes sharing one recorder in the simnet lane).
        self._seen: collections.OrderedDict = collections.OrderedDict()
        self._seq = 0  # lockck: guard(_lock)
        self.dumps = 0  # lockck: guard(_lock)
        self.remote_spans_ingested = 0  # lockck: guard(_lock)

    # -- time ----------------------------------------------------------------
    def now(self) -> float:
        return self._clock()

    # -- recording -----------------------------------------------------------
    def record(
        self,
        trace: Optional[str],
        name: str,
        site: str,
        t0: float,
        t1: Optional[float] = None,
        node: Optional[str] = None,
        uuids: Iterable[str] = (),
        attrs: Optional[dict] = None,
        **kw,
    ) -> dict:
        """Record one complete span (``t1`` defaults to now).  ``trace`` is
        the primary job uuid (None for flight-level spans, which attribute
        to every uuid in ``uuids`` instead); extra keyword args and
        ``attrs`` merge into the span's attrs dict."""
        if t1 is None:
            t1 = self.now()
        a = {k: _json_safe(v) for k, v in kw.items()}
        if attrs:
            a.update((k, _json_safe(v)) for k, v in attrs.items())
        with self._lock:
            self._seq += 1
            span = {
                "id": f"{node or self.node}/{self._seq}",
                "trace": trace,
                "name": name,
                "site": site,
                "t0": float(t0),
                "t1": float(t1),
                "node": node or self.node,
                "uuids": [str(u) for u in uuids],
                "attrs": a,
            }
            self._ring.append(span)
            self._remember_locked(span["id"])
        return span

    def event(
        self,
        trace: Optional[str],
        name: str,
        site: str,
        node: Optional[str] = None,
        uuids: Iterable[str] = (),
        attrs: Optional[dict] = None,
        **kw,
    ) -> dict:
        """An instant (zero-duration) span at the current clock reading."""
        t = self.now()
        return self.record(
            trace, name, site, t, t1=t, node=node, uuids=uuids, attrs=attrs, **kw
        )

    def _remember_locked(self, span_id: str) -> None:
        self._seen[span_id] = None
        while len(self._seen) > 2 * self._ring.maxlen:
            self._seen.popitem(last=False)

    # -- trace-context propagation (cluster wire) ----------------------------
    def link(self, child_uuid: str, trace: str) -> None:
        """Alias ``child_uuid`` (a shed part, a racer) to its root trace:
        spans recorded under the child resolve into the root's trace."""
        if child_uuid == trace:
            return
        with self._lock:
            self._links[child_uuid] = trace
            while len(self._links) > 4096:
                self._links.popitem(last=False)

    def resolve(self, uuid: Optional[str]) -> Optional[str]:
        """Follow links to the root trace id (cycle-safe).  Walks the live
        map under the lock — no copy; chains are 0-1 hops in practice."""
        with self._lock:
            return self._resolve_locked(uuid, self._links)

    @staticmethod
    def _resolve_locked(uuid, links) -> Optional[str]:
        seen = set()
        while uuid in links and uuid not in seen:
            seen.add(uuid)
            uuid = links[uuid]
        return uuid

    # -- queries -------------------------------------------------------------
    def spans(
        self, uuid: Optional[str] = None, limit: Optional[int] = None
    ) -> list:
        """Recent spans, oldest first.  With ``uuid``, only spans belonging
        to that trace (primary id or ``uuids`` attribution, links
        followed)."""
        with self._lock:
            items = list(self._ring)
            links = dict(self._links)
        if uuid is not None:
            target = self._resolve_locked(uuid, links)
            items = [
                s
                for s in items
                if self._resolve_locked(s["trace"], links) == target
                or any(
                    self._resolve_locked(u, links) == target
                    for u in s["uuids"]
                )
            ]
        if limit is not None:
            items = items[-limit:]
        return [dict(s) for s in items]

    def export(self, uuid: str, limit: int = EXPORT_SPAN_CAP) -> list:
        """The ship-back payload for a result-bearing frame: this node's
        recent spans for ``uuid``'s trace, bounded."""
        return self.spans(uuid, limit=limit)

    def ingest(self, spans) -> int:
        """Merge spans shipped from another node's recorder; invalid
        entries are skipped, duplicates (by span id) dropped.  Returns the
        number actually ingested.  Never raises — this is fed from network
        input."""
        if not isinstance(spans, list):
            return 0
        n = 0
        for s in spans[:INGEST_SPAN_CAP]:
            if not isinstance(s, dict):
                continue
            try:
                span = {
                    "id": str(s["id"]),
                    "trace": None if s.get("trace") is None else str(s["trace"]),
                    "name": str(s["name"]),
                    "site": str(s.get("site", "")),
                    "t0": float(s["t0"]),
                    "t1": float(s["t1"]),
                    "node": str(s.get("node", "remote")),
                    "uuids": [str(u) for u in s.get("uuids", ())][:64],
                    "attrs": {
                        str(k): _json_safe(v)
                        for k, v in (s.get("attrs") or {}).items()
                    },
                }
            except (KeyError, TypeError, ValueError):
                continue
            with self._lock:
                if span["id"] in self._seen:
                    continue
                self._remember_locked(span["id"])
                self._ring.append(span)
                self.remote_spans_ingested += 1
            n += 1
        return n

    # -- exports -------------------------------------------------------------
    def perfetto(self, spans: Optional[list] = None) -> dict:
        """The recent ring (or ``spans``) as Chrome-trace JSON, openable in
        Perfetto / chrome://tracing.  pid = recording node, tid = site
        family; ``args`` carries trace id, uuids, and attrs."""
        if spans is None:
            spans = self.spans()
        pids: dict = {}
        tids: dict = {}
        meta: list = []
        events: list = []
        # Rebase to the earliest span: monotonic-clock origins are
        # arbitrary (and can be huge); Chrome-trace ts must be >= 0.
        base = min((s["t0"] for s in spans), default=0.0)
        for s in sorted(spans, key=lambda s: (s["t0"], s["t1"], s["id"])):
            pid = pids.get(s["node"])
            if pid is None:
                pid = pids[s["node"]] = len(pids) + 1
                meta.append(
                    {
                        "name": "process_name",
                        "ph": "M",
                        "pid": pid,
                        "tid": 0,
                        "ts": 0,
                        "args": {"name": s["node"]},
                    }
                )
            family = s["site"].split(".", 1)[0] or "misc"
            tid = tids.get((pid, family))
            if tid is None:
                tid = tids[(pid, family)] = len(tids) + 1
                meta.append(
                    {
                        "name": "thread_name",
                        "ph": "M",
                        "pid": pid,
                        "tid": tid,
                        "ts": 0,
                        "args": {"name": family},
                    }
                )
            events.append(
                {
                    "name": s["name"],
                    "cat": s["site"],
                    "ph": "X",
                    "ts": int(round((s["t0"] - base) * 1e6)),
                    "dur": max(0, int(round((s["t1"] - s["t0"]) * 1e6))),
                    "pid": pid,
                    "tid": tid,
                    "args": {
                        "trace": s["trace"],
                        "uuids": s["uuids"],
                        **s["attrs"],
                    },
                }
            )
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    # -- the flight-recorder dump --------------------------------------------
    def dump(self, reason: str, metrics: Optional[dict] = None) -> Optional[str]:
        """Write the last ``dump_spans`` spans + an optional metrics
        snapshot to ``dump_dir`` (no-op when unset).  Called from failure
        paths (permanent faults, breaker-open transitions), so it must
        never raise — a broken disk must not break recovery."""
        if self.dump_dir is None:
            return None
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            with self._lock:
                n = self.dumps
                self.dumps += 1
                spans = list(self._ring)[-self.dump_spans :]
            path = os.path.join(
                self.dump_dir, f"flightrec-{n:03d}-{reason}.json"
            )
            doc = {
                "reason": reason,
                "node": self.node,
                "at": self.now(),
                "spans": spans,
                "metrics": metrics,
            }
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, path)
            return path
        except Exception as e:  # noqa: BLE001 - see docstring
            _LOG.error("[trace] flight-recorder dump failed: %r", e)
            return None

    def metrics(self) -> dict:
        with self._lock:
            return {
                "spans": len(self._ring),
                "ring": self._ring.maxlen,
                "links": len(self._links),
                "dumps": int(self.dumps),
                "remote_spans_ingested": int(self.remote_spans_ingested),
            }


# -- the process-wide seam ----------------------------------------------------
#
# Mirrors serving/faults.py: production runs with no recorder installed and
# every instrumentation point pays one global read + one branch; tests and
# --trace runs install one around a lifetime.

_active: Optional[TraceRecorder] = None


def install(recorder: Optional[TraceRecorder]) -> None:
    global _active
    _active = recorder


def active() -> Optional[TraceRecorder]:
    return _active


@contextlib.contextmanager
def installed(recorder: TraceRecorder):
    """Scope a recorder over a block (tests): always uninstalls."""
    install(recorder)
    try:
        yield recorder
    finally:
        install(None)
