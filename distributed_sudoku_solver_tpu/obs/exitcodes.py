"""The *ck tool family's shared exit-code contract.

``obs/traceck.py``, ``obs/promck.py`` and the source linter
(``distributed_sudoku_solver_tpu.analysis``) used to each imply their own
convention; this module is the single documented scheme, asserted by
their tests:

* ``EXIT_CLEAN`` (0)      — input checked, no findings.
* ``EXIT_VIOLATIONS`` (1) — the input was checkable and has findings
  (malformed exposition lines, non-monotone spans, invariant
  violations).
* ``EXIT_INTERNAL`` (2)   — the tool could not do its job: bad usage,
  unreadable input, checker crash.  CI treats 1 as "fix the code under
  check" and 2 as "fix the invocation/tool".

Stdlib-only, import-anywhere (obs's closed layer allows only obs
siblings, which is why the family's contract lives here rather than in
``analysis/``).
"""

EXIT_CLEAN = 0
EXIT_VIOLATIONS = 1
EXIT_INTERNAL = 2
