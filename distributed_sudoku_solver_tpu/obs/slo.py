"""Declarative service-level objectives with error-budget burn-rate alerts.

The serving tier can now answer "what is cluster p95?" (``obs/hist.py`` +
``obs/agg.py``); this module answers the question that follows it at a
millions-of-users tier: *are we inside our latency objective, and if not,
how fast are we burning the budget?*

**Grammar** (``--slo`` on the CLI)::

    --slo "solve_p95_ms<=250,error_rate<=0.01"

comma-separated objectives, each ``<metric><=<value>`` (``<`` also
accepted):

* ``<stream>_p<NN>_ms<=<T>`` — a latency objective: at least NN% of the
  stream's observations must complete within T ms.  The error *budget*
  is implied by the quantile: ``p95`` allows 5% slow, ``p99`` allows 1%.
  Streams are validated (an unknown stream is a boot-time ValueError,
  not a silently-empty objective):

  - ``solve`` — the client-visible HTTP wall, observed at every
    ``POST /solve`` terminal (``serving/http.py``) with 5xx statuses —
    including a 504 timeout, where the job merely gets cancelled —
    counted as errors.  This is the serving-tier SLI.
  - ``job`` — engine submit→resolve wall (``SolverEngine._finish_job``),
    which also covers non-HTTP work (cluster TASKs, library callers);
    errors are job-level failures.

* ``error_rate<=<R>`` — at most fraction R of the ``solve`` stream's
  observations may be errors; the budget is R itself.

**Burn rate** is the standard SRE form: over a sliding window
(``window_s``, sub-bucketed so old observations age out), ``burn =
(bad / total) / budget``.  ``burn == 1.0`` consumes the budget exactly
at the sustained allowable rate; crossing ``burn_threshold`` flips the
objective to *burning* — and the CROSSING (edge, not level) triggers the
PR-8 flight-recorder dump (``trace.active().dump("slo_burn", ...)`` —
the same atomic tmp+rename writer), so an SLO breach automatically
captures the span ring and a metrics snapshot as evidence.  Exactly one
dump per crossing: the objective must fall back under the threshold
before a new crossing can dump again.

**Hot-path contract** (the tracer's): the engine reaches the monitor
through the process-wide seam ``slo.active()`` — ``None`` unless
installed, so with no ``--slo`` the cost is one global read + one branch,
zero allocation.  All time comes from the injectable ``clock``, so the
simnet lane drives crossings deterministically with no sleeps.

Surfaces: ``GET /slo`` (state), the ``slo`` section of ``/metrics``
(counters: burns, dumps, per-objective burn rate/state), and Prometheus
via ``obs/prom.py`` (``objectives`` renders with an ``objective`` label).

Import discipline: stdlib + sibling ``obs`` modules only; never imports
the serving layers back (the metrics snapshot for dumps is an injected
``metrics_fn``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import re
import time
from collections import deque
from typing import Callable, Optional, Sequence

from distributed_sudoku_solver_tpu.obs import lockdep, trace
from distributed_sudoku_solver_tpu.obs.logctx import ctx_log

_LOG = logging.getLogger(__name__)

_LATENCY_PAT = re.compile(r"^([a-z][a-z0-9_]*)_p(\d{2})_ms(<=|<)(\d+(?:\.\d+)?)$")
_ERROR_PAT = re.compile(r"^error_rate(<=|<)(0?\.\d+|0|1(?:\.0+)?)$")

# The observation streams that actually exist (module docstring).  A
# typo'd stream must fail the boot, not quietly monitor nothing.
STREAMS = ("solve", "job")


@dataclasses.dataclass(frozen=True)
class Objective:
    """One parsed objective.  ``kind`` is ``latency`` (threshold in ms,
    budget = 1 - NN/100) or ``error_rate`` (threshold IS the budget);
    ``stream`` names the observation feed the objective watches."""

    name: str  # the raw spec text, e.g. "solve_p95_ms<=250"
    kind: str  # 'latency' | 'error_rate'
    threshold: float  # ms for latency, rate for error_rate
    budget: float  # allowed bad fraction (must be > 0)
    stream: str = "solve"


def parse_slo(spec: str) -> tuple:
    """Parse the ``--slo`` grammar into objectives; loud ValueError on any
    malformed clause — including an unknown stream name (a
    silently-unfed objective is a lie on /slo)."""
    objectives = []
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        m = _LATENCY_PAT.match(clause)
        if m:
            stream, q, _op, val = m.groups()
            if stream not in STREAMS:
                raise ValueError(
                    f"bad SLO clause {clause!r}: unknown stream {stream!r} "
                    f"(supported: {', '.join(STREAMS)})"
                )
            budget = 1.0 - int(q) / 100.0
            if budget <= 0.0:
                raise ValueError(
                    f"bad SLO clause {clause!r}: p{q} leaves no error budget"
                )
            objectives.append(
                Objective(clause, "latency", float(val), budget, stream)
            )
            continue
        m = _ERROR_PAT.match(clause)
        if m:
            _op, rate = m.groups()
            r = float(rate)
            if not (0.0 < r < 1.0):
                raise ValueError(
                    f"bad SLO clause {clause!r}: rate must be in (0, 1)"
                )
            objectives.append(Objective(clause, "error_rate", r, r, "solve"))
            continue
        raise ValueError(
            f"bad SLO clause {clause!r}: expected "
            "'<stream>_p<NN>_ms<=<T>' or 'error_rate<=<R>'"
        )
    if not objectives:
        raise ValueError(f"empty SLO spec {spec!r}")
    return tuple(objectives)


class SloMonitor:
    """Windowed burn-rate monitor over per-request observations.

    ``observe(latency_s, error=...)`` is the single feed (the engine's
    job-resolution seam); every read (``state`` / ``metrics`` /
    ``burning``) prunes the window against the injected clock, so state
    decays even when traffic stops.  ``min_samples`` guards against a
    one-request window flapping the alert.  ``metrics_fn`` (injected at
    wiring time — this module never imports the engine) supplies the
    metrics snapshot embedded in burn dumps.
    """

    def __init__(
        self,
        objectives: Sequence[Objective],
        window_s: float = 60.0,
        sub_buckets: int = 6,
        burn_threshold: float = 1.0,
        min_samples: int = 10,
        clock: Callable[[], float] = time.monotonic,
        metrics_fn: Optional[Callable[[], dict]] = None,
    ):
        if not objectives:
            raise ValueError("SloMonitor needs at least one objective")
        self.objectives = tuple(objectives)
        self.window_s = float(window_s)
        self._n_sub = max(1, int(sub_buckets))
        self._sub_s = self.window_s / self._n_sub
        self.burn_threshold = float(burn_threshold)
        self.min_samples = max(1, int(min_samples))
        self._clock = clock
        self.metrics_fn = metrics_fn
        # Dump/observe can re-enter metrics() via metrics_fn -> engine
        # .metrics() -> slo.active().metrics(): reentrant by design.
        self._lock = lockdep.named_rlock("obs.slo")  # lockck: name(obs.slo)
        # Sub-buckets: [bucket_id, total, bad-per-objective list].
        self._buckets: deque = deque()
        self._burning = [False] * len(self.objectives)  # lockck: guard(_lock)
        self._breaches = [0] * len(self.objectives)  # lockck: guard(_lock)
        self.observed = 0  # lockck: guard(_lock)
        self.burns = 0  # lockck: guard(_lock) — threshold crossings (all objectives)
        self.dumps = 0  # lockck: guard(_lock) — flight-recorder dumps written on crossings

    # -- the observation feed ------------------------------------------------
    def observe(
        self, latency_s: float, error: bool = False, stream: str = "solve",
        shed: bool = False,
    ) -> None:
        """One observation on ``stream``: the HTTP layer feeds ``solve``
        (wall + status>=500 as error), the engine feeds ``job`` (wall +
        job failure).  Objectives only see their own stream's totals, so
        a 504 storm burns the ``solve`` objectives even though the
        underlying jobs merely got cancelled.

        ``shed=True`` marks a deliberate load-shedding response (a
        brownout 503/429, a saturation 429 — serving/brownout.py): it
        counts toward ``error_rate`` totals as a NON-error (an honest
        refusal must not burn the budget it protects) but is EXCLUDED
        from latency objectives entirely — a storm of ~1 ms refusals
        would otherwise dilute the latency window, collapse the burn
        signal, and flap the brownout ladder that produced them (the
        served requests' latency is the thing the objective watches)."""
        with self._lock:
            now = self._clock()
            bid = int(now // self._sub_s)
            self._prune_locked(bid)
            if not self._buckets or self._buckets[-1][0] != bid:
                n = len(self.objectives)
                self._buckets.append([bid, [0] * n, [0] * n])
            b = self._buckets[-1]
            lat_ms = latency_s * 1e3
            for i, o in enumerate(self.objectives):
                if o.stream != stream:
                    continue
                if o.kind == "error_rate":
                    bad = error and not shed
                elif shed:
                    continue  # refusals carry no service latency
                else:
                    bad = lat_ms > o.threshold
                b[1][i] += 1
                if bad:
                    b[2][i] += 1
            self.observed += 1
            self._evaluate_locked()

    def _prune_locked(self, cur_bid: int) -> None:
        min_bid = cur_bid - self._n_sub + 1
        while self._buckets and self._buckets[0][0] < min_bid:
            self._buckets.popleft()

    def _window_locked(self):
        """(total, bad) per objective over the live window."""
        n = len(self.objectives)
        total = [0] * n
        bad = [0] * n
        for _bid, t, b in self._buckets:
            for i in range(n):
                total[i] += t[i]
                bad[i] += b[i]
        return total, bad

    def _burn_rates_locked(self):
        total, bad = self._window_locked()
        rates = []
        for i, o in enumerate(self.objectives):
            if total[i] < self.min_samples:
                rates.append(0.0)
            else:
                rates.append((bad[i] / total[i]) / o.budget)
        return total, bad, rates

    def _evaluate_locked(self) -> None:
        total, bad, rates = self._burn_rates_locked()
        for i, o in enumerate(self.objectives):
            burning = rates[i] >= self.burn_threshold
            if burning and not self._burning[i]:
                # The crossing: log it (window identified), count it, and
                # capture the evidence exactly once for this excursion.
                self._burning[i] = True
                self._breaches[i] += 1
                self.burns += 1
                ctx_log(_LOG, "slo", o.name).warning(
                    "error-budget burn rate %.2f crossed threshold %.2f "
                    "(%d/%d bad over the last %.0fs window) — "
                    "flight-recorder dump triggered",
                    rates[i], self.burn_threshold, bad[i], total[i],
                    self.window_s,
                )
                self._dump_locked(o, rates[i])
            elif not burning and self._burning[i]:
                self._burning[i] = False
                ctx_log(_LOG, "slo", o.name).info(
                    "burn rate %.2f back under threshold %.2f "
                    "(window %.0fs) — re-armed",
                    rates[i], self.burn_threshold, self.window_s,
                )

    def _dump_locked(self, o: Objective, rate: float) -> None:
        """The breach captures its own evidence: the PR-8 flight recorder
        (atomic tmp+rename writer, never raises) dumps the span ring plus
        a metrics snapshot.  No recorder installed -> the breach is still
        counted/logged; there is just no ring to dump."""
        rec = trace.active()
        if rec is None:
            return
        metrics = None
        if self.metrics_fn is not None:
            try:
                metrics = self.metrics_fn()
            except Exception:  # noqa: BLE001 - evidence is best-effort
                metrics = None
        path = rec.dump(
            "slo_burn",
            metrics={
                "objective": o.name,
                "burn_rate": round(rate, 4),
                "metrics": metrics,
            },
        )
        if path is not None:
            self.dumps += 1

    # -- read surface --------------------------------------------------------
    def burn_snapshot(self) -> dict:
        """Per-objective current burn as a PUBLIC read API (ISSUE 15):
        before this, burn was only observable at crossing edges (the
        dump), which also made anything that wants to *act* on burn — the
        brownout controller (``serving/brownout.py``) — untestable without
        a traffic burst.  Each entry: current ``burn_rate``, ``headroom``
        (distance below the crossing threshold; negative = burning),
        ``burning``, and the windowed totals the rate was computed from.
        Prunes + quiet-evaluates like every read, so the snapshot decays
        when traffic stops.  Surfaced under ``GET /slo`` as ``burn``."""
        with self._lock:
            self._prune_locked(int(self._clock() // self._sub_s))
            self._evaluate_quiet_locked()
            total, bad, rates = self._burn_rates_locked()
            return {
                o.name: {
                    "stream": o.stream,
                    "burn_rate": round(rates[i], 4),
                    "headroom": round(self.burn_threshold - rates[i], 4),
                    "burning": self._burning[i],
                    "window_total": int(total[i]),
                    "window_bad": int(bad[i]),
                }
                for i, o in enumerate(self.objectives)
            }

    def burning(self) -> bool:
        with self._lock:
            self._prune_locked(int(self._clock() // self._sub_s))
            self._evaluate_quiet_locked()
            return any(self._burning)

    def _evaluate_quiet_locked(self) -> None:
        """Reads must see decayed state (an idle window stops burning)
        without re-running the crossing side effects out of observe order:
        only the burning -> not-burning direction is applied here."""
        _total, _bad, rates = self._burn_rates_locked()
        for i in range(len(self.objectives)):
            if self._burning[i] and rates[i] < self.burn_threshold:
                self._burning[i] = False
                ctx_log(_LOG, "slo", self.objectives[i].name).info(
                    "burn rate %.2f back under threshold %.2f — re-armed",
                    rates[i], self.burn_threshold,
                )

    def state(self) -> dict:
        return self.metrics()

    def metrics(self) -> dict:
        with self._lock:
            self._prune_locked(int(self._clock() // self._sub_s))
            self._evaluate_quiet_locked()
            total, bad, rates = self._burn_rates_locked()
            return {
                "window_s": self.window_s,
                "burn_threshold": self.burn_threshold,
                "min_samples": self.min_samples,
                "observed": int(self.observed),
                "burning": any(self._burning),
                "burns": int(self.burns),
                "dumps": int(self.dumps),
                "objectives": {
                    o.name: {
                        "stream": o.stream,
                        "budget": o.budget,
                        "threshold": o.threshold,
                        "burn_rate": round(rates[i], 4),
                        "burning": self._burning[i],
                        "breaches": int(self._breaches[i]),
                        "window_total": int(total[i]),
                        "window_bad": int(bad[i]),
                    }
                    for i, o in enumerate(self.objectives)
                },
            }


# -- the process-wide seam ----------------------------------------------------
#
# Mirrors obs/trace.py and serving/faults.py: production runs with no
# monitor installed and the engine's resolution seam pays one global read
# + one branch; --slo runs and tests install one around a lifetime.

_active: Optional[SloMonitor] = None


def install(monitor: Optional[SloMonitor]) -> None:
    global _active
    _active = monitor


def active() -> Optional[SloMonitor]:
    return _active


@contextlib.contextmanager
def installed(monitor: SloMonitor):
    """Scope a monitor over a block (tests): always uninstalls."""
    install(monitor)
    try:
        yield monitor
    finally:
        install(None)
