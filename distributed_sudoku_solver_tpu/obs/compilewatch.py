"""Production compile/recompile watch: device-time truth for the XLA layer.

PR 11's jaxck proves the compiled layer *at lint time* (donation lowers,
hot programs are callback-free, HLO drift is blessed explicitly), and its
retrace guard proves one-compilation-per-program *at test time*.  In
production neither runs: a silent recompile storm — a weak-type cache
fork, an unstable static, an XLA-cache invalidation after a deploy — is
invisible until it shows up as a mystery latency cliff.  This module is
the live third leg:

* **Attribution.**  jax's monitoring events
  (``/jax/core/compile/backend_compile_duration``) say *that* an
  executable was built and how long it took, but not *which* program.
  The watch attributes compilations the same way the retrace guard does:
  per-program jit-cache sizes (``fn._cache_size()``) for every
  ``analysis/manifest.ENTRY_POINTS`` program, polled when an event
  fires.  Cache growth is the ground truth for **counts** (exact);
  event durations pair with growth FIFO, so **walls** are exact for
  serialized compiles and best-effort inside a concurrent burst.
  Compilations no registered program accounts for attribute to
  ``unregistered``.
* **Warmup, then alarm.**  Compilations during the warmup window
  (``warmup_s`` after construction, or until :meth:`CompileWatch.seal`)
  are expected — a booting node compiles its serving set once.  After
  warmup, ANY attributed compilation is an *unexpected recompile*: a
  ``[compile <program>]`` log line, a per-program ``recompiles``
  counter, a trace event, and — edge-triggered — exactly ONE
  flight-recorder dump (``trace.active().dump("recompile", ...)``) per
  excursion.  The alarm re-arms after ``rearm_s`` seconds with no
  further recompiles (recovery), so a storm costs one dump, not one per
  compile.  This is jaxck's "this PR invalidates the XLA cache for N
  programs" lint message promoted to a live production alarm.
* **Cost plane.**  The serving loops call :meth:`capture_cost` once per
  (program, shape) at flight birth: the program is re-traced via
  ``jit(...).lower(...)`` (host-side, no execution, no device sync, no
  backend compile — so no self-noise on the event listener) and
  ``Lowered.cost_analysis()`` records flops / bytes accessed from the
  unoptimized HLO.  For the chunked advance programs the dominant
  ``while``-loop body is costed once, i.e. the figure is per frontier
  ROUND — which is exactly the unit the engine's
  ``step_wall_ms_avg`` measures, so ``/metrics`` derives a live
  device-efficiency gauge (achieved GFLOP/s = flops-per-round x
  measured rounds/s; with ``peak_gflops`` configured, the ratio against
  the cost-model ceiling).  Peak-temp memory analysis is deliberately
  NOT captured: it needs ``.compile()``, which would double-compile
  every program outside the runtime cache and fire the very events this
  module watches.

**Hot-path contract** (the trace/slo/faults pattern): the jax listeners
are registered ONCE, process-wide, and forward through the
``active()`` seam — with no watch installed each compile event costs one
global read + one branch, and the serving loops' cost seam is likewise
one global read + branch (plus, when installed, one set-membership test
per flight birth, never per chunk).  Nothing here ever reads a device
value: **zero added host syncs**, enforced by the round-8 fetch-count
guard running with the watch installed.

Import discipline: stdlib + sibling ``obs`` modules + the pure-data
``analysis.manifest`` registry (the declared layerck carve-out, like
jaxck's); jax is imported lazily inside the install/construction paths
only.  Clock-injectable (``clock=``) so warmup/re-arm edges are
deterministic under test.
"""

from __future__ import annotations

import collections
import contextlib
import logging
import time
from typing import Callable, Optional

from distributed_sudoku_solver_tpu.analysis import manifest
from distributed_sudoku_solver_tpu.obs import lockdep, trace
from distributed_sudoku_solver_tpu.obs.hist import LatencyHistogram
from distributed_sudoku_solver_tpu.obs.logctx import ctx_log

_LOG = logging.getLogger(__name__)

#: The one event that means "an XLA executable was built (or pulled from
#: the persistent cache) for a program" — jax._src.dispatch
#: BACKEND_COMPILE_EVENT, pinned as a literal so this module stays
#: importable without jax.
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

#: Persistent-cache health events (record_event, no duration): cold vs
#: disk-warm is visible without guessing from wall times.
CACHE_EVENTS = {
    "/jax/compilation_cache/cache_hits": "persistent_cache_hits",
    "/jax/compilation_cache/cache_misses": "persistent_cache_misses",
}

#: Canonical ENTRY_POINTS names of the serving advance programs — the
#: cost-seam call sites in serving/engine.py and serving/scheduler.py
#: name their program through these, so the strings live in one place.
ADVANCE_STATUS = "utils.checkpoint.advance_frontier_status"
ADVANCE_FUSED_STATUS = "ops.pallas_step.advance_frontier_fused_status"
# The latency-mode megastep programs (serving/megastep.py): one whole
# flight per dispatch, so a recompile here is a whole-tier latency cliff.
ADVANCE_MEGASTEP = "ops.frontier.advance_megastep"
ADVANCE_MEGASTEP_FUSED = "ops.pallas_step.advance_megastep_fused"
# The mesh-resident advance (serving/mesh_scheduler.py): the sharded
# resident chunk program, one compile per (geometry, lanes, mesh).
MESH_ADVANCE_STATUS = "parallel.mesh_resident.mesh_advance_status"

#: The attribution bucket for compilations no registered program grew for.
UNREGISTERED = "unregistered"


def display_name(entry_name: str) -> str:
    """The short display name shared with jaxck — the manifest's ONE
    derivation (``manifest.entry_display``), looked up by entry name."""
    return manifest.DISPLAY_BY_NAME.get(
        entry_name, entry_name.rsplit(".", 1)[-1]
    )


def _load_programs() -> dict:
    """display name -> live jit callable for every resolvable
    ENTRY_POINTS program (imports the serving/ops/parallel modules; an
    unresolvable entry is skipped and reported in metrics)."""
    import importlib

    out: dict = {}
    unresolved: list = []
    for e in manifest.ENTRY_POINTS:
        disp = manifest.entry_display(e)
        modpath, attr = e["fn"].split(":")
        try:
            fn = getattr(importlib.import_module(modpath), attr)
            fn._cache_size()  # must quack like a jit function
        except Exception as exc:  # noqa: BLE001 - a missing backend is survivable
            unresolved.append(f"{disp}: {type(exc).__name__}")
            continue
        out[disp] = fn
    if unresolved:
        _LOG.warning(
            "[compilewatch] %d entry point(s) unresolved: %s",
            len(unresolved), "; ".join(unresolved),
        )
    return out


class CompileWatch:
    """Per-program compile accounting plus the post-warmup recompile alarm.

    ``programs`` maps display name -> an object with ``_cache_size()``
    (default: every resolvable ``manifest.ENTRY_POINTS`` program);
    ``warmup_s`` is the expected-compilation window after construction
    (``seal()`` ends it early); ``rearm_s`` is the quiet period after
    which the one-dump-per-excursion alarm re-arms; ``peak_gflops``
    (optional, operator-supplied — no backend exposes it) turns the
    achieved-GFLOP/s gauge into a ceiling ratio.  All timing through the
    injectable ``clock``.
    """

    def __init__(
        self,
        programs: Optional[dict] = None,
        warmup_s: float = 300.0,
        rearm_s: float = 300.0,
        peak_gflops: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._clock = clock
        self.rearm_s = float(rearm_s)
        self.peak_gflops = peak_gflops
        self._lock = lockdep.named_lock("obs.compilewatch")  # lockck: name(obs.compilewatch)
        self._fns = dict(programs) if programs is not None else _load_programs()
        self._last_size = {}
        for name, fn in self._fns.items():
            try:
                self._last_size[name] = int(fn._cache_size())
            except Exception:  # noqa: BLE001 - treat as empty, count from 0
                self._last_size[name] = 0
        self.counts: dict = {}  # display -> compilations since install
        self.recompiles: dict = {}  # display -> post-warmup compilations
        self.wall: dict = {}  # display -> LatencyHistogram (compile wall)
        self.wall_ms_total: dict = {}  # display -> float
        self._pending: collections.deque = collections.deque()  # (dur_s, t)
        self.cache_events = {v: 0 for v in CACHE_EVENTS.values()}
        self.compiles_total = 0
        self.recompiles_total = 0
        self.dumps = 0
        now = self._clock()
        self.installed_at = now
        self._warmup_until = now + max(0.0, float(warmup_s))
        self._armed = True  # the edge trigger: one dump per excursion
        self._last_unexpected: Optional[float] = None
        # Cost plane: display -> {flops, bytes_accessed, ...meta}; the
        # seen-set bounds lowering to once per (program, shape) and is
        # exposed read-only for the hot loops' cheap membership guard.
        self.costs: dict = {}
        self.cost_keys: set = set()
        self.cost_errors = 0

    # -- warmup / alarm edges -------------------------------------------------
    def seal(self) -> None:
        """End the warmup window now: every later compilation is an
        unexpected recompile (tests and short-boot deployments)."""
        with self._lock:
            self._warmup_until = self._clock()

    def warmup_over(self) -> bool:
        return self._clock() >= self._warmup_until

    # -- the event feed (via the module-level forwarders) ---------------------
    def on_duration(self, event: str, duration_s: float) -> None:
        """One jax duration event.  Backend-compile events first attribute
        every already-inserted pending compile (the event for compile N
        fires BEFORE N's cache insertion, so the poll sees 1..N-1), then
        queue this one."""
        if event != BACKEND_COMPILE_EVENT:
            return
        actions = []
        with self._lock:
            actions = self._attribute_locked()
            self._pending.append((float(duration_s), self._clock(), 0))
        self._run_actions(actions)

    def on_event(self, event: str) -> None:
        key = CACHE_EVENTS.get(event)
        if key is not None:
            with self._lock:
                self.cache_events[key] += 1

    def poll(self) -> None:
        """Attribute anything outstanding (reads call this so the last
        compile of a burst doesn't wait for the next event)."""
        with self._lock:
            actions = self._attribute_locked()
        self._run_actions(actions)

    def _attribute_locked(self) -> list:
        """Pair pending compile walls with per-program cache growth.
        Returns deferred actions (log/dump/trace) to run OUTSIDE the lock
        — the dump path re-enters the recorder and must not nest.

        A pending whose cache growth has not appeared yet may just be
        in the event-before-insertion window (the compile that fired the
        event is still being cached), so leftovers only fall through to
        ``unregistered`` after SURVIVING one full earlier attribution
        pass — a mid-window /metrics scrape can therefore never
        misattribute a registered program's compile (and never fire a
        phantom recompile alarm for it)."""
        grown: list = []
        for name, fn in self._fns.items():
            try:
                size = int(fn._cache_size())
            except Exception:  # noqa: BLE001 - a dead fn stops counting, not the watch
                continue
            d = size - self._last_size.get(name, 0)
            if d > 0:
                self._last_size[name] = size
                grown.extend([name] * d)
        actions: list = []
        while grown:
            name = grown.pop(0)
            if self._pending:
                dur, t, _seen = self._pending.popleft()
            else:
                dur, t = None, self._clock()
            actions.extend(self._note_locked(name, dur, t))
        # Leftover pendings: either genuinely unregistered compiles or
        # registered ones whose insertion this poll raced — the former
        # survive a second pass unmatched, the latter pair next time.
        survivors: collections.deque = collections.deque()
        while self._pending:
            dur, t, seen = self._pending.popleft()
            if seen >= 1:
                actions.extend(self._note_locked(UNREGISTERED, dur, t))
            else:
                survivors.append((dur, t, seen + 1))
        self._pending = survivors
        return actions

    def _note_locked(self, name: str, dur_s, t: float) -> list:
        self.counts[name] = self.counts.get(name, 0) + 1
        self.compiles_total += 1
        if dur_s is not None:
            self.wall.setdefault(name, LatencyHistogram()).record(dur_s)
            self.wall_ms_total[name] = (
                self.wall_ms_total.get(name, 0.0) + dur_s * 1e3
            )
        if t < self._warmup_until:
            return []
        # Post-warmup: an unexpected recompile.  Re-arm first (recovery =
        # rearm_s of quiet since the last one), then edge-trigger.
        self._rearm_locked(t)
        self.recompiles[name] = self.recompiles.get(name, 0) + 1
        self.recompiles_total += 1
        self._last_unexpected = t
        fire_dump = self._armed
        if fire_dump:
            self._armed = False
            self.dumps += 1
        payload = {
            "program": name,
            "wall_ms": None if dur_s is None else round(dur_s * 1e3, 3),
            "recompiles": dict(self.recompiles),
            "counts": dict(self.counts),
        }
        return [(name, fire_dump, payload)]

    def _rearm_locked(self, now: float) -> None:
        if (
            not self._armed
            and self._last_unexpected is not None
            and now - self._last_unexpected >= self.rearm_s
        ):
            self._armed = True
            ctx_log(_LOG, "compile", "watch").info(
                "recompile alarm re-armed after %.0fs quiet", self.rearm_s
            )

    def _run_actions(self, actions: list) -> None:
        for name, fire_dump, payload in actions:
            ctx_log(_LOG, "compile", name).warning(
                "unexpected recompilation after warmup (wall %s ms) — %s",
                payload["wall_ms"],
                "flight-recorder dump triggered"
                if fire_dump
                else "alarm already fired this excursion",
            )
            rec = trace.active()
            if rec is None:
                continue
            rec.event(
                None, "compile", "xla.compile", program=name,
                wall_ms=payload["wall_ms"],
            )
            if fire_dump:
                rec.dump("recompile", metrics=payload)

    # -- the cost plane -------------------------------------------------------
    def capture_cost(self, name: str, key, lower_thunk, **meta) -> None:
        """Record the cost model of one program at one live shape.

        ``name`` is the canonical ENTRY_POINTS name; ``key`` dedupes per
        (program, shape) so the lowering runs once per shape ever;
        ``lower_thunk`` returns a ``jax.stages.Lowered`` (the caller
        closes over its live args — lowering re-traces on the host, no
        execution, no sync).  Never raises: a cost model is evidence,
        not a dependency."""
        full_key = (name,) + tuple(key)
        with self._lock:
            if full_key in self.cost_keys:
                return
            self.cost_keys.add(full_key)
        disp = display_name(name)
        try:
            import warnings

            with warnings.catch_warnings():
                # Donation-unused warnings are jaxck's beat; re-lowering
                # for a cost model must not re-spray them (same policy
                # as analysis/jaxck.py's lowering).
                warnings.simplefilter("ignore")
                ca = lower_thunk().cost_analysis() or {}
            cost = {
                "flops": float(ca.get("flops", 0.0)),
                "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
            }
            if "transcendentals" in ca:
                cost["transcendentals"] = float(ca["transcendentals"])
        except Exception as e:  # noqa: BLE001 - see docstring
            with self._lock:
                self.cost_errors += 1
            _LOG.debug("[compilewatch] cost capture failed for %s: %r", disp, e)
            return
        entry = {**cost, **{k: v for k, v in meta.items()}}
        with self._lock:
            # Latest captured shape wins the section entry, but the
            # shape COUNT rides along: the efficiency gauge refuses to
            # price mixed-shape serving with one shape's flops (see
            # ``efficiency``), and the count tells the operator why.
            entry["shapes_captured"] = sum(
                1 for k in self.cost_keys if k[0] == name
            )
            self.costs[disp] = entry

    def efficiency(self, name: str, rounds: int, wall_s: float) -> Optional[dict]:
        """The live device-efficiency gauge: the measured serving rate
        (frontier rounds/s from the engine's chunk totals) priced by the
        captured per-round cost model.  With ``peak_gflops`` configured
        the ratio against the cost-model ceiling rides along.

        Honest only for shape-homogeneous serving: the engine's round
        totals span every flight shape, so once more than one shape of
        the program has been captured, pricing them all with one shape's
        flops would be off by the per-round flops ratio between shapes —
        the gauge is suppressed instead (``suppressed: mixed_shapes``;
        the cost entry's ``shapes_captured`` says why)."""
        disp = display_name(name)
        with self._lock:
            cost = self.costs.get(disp)
        if cost is None or rounds <= 0 or wall_s <= 0:
            return None
        if cost.get("shapes_captured", 1) > 1:
            return {
                "program": disp,
                "suppressed": "mixed_shapes",
                "shapes_captured": int(cost["shapes_captured"]),
            }
        flops = cost.get("flops", 0.0)
        rounds_per_s = rounds / wall_s
        out = {
            "program": disp,
            "flops_per_round": flops,
            "achieved_rounds_per_s": round(rounds_per_s, 3),
            "achieved_gflops_per_s": round(flops * rounds_per_s / 1e9, 6),
        }
        if self.peak_gflops:
            out["peak_gflops"] = float(self.peak_gflops)
            if flops > 0:
                # The cost-model ceiling: rounds/s if the device did
                # nothing but this program at peak throughput.
                ceiling = self.peak_gflops * 1e9 / flops
                out["ceiling_rounds_per_s"] = round(ceiling, 3)
                out["device_efficiency"] = round(rounds_per_s / ceiling, 6)
        return out

    # -- reads ----------------------------------------------------------------
    def program_counts(self) -> dict:
        """display -> compilations since install (attribution ground
        truth: per-program jit-cache growth)."""
        self.poll()
        with self._lock:
            return dict(self.counts)

    def metrics(self) -> dict:
        self.poll()
        with self._lock:
            now = self._clock()
            self._rearm_locked(now)
            programs = {}
            for name in sorted(set(self.counts) | set(self.recompiles)):
                rec: dict = {"count": int(self.counts.get(name, 0))}
                if self.recompiles.get(name):
                    rec["recompiles"] = int(self.recompiles[name])
                if name in self.wall_ms_total:
                    rec["wall_ms_total"] = round(self.wall_ms_total[name], 3)
                if name in self.wall:
                    rec["wall_ms"] = self.wall[name].to_dict()
                programs[name] = rec
            return {
                "programs": programs,
                "registered": len(self._fns),
                "compiles_total": int(self.compiles_total),
                "recompiles_total": int(self.recompiles_total),
                "warmup_over": now >= self._warmup_until,
                "armed": self._armed,
                "dumps": int(self.dumps),
                "cache": dict(self.cache_events),
            }

    def cost_metrics(self) -> Optional[dict]:
        with self._lock:
            if not self.costs and not self.cost_errors:
                return None
            out: dict = {"programs": {k: dict(v) for k, v in self.costs.items()}}
            if self.cost_errors:
                out["errors"] = int(self.cost_errors)
            return out


# -- the process-wide seam ----------------------------------------------------
#
# Mirrors obs/trace.py and obs/slo.py.  The jax listeners are registered
# exactly once (jax's monitoring API has no public unregister) and forward
# through the global — uninstalled, each event costs one read + one branch.

_active: Optional[CompileWatch] = None
_listeners_registered = False


def _forward_duration(event, duration_secs, **kw):
    w = _active
    if w is None:
        return
    try:
        w.on_duration(event, duration_secs)
    except Exception:  # noqa: BLE001 - never raise into jax's compile path
        _LOG.exception("[compilewatch] duration listener failed")


def _forward_event(event, **kw):
    w = _active
    if w is None:
        return
    try:
        w.on_event(event)
    except Exception:  # noqa: BLE001 - never raise into jax's compile path
        _LOG.exception("[compilewatch] event listener failed")


def _ensure_listeners() -> None:
    global _listeners_registered
    if _listeners_registered:
        return
    from jax._src import monitoring  # lazy: obs stays importable without jax

    monitoring.register_event_duration_secs_listener(_forward_duration)
    monitoring.register_event_listener(_forward_event)
    _listeners_registered = True


def install(watch: Optional[CompileWatch]) -> None:
    global _active
    if watch is not None:
        _ensure_listeners()
    _active = watch


def active() -> Optional[CompileWatch]:
    return _active


@contextlib.contextmanager
def installed(watch: CompileWatch):
    """Scope a watch over a block (tests): always uninstalls."""
    install(watch)
    try:
        yield watch
    finally:
        install(None)
