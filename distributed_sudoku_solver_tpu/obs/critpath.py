"""Per-job critical-path attribution over PR-8 stitched traces.

The trace plane answers "what happened to job X"; the SLO plane answers
"are we inside budget".  Neither answers the question an operator asks
when p95 blows up: *where did the time go* — queue wait, device chunks,
the host's one sync per chunk, the cluster wire, recovery churn?  This
module closes that gap with a deterministic decomposition of a job's
stitched spans into named phases:

=============  ===========================================================
phase          spans attributed to it
=============  ===========================================================
``sync``       ``chunk.sync`` / ``resident.sync`` (site ``fetch.status``)
               — the host blocked in the one per-chunk status fetch,
               which through a tunnel includes the RPC floor and on any
               backend includes un-overlapped device compute
``event``      ``verdict.sync`` / ``finalize.sync`` (``fetch.event`` /
               ``fetch.finalize``) — the rarer resolution-chunk fetches
``dispatch``   ``chunk.dispatch`` / ``resident.chunk.dispatch`` — host
               time enqueueing device work (async; should stay thin)
``wire``       ``send.*`` / ``recv.*`` — cluster frames carrying the job
``recovery``   ``recovery.*`` / ``fault.*`` / ``breaker`` transitions
``queue``      the ``admission`` span — submit to flight launch /
               resident attach
``other``      the remainder of the job window no span covers (host
               scheduling gaps, the engine loop serving other flights)
=============  ===========================================================

**The decomposition is a partition, not a sum of span walls.**  Spans
overlap (the always-ahead loop dispatches chunk k+1 while chunk k's sync
blocks; a flight-level chunk span covers many jobs), so naive summing
double-counts.  :func:`decompose` instead sweeps the job's window
``[earliest span t0, resolve t1]`` as disjoint segments, attributing each
segment to the highest-priority covering phase (priority = the table
order above, ``sync`` first).  Phase walls therefore sum to the job's
end-to-end wall *exactly* (float rounding aside — the pinned tolerance is
0.1%), on any clock the recorder was driven by: the simnet virtual clock
and a real wall clock decompose identically.

Surfaces:

* ``GET /trace/<uuid>?analyze=1`` (``serving/http.py``) — the per-job
  decomposition next to the raw spans.
* :class:`CritPathMonitor` (the ``install``/``active``/``installed``
  seam) — fed by ``SolverEngine._finish_job`` when BOTH a recorder and
  the monitor are installed: per-phase mergeable histograms
  (``critpath_<phase>_ms``, exported inside the engine's ``hist``
  section so ``obs/agg.py`` vector-adds them cluster-wide), cumulative
  per-phase attribution shares, and the **slow-job watchdog**: a job
  whose wall breaches the SLO-derived threshold (the smallest latency
  objective on the ``--slo`` plane, or an explicit ``slow_ms``)
  auto-dumps its critical path through the PR-8 flight recorder
  (``dump("slow_job", ...)``), cooldown-limited so a storm costs one
  dump per window, not one per job.

Hot-path contract: the engine reaches the monitor only inside its
existing ``rec is not None`` branch — untraced serving pays nothing new;
traced serving pays one ring scan per *resolved job* (host-side, zero
device syncs — the round-8 fetch-count guard runs with the monitor
installed to prove it).

Import discipline: stdlib + sibling ``obs`` modules only.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Callable, List, Optional, Tuple

from distributed_sudoku_solver_tpu.obs import lockdep
from distributed_sudoku_solver_tpu.obs import slo as slo_mod
from distributed_sudoku_solver_tpu.obs import trace
from distributed_sudoku_solver_tpu.obs.hist import LatencyHistogram
from distributed_sudoku_solver_tpu.obs.logctx import job_log

_LOG = logging.getLogger(__name__)

#: Phase names in priority order (highest first) — the order segments are
#: claimed when spans overlap.  ``other`` is the residual, never claimed.
PHASES = ("sync", "event", "dispatch", "wire", "recovery", "queue")
ALL_PHASES = PHASES + ("other",)

#: Documented sum tolerance: the decomposition is an exact partition, so
#: phase walls and end-to-end may differ only by float rounding.
SUM_TOLERANCE = 1e-3  # 0.1%

_SYNC_SITES = frozenset(("fetch.status",))
_EVENT_SITES = frozenset(("fetch.event", "fetch.finalize"))
# The megastep flight span (site ``megastep.advance``) is a DISPATCH
# site on purpose: its wall is the in-graph chunk loop — device compute
# the host deliberately waits out once per flight, not a per-chunk host
# sync.  The flight's fetch span carries site ``megastep.fetch.status``,
# which classify() treats as a marker: attributing that wall to ``sync``
# would tell the operator to attack a floor the megastep already pays
# exactly once (the round-16 decompose pin in tests/test_critpath.py).
_DISPATCH_SITES = frozenset(
    ("engine.advance", "resident.advance", "megastep.advance")
)
_RECOVERY_SITES = frozenset(("engine.recovery", "resident.breaker"))


def classify(span: dict) -> Optional[str]:
    """Phase of one span, or None for markers (http.solve, resolve,
    compile events) that bound the window but claim no time themselves."""
    site = span.get("site") or ""
    name = span.get("name") or ""
    if site in _SYNC_SITES:
        return "sync"
    if site in _EVENT_SITES:
        return "event"
    if site in _DISPATCH_SITES:
        return "dispatch"
    if name.startswith("send.") or name.startswith("recv."):
        return "wire"
    if (
        site in _RECOVERY_SITES
        or name.startswith("recovery.")
        or name.startswith("fault.")
        or name == "breaker"
    ):
        return "recovery"
    if name == "admission":
        return "queue"
    return None


def decompose(spans: List[dict]) -> Optional[dict]:
    """Decompose one job's spans into the phase partition.

    ``spans`` is the recorder's stitched span list for a single trace
    (``TraceRecorder.spans(uuid)``).  Returns None when the spans carry
    no usable window (empty, or zero-width).  The result's
    ``phases`` (ms) sum to ``end_to_end_ms`` within ``SUM_TOLERANCE``
    by construction — pinned in tests on both the simnet virtual clock
    and a real run.
    """
    if not spans:
        return None
    t_start = min(float(s["t0"]) for s in spans)
    resolve = [s for s in spans if s.get("name") == "resolve"]
    t_end = (
        max(float(s["t1"]) for s in resolve)
        if resolve
        else max(float(s["t1"]) for s in spans)
    )
    if t_end <= t_start:
        return None
    # Clip phase intervals into the window; markers claim nothing.
    intervals: List[Tuple[float, float, int]] = []  # (t0, t1, priority idx)
    for s in spans:
        phase = classify(s)
        if phase is None:
            continue
        a = max(t_start, float(s["t0"]))
        b = min(t_end, float(s["t1"]))
        if b > a:
            intervals.append((a, b, PHASES.index(phase)))
    phases = {p: 0.0 for p in ALL_PHASES}
    # Sweep line, O(n log n): a long job's trace can carry thousands of
    # chunk spans and this runs on the device loop at resolve time — a
    # per-segment interval scan would be quadratic there.
    events = []
    for a, b, pri in intervals:
        events.append((a, 1, pri))
        events.append((b, -1, pri))
    events.sort()
    bounds = sorted({t_start, t_end} | {e[0] for e in events})
    active = [0] * len(PHASES)
    ei = 0
    for a, b in zip(bounds, bounds[1:]):
        while ei < len(events) and events[ei][0] <= a:
            _, d, pri = events[ei]
            active[pri] += d
            ei += 1
        best = next((i for i, n in enumerate(active) if n > 0), None)
        phases[PHASES[best] if best is not None else "other"] += b - a
    end_to_end = t_end - t_start
    http = [s for s in spans if s.get("name") == "http.solve"]
    out = {
        "end_to_end_ms": round(end_to_end * 1e3, 6),
        "phases_ms": {p: round(v * 1e3, 6) for p, v in phases.items()},
        "shares": {
            p: round(v / end_to_end, 6) for p, v in phases.items()
        },
        "spans": len(spans),
        "nodes": sorted({s.get("node", "") for s in spans}),
        "t0": t_start,
        "t1": t_end,
    }
    if http:
        out["http_ms"] = round(
            (float(http[-1]["t1"]) - float(http[-1]["t0"])) * 1e3, 6
        )
    return out


class CritPathMonitor:
    """Aggregating monitor + slow-job watchdog over per-job decompositions.

    ``slow_ms`` pins the watchdog threshold explicitly; None derives it
    from the installed SLO plane (the smallest latency objective's
    threshold — a job breaching its objective is by definition slow).
    With neither, the watchdog is off and only aggregation runs.
    ``dump_cooldown_s`` bounds dump volume under a slow-job storm.
    Clock-injectable like every obs plane.
    """

    def __init__(
        self,
        slow_ms: Optional[float] = None,
        dump_cooldown_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.slow_ms = slow_ms
        self.dump_cooldown_s = float(dump_cooldown_s)
        self._clock = clock
        self._lock = lockdep.named_lock("obs.critpath")  # lockck: name(obs.critpath)
        self.hist = {
            f"critpath_{p}_ms": LatencyHistogram() for p in ALL_PHASES
        }
        self.attribution_ms = {p: 0.0 for p in ALL_PHASES}
        self.jobs = 0
        self.slow_jobs = 0
        self.slow_dumps = 0
        self._last_dump: Optional[float] = None

    def threshold_ms(self) -> Optional[float]:
        if self.slow_ms is not None:
            return float(self.slow_ms)
        mon = slo_mod.active()
        if mon is None:
            return None
        lat = [o.threshold for o in mon.objectives if o.kind == "latency"]
        return min(lat) if lat else None

    def observe_job(self, uuid: str, wall_s: float) -> None:
        """One resolved job: decompose its stitched spans, aggregate, and
        run the watchdog.  No recorder installed -> no spans -> no-op
        (the monitor is only reachable from inside the engine's traced
        branch anyway).  Never raises into the device loop."""
        rec = trace.active()
        if rec is None:
            return
        try:
            d = decompose(rec.spans(uuid))
        except Exception:  # noqa: BLE001 - evidence, not a dependency
            _LOG.exception("[critpath] decomposition failed for %s", uuid)
            return
        if d is None:
            return
        with self._lock:
            self.jobs += 1
            for p in ALL_PHASES:
                ms = d["phases_ms"][p]
                self.attribution_ms[p] += ms
                if ms > 0:
                    self.hist[f"critpath_{p}_ms"].record(ms / 1e3)
        thr = self.threshold_ms()
        if thr is None or wall_s * 1e3 <= thr:
            return
        with self._lock:
            self.slow_jobs += 1
            now = self._clock()
            fire = (
                self._last_dump is None
                or now - self._last_dump >= self.dump_cooldown_s
            )
            if fire:
                self._last_dump = now
                self.slow_dumps += 1
        top = max(
            ((p, d["phases_ms"][p]) for p in ALL_PHASES), key=lambda kv: kv[1]
        )
        job_log(_LOG, uuid).warning(
            "[critpath] slow job: %.1f ms > %.1f ms threshold — dominant "
            "phase %s (%.1f ms, %.0f%%)%s",
            wall_s * 1e3, thr, top[0], top[1],
            100.0 * d["shares"][top[0]],
            "" if fire else " (dump suppressed: cooldown)",
        )
        if fire:
            rec.dump("slow_job", metrics={"uuid": uuid, "analysis": d})

    # -- reads ----------------------------------------------------------------
    def hist_dicts(self) -> dict:
        """The mergeable per-phase histograms, keyed for the engine's
        ``hist`` section (cluster rollup vector-adds them for free)."""
        with self._lock:
            return {k: h.to_dict() for k, h in self.hist.items() if len(h)}

    def metrics(self) -> dict:
        with self._lock:
            total = sum(self.attribution_ms.values())
            out = {
                "jobs": int(self.jobs),
                "attribution_ms": {
                    p: round(v, 3) for p, v in self.attribution_ms.items()
                },
                "slow_jobs": int(self.slow_jobs),
                "slow_dumps": int(self.slow_dumps),
            }
            if total > 0:
                out["shares_pct"] = {
                    p: round(100.0 * v / total, 2)
                    for p, v in self.attribution_ms.items()
                }
        thr = self.threshold_ms()
        if thr is not None:
            out["threshold_ms"] = thr
        return out


# -- the process-wide seam ----------------------------------------------------

_active: Optional[CritPathMonitor] = None


def install(monitor: Optional[CritPathMonitor]) -> None:
    global _active
    _active = monitor


def active() -> Optional[CritPathMonitor]:
    return _active


@contextlib.contextmanager
def installed(monitor: CritPathMonitor):
    """Scope a monitor over a block (tests): always uninstalls."""
    install(monitor)
    try:
        yield monitor
    finally:
        install(None)
