"""Prometheus exposition lint (``promck``): traceck's sibling for the
``/metrics?format=prometheus`` surface.

``obs/prom.py`` (and now the histogram/SLO renderers layered on it)
promises well-formed text exposition; this module is the executable form
of that promise, used by the tests over the LIVE endpoint output and
runnable standalone::

    python -m distributed_sudoku_solver_tpu.obs.promck metrics.txt

Checks (returns a list of error strings; empty = well-formed):

* every non-comment line parses as ``name{labels} value`` with a valid
  metric name, strictly-escaped label values (raw ``"``, newline, or a
  stray backslash inside a label value is a scrape-breaking bug), and a
  float-parseable value;
* no duplicate series: the same ``(name, label set)`` emitted twice makes
  Prometheus reject the whole scrape;
* no duplicate label names within one series;
* histogram families (``*_bucket`` with an ``le`` label): ``le`` values
  parse, cumulative counts are non-decreasing in ``le`` order, and the
  family ends with an ``le="+Inf"`` bucket.

Exit codes follow the *ck-family contract (``obs/exitcodes.py``): 0
clean, 1 findings, 2 internal/usage error (bad invocation, unreadable
input).  Stdlib only.
"""

from __future__ import annotations

import math
import re
import sys
from typing import List, Union

from distributed_sudoku_solver_tpu.obs.exitcodes import (
    EXIT_CLEAN,
    EXIT_INTERNAL,
    EXIT_VIOLATIONS,
)

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LINE = re.compile(rf"^({_NAME})(?:\{{(.*)\}})? (\S+)$")
# One label, strictly escaped: only \\ , \" and \n escapes; no raw quote,
# backslash, or newline inside the value.
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:\\\\|\\"|\\n|[^"\\])*)"')


def _parse_labels(raw: str, where: str, errors: List[str]):
    """-> list[(name, value)] or None on a malformed label block."""
    labels = []
    pos = 0
    while pos < len(raw):
        m = _LABEL.match(raw, pos)
        if m is None:
            errors.append(f"{where}: malformed/unescaped labels at {raw[pos:]!r}")
            return None
        labels.append((m.group(1), m.group(2)))
        pos = m.end()
        if pos < len(raw):
            if raw[pos] != ",":
                errors.append(
                    f"{where}: malformed labels (expected ',') at {raw[pos:]!r}"
                )
                return None
            pos += 1
    return labels


def _parse_value(s: str):
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    try:
        return float(s)
    except ValueError:
        return None


def check_text(text: str) -> List[str]:
    """Validate one exposition body; returns error strings."""
    errors: List[str] = []
    seen: set = set()
    # (bucket family key) -> list of (le, cumulative count, line no)
    families: dict = {}
    for ln, line in enumerate(text.splitlines(), 1):
        if not line or line.startswith("#"):
            continue
        where = f"line {ln}"
        m = _LINE.match(line)
        if m is None:
            errors.append(f"{where}: unparseable: {line!r}")
            continue
        name, raw_labels, raw_value = m.group(1), m.group(2), m.group(3)
        labels = _parse_labels(raw_labels or "", where, errors)
        if labels is None:
            continue
        lnames = [k for k, _ in labels]
        if len(lnames) != len(set(lnames)):
            errors.append(f"{where}: duplicate label name in {line!r}")
            continue
        value = _parse_value(raw_value)
        if value is None:
            errors.append(f"{where}: unparseable value {raw_value!r}")
            continue
        series = (name, tuple(sorted(labels)))
        if series in seen:
            errors.append(f"{where}: duplicate series {name}{dict(labels)}")
        seen.add(series)
        if name.endswith("_bucket"):
            le = dict(labels).get("le")
            if le is None:
                errors.append(f"{where}: {name} bucket without an 'le' label")
                continue
            le_v = _parse_value(le)
            if le_v is None:
                errors.append(f"{where}: unparseable le {le!r}")
                continue
            key = (name, tuple(sorted(p for p in labels if p[0] != "le")))
            families.setdefault(key, []).append((le_v, value, ln))
    for (name, labels), buckets in families.items():
        buckets.sort(key=lambda b: b[0])
        if not buckets or not math.isinf(buckets[-1][0]):
            errors.append(
                f"{name}{dict(labels)}: histogram family missing an "
                'le="+Inf" bucket'
            )
        prev = None
        for le_v, count, ln in buckets:
            if prev is not None and count < prev:
                errors.append(
                    f"line {ln}: non-monotone le buckets in {name}: "
                    f"count {count:g} at le={le_v:g} after {prev:g}"
                )
            prev = count
    return errors


def _load(path: str) -> str:
    """The one read path, shared by check_file and main so the two cannot
    drift (the exit-code split lives at the callers)."""
    with open(path) as f:
        return f.read()


def check_file(path: str) -> List[str]:
    try:
        text = _load(path)
    except OSError as e:
        return [f"{path}: unreadable: {e}"]
    return check_text(text)


def main(argv: Union[List[str], None] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print(
            "usage: python -m distributed_sudoku_solver_tpu.obs.promck "
            "<metrics.txt>",
            file=sys.stderr,
        )
        return EXIT_INTERNAL
    # Unreadable input is the tool failing to check, not the exposition
    # failing the check (exit-code contract, module docstring).
    try:
        text = _load(argv[0])
    except OSError as e:
        print(f"promck: {argv[0]}: unreadable: {e}", file=sys.stderr)
        return EXIT_INTERNAL
    errors = check_text(text)
    if errors:
        for e in errors:
            print(f"promck: {e}", file=sys.stderr)
        return EXIT_VIOLATIONS
    n = sum(
        1
        for ln in text.splitlines()
        if ln.strip() and not ln.startswith("#")
    )
    print(f"promck: OK ({n} series)")
    return EXIT_CLEAN


if __name__ == "__main__":
    raise SystemExit(main())
