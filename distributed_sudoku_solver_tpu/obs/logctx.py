"""Log correlation: every record that concerns a job carries its uuid.

The engine/scheduler/cluster layers log through module-level loggers, and
until round 11 a failure record ("batch failed", "undeliverable after N
attempts") named the *site* but not the *job* — grep-ing a uuid from a
trace or an HTTP error found nothing.  Two helpers fix that without
touching handler/formatter configuration (the uuid rides the message text,
so it survives any formatter, and also lands on ``record.uuid`` for
structured handlers):

* :func:`job_log` — a ``LoggerAdapter`` for single-job records::

      job_log(_LOG, job.uuid).error("retry budget exhausted: %s", label)
      # -> "[job 1f2e3d4c] retry budget exhausted: ..."

* :func:`uuids_label` — a bounded inline label for batch-level records
  (a failed flight concerns many jobs)::

      _LOG.error("[engine] batch failed (%s): %r", uuids_label(jobs), e)
      # -> "... (uuids=1f2e3d4c,9a8b7c6d,+3) ..."

* :func:`ctx_log` — the generic form for non-job identities (an SLO
  objective's window, a peer whose metrics pull failed)::

      ctx_log(_LOG, "slo", "solve_p95_ms<=250").warning("burn rate ...")
      # -> "[slo solve_p95_ms<=250] burn rate ..."
      ctx_log(_LOG, "peer", addr).warning("metrics pull failed: ...")
      # -> "[peer 10.0.0.2:7000] metrics pull failed: ..."

Stdlib only.
"""

from __future__ import annotations

import logging
from typing import Iterable


def _short(uuid: str) -> str:
    return uuid[:8] if len(uuid) > 8 else uuid


class JobLogAdapter(logging.LoggerAdapter):
    """Prefixes messages with ``[job <uuid8>]`` and sets ``record.uuid``."""

    def __init__(self, logger: logging.Logger, uuid: str):
        super().__init__(logger, {"uuid": uuid})

    def process(self, msg, kwargs):
        extra = kwargs.setdefault("extra", {})
        extra.setdefault("uuid", self.extra["uuid"])
        return f"[job {self.extra['uuid']}] {msg}", kwargs


def job_log(logger: logging.Logger, uuid: str) -> JobLogAdapter:
    return JobLogAdapter(logger, uuid)


class CtxLogAdapter(logging.LoggerAdapter):
    """Prefixes messages with ``[<tag> <value>]`` and sets the record
    attribute ``<tag>`` for structured handlers — ``job_log`` generalized
    to any identity worth grepping for."""

    def __init__(self, logger: logging.Logger, tag: str, value):
        super().__init__(logger, {tag: value})
        self._tag = tag
        self._value = value

    def process(self, msg, kwargs):
        extra = kwargs.setdefault("extra", {})
        extra.setdefault(self._tag, self._value)
        return f"[{self._tag} {self._value}] {msg}", kwargs


def ctx_log(logger: logging.Logger, tag: str, value) -> CtxLogAdapter:
    return CtxLogAdapter(logger, tag, value)


def uuids_label(jobs_or_uuids: Iterable, limit: int = 4) -> str:
    """``uuids=aaaa,bbbb,+N`` for multi-job records; accepts Job objects
    (anything with a ``uuid`` attribute) or uuid strings."""
    uuids = [
        getattr(j, "uuid", j) for j in jobs_or_uuids
    ]
    shown = ",".join(_short(str(u)) for u in uuids[:limit])
    extra = len(uuids) - limit
    if extra > 0:
        shown += f",+{extra}"
    return f"uuids={shown or '-'}"
