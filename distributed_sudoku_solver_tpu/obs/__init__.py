"""Observability: per-job flight-recorder tracing, Prometheus exposition,
and log correlation (round 11).

The serving and cluster layers gained deep *aggregate* observability over
rounds 6-10 (``GET /metrics``: latency windows, the dispatch/sync overlap
split, fault counters, breaker states) — but when one job's p95 blows up
or a breaker opens, aggregates cannot say *which* job took *which* path
through *which* chunks.  This package holds the per-job plane:

* :mod:`obs.trace` — a process-wide, clock-injectable span recorder with a
  bounded ring (flight recorder).  Instrumentation points reuse the fault
  plane's site vocabulary (``serving/faults.py`` ``fire`` sites and the
  cluster wire egress), recording is guarded exactly like
  ``faults.active()`` (disabled = one branch, zero allocation), and the
  spans add **zero host syncs** — enforced by the round-8
  one-sync-per-chunk guard running with tracing enabled.
* :mod:`obs.traceck` — validator for exported Chrome-trace JSON
  (``python -m distributed_sudoku_solver_tpu.obs.traceck trace.json``).
* :mod:`obs.prom` — Prometheus text exposition of the nested
  ``/metrics`` dict (``GET /metrics?format=prometheus``).
* :mod:`obs.logctx` — uuid-carrying log adapters so engine/scheduler/
  cluster records that concern a job are grep-correlatable with its trace.
* :mod:`obs.compilewatch` — the production compile/recompile watch
  (round 15): per-program XLA compile counts/walls attributed through
  the ``analysis/manifest.ENTRY_POINTS`` registry, a post-warmup
  edge-triggered recompile alarm, and the per-program cost plane
  (flops/bytes + the live device-efficiency gauge).
* :mod:`obs.critpath` — per-job critical-path attribution over the
  stitched traces (round 15): an exact phase partition of each job's
  wall (``GET /trace/<uuid>?analyze=1``), mergeable per-phase
  histograms, and the slow-job watchdog.
* :mod:`obs.lockdep` — the runtime lockdep witness (round 16): the
  ``named_lock``/``named_rlock``/``named_condition`` factories every
  repo lock is created through, and the install/active seam that —
  armed across tier-1 — checks each acquisition against the manifest
  lock hierarchy the moment it happens and accumulates the observed
  order graph ``analysis/deadck.py`` cross-checks.

Import discipline: stdlib only, like ``serving/faults.py`` — every layer
imports ``obs``; ``obs`` imports none of them back.  (One declared
carve-out: ``obs.compilewatch`` lazily imports jax behind its install
seam and reads the pure-data ``analysis.manifest`` registry — see
``manifest.LAYERS``.)
"""
