"""Cluster-scope metrics aggregation: per-node bodies -> one honest rollup.

The coordinator-side half of ``GET /metrics?scope=cluster``: given the
``/metrics`` bodies of every reachable member (its own plus METRICS_PULL
replies, ``cluster/node.py``), :func:`rollup` merges exactly the things
that merge *soundly*:

* **Histograms** (``hist`` sections, ``obs/hist.py`` log2 dicts) merge by
  vector add — the whole reason the histogram plane exists.  Cluster
  quantiles are then estimated from the MERGED counts, which is the only
  honest way to get a cluster p95 (averaging per-node p95s is not).
* **A small counter whitelist** (``jobs_done`` / ``solved`` /
  ``validations``) sums.
* **RPC-floor estimates** (``rpc_floor_ms``) min-merge: the ring's floor
  is the best floor any member has measured.

Everything else — percentile snapshots, per-geometry breakdowns, string
state — is deliberately NOT rolled up: those live in the per-node
breakdown the endpoint returns alongside, where they are still true.

:func:`status_from` derives the compact ``GET /status`` health view from
a cluster view (member reachability/staleness flags, cluster quantiles,
the floor, and the SLO plane's state).

Stdlib + sibling ``obs`` modules only; never imports the serving or
cluster layers back.
"""

from __future__ import annotations

from typing import Iterable, Optional

from distributed_sudoku_solver_tpu.obs import hist as hist_mod
from distributed_sudoku_solver_tpu.obs import slo as slo_mod

# Scalar counters that sum soundly across members (lifetime totals with
# one writer each).  Windowed or ratio-shaped values never belong here.
SUM_COUNTERS = ("jobs_done", "solved", "validations")

QUANTILES = (("p50_ms", 0.50), ("p95_ms", 0.95), ("p99_ms", 0.99))


def rollup(bodies: Iterable[Optional[dict]]) -> dict:
    """Merge member ``/metrics`` bodies (None/garbage entries skipped —
    the caller flags those peers unreachable) into the cluster rollup."""
    hists: dict = {}
    counters: dict = {}
    floor: Optional[dict] = None
    for body in bodies:
        if not isinstance(body, dict):
            continue
        h = body.get("hist")
        if isinstance(h, dict):
            for k in sorted(h, key=str):
                if hist_mod.is_hist(h[k]):
                    hists[str(k)] = hist_mod.merge_hist(hists.get(str(k)), h[k])
        for k in SUM_COUNTERS:
            v = body.get(k)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                counters[k] = counters.get(k, 0) + v
        f = body.get("rpc_floor_ms")
        if hist_mod.is_min_est(f):
            floor = hist_mod.merge_min_est(floor, f)
    quantiles = {}
    for k, h in hists.items():
        n = hist_mod.hist_count(h)
        if n == 0:
            continue
        quantiles[k] = {
            "count": n,
            **{
                name: round(hist_mod.hist_quantile(h, q), 3)
                for name, q in QUANTILES
            },
        }
    out = {"hist": hists, "counters": counters, "quantiles": quantiles}
    if floor is not None:
        out["rpc_floor_ms"] = floor
    return out


def status_from(cluster_view: dict) -> dict:
    """The ``GET /status`` body: membership health + cluster quantiles +
    the SLO plane, derived from a ``cluster_metrics_view()`` result."""
    nodes = cluster_view.get("nodes", {})
    members = {
        addr: {
            "stale": bool(n.get("stale")),
            "unreachable": bool(n.get("unreachable")),
        }
        for addr, n in nodes.items()
    }
    unreachable = sum(1 for m in members.values() if m["unreachable"])
    ru = cluster_view.get("rollup", {})
    mon = slo_mod.active()
    slo_state = mon.state() if mon is not None else None
    # Cluster health must see the MEMBERS' SLO planes too: each pulled
    # metrics body carries its node's slo section (when that node runs
    # --slo), and a member burning its budget is a cluster problem even
    # when the serving node's own monitor is green.  The local monitor
    # stays the fallback for bodies without the section.
    burning_members = sorted(
        addr
        for addr, n in nodes.items()
        if isinstance(n.get("metrics"), dict)
        and (n["metrics"].get("slo") or {}).get("burning")
    )
    burning = bool(slo_state and slo_state.get("burning")) or bool(
        burning_members
    )
    return {
        "address": cluster_view.get("address"),
        "coordinator": cluster_view.get("coordinator"),
        "view": cluster_view.get("view"),
        "members": members,
        "unreachable": unreachable,
        "quantiles": ru.get("quantiles", {}),
        "rpc_floor_ms": ru.get("rpc_floor_ms"),
        "counters": ru.get("counters", {}),
        "slo": slo_state,
        "slo_burning_members": burning_members,
        # Degraded = the aggregation itself is partial (a member did not
        # answer); healthy additionally requires no objective burning
        # anywhere in the ring.
        "degraded": unreachable > 0,
        "healthy": unreachable == 0 and not burning,
    }
