"""Cluster-scope metrics aggregation: per-node bodies -> one honest rollup.

The coordinator-side half of ``GET /metrics?scope=cluster``: given the
``/metrics`` bodies of every reachable member (its own plus METRICS_PULL
replies, ``cluster/node.py``), :func:`rollup` merges exactly the things
that merge *soundly*:

* **Histograms** (``hist`` sections, ``obs/hist.py`` log2 dicts) merge by
  vector add — the whole reason the histogram plane exists.  Cluster
  quantiles are then estimated from the MERGED counts, which is the only
  honest way to get a cluster p95 (averaging per-node p95s is not).
* **A small counter whitelist** (``jobs_done`` / ``solved`` /
  ``validations``) sums.
* **RPC-floor estimates** (``rpc_floor_ms``) min-merge: the ring's floor
  is the best floor any member has measured.
* **Compile-watch sections** (``compile``, obs/compilewatch.py) sum
  per-program compile counts / recompiles / walls (wall histograms
  vector-add), so "which program is recompiling, cluster-wide?" has one
  answer; alarm state stays per-node.
* **Critical-path sections** (``critpath``, obs/critpath.py) sum jobs
  and per-phase attribution totals; cluster shares are re-derived from
  the merged totals (the per-phase ``critpath_*_ms`` histograms already
  merge through the ``hist`` rule above).
* **Brownout sections** (``brownout``, serving/brownout.py) sum
  transition/shed counters and residency vectors; the per-node stage
  max-merges (``stage_max``) with a browning-member count, and
  :func:`status_from` turns browning members AMBER.

Everything else — percentile snapshots, per-geometry breakdowns, string
state — is deliberately NOT rolled up: those live in the per-node
breakdown the endpoint returns alongside, where they are still true.

:func:`status_from` derives the compact ``GET /status`` health view from
a cluster view (member reachability/staleness flags, cluster quantiles,
the floor, and the SLO plane's state).

Stdlib + sibling ``obs`` modules only; never imports the serving or
cluster layers back.
"""

from __future__ import annotations

from typing import Iterable, Optional

from distributed_sudoku_solver_tpu.obs import hist as hist_mod
from distributed_sudoku_solver_tpu.obs import slo as slo_mod

# Scalar counters that sum soundly across members (lifetime totals with
# one writer each).  Windowed or ratio-shaped values never belong here.
SUM_COUNTERS = ("jobs_done", "solved", "validations")

QUANTILES = (("p50_ms", 0.50), ("p95_ms", 0.95), ("p99_ms", 0.99))


#: Per-program compile-section scalars that sum soundly across members
#: (lifetime totals, one writer each — the node's own compile watch).
_COMPILE_SUM_FIELDS = ("count", "recompiles", "wall_ms_total")


def _merge_compile(acc: dict, sec: dict) -> None:
    """Sum one member's ``compile`` section into the rollup: per-program
    counts/recompiles/walls (the federation the simnet 3-node test pins)
    plus the totals.  Warmup/armed state is deliberately NOT merged —
    alarm state is per-node truth and lives in the per-node breakdown."""
    programs = sec.get("programs")
    if isinstance(programs, dict):
        for name in sorted(programs, key=str):
            rec = programs[name]
            if not isinstance(rec, dict):
                continue
            slot = acc.setdefault("programs", {}).setdefault(str(name), {})
            for f in _COMPILE_SUM_FIELDS:
                v = rec.get(f)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    slot[f] = slot.get(f, 0) + v
            if hist_mod.is_hist(rec.get("wall_ms")):
                slot["wall_ms"] = hist_mod.merge_hist(
                    slot.get("wall_ms"), rec["wall_ms"]
                )
    for f in ("compiles_total", "recompiles_total", "dumps"):
        v = sec.get(f)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            acc[f] = acc.get(f, 0) + v


def _merge_brownout(acc: dict, sec: dict) -> None:
    """Sum one member's ``brownout`` section (serving/brownout.py):
    transition/shed counters and residency vectors sum soundly; the
    stage itself is per-node state, so the rollup carries the MAX stage
    across members plus a browning-member count — "is anyone shedding,
    and how hard" has one cluster answer while each node's own stage
    stays in the per-node breakdown."""
    for f in ("transitions", "escalations", "deescalations", "shed_total"):
        v = sec.get(f)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            acc[f] = acc.get(f, 0) + v
    stage = sec.get("stage")
    if isinstance(stage, int) and not isinstance(stage, bool):
        acc["stage_max"] = max(acc.get("stage_max", 0), stage)
        if stage > 0:
            acc["browning_members"] = acc.get("browning_members", 0) + 1
    shed = sec.get("shed")
    if isinstance(shed, dict):
        slot = acc.setdefault("shed", {})
        for t in sorted(shed, key=str):
            v = shed[t]
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                slot[str(t)] = slot.get(str(t), 0) + v
    res = sec.get("stage_residency_s")
    if isinstance(res, list) and all(
        isinstance(v, (int, float)) and not isinstance(v, bool) for v in res
    ):
        cur = acc.setdefault("stage_residency_s", [0.0] * len(res))
        for i, v in enumerate(res[: len(cur)]):
            cur[i] = round(cur[i] + v, 3)


#: Gossip EVENT counters that sum soundly across members.  The state
#: gauges (alive/suspect/dead/members, self incarnation) are per-node
#: truth — every member counts the whole ring, so summing them would
#: multiply the answer by the membership; they stay in the per-node
#: breakdown.
_DHT_GOSSIP_SUM = (
    "refutations",
    "suspicions",
    "deaths",
    "resurrections",
    "stale_ignored",
    "merged",
)


def _merge_dht(acc: dict, sec: dict) -> None:
    """Sum one member's ``dht`` section (cluster/dht/): gossip event
    counters, cluster-cache shard counters (summing ``entries`` across
    shards IS the cluster cache size — shards are disjoint by ring
    ownership; ``capacity`` is per-node policy and is deliberately NOT
    merged), and cache-affine routing decisions."""
    gossip = sec.get("gossip")
    if isinstance(gossip, dict):
        slot = acc.setdefault("gossip", {})
        for f in _DHT_GOSSIP_SUM:
            v = gossip.get(f)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                slot[f] = slot.get(f, 0) + v
    cache = sec.get("cluster_cache")
    if isinstance(cache, dict):
        slot = acc.setdefault("cluster_cache", {})
        for f in sorted(cache, key=str):
            if f == "capacity":
                continue
            v = cache[f]
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                slot[str(f)] = slot.get(str(f), 0) + v
    aff = sec.get("affinity")
    if isinstance(aff, dict):
        slot = acc.setdefault("affinity", {})
        for f in ("routed", "declined"):
            v = aff.get(f)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                slot[f] = slot.get(f, 0) + v


def _merge_critpath(acc: dict, sec: dict) -> None:
    """Sum one member's ``critpath`` section: jobs + per-phase
    attribution totals (ms sums merge soundly; shares are re-derived
    from the merged totals — averaging per-node shares would not be)."""
    for f in ("jobs", "slow_jobs", "slow_dumps"):
        v = sec.get(f)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            acc[f] = acc.get(f, 0) + v
    attr = sec.get("attribution_ms")
    if isinstance(attr, dict):
        slot = acc.setdefault("attribution_ms", {})
        for p in sorted(attr, key=str):
            v = attr[p]
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                slot[str(p)] = slot.get(str(p), 0.0) + v


def rollup(bodies: Iterable[Optional[dict]]) -> dict:
    """Merge member ``/metrics`` bodies (None/garbage entries skipped —
    the caller flags those peers unreachable) into the cluster rollup."""
    hists: dict = {}
    counters: dict = {}
    floor: Optional[dict] = None
    compile_acc: dict = {}
    critpath_acc: dict = {}
    brownout_acc: dict = {}
    dht_acc: dict = {}
    for body in bodies:
        if not isinstance(body, dict):
            continue
        h = body.get("hist")
        if isinstance(h, dict):
            for k in sorted(h, key=str):
                if hist_mod.is_hist(h[k]):
                    hists[str(k)] = hist_mod.merge_hist(hists.get(str(k)), h[k])
        for k in SUM_COUNTERS:
            v = body.get(k)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                counters[k] = counters.get(k, 0) + v
        f = body.get("rpc_floor_ms")
        if hist_mod.is_min_est(f):
            floor = hist_mod.merge_min_est(floor, f)
        if isinstance(body.get("compile"), dict):
            _merge_compile(compile_acc, body["compile"])
        if isinstance(body.get("critpath"), dict):
            _merge_critpath(critpath_acc, body["critpath"])
        if isinstance(body.get("brownout"), dict):
            _merge_brownout(brownout_acc, body["brownout"])
        if isinstance(body.get("dht"), dict):
            _merge_dht(dht_acc, body["dht"])
    quantiles = {}
    for k, h in hists.items():
        n = hist_mod.hist_count(h)
        if n == 0:
            continue
        quantiles[k] = {
            "count": n,
            **{
                name: round(hist_mod.hist_quantile(h, q), 3)
                for name, q in QUANTILES
            },
        }
    out = {"hist": hists, "counters": counters, "quantiles": quantiles}
    if floor is not None:
        out["rpc_floor_ms"] = floor
    if compile_acc:
        out["compile"] = compile_acc
    if brownout_acc:
        out["brownout"] = brownout_acc
    if dht_acc:
        out["dht"] = dht_acc
    if critpath_acc:
        total = sum(
            v for v in critpath_acc.get("attribution_ms", {}).values()
        )
        if total > 0:
            critpath_acc["shares_pct"] = {
                p: round(100.0 * v / total, 2)
                for p, v in critpath_acc["attribution_ms"].items()
            }
        out["critpath"] = critpath_acc
    return out


def status_from(cluster_view: dict) -> dict:
    """The ``GET /status`` body: membership health + cluster quantiles +
    the SLO plane, derived from a ``cluster_metrics_view()`` result."""
    nodes = cluster_view.get("nodes", {})
    members = {
        addr: {
            "stale": bool(n.get("stale")),
            "unreachable": bool(n.get("unreachable")),
        }
        for addr, n in nodes.items()
    }
    unreachable = sum(1 for m in members.values() if m["unreachable"])
    ru = cluster_view.get("rollup", {})
    mon = slo_mod.active()
    slo_state = mon.state() if mon is not None else None
    # Cluster health must see the MEMBERS' SLO planes too: each pulled
    # metrics body carries its node's slo section (when that node runs
    # --slo), and a member burning its budget is a cluster problem even
    # when the serving node's own monitor is green.  The local monitor
    # stays the fallback for bodies without the section.
    burning_members = sorted(
        addr
        for addr, n in nodes.items()
        if isinstance(n.get("metrics"), dict)
        and (n["metrics"].get("slo") or {}).get("burning")
    )
    burning = bool(slo_state and slo_state.get("burning")) or bool(
        burning_members
    )
    # A browning-out member turns the ring AMBER the way a burning one
    # turns it red: the member is still serving (cache/hard-tail answers
    # at stage <= 2), but it is refusing part of its traffic on purpose —
    # capacity planning should hear that before the budget burns.
    brownout_members = sorted(
        addr
        for addr, n in nodes.items()
        if isinstance(n.get("metrics"), dict)
        and int((n["metrics"].get("brownout") or {}).get("stage") or 0) > 0
    )
    return {
        "address": cluster_view.get("address"),
        "coordinator": cluster_view.get("coordinator"),
        "view": cluster_view.get("view"),
        "members": members,
        "unreachable": unreachable,
        "quantiles": ru.get("quantiles", {}),
        "rpc_floor_ms": ru.get("rpc_floor_ms"),
        "counters": ru.get("counters", {}),
        "slo": slo_state,
        "slo_burning_members": burning_members,
        "brownout_members": brownout_members,
        # The compact traffic light: red = an objective is burning
        # somewhere, amber = someone is shedding (or the rollup is
        # partial), green = all clear.  `healthy`/`degraded` keep their
        # pre-round-18 meanings for existing consumers.
        "state": (
            "red" if burning
            else "amber" if (brownout_members or unreachable > 0)
            else "green"
        ),
        # Degraded = the aggregation itself is partial (a member did not
        # answer); healthy additionally requires no objective burning
        # anywhere in the ring.
        "degraded": unreachable > 0,
        "healthy": unreachable == 0 and not burning,
    }
