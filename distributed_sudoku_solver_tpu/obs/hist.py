"""Mergeable, lock-cheap log2-bucketed latency histograms.

``StatWindow`` (utils/profiling.py) answers "what is THIS node's p95?",
but its percentile snapshots cannot be combined: the p95 of two p95s is
not the p95 of the union, so a ring of N nodes has no honest answer to
"what is *cluster* p95?".  :class:`LatencyHistogram` is the mergeable
twin, threaded beside the StatWindows at the same phase seams:

* **Fixed log2 bucket edges** — bucket ``i`` counts samples with
  ``v_ms <= EDGE0_MS * 2**i`` (last bucket = +Inf overflow).  Every
  histogram in every process shares the one scheme, so histograms from
  different nodes merge by plain vector add (:func:`merge_hist`) — the
  property cluster-scope aggregation (``obs/agg.py``) is built on.
* **Lock-cheap recording** — one bucket-index computation (``frexp``,
  no log calls) and one locked integer increment per sample; no numpy,
  no percentile math on the hot path.  Quantiles are estimated at READ
  time from the cumulative counts (log-linear interpolation inside the
  bucket), the same trade Prometheus histograms make.
* **Optional exemplars** — a trace uuid per bucket (latest wins),
  linking a slow bucket straight to its PR-8 stitched trace
  (``GET /trace/<uuid>``).  Callers pass an exemplar ONLY when a
  recorder is installed, so the disabled path allocates nothing extra.

:class:`MinEstimator` is the companion floor tracker: fed from the
``chunk.sync`` seams, its minimum is a live estimate of the per-sync RPC
floor (``rpc_floor_ms`` on ``/metrics``) — the baseline number ROADMAP
item #2 (kill the interactive dispatch floor) needs to attack and then
prove it moved.

Prometheus rendering (cumulative ``le`` buckets, ``_sum``/``_count``)
lives in ``obs/prom.py``; the dict forms here (``to_dict`` /
:func:`merge_hist` / :func:`hist_quantile`) are the wire/merge format.

Import discipline: stdlib only (like the rest of ``obs/``).
"""

from __future__ import annotations

import math
from distributed_sudoku_solver_tpu.obs import lockdep
from typing import Optional

# The one process-independent bucket scheme: first edge 1 µs, doubling
# 31 times (last finite edge ~17.9 min), bucket 31 = +Inf.  Changing
# either constant is a wire-format change for METRICS_PULL replies —
# merge_hist refuses mixed schemes rather than silently mis-adding.
EDGE0_MS = 1e-3
N_BUCKETS = 32
HIST_TYPE = "log2_hist"
MIN_EST_TYPE = "min_est"


def bucket_index(v_ms: float) -> int:
    """Smallest ``i`` with ``v_ms <= EDGE0_MS * 2**i`` (clamped into the
    scheme; non-positive samples land in bucket 0)."""
    if v_ms <= EDGE0_MS:
        return 0
    m, e = math.frexp(v_ms / EDGE0_MS)
    i = e - 1 if m == 0.5 else e  # ceil(log2(ratio)) without log()
    return i if i < N_BUCKETS else N_BUCKETS - 1


def bucket_edge_ms(i: int) -> float:
    """Upper edge of bucket ``i`` in ms (``inf`` for the overflow bucket)."""
    return math.inf if i >= N_BUCKETS - 1 else EDGE0_MS * (2.0 ** i)


class LatencyHistogram:
    """Thread-safe log2-bucket histogram over latency samples in seconds
    (stored and exported in ms, matching every ``*_ms`` metric)."""

    def __init__(self):
        self._lock = lockdep.named_lock("obs.hist")  # lockck: name(obs.hist)
        self._counts = [0] * N_BUCKETS
        self._n = 0
        self._sum_ms = 0.0
        # bucket index (as str, the JSON dict-key form) -> trace uuid.
        # Bounded by construction: at most one exemplar per bucket.
        self._exemplars: dict = {}

    def __len__(self) -> int:
        return self._n

    def record(self, seconds: float, exemplar: Optional[str] = None) -> None:
        v_ms = seconds * 1e3
        i = bucket_index(v_ms)
        with self._lock:
            self._counts[i] += 1
            self._n += 1
            self._sum_ms += v_ms
            if exemplar is not None:
                self._exemplars[str(i)] = exemplar

    def to_dict(self) -> dict:
        """The canonical JSON-safe form — the METRICS_PULL wire format and
        the merge/render input (``type`` tags it for obs/agg + obs/prom)."""
        with self._lock:
            d = {
                "type": HIST_TYPE,
                "edge0_ms": EDGE0_MS,
                "counts": list(self._counts),
                "sum_ms": round(self._sum_ms, 6),
            }
            if self._exemplars:
                d["exemplars"] = dict(self._exemplars)
            return d

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            counts = list(self._counts)
        return hist_quantile(
            {"type": HIST_TYPE, "edge0_ms": EDGE0_MS, "counts": counts}, q
        )


def is_hist(d) -> bool:
    return (
        isinstance(d, dict)
        and d.get("type") == HIST_TYPE
        and isinstance(d.get("counts"), list)
    )


def merge_hist(acc: Optional[dict], other: dict) -> dict:
    """Vector-add ``other`` into ``acc`` (None = start fresh); exemplars
    keep the donor's where present (latest wins — any exemplar is a valid
    representative of its bucket).  Raises ``ValueError`` on a scheme
    mismatch: silently mis-adding differently-bucketed histograms would
    corrupt every cluster quantile downstream."""
    if not is_hist(other):
        raise ValueError(f"not a {HIST_TYPE} dict: {other!r}")
    if acc is None:
        return {
            "type": HIST_TYPE,
            "edge0_ms": float(other.get("edge0_ms", EDGE0_MS)),
            "counts": [int(c) for c in other["counts"]],
            "sum_ms": float(other.get("sum_ms", 0.0)),
            **(
                {"exemplars": dict(other["exemplars"])}
                if other.get("exemplars")
                else {}
            ),
        }
    if float(other.get("edge0_ms", EDGE0_MS)) != float(
        acc.get("edge0_ms", EDGE0_MS)
    ) or len(other["counts"]) != len(acc["counts"]):
        raise ValueError(
            "histogram scheme mismatch: "
            f"edge0={other.get('edge0_ms')}/{acc.get('edge0_ms')} "
            f"n={len(other['counts'])}/{len(acc['counts'])}"
        )
    acc["counts"] = [
        int(a) + int(b) for a, b in zip(acc["counts"], other["counts"])
    ]
    acc["sum_ms"] = float(acc.get("sum_ms", 0.0)) + float(other.get("sum_ms", 0.0))
    if other.get("exemplars"):
        ex = acc.setdefault("exemplars", {})
        ex.update(other["exemplars"])
    return acc


def hist_count(d: dict) -> int:
    return sum(int(c) for c in d.get("counts", ()))


def hist_quantile(d: dict, q: float) -> Optional[float]:
    """Estimated ``q``-quantile in ms from a histogram dict: log-linear
    interpolation inside the bucket that crosses the target rank (the
    overflow bucket reports its lower edge — an honest lower bound)."""
    counts = [int(c) for c in d.get("counts", ())]
    total = sum(counts)
    if total == 0:
        return None
    edge0 = float(d.get("edge0_ms", EDGE0_MS))
    target = q * total
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if cum + c >= target:
            upper = edge0 * (2.0 ** i)
            if i >= len(counts) - 1:
                return edge0 * (2.0 ** (i - 1))  # +Inf bucket: lower bound
            lower = 0.0 if i == 0 else edge0 * (2.0 ** (i - 1))
            frac = (target - cum) / c
            return lower + (upper - lower) * frac
        cum += c
    return edge0 * (2.0 ** (len(counts) - 2))


class MinEstimator:
    """Online floor estimate over a stream of wall samples (seconds in,
    ms out): the lifetime minimum plus a windowed "recent" minimum (last
    completed window of ``window`` samples), so a floor that MOVED — the
    success criterion of ROADMAP item #2 — is visible without restarting
    the process."""

    def __init__(self, window: int = 256):
        self._lock = lockdep.named_lock("obs.minest")  # lockck: name(obs.minest)
        self._window = max(1, window)
        self._min_ms: Optional[float] = None
        self._cur_min_ms: Optional[float] = None
        self._cur_n = 0
        self._recent_ms: Optional[float] = None
        self._n = 0

    def record(self, seconds: float) -> None:
        v_ms = seconds * 1e3
        with self._lock:
            self._n += 1
            if self._min_ms is None or v_ms < self._min_ms:
                self._min_ms = v_ms
            if self._cur_min_ms is None or v_ms < self._cur_min_ms:
                self._cur_min_ms = v_ms
            self._cur_n += 1
            if self._cur_n >= self._window:
                self._recent_ms = self._cur_min_ms
                self._cur_min_ms = None
                self._cur_n = 0

    def to_dict(self) -> Optional[dict]:
        with self._lock:
            if self._n == 0:
                return None
            recent = self._recent_ms
            if recent is None:
                recent = self._cur_min_ms  # window not yet full: best so far
            return {
                "type": MIN_EST_TYPE,
                "min": round(float(self._min_ms), 6),
                "recent": round(float(recent), 6),
                "samples": int(self._n),
            }


def is_min_est(d) -> bool:
    return isinstance(d, dict) and d.get("type") == MIN_EST_TYPE


def merge_min_est(acc: Optional[dict], other: dict) -> dict:
    """Cluster merge for floor estimates: the floor of a ring is the min
    of the members' floors; samples sum."""
    if not is_min_est(other):
        raise ValueError(f"not a {MIN_EST_TYPE} dict: {other!r}")
    if acc is None:
        return {
            "type": MIN_EST_TYPE,
            "min": float(other["min"]),
            "recent": float(other.get("recent", other["min"])),
            "samples": int(other.get("samples", 0)),
        }
    acc["min"] = min(float(acc["min"]), float(other["min"]))
    acc["recent"] = min(
        float(acc.get("recent", acc["min"])),
        float(other.get("recent", other["min"])),
    )
    acc["samples"] = int(acc.get("samples", 0)) + int(other.get("samples", 0))
    return acc
