"""Chrome-trace JSON validator (``traceck``): the tooling half of the
trace plane's contract.

``TraceRecorder.perfetto`` (and everything built on it: ``GET
/trace?format=perfetto``, ``bench_poisson.py --trace-out``) promises valid
Chrome-trace JSON with monotone spans; this module is the executable form
of that promise, used by the tests and runnable standalone::

    python -m distributed_sudoku_solver_tpu.obs.traceck trace.json

Checks (returns a list of error strings; empty = well-formed):

* top level is an object with a ``traceEvents`` list;
* every event is an object with string ``name``, ``ph`` in the emitted
  set (``X`` complete, ``M`` metadata), integer ``pid``/``tid``;
* ``X`` events carry numeric ``ts >= 0`` and ``dur >= 0``;
* spans are monotone: within each ``(pid, tid)`` lane, ``X`` events'
  ``ts`` never decreases (Perfetto renders out-of-order slices as a
  corrupt-looking track).

Exit codes follow the *ck-family contract (``obs/exitcodes.py``): 0
clean, 1 findings, 2 internal/usage error (bad invocation, unreadable
input).  Stdlib only.
"""

from __future__ import annotations

import json
import sys
from typing import List, Union

from distributed_sudoku_solver_tpu.obs.exitcodes import (
    EXIT_CLEAN,
    EXIT_INTERNAL,
    EXIT_VIOLATIONS,
)

_ALLOWED_PH = {"X", "M", "i", "I"}


def check(doc) -> List[str]:
    """Validate a parsed Chrome-trace document; returns error strings."""
    errors: List[str] = []
    if not isinstance(doc, dict):
        return [f"top level must be an object, got {type(doc).__name__}"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["missing or non-list 'traceEvents'"]
    last_ts: dict = {}  # (pid, tid) -> last X-event ts
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        name = ev.get("name")
        if not isinstance(name, str) or not name:
            errors.append(f"{where}: missing/empty 'name'")
        ph = ev.get("ph")
        if ph not in _ALLOWED_PH:
            errors.append(f"{where}: bad 'ph' {ph!r}")
            continue
        pid, tid = ev.get("pid"), ev.get("tid")
        if not isinstance(pid, int) or not isinstance(tid, int):
            errors.append(f"{where}: pid/tid must be integers")
            continue
        if ph == "M":
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f"{where}: bad 'ts' {ts!r}")
            continue
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f"{where}: bad 'dur' {dur!r}")
            lane = (pid, tid)
            if ts < last_ts.get(lane, float("-inf")):
                errors.append(
                    f"{where}: non-monotone ts {ts} after "
                    f"{last_ts[lane]} in lane pid={pid} tid={tid}"
                )
            else:
                last_ts[lane] = ts
    return errors


def _load(path: str):
    """The one read-and-parse path, shared by check_file and main so the
    two cannot drift (the exit-code split lives at the callers)."""
    with open(path) as f:
        return json.load(f)


def check_file(path: str) -> List[str]:
    try:
        doc = _load(path)
    except (OSError, json.JSONDecodeError) as e:
        return [f"{path}: unreadable or not JSON: {e}"]
    return check(doc)


def main(argv: Union[List[str], None] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m distributed_sudoku_solver_tpu.obs.traceck "
              "<trace.json>", file=sys.stderr)
        return EXIT_INTERNAL
    # Unreadable input is the tool failing to check, not the trace
    # failing the check (exit-code contract, module docstring).
    try:
        doc = _load(argv[0])
    except (OSError, json.JSONDecodeError) as e:
        print(f"traceck: {argv[0]}: unreadable or not JSON: {e}",
              file=sys.stderr)
        return EXIT_INTERNAL
    errors = check(doc)
    if errors:
        for e in errors:
            print(f"traceck: {e}", file=sys.stderr)
        return EXIT_VIOLATIONS
    n = len(doc.get("traceEvents", []))
    print(f"traceck: OK ({n} events)")
    return EXIT_CLEAN


if __name__ == "__main__":
    raise SystemExit(main())
