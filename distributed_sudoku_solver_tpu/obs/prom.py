"""Prometheus text exposition of the nested ``/metrics`` dict.

The JSON ``GET /metrics`` body grew organically over rounds 6-10 into a
nested dict (latency windows, per-geometry resident sections, per-method
fault counters); scraping it requires a JSON exporter sidecar.
:func:`render` flattens it into Prometheus exposition-format lines
(``name{labels} value``) for ``GET /metrics?format=prometheus``:

* nested dict keys join into the metric name
  (``job_latency_ms.p95`` -> ``dsst_job_latency_ms_p95``);
* per-geometry dicts (keys shaped ``9x9``) become a ``geometry`` label
  instead of polluting metric names with digits;
* known enumeration dicts (``duplicates_dropped`` per wire method, an
  injector's per-site counters) become labels too;
* string leaves become info-style gauges: the string is a label on a
  ``1``-valued metric (``dsst_faults_breaker_state{state="open"} 1``);
* numeric lists label by ``index`` (occupancy histogram buckets, the
  ``[term, epoch]`` view);
* ``obs/hist.py`` log2 histograms (dicts tagged ``type: log2_hist``)
  render as real Prometheus histograms: cumulative ``_bucket{le=...}``
  series (edges in ms, ``+Inf`` last) plus ``_sum``/``_count`` — so a
  Prometheus server can `histogram_quantile()` across a scraped ring
  exactly the way ``obs/agg.py`` merges them server-side.  Exemplars
  stay JSON-only (the classic text format has no exemplar syntax).
* ``rpc_floor_ms`` floor estimates (``type: min_est``) render their
  numeric fields as plain gauges.

Output is deterministic (keys sorted at every level) so the golden-file
test pins the format, and ``obs/promck.py`` lints the result (duplicate
series, label escaping, monotone ``le`` buckets).  Stdlib only.
"""

from __future__ import annotations

import re
from typing import List

_GEOM_KEY = re.compile(r"^\d+x\d+$")
_NAME_BAD = re.compile(r"[^a-zA-Z0-9_]")
# Dicts whose keys enumerate a label, not a metric-name path: the parent
# key maps to the label name applied to each child.
_LABEL_DICTS = {
    "duplicates_dropped": "method",
    "dispatches": "site",
    "injected": "site_kind",
    # SLO objectives ("solve_p95_ms<=250") and cluster member addresses
    # ("10.0.0.1:7000") are identities, not name-path material.
    "objectives": "objective",
    "cluster_nodes": "node",
    # Compile-watch / cost-plane program tables (obs/compilewatch.py):
    # per-program series label by display name instead of minting one
    # metric family per compiled program.
    "programs": "program",
    # Front-door route counters (serving/frontdoor): one series per
    # routing tier (cache/propagation/native/device) under a `route`
    # label, mirroring the frontdoor_<route>_ms histograms in `hist`.
    "routes": "route",
    # Brownout per-tier shed counters (serving/brownout.py): one series
    # per shed tier (easy/hard) under a `tier` label.
    "shed": "tier",
}


def _esc(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _name(parts) -> str:
    return _NAME_BAD.sub("_", "_".join(parts))


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return format(float(v), ".10g")


def _line(parts, labels, v) -> str:
    name = _name(parts)
    if labels:
        lab = ",".join(f'{k}="{_esc(str(val))}"' for k, val in labels)
        return f"{name}{{{lab}}} {_fmt(v)}"
    return f"{name} {_fmt(v)}"


def _hist_lines(parts: list, labels: list, val: dict, lines: List[str]) -> None:
    """An obs/hist.py log2 histogram as Prometheus histogram series:
    cumulative ``le`` buckets (ms edges), then ``_sum`` and ``_count``."""
    edge0 = float(val.get("edge0_ms", 0.001))
    counts = val.get("counts") or []
    cum = 0
    for i, c in enumerate(counts):
        cum += int(c)
        le = "+Inf" if i == len(counts) - 1 else _fmt(edge0 * (2.0 ** i))
        lines.append(_line(parts + ["bucket"], labels + [("le", le)], cum))
    lines.append(_line(parts + ["sum"], labels, float(val.get("sum_ms", 0.0))))
    lines.append(_line(parts + ["count"], labels, cum))


def _walk(parts: list, val, labels: list, lines: List[str]) -> None:
    if isinstance(val, bool) or isinstance(val, (int, float)):
        lines.append(_line(parts, labels, val))
    elif isinstance(val, str):
        # Info-style: the leaf key doubles as the label name.
        lines.append(_line(parts, labels + [(parts[-1], val)], 1))
    elif isinstance(val, dict):
        if not val:
            return
        if val.get("type") == "log2_hist":
            _hist_lines(parts, labels, val, lines)
            return
        if val.get("type") == "min_est":
            # Floor estimate: numeric fields as gauges, the tag skipped.
            for k in sorted(val, key=str):
                if k != "type":
                    _walk(parts + [str(k)], val[k], labels, lines)
            return
        keys = sorted(val, key=str)
        if all(isinstance(k, str) and _GEOM_KEY.match(k) for k in keys):
            for k in keys:
                _walk(parts, val[k], labels + [("geometry", k)], lines)
        elif parts and parts[-1] in _LABEL_DICTS:
            label = _LABEL_DICTS[parts[-1]]
            for k in keys:
                child = val[k]
                child_labels = labels + [(label, str(k))]
                if isinstance(child, dict):
                    # Exactly ONE labeled level: the child's own keys are
                    # ordinary name-path segments (an SLO objective's
                    # fields, a member's reachability gauges) — without
                    # this, a nested dict re-matches the rule and emits a
                    # duplicate label name, which breaks the scrape.
                    for ck in sorted(child, key=str):
                        _walk(parts + [str(ck)], child[ck], child_labels, lines)
                else:
                    _walk(parts, child, child_labels, lines)
        else:
            for k in keys:
                _walk(parts + [str(k)], val[k], labels, lines)
    elif isinstance(val, (list, tuple)):
        for i, item in enumerate(val):
            if isinstance(item, (bool, int, float)):
                _walk(parts, item, labels + [("index", str(i))], lines)
    # None and anything else: skipped (no honest numeric reading).


def render(metrics: dict, prefix: str = "dsst") -> str:
    """The full exposition body for one scrape (trailing newline included,
    as the exposition format requires)."""
    lines: List[str] = []
    _walk([prefix], metrics, [], lines)
    return "\n".join(lines) + ("\n" if lines else "")
