"""Opt-in ordering trace: branch-training examples + route/wall outcomes.

The learned pieces of ROADMAP #4 train **offline** from data this module
journals during normal serving:

* **route outcomes** — per job: the front door's probe score, the route it
  took (cache / probe-solved / native / device), the wall time, and the
  device node count when the job went to a flight.  ``benchmarks/
  train_ordering.py fit-threshold`` replays these to pick the
  ``easy_score`` routing threshold that actually separates the
  probe-solvable tier from the device tier, replacing the fixed default
  (``serving/frontdoor/learn.py``).
* **branch examples** — per solved grid (sampled): the grid itself, so the
  host-side replay (``ops/ordering.py:record_branch_examples``) can
  journal every (state, chosen-cell, subtree-nodes) decision off the hot
  path.  The device kernels never journal per-branch data — that would be
  a host sync per node; recording the *grid* costs one line of JSONL.

Like ``obs/trace.py``, production runs with no recorder installed and
every hook site pays one global read + one branch.  The recorder appends
JSONL (one self-describing event per line, ``{"kind": ...}``) so a crash
loses at most one line and training can stream the file.  Layering: obs
is a closed layer importable from serving — the front door cannot import
ops, so the hooks live here and the ops-side replay reads the file.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
from typing import Optional

from distributed_sudoku_solver_tpu.obs import lockdep


class OrderTraceRecorder:
    """Append-only JSONL journal of route outcomes and sampled grids.

    ``sample_grids``: record every k-th resolved grid as a branch-example
    source (1 = every grid).  Grids serialize as the flat digit string the
    cluster wire format uses — 81 chars at 9x9, '0' for empty."""

    def __init__(self, path: str, sample_grids: int = 1):
        self.path = path
        self.sample_grids = max(1, int(sample_grids))
        self._lock = lockdep.named_lock("obs.ordertrace")  # lockck: name(obs.ordertrace)
        self._fh = open(path, "a", encoding="utf-8")  # lockck: guard(_lock)
        self._grid_seen = 0  # lockck: guard(_lock)
        self.events = 0  # lockck: guard(_lock)

    def _emit_locked(self, event: dict) -> None:
        self._fh.write(json.dumps(event, sort_keys=True) + "\n")
        self._fh.flush()
        self.events += 1

    def route(
        self,
        uuid: str,
        score: int,
        empties: int,
        route: str,
        wall_ms: float,
        solved: bool,
        unsat: bool,
        nodes: int = 0,
    ) -> None:
        """One resolved job: what the probe saw and how the route paid off."""
        with self._lock:
            self._emit_locked(
                {
                    "kind": "route",
                    "uuid": uuid,
                    "score": int(score),
                    "empties": int(empties),
                    "route": route,
                    "wall_ms": round(float(wall_ms), 3),
                    "solved": bool(solved),
                    "unsat": bool(unsat),
                    "nodes": int(nodes),
                }
            )

    def grid(self, grid, n: int) -> None:
        """Sampled branch-example source; ``grid`` is any [n, n] int array."""
        with self._lock:
            self._grid_seen += 1
            if (self._grid_seen - 1) % self.sample_grids:
                return
            flat = "".join(str(int(grid[r][c])) for r in range(n) for c in range(n))
            self._emit_locked({"kind": "grid", "n": n, "grid": flat})

    def close(self) -> None:
        with self._lock:
            self._fh.close()


def read_events(path: str) -> list:
    """All events in a journal file (skipping any torn final line)."""
    out = []
    if not os.path.exists(path):
        return out
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # torn tail line from a crash mid-write
    return out


# -- the process-wide seam ----------------------------------------------------

_active: Optional[OrderTraceRecorder] = None


def install(recorder: Optional[OrderTraceRecorder]) -> None:
    global _active
    _active = recorder


def active() -> Optional[OrderTraceRecorder]:
    return _active


@contextlib.contextmanager
def installed(recorder: OrderTraceRecorder):
    """Scope a recorder over a block (tests): always uninstalls."""
    install(recorder)
    try:
        yield recorder
    finally:
        install(None)
        recorder.close()
