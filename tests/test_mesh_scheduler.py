"""Round-21 pod-scale resident serving: the mesh-resident flight.

The suite-wide conftest forces an 8-device CPU host platform
(``--xla_force_host_platform_device_count=8``), so every test here runs on
a REAL multi-device mesh — shard_map partitioning, psum merges, and the
cross-shard ring steal all execute against distinct device buffers, not a
degenerate 1-device identity.  CPU "devices" share one socket, so nothing
here asserts wall-clock scaling (that's bench_poisson's job); these tests
pin semantics:

* the engine selects ``MeshResidentFlight`` when ``mesh_devices`` fits,
  and degrades to the single-chip flight (counting ``mesh_unfit``) when
  it does not;
* the admission surface is UNCHANGED: lifecycle, queueing, cancel, and
  deadline expiry behave identically with slots spread over four shards;
* cross-shard steal actually fires (ring-shipped rows observable on
  ``metrics()["mesh"]``) and home lanes are never clobbered — verdicts
  stay bit-identical to the single-chip resident run;
* the round-8 contract survives sharding: exactly ONE status fetch per
  consumed chunk on the mesh loop;
* an injected collective fault (``mesh.advance``) classifies transient
  and rebuilds through the round-9 breaker — jobs requeue and complete.
"""

import numpy as np
import pytest

import distributed_sudoku_solver_tpu.serving.engine as engine_mod
from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.serving import faults
from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
from distributed_sudoku_solver_tpu.serving.mesh_scheduler import (
    MeshResidentFlight,
)
from distributed_sudoku_solver_tpu.serving.scheduler import (
    ResidentConfig,
    ResidentFlight,
)
from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9
from tests.test_scheduler import wait_for

SMALL = SolverConfig(min_lanes=8, stack_slots=16)
# 2 slots PER SHARD x 4 shards = 8 total; gang 4 leaves 3 steal-installable
# lanes per gang (home lanes excluded from ring installs).
MESH_RC = ResidentConfig(
    job_slots=2, gang_lanes=4, queue_depth=32, attach_batch=4,
    chunk_steps=16, mesh_devices=4,
)
BOARDS = [EASY_9, HARD_9[0], HARD_9[1], HARD_9[2]]


def _mesh_metrics(eng):
    return eng.metrics()["resident"]["9x9"].get("mesh")


def _solve_all(eng, boards, timeout=180):
    jobs = [eng.submit(b) for b in boards]
    for j in jobs:
        assert j.wait(timeout), "job timed out"
    return jobs


def test_mesh_flight_selected_and_pool_scales(heavy_compile_guard):
    """mesh_devices=4 on an 8-device host: the engine builds a
    MeshResidentFlight whose slot pool is job_slots * devices, lanes
    divide evenly over shards, and metrics() grows the mesh section."""
    eng = SolverEngine(config=SMALL, max_batch=8, resident=MESH_RC).start()
    try:
        jobs = _solve_all(eng, BOARDS)
        for j in jobs:
            assert j.solved and j.error is None, (j.error, j.last_fault)
            assert is_valid_solution(j.solution)
        rf = eng._resident[SUDOKU_9]
        assert isinstance(rf, MeshResidentFlight)
        assert rf.n_slots == MESH_RC.job_slots * MESH_RC.mesh_devices
        assert rf.config.lanes % MESH_RC.mesh_devices == 0
        m = _mesh_metrics(eng)
        assert m is not None
        assert m["devices"] == 4
        assert len(m["slot_occupancy"]) == 4
        assert len(m["shard_live_lanes"]) == 4
        assert m["rebuilds"] == 0
        assert eng.metrics().get("mesh_unfit", 0) == 0
    finally:
        eng.stop(timeout=2)


def test_mesh_lifecycle_occupies_multiple_shards():
    """Six concurrent tenants on a 2-slot-per-shard mesh MUST spread past
    shard 0 (slot s lives on shard s // job_slots) — caught mid-flight via
    the per-shard occupancy gauge, then everything drains clean."""
    eng = SolverEngine(
        config=SMALL, max_batch=8, handicap_s=0.05,
        resident=ResidentConfig(
            job_slots=2, gang_lanes=4, queue_depth=32, attach_batch=8,
            chunk_steps=1, mesh_devices=4,
        ),
    ).start()
    try:
        boards = [HARD_9[0], HARD_9[1], HARD_9[2]] * 2
        jobs = [eng.submit(b) for b in boards]
        assert wait_for(
            lambda: sum(
                1 for s in _mesh_metrics(eng)["slot_occupancy"] if s > 0
            ) >= 2,
            timeout=60,
        ), "tenants never spread past one shard"
        for j in jobs:
            assert j.wait(180) and j.solved, (j.error, j.last_fault)
            assert is_valid_solution(j.solution)
        assert wait_for(
            lambda: sum(_mesh_metrics(eng)["slot_occupancy"]) == 0,
            timeout=30,
        )
    finally:
        eng.stop(timeout=2)


def test_mesh_cancel_and_deadline_across_shards():
    """Cancel and deadline expiry keep their single-chip semantics when
    the victim's slot lives on a non-zero shard: prompt resolution, slot
    freed, pool still serves the next tenant."""
    eng = SolverEngine(
        config=SMALL, max_batch=8, handicap_s=0.06,
        resident=ResidentConfig(
            job_slots=2, gang_lanes=4, queue_depth=32, attach_batch=8,
            chunk_steps=1, mesh_devices=4,
        ),
    ).start()
    try:
        # Fill shard 0 with long-running tenants, then land the victims on
        # a later shard.
        # HARD_9[0]/[1] branch deeply; HARD_9[2] solves by propagation
        # alone (nodes=0) and would beat any deadline — not used here.
        pad = [eng.submit(HARD_9[0]), eng.submit(HARD_9[1])]
        victim = eng.submit(HARD_9[0])
        expiring = eng.submit(HARD_9[1], deadline_s=0.3)
        assert wait_for(
            lambda: sum(_mesh_metrics(eng)["slot_occupancy"][1:]) >= 1,
            timeout=60,
        ), "victims never reached a non-zero shard"
        eng.cancel(victim.uuid)
        assert victim.wait(30), "cancelled mesh tenant must resolve promptly"
        assert victim.cancelled and not victim.solved and not victim.unsat
        assert expiring.wait(60)
        assert expiring.error == "deadline expired"
        assert not expiring.solved and not expiring.unsat
        for j in pad:
            assert j.wait(180) and j.solved
        rm = eng.metrics()["resident"]["9x9"]
        assert rm["cancelled"] >= 1 and rm["deadline_expired"] >= 1
        ok = eng.submit(EASY_9)
        assert ok.wait(60) and ok.solved, "slot not recycled on the mesh"
    finally:
        eng.stop(timeout=2)


def test_cross_shard_steal_fires():
    """One hard tenant + three idle shards: the receiver-initiated ring
    MUST ship stack rows across shards (idle shards request, the loaded
    shard donates into non-home lanes).  The shipped-row counter in the
    status word is the proof — and the verdict must survive the theft."""
    eng = SolverEngine(config=SMALL, max_batch=8, resident=MESH_RC).start()
    try:
        # AI Escargot branches (~70 expansions); HARD_9[2] would be
        # useless here — it solves by propagation with an empty stack.
        j = eng.submit(HARD_9[0])
        assert j.wait(180) and j.solved, (j.error, j.last_fault)
        assert is_valid_solution(j.solution)
        assert j.nodes > 0, "board solved by propagation — nothing to steal"
        m = _mesh_metrics(eng)
        assert m["ring_shipped"] > 0, (
            "cross-shard steal never fired on a deep single-tenant search",
            m,
        )
    finally:
        eng.stop(timeout=2)


def test_mesh_verdicts_bit_identical_to_single_chip():
    """The whole point of home-lane exclusion + chunk-boundary counter
    re-replication: the mesh flight is an execution strategy, not a
    different solver.  Same boards, same config => byte-equal solutions
    against the single-chip resident flight."""
    boards = BOARDS * 2
    single = SolverEngine(
        config=SMALL, max_batch=8,
        resident=ResidentConfig(
            job_slots=8, gang_lanes=4, queue_depth=32, attach_batch=4,
            chunk_steps=16,
        ),
    ).start()
    try:
        base = _solve_all(single, boards)
        assert isinstance(single._resident[SUDOKU_9], ResidentFlight)
        assert not isinstance(single._resident[SUDOKU_9], MeshResidentFlight)
    finally:
        single.stop(timeout=2)
    mesh = SolverEngine(config=SMALL, max_batch=8, resident=MESH_RC).start()
    try:
        got = _solve_all(mesh, boards)
        assert isinstance(mesh._resident[SUDOKU_9], MeshResidentFlight)
    finally:
        mesh.stop(timeout=2)
    for b, g in zip(base, got):
        assert b.solved and g.solved, (b.error, g.error)
        np.testing.assert_array_equal(g.solution, b.solution)


def test_mesh_fallback_when_too_few_devices():
    """mesh_devices beyond the visible device count: the engine counts
    mesh_unfit, logs the degrade, and serves on the single-chip flight —
    jobs never notice."""
    eng = SolverEngine(
        config=SMALL, max_batch=8,
        resident=ResidentConfig(
            job_slots=4, gang_lanes=4, queue_depth=32, attach_batch=4,
            chunk_steps=16, mesh_devices=64,
        ),
    ).start()
    try:
        j = eng.submit(HARD_9[0])
        assert j.wait(120) and j.solved, (j.error, j.last_fault)
        rf = eng._resident[SUDOKU_9]
        assert not isinstance(rf, MeshResidentFlight)
        m = eng.metrics()
        assert m["mesh_unfit"] >= 1
        assert _mesh_metrics(eng) is None
    finally:
        eng.stop(timeout=2)


def test_mesh_loop_exactly_one_sync_per_chunk(monkeypatch):
    """The round-8 contract on the mesh loop: the status word (now with
    ring/per-shard telemetry appended) is still ONE fetch per consumed
    chunk, plus the single verdict-collection event — psum/all_gather
    merges happen in-graph, never as extra host syncs."""
    calls: list = []
    orig = engine_mod.host_fetch

    def counting(x, floor_s=0.0, tag="status"):
        calls.append(tag)
        return orig(x, floor_s=floor_s, tag=tag)

    monkeypatch.setattr(engine_mod, "host_fetch", counting)
    eng = SolverEngine(
        config=SMALL, max_batch=8,
        resident=ResidentConfig(
            job_slots=2, gang_lanes=4, queue_depth=32, attach_batch=4,
            chunk_steps=2, mesh_devices=4,
        ),
    ).start()
    try:
        j = eng.submit(HARD_9[1])
        assert j.wait(180) and j.solved, (j.error, j.last_fault)
        rf = eng._resident[SUDOKU_9]
        assert isinstance(rf, MeshResidentFlight)
        assert wait_for(lambda: all(s is None for s in rf.slots), timeout=20)
        chunks = rf.chunks
    finally:
        eng.stop(timeout=2)
    statuses = calls.count("status")
    events = calls.count("event")
    assert statuses == chunks, (
        "mesh status fetches must be exactly one per consumed chunk",
        statuses, chunks,
    )
    assert statuses >= 2, "workload too easy to exercise the mesh chunk loop"
    assert events == 1, "exactly one verdict collection for one tenant"
    assert calls.count("finalize") == 0
    assert len(calls) == statuses + events, calls


def test_mesh_breaker_rebuild_after_collective_fault():
    """Shard loss is a failed collective: inject a runtime fault at the
    mesh.advance seam, the flight classifies it TRANSIENT, drops the
    donated sharded state, requeues the held jobs, and rebuilds through
    the round-9 breaker — every job completes with a valid verdict and
    the rebuild shows on both the faults and mesh metric sections."""
    inj = faults.FaultInjector(
        faults.FaultSchedule.at({"mesh.advance": {0: "runtime"}})
    )
    with faults.injected(inj):
        eng = SolverEngine(
            config=SMALL, max_batch=8, resident=MESH_RC,
            recovery=faults.RecoveryPolicy(
                max_retries=10, rebuild_cooldown_s=0.0
            ),
        ).start()
        try:
            jobs = _solve_all(eng, [HARD_9[0], HARD_9[1]])
            for j in jobs:
                assert j.solved and j.error is None, (j.error, j.last_fault)
                assert is_valid_solution(j.solution)
            rm = eng.metrics()["resident"]["9x9"]
            assert rm["faults"]["rebuilds"] >= 1
            assert rm["mesh"]["rebuilds"] >= 1
            assert eng.metrics()["faults"]["budget_exhausted"] == 0
        finally:
            eng.stop(timeout=2)
    assert sum(inj.metrics()["injected"].values()) >= 1


def _pressure_stub(cls, pending: int, free: int, slots: int, depth: int):
    """A slots-shaped stand-in: ``admission_pressure`` reads only the
    pending list, the slot array, ``rcfg.queue_depth``, and the wait
    window — no devices needed to pin the arithmetic."""
    import threading
    from types import SimpleNamespace

    fl = object.__new__(cls)
    fl._lock = threading.Lock()
    fl._pending = [object()] * pending
    fl.slots = [None] * free + [object()] * (slots - free)
    fl.rcfg = SimpleNamespace(queue_depth=depth)
    fl.admission_wait = SimpleNamespace(snapshot=lambda: {})
    return fl


def test_mesh_admission_pressure_subtracts_free_slot_headroom():
    """ISSUE 20 satellite: the brownout queue signal on a mesh flight.
    Pending jobs that fit the mesh's FREE shard slots attach on the next
    chunk — they are not sustained pressure — so a browning node with
    ``mesh_devices`` headroom reads LOWER than the single-chip flight
    and gets wider before the controller sheds.  With the pool full the
    two flights read identically."""
    # 4 pending, 3 free slots across the shards, queue_depth 8.
    single = _pressure_stub(ResidentFlight, pending=4, free=3, slots=8, depth=8)
    mesh = _pressure_stub(MeshResidentFlight, pending=4, free=3, slots=8, depth=8)
    assert single.admission_pressure() == (0.5, 0.0)
    assert mesh.admission_pressure() == (0.125, 0.0)  # (4 - 3) / 8
    # Headroom covers everything pending: zero pressure, keep admitting.
    roomy = _pressure_stub(MeshResidentFlight, pending=2, free=6, slots=8, depth=8)
    assert roomy.admission_pressure() == (0.0, 0.0)
    # Full pool: the mesh signal degenerates to the single-chip one.
    full_s = _pressure_stub(ResidentFlight, pending=6, free=0, slots=8, depth=8)
    full_m = _pressure_stub(MeshResidentFlight, pending=6, free=0, slots=8, depth=8)
    assert full_m.admission_pressure() == full_s.admission_pressure() == (0.75, 0.0)
