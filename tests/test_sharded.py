"""Multi-chip semantics on the virtual 8-device CPU mesh (SURVEY.md §4 item 3).

The real `shard_map` + collectives run on fake devices — the TPU-world
replacement for the reference's loopback-multiprocess methodology.
"""

import jax
import numpy as np
import pytest

from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.ops.solve import solve_batch
from distributed_sudoku_solver_tpu.parallel import make_mesh, solve_batch_sharded
from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution, solve_oracle
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9


def _respects_clues(solution, puzzle):
    puzzle = np.asarray(puzzle)
    solution = np.asarray(solution)
    return bool(np.all((puzzle == 0) | (solution == puzzle)))


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_sharded_matches_single_device():
    grids = np.stack([EASY_9, *HARD_9])
    cfg = SolverConfig(min_lanes=64, stack_slots=64)
    res1 = solve_batch(grids, SUDOKU_9, cfg)
    res8 = solve_batch_sharded(grids, SUDOKU_9, cfg, mesh=make_mesh())
    assert np.all(np.asarray(res8.solved))
    assert not np.any(np.asarray(res8.overflowed))
    np.testing.assert_array_equal(np.asarray(res8.solved), np.asarray(res1.solved))
    for j in range(grids.shape[0]):
        sol = np.asarray(res8.solution[j])
        assert is_valid_solution(sol)
        assert _respects_clues(sol, grids[j])
    # Unique-solution boards: bit-exact with the single-device path + oracle.
    np.testing.assert_array_equal(
        np.asarray(res8.solution), np.asarray(res1.solution)
    )


def test_sharded_bit_exact_vs_oracle():
    grids = np.stack(HARD_9)
    res = solve_batch_sharded(grids, SUDOKU_9, SolverConfig())
    for j in range(grids.shape[0]):
        expect = solve_oracle(grids[j])
        np.testing.assert_array_equal(np.asarray(res.solution[j]), expect)


def test_ring_steal_spreads_one_hard_job():
    # One job on an 8-chip mesh: only cross-chip stealing can occupy 7 chips.
    # HARD_9[0] ("AI Escargot") needs ~70 branch nodes even with propagation;
    # HARD_9[2] would be useless here — it solves by propagation alone.
    grids = np.asarray(HARD_9[0])[None]
    cfg = SolverConfig(min_lanes=32, stack_slots=64, ring_steal_k=4)
    res = solve_batch_sharded(grids, SUDOKU_9, cfg)
    assert bool(res.solved[0])
    assert int(res.steals) > 0
    assert is_valid_solution(np.asarray(res.solution[0]))


def test_sharded_unsat_is_proven():
    # Two identical digits in one row -> contradiction at the root.
    bad = np.asarray(EASY_9).copy()
    bad[0, 0] = 5
    bad[0, 1] = 5
    grids = bad[None]
    res = solve_batch_sharded(grids, SUDOKU_9, SolverConfig())
    assert not bool(res.solved[0])
    assert bool(res.unsat[0])
    assert not bool(res.overflowed[0])


def test_single_device_submesh():
    mesh = make_mesh(jax.devices()[:1])
    grids = np.stack([EASY_9])
    res = solve_batch_sharded(grids, SUDOKU_9, SolverConfig(), mesh=mesh)
    assert bool(res.solved[0])
    assert is_valid_solution(np.asarray(res.solution[0]))


@pytest.mark.parametrize("n_dev", [2, 4])
def test_submesh_sizes(n_dev):
    mesh = make_mesh(jax.devices()[:n_dev])
    grids = np.stack([EASY_9, HARD_9[0]])
    res = solve_batch_sharded(grids, SUDOKU_9, SolverConfig(), mesh=mesh)
    assert np.all(np.asarray(res.solved))
    for j in range(2):
        assert is_valid_solution(np.asarray(res.solution[j]))
