"""DHT-plane tests (round 20, ``cluster/dht/``): gossip membership,
consistent-hash ownership of the canonical digest space, and the
cluster-wide result cache.

Two lanes.  The unit lane drives the pure state machines (HashRing,
Gossip, ClusterCache) directly — no network, fake clocks.  The simnet
lane (marked like tests/test_simnet.py: no real sockets, no wall-clock
sleeps) pins the ISSUE acceptance points: a board solved on any member
answers every symmetry-equivalent resubmission anywhere in the ring
bit-exactly with zero solver dispatches at the requester; negative
(unsat) entries propagate; a digest owner dying mid-fill degrades to a
local solve with no lost job; duplicate CACHE_PUT frames apply once;
and cache-affine routing declines unhealthy owners.  The 500-node soak
lives at the bottom, slow-marked.
"""

import dataclasses
import threading

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.cluster.dht.cluster_cache import ClusterCache
from distributed_sudoku_solver_tpu.cluster.dht.hashring import HashRing
from distributed_sudoku_solver_tpu.cluster.dht.membership import (
    ALIVE,
    DEAD,
    SUSPECT,
    Gossip,
)
from distributed_sudoku_solver_tpu.cluster.node import ClusterConfig
from distributed_sudoku_solver_tpu.cluster.simnet import SimNet, wait_until
from distributed_sudoku_solver_tpu.cluster.wire import WireError
from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
from distributed_sudoku_solver_tpu.serving.faults import FaultSchedule
from distributed_sudoku_solver_tpu.serving.frontdoor.canonical import (
    apply_transform,
    canonicalize,
    random_transform,
)
from distributed_sudoku_solver_tpu.serving.frontdoor.router import FrontDoorConfig
from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution, solve_oracle
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

from tests.test_cluster import a_geom, oracle_solve_fn
from tests.test_simnet import SIM, form_ring, net, sim_node  # noqa: F401 - fixtures

pytestmark = pytest.mark.simnet


# -- unit lane: HashRing ------------------------------------------------------


def test_hashring_deterministic_and_bounded_movement():
    members = [f"10.0.0.{i}:7000" for i in range(8)]
    r1, r2 = HashRing(vnodes=32), HashRing(vnodes=32)
    for m in members:
        r1.add(m)
    for m in reversed(members):  # insertion order must not matter
        r2.add(m)
    keys = [f"digest-{i:04x}" for i in range(2000)]
    owners = [r1.owner(k) for k in keys]
    assert owners == [r2.owner(k) for k in keys], (
        "converged views must agree on ownership regardless of join order"
    )
    # Every member owns a nontrivial share (vnode spreading).
    share = {m: owners.count(m) for m in members}
    assert all(share[m] > 0 for m in members), f"starved member: {share}"

    # A join moves only the arcs adjacent to the new member's points:
    # keys NOT owned by the joiner keep their old owner.
    r1.add("10.0.0.99:7000")
    moved = 0
    for k, old in zip(keys, owners):
        now = r1.owner(k)
        if now != old:
            moved += 1
            assert now == "10.0.0.99:7000", (
                f"key {k} moved {old} -> {now}: movement must only flow "
                "to the joining member"
            )
    # Expected movement ~ 1/9 of keys; assert a generous 3x bound.
    assert 0 < moved < len(keys) // 3
    # And the leave is the exact inverse.
    r1.remove("10.0.0.99:7000")
    assert [r1.owner(k) for k in keys] == owners

    # Replica sets: distinct members, owner first.
    reps = r1.replicas(keys[0], 3)
    assert reps[0] == r1.owner(keys[0])
    assert len(reps) == len(set(reps)) == 3

    summary = r1.summary()
    assert summary["members"] == 8
    assert abs(sum(summary["share"].values()) - 1.0) < 1e-9


def test_hashring_empty_and_single():
    r = HashRing()
    assert r.owner("x") is None and r.replicas("x") == []
    assert r.summary() == {"members": 0, "points": 0, "share": {}}
    r.add("a:1")
    assert r.owner("anything") == "a:1"
    assert r.replicas("anything", 5) == ["a:1"]


# -- unit lane: Gossip --------------------------------------------------------


def _gossip(addr="a:1", suspicion_s=2.0, piggyback=4):
    t = [0.0]
    g = Gossip(addr, lambda: t[0], suspicion_s=suspicion_s, piggyback=piggyback)
    return g, t


def test_gossip_suspicion_death_and_resurrection():
    g, t = _gossip()
    g.reconcile(["a:1", "b:1", "c:1"])
    assert g.state_of("b:1") == ALIVE and g.is_healthy("b:1")

    g.on_probe_fail("b:1")
    assert g.state_of("b:1") == SUSPECT
    assert not g.is_healthy("b:1")
    # Suspicion has not expired: no death reported yet.
    t[0] = 1.0
    _, newly_dead = g.tick()
    assert newly_dead == []
    # An ACK inside the window refutes the suspicion.
    g.on_ack("b:1")
    assert g.state_of("b:1") == ALIVE
    # Suspect again and let it expire: reported DEAD exactly once.
    g.on_probe_fail("b:1")
    t[0] = 4.0
    _, newly_dead = g.tick()
    assert newly_dead == ["b:1"]
    assert g.state_of("b:1") == DEAD
    _, again = g.tick()
    assert again == []
    # DEAD members are never probe targets.
    targets = {g.tick()[0] for _ in range(4)}
    assert targets == {"c:1"}
    # The authoritative view re-admitting the member IS the refutation.
    g.reconcile(["a:1", "b:1", "c:1"])
    assert g.state_of("b:1") == ALIVE
    m = g.metrics()
    assert m["suspicions"] == 2 and m["deaths"] == 1 and m["resurrections"] == 1


def test_gossip_incarnation_order_and_self_refutation():
    g, _ = _gossip()
    g.reconcile(["a:1", "b:1"])
    # Higher incarnation wins; stale (lower) incarnations are ignored.
    g.merge([{"m": "b:1", "s": SUSPECT, "i": 0}])
    assert g.state_of("b:1") == SUSPECT
    g.merge([{"m": "b:1", "s": ALIVE, "i": 1}])
    assert g.state_of("b:1") == ALIVE
    g.merge([{"m": "b:1", "s": DEAD, "i": 0}])
    assert g.state_of("b:1") == ALIVE, "stale incarnation must not regress state"
    assert g.metrics()["stale_ignored"] == 1
    # Tie: DEAD > SUSPECT > ALIVE.
    g.merge([{"m": "b:1", "s": DEAD, "i": 1}])
    assert g.state_of("b:1") == DEAD
    # Seeing ourselves suspected refutes by bumping our incarnation,
    # which rides the next updates() batch.
    g.merge([{"m": "a:1", "s": SUSPECT, "i": 0}])
    ups = g.updates()
    assert ups[0]["m"] == "a:1" and ups[0]["i"] == 1 and ups[0]["s"] == ALIVE
    assert g.metrics()["refutations"] == 1


def test_gossip_piggyback_is_bounded():
    g, _ = _gossip(piggyback=4)
    g.reconcile([f"m{i}:1" for i in range(32)] + ["a:1"])
    for i in range(16):
        g.on_probe_fail(f"m{i}:1")  # 16 fresh state changes to spread
    ups = g.updates()
    assert len(ups) <= 4, f"piggyback exceeded its bound: {len(ups)}"
    assert ups[0]["m"] == "a:1", "self entry must always lead the batch"
    # Spread budgets drain: repeated batches eventually carry only self.
    for _ in range(64):
        g.updates()
    assert len(g.updates()) == 1


# -- unit lane: ClusterCache --------------------------------------------------


class _FakeClock:
    def __init__(self):
        self.t = 0.0
        self.slept = []

    def now(self):
        return self.t

    def sleep(self, dt):
        self.slept.append(dt)
        self.t += dt


def test_cluster_cache_owner_routing_and_negative_hits():
    sent = []

    def request_fn(owner, frame, timeout):
        sent.append((owner, frame))
        raise WireError("owner unreachable")

    cc = ClusterCache(
        "a:1",
        owner_fn=lambda d: "b:1" if d.startswith("remote") else "a:1",
        request_fn=request_fn,
        put_fn=lambda o, f: None,
        clock=_FakeClock(),
        uuid_fn=lambda: "u-1",
        capacity=2,
    )
    # Remote miss path: a WireError is a miss, never an exception.
    assert cc.lookup("remote-1") is None
    assert sent[0][0] == "b:1" and sent[0][1]["method"] == "CACHE_GET"
    m = cc.metrics()
    assert m["remote_errors"] == 1 and m["misses"] == 1

    # Local shard: store, hit, negative hit, LRU eviction.
    cc.store("local-1", {"verdict": "solved", "solution": [[1]]})
    cc.store("local-2", {"verdict": "unsat", "solution": None})
    assert cc.lookup("local-1")["verdict"] == "solved"
    assert cc.lookup("local-2")["verdict"] == "unsat"
    assert cc.metrics()["negative_hits"] == 1
    cc.store("local-3", {"verdict": "solved", "solution": [[2]]})  # evicts LRU
    assert len(cc) == 2 and cc.metrics()["evictions"] == 1


def test_cluster_cache_put_retry_budget():
    clock = _FakeClock()
    attempts = []

    def put_fn(owner, frame):
        attempts.append(frame["uuid"])
        if len(attempts) < 3:
            raise WireError("flaky link")

    cc = ClusterCache(
        "a:1",
        owner_fn=lambda d: "b:1",
        request_fn=lambda o, f, t: {},
        put_fn=put_fn,
        clock=clock,
        uuid_fn=lambda: "put-uuid",
        put_retries=2,
        retry_delay_s=0.5,
    )
    # Drive the retry loop synchronously (store() runs it on a daemon
    # thread; the loop itself is the unit under test).
    cc._put_loop("b:1", {"method": "CACHE_PUT", "uuid": "put-uuid", "digest": "d", "entry": {}})
    assert attempts == ["put-uuid"] * 3, "every attempt must reuse the uuid"
    assert clock.slept == [0.5, 0.5]
    assert cc.metrics()["puts_sent"] == 1 and cc.metrics()["puts_failed"] == 0
    # Budget exhaustion counts a lost fill, not an error.
    attempts.clear()

    def always_fail(owner, frame):
        attempts.append(1)
        raise WireError("down")

    cc._put_fn = always_fail
    cc._put_loop("b:1", {"method": "CACHE_PUT", "uuid": "u2", "digest": "d", "entry": {}})
    assert len(attempts) == 3
    assert cc.metrics()["puts_failed"] == 1


# -- simnet lane --------------------------------------------------------------

#: Affinity off for the cache-path tests: the requester must answer
#: through CACHE_GET routing, not by forwarding the whole job to the
#: digest owner (that path gets its own test below).
SIM_NOAFF = dataclasses.replace(SIM, dht_affinity=False)


def fd_engine(calls=None):
    """Oracle-backed engine WITH a front door (the L2 seam's consumer).
    ``easy_score=0`` pins probed-open boards to the engine path — no
    native racer, so ``calls`` counts every non-cached solve exactly."""
    base = oracle_solve_fn()

    def solve(grids, geom, cfg):
        if calls is not None:
            calls.append(len(grids))
        return base(grids, geom, cfg)

    # batch_window_s is deliberately NOT microscopic: commit_device
    # attaches the cache-fill hook after submit() places the job, and an
    # instantaneous oracle behind a 1ms window can resolve first (a
    # documented bounded miss).  50ms makes the fill deterministic.
    return SolverEngine(
        solve_fn=solve,
        batch_window_s=0.05,
        frontdoor=FrontDoorConfig(easy_score=0),
    ).start()


def _digest_of(board) -> str:
    cf = canonicalize(np.asarray(board, np.int32), SUDOKU_9)
    assert cf is not None
    return cf.digest


def _dht_ring(net, k, config=SIM_NOAFF):
    """k-node ring of front-door engines; returns (nodes, per-node call
    counters)."""
    calls = [[] for _ in range(k)]
    engines = {i: fd_engine(calls[i]) for i in range(k)}
    nodes = form_ring(net, k, config=config, engines=engines)
    return nodes, calls


def _owner_node(nodes, digest):
    owner = nodes[0]._ring_owner(digest)
    return next(n for n in nodes if n.addr_s == owner)


def test_hit_anywhere_is_hit_everywhere(net):
    """ISSUE acceptance: a board solved once on any member answers every
    symmetry-equivalent resubmission from ANY other member bit-exactly,
    with zero solver dispatches at the requester (CACHE_GET to the
    digest owner, promoted into the requester's L1)."""
    nodes, calls = _dht_ring(net, 3)
    a = nodes[0]
    board = np.asarray(HARD_9[0], np.int32)
    expect = solve_oracle(board, a_geom(board))
    digest = _digest_of(board)

    # Warm: solve once through A's engine (local front door, device
    # route) — the fill replicates to the digest owner's shard.
    j0 = a.engine.submit(board)
    assert j0.wait(60) and j0.solved
    assert np.array_equal(j0.solution, expect)
    owner = _owner_node(nodes, digest)
    assert wait_until(net, lambda: len(owner.dcache) >= 1, timeout=30), (
        "cache fill never reached the digest owner's shard"
    )

    rng = np.random.default_rng(0xD147)
    for i, n in enumerate(nodes):
        if n is a:
            continue
        before = len(calls[i])
        # Same board AND a random symmetry transform of it: one orbit,
        # one entry, hit either way.
        tr = random_transform(SUDOKU_9, rng)
        for grid, want in (
            (board, expect),
            (apply_transform(board, tr), apply_transform(expect, tr)),
        ):
            j = n.engine.submit(grid)
            assert j.wait(60) and j.solved, f"node {i}: {j.error!r}"
            assert j.route == "cache", f"node {i} routed {j.route!r}"
            assert np.array_equal(j.solution, np.asarray(want, np.int32)), (
                f"node {i}: cached answer not bit-exact"
            )
            assert is_valid_solution(j.solution)
        assert len(calls[i]) == before, (
            f"node {i} dispatched its solver on a cached orbit"
        )
        if n is not owner:
            assert n.dcache.metrics()["remote_hits"] >= 1
        # Exactly one L2 round-trip per node: the first hit is promoted
        # into L1, so the transformed resubmit answers from L1 alone.
        assert n.engine.frontdoor.cluster_hits == 1
        assert n.engine.frontdoor.metrics()["cache"]["hits"] >= 1


def test_negative_entry_propagates(net):
    """An unsat proof on one member answers as a cached 'unsat' verdict
    cluster-wide — repeats of a contradictory orbit never re-probe."""
    nodes, calls = _dht_ring(net, 3)
    a, b = nodes[0], nodes[1]
    bad = np.asarray(EASY_9, np.int32).copy()
    row = bad[0]
    givens = row[row > 0]
    hole = int(np.flatnonzero(row == 0)[0])
    bad[0, hole] = givens[0]  # duplicate in row 0: propagation-proven unsat
    digest = _digest_of(bad)

    j0 = a.engine.submit(bad)
    assert j0.wait(60) and j0.unsat and not j0.solved
    assert j0.route == "propagation"
    owner = _owner_node(nodes, digest)
    assert wait_until(net, lambda: len(owner.dcache) >= 1, timeout=30), (
        "negative fill never reached the digest owner's shard"
    )

    before = len(calls[1])
    j1 = b.engine.submit(bad)
    assert j1.wait(60) and j1.unsat and not j1.solved
    assert j1.route == "cache", "negative verdict must come from the cache"
    assert len(calls[1]) == before
    assert b.dcache.metrics()["negative_hits"] >= 1


def test_owner_failure_mid_fill_falls_back_to_local_solve(net):
    """A partitioned digest owner turns lookups into misses and fills
    into bounded retries — the requester solves locally, the job
    completes bit-exactly, nothing is lost or raised."""
    nodes, calls = _dht_ring(net, 3)
    board = np.asarray(HARD_9[1], np.int32)
    expect = solve_oracle(board, a_geom(board))
    digest = _digest_of(board)
    owner = _owner_node(nodes, digest)
    others = [n for n in nodes if n is not owner]
    requester = others[0]
    r_idx = nodes.index(requester)

    net.partition([owner.addr_s], [n.addr_s for n in others])
    before = len(calls[r_idx])
    j = requester.engine.submit(board)
    assert j.wait(120) and j.solved, f"job lost to a dead owner: {j.error!r}"
    assert np.array_equal(j.solution, expect)
    assert len(calls[r_idx]) > before, "fallback must be a LOCAL solve"
    m = requester.dcache.metrics()
    assert m["remote_errors"] >= 1, "owner miss must be counted"
    # The L1 took the entry even though the cluster fill is stranded:
    # an immediate repeat answers from cache.
    j2 = requester.engine.submit(board)
    assert j2.wait(30) and j2.solved and j2.route == "cache"
    net.heal()


def test_cache_put_dedupe(net):
    """At-least-once fills: the same CACHE_PUT frame delivered twice
    applies once — the node-level uuid dedupe drops the duplicate."""
    nodes, _ = _dht_ring(net, 2)
    b = nodes[1]
    frame = {
        "method": "CACHE_PUT",
        "uuid": "put-dedupe-1",
        "digest": "f00d" * 16,
        "entry": {"verdict": "solved", "solution": [[1]], "nodes": 0},
        "from": nodes[0].addr_s,
    }
    net.inject(b.addr, dict(frame))
    net.inject(b.addr, dict(frame))
    assert wait_until(
        net, lambda: b.duplicates_dropped.get("CACHE_PUT", 0) == 1, timeout=30
    ), "duplicate CACHE_PUT was not deduped"
    m = b.dcache.metrics()
    assert m["puts_applied"] == 1, "duplicate CACHE_PUT mutated the shard"
    assert m["entries"] == 1


def test_affinity_routes_to_owner_and_declines_unhealthy(net):
    """Cache-affine placement: a cacheable submit lands on its digest
    owner; a suspected (probe-failing) owner is declined at the
    requester and the job still completes elsewhere."""
    nodes, calls = _dht_ring(net, 2, config=SIM)  # affinity ON
    board = np.asarray(HARD_9[1], np.int32)
    digest = _digest_of(board)
    owner = _owner_node(nodes, digest)
    requester = next(n for n in nodes if n is not owner)
    o_idx = nodes.index(owner)

    j = requester.submit(board)
    assert wait_until(net, lambda: j.done.is_set(), timeout=120)
    assert j.solved
    with requester._lock:
        assert requester.affinity_routed >= 1
    assert len(calls[o_idx]) >= 1, "affine job must solve at the digest owner"

    # Kill the requester->owner PROBE channel only: gossip suspects the
    # owner while the view (heartbeats untouched) keeps it a member.
    probe_link = f"link:{requester.addr_s}->{owner.addr_s}:PROBE"
    net.set_schedule(
        FaultSchedule(lambda site, idx: "drop" if site == probe_link else None)
    )
    assert wait_until(
        net,
        lambda: requester.gossip.state_of(owner.addr_s) == SUSPECT,
        timeout=60,
    ), "dropped probes never raised suspicion"
    assert owner.addr_s in requester.network  # still a member

    j2 = requester.submit(np.asarray(HARD_9[0], np.int32))
    assert wait_until(net, lambda: j2.done.is_set(), timeout=120)
    assert j2.solved
    with requester._lock:
        routed_after = requester.affinity_routed
        declined = requester.affinity_declined
    # Either the second board's owner was the suspect (declined) or it
    # hashed to the requester itself (routed, self is always healthy) —
    # both legal; what is pinned is that NOTHING was affinity-routed to
    # the suspected owner.
    if nodes[0]._ring_owner(_digest_of(HARD_9[0])) == owner.addr_s:
        assert declined >= 1, "suspected owner must be declined"
    else:
        assert routed_after >= 1
    net.set_schedule(None)


def test_dht_view_and_metrics_rollup(net):
    """The /network?scope=dht body and the cluster metrics rollup carry
    the DHT plane: gossip states, ring shares, shard counters; the
    agg merge sums gossip events and cache numerics across members."""
    nodes, _ = _dht_ring(net, 3)
    a = nodes[0]
    board = np.asarray(HARD_9[0], np.int32)
    digest = _digest_of(board)
    j = a.engine.submit(board)
    assert j.wait(60) and j.solved

    view = a.dht_view(owner_of=digest)
    assert set(view["members"]) == set(a.network)
    assert all(m["state"] == ALIVE for m in view["members"].values())
    assert view["ring"]["members"] == 3
    assert view["owner"]["digest"] == digest
    assert view["owner"]["owner"] == a._ring_owner(digest)
    assert view["owner"]["owner"] in view["owner"]["replicas"]
    assert view["cluster_cache"]["capacity"] > 0

    dht = a.metrics_view()["dht"]
    assert dht["gossip"]["alive"] == 3
    assert "cluster_cache" in dht and "affinity" in dht

    # 3 members' shards hold the one filled orbit between them, and the
    # rollup's entries sum IS the cluster cache size (disjoint shards).
    assert wait_until(
        net,
        lambda: a.cluster_metrics_view()["rollup"]["dht"]["cluster_cache"][
            "entries"
        ] >= 1,
        timeout=30,
    )
    roll = a.cluster_metrics_view()["rollup"]
    assert "gossip" in roll["dht"] and "merged" in roll["dht"]["gossip"]
    assert "capacity" not in roll["dht"]["cluster_cache"], (
        "per-node capacity must not sum across shards"
    )
    assert roll["members_total"] == 3 and roll["sampled"] is False

    # Sampled pull: bounded fan-out, deterministic rollup metadata.
    sampled = a.cluster_metrics_view(sample=1)
    assert sampled["rollup"]["members_total"] == 3
    assert sampled["rollup"]["sampled"] is True
    assert len(sampled["nodes"]) == 2  # self + one sampled peer


# -- the 500-node soak (slow lane) --------------------------------------------


@pytest.mark.slow
def test_500_node_gossip_soak_chaos_churn_coordinator_kill(net):
    """ISSUE acceptance: 500 virtual members form one view, survive
    seeded link chaos + a partition + member churn + a coordinator
    kill, and every job submitted through the storm completes with a
    solution bit-identical to the fault-free oracle.  Gossip keeps
    per-beat traffic O(1) per member the whole way (one PROBE each)."""
    n_nodes = 500
    soak_cfg = ClusterConfig(
        heartbeat_s=2.0,
        fail_factor=8.0,
        io_timeout_s=2.0,
        stats_timeout_s=1.0,
        needwork=False,
        progress_interval_s=0.0,
        send_retries=4,
        retry_delay_s=0.25,
        tombstone_probe_s=3600.0,
    )
    # 8 shared oracle engines: the soak exercises the PROTOCOL plane;
    # 500 independent engines would only stress the CI box.
    engines = [
        SolverEngine(solve_fn=oracle_solve_fn(), batch_window_s=0.001).start()
        for _ in range(8)
    ]
    nodes = [sim_node(net, config=soak_cfg, engine=engines[0])]
    for i in range(1, n_nodes):
        nodes.append(
            sim_node(
                net,
                anchor=nodes[0].addr,
                config=soak_cfg,
                engine=engines[i % len(engines)],
            )
        )
    a = nodes[0]
    assert wait_until(
        net,
        lambda: all(len(n.network) == n_nodes for n in nodes),
        timeout=1200,
        step=2.0,
    ), (
        f"view never converged: "
        f"{sorted({len(n.network) for n in nodes})[:5]}..."
    )

    boards = [np.asarray(EASY_9, np.int32)] + [
        np.asarray(h, np.int32) for h in HARD_9[:2]
    ]
    expect = [solve_oracle(g, a_geom(g)) for g in boards]
    assert all(s is not None for s in expect)

    # Weather on: low-rate seeded chaos across every link (at 500 nodes
    # a beat is ~1500 messages; 2% keeps the failure paths hot without
    # drowning the at-least-once budgets).
    net.set_schedule(
        FaultSchedule.seeded(seed=17, rate=0.02, kinds=("drop", "dup", "delay"))
    )
    # Stride starts at 1 so no job is submitted via nodes[0] (the
    # coordinator we kill later): indices 1, 38, 75, 112, 149, 186 all
    # stay live through the partition (100..109) and kills (200..204).
    jobs = [
        (i, nodes[(i * 37 + 1) % n_nodes].submit(boards[i % len(boards)]))
        for i in range(6)
    ]

    # Partition a 10-member block long enough for eviction, then heal.
    block = [n.addr_s for n in nodes[100:110]]
    net.partition(block, [n.addr_s for n in nodes if n.addr_s not in block])
    assert wait_until(
        net,
        lambda: all(m not in a.network for m in block),
        timeout=600,
        step=2.0,
    ), "partitioned block never evicted"
    jobs += [
        (i, nodes[(i * 37) % 100].submit(boards[i % len(boards)]))
        for i in range(6, 12)
    ]
    net.heal()
    assert wait_until(
        net,
        lambda: all(len(nodes[i].network) == n_nodes for i in range(0, 500, 50)),
        timeout=1200,
        step=2.0,
    ), "healed block never rejoined"

    # Churn: kill five members outright (they stay dead).
    killed = nodes[200:205]
    for n in killed:
        n.kill()
    dead_addrs = {n.addr_s for n in killed}
    live = [n for n in nodes if n.addr_s not in dead_addrs]

    # Coordinator kill under churn: promotion must reconverge the fleet.
    a.kill()
    dead_addrs.add(a.addr_s)
    live = [n for n in live if n is not a]
    assert wait_until(
        net,
        lambda: all(
            live[i].coordinator not in dead_addrs
            and len(live[i].network) == n_nodes - 6
            for i in range(0, len(live), 50)
        ),
        timeout=2400,
        step=2.0,
    ), "fleet never reconverged after churn + coordinator kill"
    coord = live[0].coordinator
    assert all(live[i].coordinator == coord for i in range(0, len(live), 97))

    jobs += [
        (i, live[(i * 41) % len(live)].submit(boards[i % len(boards)]))
        for i in range(12, 18)
    ]

    # Zero lost jobs, bit-identical solutions.  Every job was submitted
    # via a member that stays alive for the whole soak (the strides dodge
    # the partition block, the killed span, and the coordinator), so
    # at-least-once delivery must land every single one.
    assert wait_until(
        net,
        lambda: all(j.done.is_set() for _, j in jobs),
        timeout=2400,
        step=2.0,
    ), (
        f"lost jobs: {[(i, j.error) for i, j in jobs if not j.done.is_set()]}"
    )
    for i, j in jobs:
        assert j.solved, f"job {i} unsolved: {j.error!r}"
        assert np.array_equal(j.solution, expect[i % len(boards)]), (
            f"job {i} not bit-identical to the fault-free oracle"
        )

    # The storm actually blew: fault plane + gossip state machine hot.
    assert net.counters["dropped"] > 0
    assert net.counters["duplicated"] > 0
    assert net.counters["blocked"] > 0
    g_tot = {"suspicions": 0, "deaths": 0, "merged": 0}
    for i in range(0, len(live), 25):
        m = live[i].gossip.metrics()
        for k in g_tot:
            g_tot[k] += m[k]
    assert g_tot["merged"] > 0, "gossip piggyback never propagated state"
    assert g_tot["suspicions"] > 0, "chaos never raised a suspicion"
