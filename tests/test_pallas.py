"""Pallas propagation kernel: bit-exactness vs the XLA path.

Runs the *same kernel code* the TPU executes, in Pallas interpreter mode on
CPU (``ops/pallas_propagate.py`` auto-selects interpret off-TPU) — the
kernel-level analog of the suite-wide virtual-mesh methodology (SURVEY.md §4).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_4, SUDOKU_6, SUDOKU_9
from distributed_sudoku_solver_tpu.ops.bitmask import encode_grid
from distributed_sudoku_solver_tpu.ops.pallas_propagate import (
    propagate_fixpoint_pallas,
    sweep_mosaic,
)
from distributed_sudoku_solver_tpu.ops.propagate import propagate, propagate_sweep
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.ops.solve import solve_batch
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9, puzzle_batch


def _random_cands(geom, batch, seed):
    """Arbitrary candidate tensors (not just reachable boards): the kernel
    must agree with the XLA sweep on *any* uint32 masks in range."""
    rng = np.random.default_rng(seed)
    full = geom.full_mask
    return jnp.asarray(
        rng.integers(0, full + 1, size=(batch, geom.n, geom.n), dtype=np.uint32)
    )


@pytest.mark.parametrize("geom", [SUDOKU_4, SUDOKU_6, SUDOKU_9])
def test_sweep_mosaic_matches_xla_sweep(geom):
    cand = _random_cands(geom, 64, seed=geom.n)
    ref = propagate_sweep(cand, geom)
    got = sweep_mosaic(cand, geom)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_sweep_mosaic_boards_last_axes():
    cand = _random_cands(SUDOKU_9, 32, seed=5)
    ref = propagate_sweep(cand, SUDOKU_9)
    got_t = sweep_mosaic(jnp.transpose(cand, (1, 2, 0)), SUDOKU_9, row_ax=0, col_ax=1)
    np.testing.assert_array_equal(
        np.asarray(ref), np.asarray(jnp.transpose(got_t, (2, 0, 1)))
    )


@pytest.mark.parametrize("batch,tile", [(8, 8), (24, 8)])
def test_fixpoint_kernel_matches_xla(batch, tile):
    grids = np.stack([EASY_9, *HARD_9] * 6)[:batch].astype(np.int32)
    cand = encode_grid(jnp.asarray(grids), SUDOKU_9)
    ref, ref_sweeps = propagate(cand, SUDOKU_9)
    got, sweeps = propagate_fixpoint_pallas(cand, SUDOKU_9, tile=tile)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
    # per-tile convergence never needs more rounds than the global loop
    assert int(sweeps) <= int(ref_sweeps)


def test_fixpoint_pads_ragged_batch():
    grids = np.stack([EASY_9] * 5).astype(np.int32)  # 5 % 4 != 0
    cand = encode_grid(jnp.asarray(grids), SUDOKU_9)
    ref, _ = propagate(cand, SUDOKU_9)
    got, _ = propagate_fixpoint_pallas(cand, SUDOKU_9, tile=4)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_solve_batch_pallas_propagator_end_to_end():
    grids = np.concatenate(
        [np.stack([EASY_9, *HARD_9]), puzzle_batch(SUDOKU_9, 4, seed=11, n_clues=28)]
    ).astype(np.int32)
    cfg_x = SolverConfig(min_lanes=16, stack_slots=32, propagator="xla")
    cfg_p = SolverConfig(min_lanes=16, stack_slots=32, propagator="pallas")
    rx = solve_batch(grids, SUDOKU_9, cfg_x)
    rp = solve_batch(grids, SUDOKU_9, cfg_p)
    assert np.asarray(rx.solved).all() and np.asarray(rp.solved).all()
    np.testing.assert_array_equal(np.asarray(rx.solution), np.asarray(rp.solution))


@pytest.mark.parametrize("geom", [SUDOKU_6, SUDOKU_9])
def test_box_line_mosaic_matches_xla(geom):
    from distributed_sudoku_solver_tpu.ops.pallas_propagate import box_line_mosaic
    from distributed_sudoku_solver_tpu.ops.propagate import box_line_sweep

    cand = _random_cands(geom, 48, seed=13 + geom.n)
    ref = box_line_sweep(cand, geom)
    got = box_line_mosaic(cand, geom, row_ax=1, col_ax=2)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_box_line_mosaic_rectangular_boxes():
    from distributed_sudoku_solver_tpu.models.geometry import Geometry
    from distributed_sudoku_solver_tpu.ops.pallas_propagate import box_line_mosaic
    from distributed_sudoku_solver_tpu.ops.propagate import box_line_sweep

    geom = Geometry(3, 4)
    cand = _random_cands(geom, 16, seed=99)
    np.testing.assert_array_equal(
        np.asarray(box_line_sweep(cand, geom)),
        np.asarray(box_line_mosaic(cand, geom, row_ax=1, col_ax=2)),
    )


@pytest.mark.parametrize("backend", ["pallas", "slices"])
def test_extended_fixpoint_parity_all_backends(backend):
    from distributed_sudoku_solver_tpu.ops.pallas_propagate import (
        propagate_fixpoint_pallas,
        propagate_fixpoint_slices,
    )
    from distributed_sudoku_solver_tpu.ops.propagate import propagate

    grids = np.stack([EASY_9, *HARD_9] * 4).astype(np.int32)
    cand = encode_grid(jnp.asarray(grids), SUDOKU_9)
    ref, _ = propagate(cand, SUDOKU_9, rules="extended")
    if backend == "pallas":
        got, _ = propagate_fixpoint_pallas(cand, SUDOKU_9, tile=8, rules="extended")
    else:
        got, _ = propagate_fixpoint_slices(cand, SUDOKU_9, rules="extended")
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_fixpoint_rejects_unknown_rules():
    from distributed_sudoku_solver_tpu.ops.pallas_propagate import (
        propagate_fixpoint_pallas,
        propagate_fixpoint_slices,
    )

    cand = encode_grid(jnp.asarray(np.stack([EASY_9]).astype(np.int32)), SUDOKU_9)
    with pytest.raises(ValueError, match="rules"):
        propagate_fixpoint_pallas(cand, SUDOKU_9, rules="extend")
    with pytest.raises(ValueError, match="rules"):
        propagate_fixpoint_slices(cand, SUDOKU_9, rules="extend")
