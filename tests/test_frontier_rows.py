"""Row-extraction algebra: shed/purge/multi-root-reseed on the frontier.

These are the device-side primitives under mid-flight cancellation, progress
checkpointing, and cluster mid-job offload (VERDICT r1 items #2-#4).  Key
invariant: a job's remaining search space IS the disjunction of its lanes'
top rows + stack rows, and those rows are *disjoint* subtrees (each branch
splits guess vs rest), so shedding rows partitions the space exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import geometry_for_size
from distributed_sudoku_solver_tpu.ops.frontier import (
    SolverConfig,
    frontier_live,
    init_frontier_roots,
    purge_jobs,
    shed_rows,
)
from distributed_sudoku_solver_tpu.ops.solve import (
    finalize_frontier,
    solve_batch,
    sudoku_csp,
)
from distributed_sudoku_solver_tpu.utils.checkpoint import (
    advance_frontier,
    start_frontier,
)
from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution
from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9

GEOM = geometry_for_size(9)
CFG = SolverConfig(min_lanes=4, stack_slots=16, branch="first")


def _mid_state(grid, steps=4):
    state = start_frontier(jnp.asarray(np.asarray(grid)[None]), GEOM, CFG)
    return advance_frontier(state, jnp.int32(steps), GEOM, CFG)


def test_shed_rows_partitions_search_space():
    grid = HARD_9[0]
    full = solve_batch(jnp.asarray(np.asarray(grid)[None]), GEOM, CFG)
    assert bool(full.solved[0])
    sol = np.asarray(full.solution[0])

    state = _mid_state(grid)
    assert int(np.asarray(state.count).sum()) >= 1, "need stack rows to shed"
    new_state, rows, valid = jax.jit(shed_rows, static_argnames=("k",))(
        state, jnp.int32(0), 2
    )
    rows = np.asarray(rows)[np.asarray(valid)]
    assert rows.shape[0] >= 1

    # Remaining space: run the post-shed state to completion.
    rem = finalize_frontier(
        advance_frontier(new_state, jnp.int32(CFG.max_steps), GEOM, CFG)
    )
    # Shed space: re-enter the rows as a multi-root job.
    shed_state = init_frontier_roots(
        jnp.asarray(rows), jnp.zeros(rows.shape[0], jnp.int32), 1, CFG
    )
    shed_res = finalize_frontier(
        advance_frontier(shed_state, jnp.int32(CFG.max_steps), GEOM, CFG)
    )

    rem_solved = bool(rem.solved[0])
    shed_solved = bool(shed_res.solved[0])
    # Disjoint subtrees of a uniquely-solvable board: exactly one side solves.
    assert rem_solved != shed_solved
    from distributed_sudoku_solver_tpu.ops.bitmask import decode_grid

    winner = rem if rem_solved else shed_res
    got = np.asarray(decode_grid(winner.solution[0]))
    np.testing.assert_array_equal(got, sol)
    # The losing side proves its subspace empty (exhaustion composes).
    loser = shed_res if rem_solved else rem
    assert bool(loser.unsat[0])


def test_shed_rows_k_exceeding_lanes_no_duplicates():
    """ADVICE r2 #2 + review: k > n_lanes must not ship the same stack row
    twice (clamped OOB gathers repeat the last donor), and the one genuinely
    shipped row must actually leave the donor's stack (the mixed-value
    scatter at a duplicated index is order-undefined)."""
    cfg = SolverConfig(min_lanes=1, lanes=1, stack_slots=16, branch="first")
    state = start_frontier(jnp.asarray(np.asarray(HARD_9[0])[None]), GEOM, cfg)
    state = advance_frontier(state, jnp.int32(4), GEOM, cfg)
    count_before = int(np.asarray(state.count)[0])
    assert count_before >= 1
    new_state, rows, valid = jax.jit(shed_rows, static_argnames=("k",))(
        state, jnp.int32(0), 8
    )
    valid = np.asarray(valid)
    assert valid.sum() == 1, "one donor lane can donate exactly one row"
    assert int(np.asarray(new_state.count)[0]) == count_before - 1, (
        "the shipped row must be removed from the donor stack"
    )


def test_purge_jobs_frees_lanes_and_never_claims_unsat():
    state = _mid_state(HARD_9[0])
    assert bool(np.asarray(frontier_live(state)).any())
    purged = jax.jit(purge_jobs)(state, jnp.ones(1, bool))
    assert not bool(np.asarray(frontier_live(purged)).any())
    res = finalize_frontier(purged)
    assert not bool(res.solved[0])
    assert not bool(res.unsat[0]), "a cancelled job must not be reported proven-unsat"


def test_multi_root_reseed_matches_full_solve():
    grid = HARD_9[1]
    full = solve_batch(jnp.asarray(np.asarray(grid)[None]), GEOM, CFG)
    sol = np.asarray(full.solution[0])

    state = _mid_state(grid, steps=3)
    # Gather ALL rows of job 0 (tops + stack rows) host-side, the snapshot path.
    from distributed_sudoku_solver_tpu.serving.engine import _rows_of_job_host

    rows = _rows_of_job_host(state, 0)
    assert rows.shape[0] >= 1
    reseed = init_frontier_roots(
        jnp.asarray(rows), jnp.zeros(rows.shape[0], jnp.int32), 1, CFG
    )
    res = finalize_frontier(
        advance_frontier(reseed, jnp.int32(CFG.max_steps), GEOM, CFG)
    )
    assert bool(res.solved[0])
    from distributed_sudoku_solver_tpu.ops.bitmask import decode_grid

    got = np.asarray(decode_grid(res.solution[0]))
    np.testing.assert_array_equal(got, sol)
    assert is_valid_solution(got)


def test_init_frontier_roots_padding_rows_ignored():
    grid = HARD_9[0]
    from distributed_sudoku_solver_tpu.ops.bitmask import encode_grid

    enc = np.asarray(encode_grid(jnp.asarray(np.asarray(grid)[None]), GEOM))
    roots = np.zeros((4, 9, 9), np.uint32)
    roots[0] = enc[0]
    job_of_root = np.array([0, -1, -1, -1], np.int32)  # 3 padding rows
    state = init_frontier_roots(jnp.asarray(roots), jnp.asarray(job_of_root), 1, CFG)
    res = finalize_frontier(
        advance_frontier(state, jnp.int32(CFG.max_steps), GEOM, CFG)
    )
    assert bool(res.solved[0])
    full = solve_batch(jnp.asarray(np.asarray(grid)[None]), GEOM, CFG)
    np.testing.assert_array_equal(
        np.asarray(res.solution[0]),
        np.asarray(
            encode_grid(full.solution, GEOM)[0]
        ),
    )
