"""Helper process for tests/test_multihost.py — NOT a test module.

One OS process per cluster member, each with its own ``jax.distributed``
runtime (CPU backend), its own engine, and its own ClusterNode — the
framework's answer to the reference actually running on multiple machines
(``/root/reference/DHT_Node.py:623-665``).  The parent test orchestrates:

* role 0: coordinator; waits for the ring, dispatches jobs (some land on
  role 1 over the TCP control plane), signals role 1 to die abruptly,
  asserts the membership repairs and later jobs still solve, writes a
  JSON result file.
* role 1: joins, serves tasks, then ``os._exit``s when the die-file
  appears (a kill -9 stand-in that never runs LEAVE).
"""

import json
import os
import sys
import time
from types import SimpleNamespace


def main() -> None:
    role = int(sys.argv[1])
    coord_port = int(sys.argv[2])
    p2p0 = int(sys.argv[3])
    p2p1 = int(sys.argv[4])
    workdir = sys.argv[5]

    import jax

    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{coord_port}",
        num_processes=2,
        process_id=role,
    )
    assert jax.process_count() == 2, jax.process_count()

    import numpy as np

    from distributed_sudoku_solver_tpu.cluster.node import ClusterConfig, ClusterNode
    from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
    from distributed_sudoku_solver_tpu.utils.oracle import solve_oracle
    from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9

    def oracle_solve_fn(grids, geom, cfg):
        g = np.asarray(grids)
        sols, solved = [], []
        for i in range(g.shape[0]):
            s = solve_oracle(g[i], geom)
            solved.append(s is not None)
            sols.append(s if s is not None else np.zeros_like(g[i]))
        solved = np.asarray(solved)
        return SimpleNamespace(
            solved=solved,
            unsat=~solved,
            solution=np.stack(sols),
            nodes=np.full(g.shape[0], 7),
        )

    cfg = ClusterConfig(heartbeat_s=0.25, fail_factor=8.0, io_timeout_s=2.0)
    engine = SolverEngine(solve_fn=oracle_solve_fn, batch_window_s=0.001).start()
    node = ClusterNode(
        engine,
        host="127.0.0.1",
        port=p2p0 if role == 0 else p2p1,
        anchor=None,  # joined manually below, with retries (startup race)
        config=cfg,
    ).start()

    if role == 1:
        # Two fresh processes race to their listeners; retry the join until
        # the coordinator's view includes us (JOIN_REQ is idempotent).
        from distributed_sudoku_solver_tpu.cluster import wire
        from distributed_sudoku_solver_tpu.cluster.wire import WireError

        deadline = time.monotonic() + 60
        while len(node.network) < 2 and time.monotonic() < deadline:
            try:
                wire.send_msg(
                    ("127.0.0.1", p2p0),
                    {"method": "JOIN_REQ", "addr": node.addr_s},
                    2.0,
                )
            except WireError:
                pass
            time.sleep(0.5)

    die_file = os.path.join(workdir, "die")
    result_file = os.path.join(workdir, f"result{role}.json")

    def wait_for(pred, timeout=60.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.05)
        return False

    if role == 1:
        # Serve until told to die — no LEAVE, no cleanup: a crash stand-in.
        with open(result_file, "w") as f:
            json.dump({"joined": wait_for(lambda: len(node.network) == 2)}, f)
        while not os.path.exists(die_file):
            time.sleep(0.05)
        os._exit(9)

    out = {"process_count": jax.process_count()}
    out["ring_formed"] = wait_for(lambda: len(node.network) == 2)
    # Dispatch across processes: least-outstanding spreads over both members.
    jobs = [node.submit(EASY_9) for _ in range(6)]
    out["all_solved"] = all(j.wait(30) and j.solved for j in jobs)
    out["remote_used"] = any(
        node._outstanding.get(m, 0) >= 0 for m in node.network if m != node.addr_s
    ) and len(node.network) == 2
    # node._outstanding counts net to 0 after completion; prove remote
    # execution from the peer's stats instead.
    peer_stats = node.stats_view()
    out["peer_validations"] = sum(
        n["validations"] or 0
        for n in peer_stats["nodes"]
        if n["address"] != node.addr_s
    )

    # Kill the peer abruptly; membership must repair and service continue.
    with open(die_file, "w") as f:
        f.write("die")
    out["peer_removed"] = wait_for(lambda: len(node.network) == 1, timeout=30)
    post = node.submit(EASY_9)
    out["post_kill_solved"] = post.wait(30) and post.solved

    with open(result_file, "w") as f:
        json.dump(out, f)
    node.kill()
    engine.stop(timeout=2)
    os._exit(0)


if __name__ == "__main__":
    main()
