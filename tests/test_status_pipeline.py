"""Round-8 serving contract: one fetch per chunk, zero state copies,
always-ahead dispatch.

Three properties are pinned here:

* **Packed status word** (``ops/frontier.chunk_status`` /
  ``unpack_status``): the one small array each serving chunk fetches
  carries exactly what the old scattered fetches carried — steps, per-job
  solved / has-work bitmasks, and the lane-occupancy delta histogram.
* **Donation is invisible** (bit-exactness): every frontier-threading
  program now donates its input state; on this CPU backend donation is
  real (the input buffer is deleted and reused), so the donated-vs-
  undonated A/B below is a genuine aliasing-correctness check, not a
  no-op.
* **Fetch-count guard**: the serving hot loops read device values ONLY
  through ``serving.engine.host_fetch`` — wrapping that seam counts host
  syncs, and the guard asserts exactly one ``status`` fetch per consumed
  chunk (plus event/finalize fetches only where a job actually resolved).
  A stray ``np.asarray`` added to a hot loop fails here instead of
  silently re-adding ~100 ms/chunk through a tunneled device.

``heavy_compile_guard`` is requested ONCE, by the first donation A/B
test — that clears a crowded cache right before the donation section,
whose undonated twins (composite first, the outsized fused twin two
tests later) are this module's heavy compiles — per-test use would
clear_caches()-storm the rest of the suite (ROADMAP timing note).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distributed_sudoku_solver_tpu.serving.engine as engine_mod
from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
from distributed_sudoku_solver_tpu.ops.frontier import (
    Frontier,
    SolverConfig,
    attach_roots,
    chunk_status,
    detach,
    frontier_live,
    purge_jobs,
    run_frontier,
    shed_rows,
    status_len,
    unpack_status,
)
from distributed_sudoku_solver_tpu.ops.solve import sudoku_csp
from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
from distributed_sudoku_solver_tpu.serving.scheduler import ResidentConfig
from distributed_sudoku_solver_tpu.utils.checkpoint import (
    advance_frontier_status,
    start_frontier,
)
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

SMALL = SolverConfig(min_lanes=8, stack_slots=16)
FUSED_SMALL = SolverConfig(
    min_lanes=8, stack_slots=16, step_impl="fused", fused_steps=2
)
RC = ResidentConfig(
    job_slots=4, gang_lanes=4, queue_depth=32, attach_batch=4, chunk_steps=16
)


def wait_for(pred, timeout=30.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return False


def _host_tree(state):
    return jax.tree_util.tree_map(np.asarray, state)


def _device_tree(host):
    return jax.tree_util.tree_map(jnp.asarray, host)


def _mid_state_host(cfg, steps=6):
    """A mid-search frontier as a HOST tree (re-deviced per consumer, so
    donated programs can eat their copy without starving the next one)."""
    grids = np.stack([HARD_9[0], HARD_9[1], EASY_9]).astype(np.int32)
    state = start_frontier(jnp.asarray(grids), SUDOKU_9, cfg)
    state, _ = advance_frontier_status(state, jnp.int32(steps), SUDOKU_9, cfg)
    return _host_tree(state)


# -- the packed status word ---------------------------------------------------


@pytest.mark.parametrize("n_jobs", [1, 37])
def test_status_word_roundtrip(n_jobs):
    """chunk_status packs exactly what unpack_status recovers, including
    multi-word bitmasks (37 jobs -> two 32-bit words per mask) and the
    occupancy delta histogram."""
    rng = np.random.RandomState(3 + n_jobs)
    n_lanes, s = 16, 4
    job = rng.randint(-1, n_jobs, size=n_lanes).astype(np.int32)
    has_top = rng.rand(n_lanes) < 0.7
    solved = rng.rand(n_jobs) < 0.3
    prev_rounds = rng.randint(0, 5, size=n_lanes).astype(np.int32)
    lane_rounds = prev_rounds + rng.randint(0, 9, size=n_lanes).astype(np.int32)
    state = Frontier(
        top=jnp.zeros((n_lanes, 9, 9), jnp.uint32),
        has_top=jnp.asarray(has_top),
        stack=jnp.zeros((n_lanes, s, 9, 9), jnp.uint32),
        base=jnp.zeros(n_lanes, jnp.int32),
        count=jnp.zeros(n_lanes, jnp.int32),
        job=jnp.asarray(job),
        solved=jnp.asarray(solved),
        solution=jnp.zeros((n_jobs, 9, 9), jnp.uint32),
        overflowed=jnp.zeros(n_jobs, bool),
        nodes=jnp.zeros(n_jobs, jnp.int32),
        sol_count=jnp.zeros(n_jobs, jnp.int32),
        steps=jnp.int32(50),
        sweeps=jnp.int32(0),
        expansions=jnp.int32(0),
        steals=jnp.int32(0),
        lane_rounds=jnp.asarray(lane_rounds),
    )
    status = np.asarray(
        jax.jit(chunk_status)(jnp.int32(42), jnp.asarray(prev_rounds), state)
    )
    assert status.shape == (status_len(n_jobs),)
    info = unpack_status(status, n_jobs)
    assert info["steps"] == 50
    delta = lane_rounds - prev_rounds
    assert info["live_sum"] == int(delta.sum())
    want_hist = np.bincount(
        np.clip((delta * 10) // (50 - 42), 0, 9), minlength=10
    )
    np.testing.assert_array_equal(info["hist"], want_hist)
    np.testing.assert_array_equal(info["solved"], solved)
    live = np.asarray(frontier_live(state))
    want_work = np.zeros(n_jobs, bool)
    for lane in np.flatnonzero(live):
        want_work[job[lane]] = True
    np.testing.assert_array_equal(info["has_work"], want_work)


# -- donation safety ----------------------------------------------------------


def test_donated_programs_bit_identical_to_undonated(heavy_compile_guard):
    """Every donated frontier program produces output bit-identical to an
    undonated twin of the same computation — donation changes buffer
    ownership, never values.  Donation is real on this backend: the
    donated-away input must raise on a later read."""
    from distributed_sudoku_solver_tpu.serving.engine import _purge, _shed_jit

    host = _mid_state_host(SMALL)
    csp = sudoku_csp(SUDOKU_9, SMALL)

    @jax.jit  # fresh executable, no donation
    def undonated_advance(state, steps_delta):
        new = run_frontier(
            state, csp, SMALL, step_limit=state.steps + steps_delta
        )
        return new, chunk_status(state.steps, state.lane_rounds, new)

    ref_state, ref_status = undonated_advance(_device_tree(host), jnp.int32(8))
    donated_in = _device_tree(host)
    got_state, got_status = advance_frontier_status(
        donated_in, jnp.int32(8), SUDOKU_9, SMALL
    )
    for name, a, b in zip(
        Frontier._fields, ref_state, got_state, strict=True
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)
    np.testing.assert_array_equal(np.asarray(ref_status), np.asarray(got_status))
    with pytest.raises(RuntimeError):
        np.asarray(donated_in.top)  # input really was donated away

    # purge / shed (engine's donated wrappers vs the eager pure functions).
    dead = jnp.asarray(np.array([True, False, False]))
    ref = purge_jobs(_device_tree(host), dead)
    got = _purge(_device_tree(host), dead)
    for name, a, b in zip(Frontier._fields, ref, got, strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)
    ref_st, ref_rows, ref_valid = shed_rows(_device_tree(host), jnp.int32(0), 2)
    got_st, got_rows, got_valid = _shed_jit(_device_tree(host), jnp.int32(0), 2)
    np.testing.assert_array_equal(np.asarray(ref_rows), np.asarray(got_rows))
    np.testing.assert_array_equal(np.asarray(ref_valid), np.asarray(got_valid))
    for name, a, b in zip(Frontier._fields, ref_st, got_st, strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


def test_donated_attach_detach_bit_identical():
    """The resident flight's donated attach/detach wrappers vs the eager
    ops, on the real resident shapes (gang-scoped lanes, slot rows)."""
    from distributed_sudoku_solver_tpu.ops.bitmask import encode_grid
    from distributed_sudoku_solver_tpu.serving.scheduler import (
        _attach_jit,
        _detach_jit,
        _init_resident,
        resident_solver_config,
    )

    cfg = resident_solver_config(SMALL, SUDOKU_9, RC)
    host = _host_tree(_init_resident(SUDOKU_9, cfg, RC.job_slots))
    grids = np.zeros((RC.attach_batch, 9, 9), np.int32)
    grids[0], grids[1] = EASY_9, HARD_9[0]
    slot_ids = np.asarray([0, 2, -1, -1], np.int32)
    ref = attach_roots(
        _device_tree(host),
        encode_grid(jnp.asarray(grids), SUDOKU_9),
        jnp.asarray(slot_ids),
        cfg.steal_gang,
    )
    got = _attach_jit(
        _device_tree(host),
        jnp.asarray(grids),
        jnp.asarray(slot_ids),
        SUDOKU_9,
        cfg.steal_gang,
    )
    for name, a, b in zip(Frontier._fields, ref, got, strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)
    attached = _host_tree(got)
    mask = jnp.asarray(np.array([True, False, True, False]))
    ref_d = detach(_device_tree(attached), mask)
    got_d = _detach_jit(_device_tree(attached), mask)
    for name, a, b in zip(Frontier._fields, ref_d, got_d, strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


def test_donated_fused_advance_bit_identical_to_undonated():
    """The fused serving chunk program under donation vs an undonated twin
    (the fused path's own layout gymnastics make this the surface most
    likely to miscompile under aliasing)."""
    from distributed_sudoku_solver_tpu.ops.pallas_step import (
        _run_fused,
        advance_frontier_fused_status,
        frontier_to_fused,
        fused_to_frontier,
    )

    host = _mid_state_host(FUSED_SMALL, steps=2)

    cfg = FUSED_SMALL

    @jax.jit  # fresh executable, no donation
    def undonated(state, steps_delta):
        limit = jnp.minimum(
            state.steps + steps_delta, jnp.int32(cfg.max_steps)
        )
        fs = _run_fused(frontier_to_fused(state), SUDOKU_9, cfg, limit)
        new = fused_to_frontier(fs)
        return new, chunk_status(state.steps, state.lane_rounds, new)

    ref_state, ref_status = undonated(_device_tree(host), jnp.int32(4))
    got_state, got_status = advance_frontier_fused_status(
        _device_tree(host), jnp.int32(4), SUDOKU_9, cfg
    )
    for name, a, b in zip(
        Frontier._fields, ref_state, got_state, strict=True
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)
    np.testing.assert_array_equal(np.asarray(ref_status), np.asarray(got_status))


# -- the fetch-count guard ----------------------------------------------------


@pytest.fixture
def counted_fetches(monkeypatch):
    """Wrap THE fetch seam; every host sync in the serving hot loops lands
    in the returned list as its tag."""
    calls: list = []
    orig = engine_mod.host_fetch

    def counting(x, floor_s=0.0, tag="status"):
        calls.append(tag)
        return orig(x, floor_s=floor_s, tag=tag)

    monkeypatch.setattr(engine_mod, "host_fetch", counting)
    return calls


@pytest.fixture(
    params=["untraced", "traced", "watched", "lockdep", "journaled"],
    ids=["untraced", "traced", "watched", "lockdep", "journaled"],
)
def tracing(request, tmp_path):
    """Run the sync-count guards three ways: the round-11 trace plane
    (obs/trace.py) promises ZERO host syncs — every span is built from
    values the loop already holds — so the one-sync-per-chunk contract
    must hold bit-identically with a recorder installed; and the
    round-15 compile watch + critical-path monitor (obs/compilewatch.py,
    obs/critpath.py) make the same promise — attribution polls jit-cache
    sizes and the cost seam lowers on the host, so the ``watched``
    variant (all three planes installed) must count identically too
    (the ISSUE-12 zero-added-syncs acceptance)."""
    if request.param == "untraced":
        yield None
        return
    if request.param == "journaled":
        # ISSUE-20 acceptance: the WAL lives entirely on the submit path
        # (synchronous accept append) and the fsync batcher thread —
        # record_resolved is a buffered dict append — so the one-sync-
        # per-chunk counts must be bit-identical with a journal
        # installed, and the device loop must never touch the disk.
        from distributed_sudoku_solver_tpu.serving import journal as journal_wal

        with journal_wal.installed(journal_wal.Journal(str(tmp_path))) as jr:
            yield None
        assert jr.metrics()["accepted"] > 0  # vacuity: the WAL saw the jobs
        assert jr.durable, "journal degraded during a fault-free run"
        return
    if request.param == "lockdep":
        # ISSUE-13 acceptance: the one-sync-per-chunk guard re-runs with
        # a FRESH armed lock witness (scoped over the session one) and
        # the counts must be bit-identical — the witness's per-acquire
        # bookkeeping adds zero host syncs and zero hierarchy
        # violations on the hot loop.
        from distributed_sudoku_solver_tpu.obs import lockdep

        with lockdep.installed(lockdep.manifest_witness(strict=True)) as w:
            yield None
        assert w.violations == [], w.violations
        assert w.acquisitions > 0  # vacuity: the loop did take locks
        return
    from distributed_sudoku_solver_tpu.obs import trace

    rec = trace.TraceRecorder(ring=8192)
    trace.install(rec)
    if request.param == "watched":
        from distributed_sudoku_solver_tpu.obs import compilewatch, critpath

        compilewatch.install(compilewatch.CompileWatch(warmup_s=3600.0))
        critpath.install(critpath.CritPathMonitor())
        try:
            yield rec
        finally:
            critpath.install(None)
            compilewatch.install(None)
            trace.install(None)
        return
    try:
        yield rec
    finally:
        trace.install(None)


def test_static_loop_exactly_one_sync_per_chunk(counted_fetches, tracing):
    """A multi-chunk single-job static flight: every consumed chunk costs
    exactly one 'status' fetch; the only other sync is the terminal
    finalize.  A stray value read added to the hot loop shows up as an
    unexplained extra call and fails here."""
    eng = SolverEngine(config=SMALL, max_batch=8, chunk_steps=2).start()
    try:
        j = eng.submit(HARD_9[1])
        assert j.wait(120) and j.solved, j.error
        assert wait_for(lambda: not eng._flights, timeout=20)
    finally:
        eng.stop(timeout=2)
    statuses = counted_fetches.count("status")
    finalizes = counted_fetches.count("finalize")
    assert statuses == eng.chunk_wall.snapshot()["count"], (
        "status fetches must be exactly one per consumed chunk"
    )
    assert statuses >= 3, "workload too easy to exercise the chunk loop"
    assert finalizes == 1
    # A 1-job flight resolves at finalize, never mid-flight: no event
    # fetches, and nothing else in the loop may sync at all.
    assert len(counted_fetches) == statuses + finalizes, counted_fetches
    if tracing is not None:
        # The trace plane really recorded the chunks it claims cost no
        # syncs (an empty ring would make the traced run vacuous).
        names = [s["name"] for s in tracing.spans()]
        assert names.count("chunk.sync") == statuses
        assert "resolve" in names


def test_resident_loop_exactly_one_sync_per_chunk(counted_fetches, tracing):
    """The resident scheduler round: one 'status' fetch per consumed
    chunk, one 'event' fetch on the single round where the tenant's
    verdict is collected, and no terminal finalize (the frontier never
    retires)."""
    eng = SolverEngine(config=SMALL, max_batch=8, resident=RC).start()
    try:
        j = eng.submit(HARD_9[1])
        assert j.wait(120) and j.solved, j.error
        rf = eng._resident[SUDOKU_9]
        assert wait_for(lambda: all(s is None for s in rf.slots), timeout=20)
        chunks = rf.chunks
    finally:
        eng.stop(timeout=2)
    statuses = counted_fetches.count("status")
    events = counted_fetches.count("event")
    assert statuses == chunks, (
        "resident status fetches must be exactly one per consumed chunk"
    )
    assert statuses >= 1
    assert events == 1, "exactly one verdict collection for one tenant"
    assert counted_fetches.count("finalize") == 0
    assert len(counted_fetches) == statuses + events, counted_fetches
    if tracing is not None:
        names = [s["name"] for s in tracing.spans()]
        assert names.count("resident.sync") == statuses
        assert names.count("verdict.sync") == events
        # The admission span carries the resident route attribution.
        adm = [s for s in tracing.spans() if s["name"] == "admission"]
        assert adm and adm[0]["attrs"]["route"] == "resident"


# -- the megastep lane (round 19): ONE sync per FLIGHT ------------------------


def _megastep_engine():
    """A latency-mode engine whose megastep chunks are tiny (2 steps), so
    a hard board NEEDS several in-graph chunks — proving the fused loop
    really looped while the host fetched once."""
    from distributed_sudoku_solver_tpu.serving.megastep import MegastepConfig

    return SolverEngine(
        config=SMALL,
        max_batch=8,
        latency_mode=True,
        megastep=MegastepConfig(gang_lanes=8, chunk_steps=2, max_chunks=64),
    ).start()


def test_megastep_exactly_one_status_sync_per_flight(counted_fetches, tracing):
    """The round-19 contract, the whole point of the megastep: a hard
    board whose chunked flight costs one 'status' fetch PER CHUNK (the
    static test above measures >=3) costs exactly ONE host sync for the
    entire flight — the in-graph ``lax.while_loop`` consumed the chunks,
    and the single batched fetch carried status + chunk count + verdict.
    No event fetch, no finalize, nothing else.  Runs under all four
    obs-plane variants (untraced / traced / watched / lockdep): every
    plane promises zero added syncs, and the lockdep variant additionally
    proves the rank-36 flight lock nests violation-free."""
    eng = _megastep_engine()
    try:
        j = eng.submit(HARD_9[1])
        assert j.wait(120) and j.solved, j.error
        mf = eng._megasteps[SUDOKU_9]
        flights, chunks = mf.flights, mf.chunks_total
    finally:
        eng.stop(timeout=2)
    assert flights == 1
    assert chunks >= 3, "workload too easy to exercise the in-graph loop"
    assert counted_fetches == ["status"], (
        "a megastep flight must cost exactly one host sync", counted_fetches
    )
    if tracing is not None:
        names = [s["name"] for s in tracing.spans()]
        assert names.count("megastep.sync") == 1
        assert names.count("megastep.chunk.dispatch") == 1
        adm = [s for s in tracing.spans() if s["name"] == "admission"]
        assert adm and adm[0]["attrs"]["route"] == "megastep"


def test_megastep_early_exit_no_stale_verdict(counted_fetches):
    """The in-graph loop exits on all-solved at some inner chunk k, not
    at the max_chunks budget; and the post-loop verdict is the EXITED
    state — back-to-back flights recycling the same device mailbox must
    each fetch their own board's solution (a stale verdict from flight
    N-1 leaking into flight N's fetch is the classic donation/aliasing
    failure this pins)."""
    from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution

    boards = [HARD_9[1], HARD_9[0], EASY_9]
    eng = _megastep_engine()
    try:
        sols = []
        for b in boards:
            j = eng.submit(b)
            assert j.wait(120) and j.solved, j.error
            sols.append(np.asarray(j.solution))
        mf = eng._megasteps[SUDOKU_9]
        assert mf.flights == len(boards)
        # Early exit fired: the budget is 64 chunks/flight, a solved
        # board stops the loop orders of magnitude earlier.
        assert mf.chunks_total < len(boards) * mf.cfg.max_chunks / 2
    finally:
        eng.stop(timeout=2)
    assert counted_fetches == ["status"] * len(boards), counted_fetches
    for b, sol in zip(boards, sols):
        assert is_valid_solution(sol)
        clues = np.asarray(b, np.int32)
        np.testing.assert_array_equal(sol[clues > 0], clues[clues > 0])
    assert not np.array_equal(sols[0], sols[1]), "stale verdict leaked"


# -- padded-bucket job dimension (flight frontiers pad to a power of two) -----


def _drive_flight(eng, fl, max_passes=200):
    for _ in range(max_passes):
        if eng._advance_flight(fl):
            return
    raise AssertionError("flight did not finish")


def test_non_pow2_flight_cancel_purges_against_padded_bucket():
    """A 5-job flight pads its frontier to an 8-job bucket; the cancel
    purge's dead mask must be bucket-sized, not len(jobs)-sized
    (regression: a (5,) mask against (8,) state raised in the loop and
    errored every job in the flight).  Driven by hand — the engine is
    never started, so the flight advances deterministically."""
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9 as G9

    eng = SolverEngine(config=SMALL, max_batch=8, chunk_steps=4)
    jobs = [eng.submit(HARD_9[i % 3]) for i in range(5)]
    batch = []
    while True:
        got = eng._take_batch(wait=False)
        if not got:
            break
        batch.extend(got)
    assert len(batch) == 5
    eng._launch_flights(G9, SMALL, batch)
    assert len(eng._flights) == 1
    fl = eng._flights[0]
    assert fl.state.solved.shape[0] == 8  # padded bucket
    eng._advance_flight(fl)  # chunk 0 in flight
    eng.cancel(jobs[3].uuid)
    _drive_flight(eng, fl)
    assert jobs[3].cancelled and not jobs[3].solved
    for i, j in enumerate(jobs):
        if i != 3:
            assert j.solved, (i, j.error)


def test_wide_flight_status_bitmasks_use_padded_bucket_width():
    """65 jobs pad to a 128-job bucket: the status word carries
    ceil(128/32)=4 words per bitmask while ceil(65/32)=3 — unpacking at
    the wrong width misaligns has_work behind solved's padding words and
    the loop finalizes a still-searching flight early (regression)."""
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9 as G9

    eng = SolverEngine(config=SMALL, max_batch=128, chunk_steps=4)
    jobs = [eng.submit(HARD_9[i % 3] if i < 64 else EASY_9) for i in range(65)]
    batch = []
    while True:
        got = eng._take_batch(wait=False)
        if not got:
            break
        batch.extend(got)
    assert len(batch) == 65
    eng._launch_flights(G9, SMALL, batch)
    assert len(eng._flights) == 1
    fl = eng._flights[0]
    assert fl.state.solved.shape[0] == 128  # padded bucket
    _drive_flight(eng, fl)
    for i, j in enumerate(jobs):
        assert j.solved, (i, j.error)
        assert not j.unsat


# -- reaction lag of the always-ahead loop ------------------------------------


def test_cancel_honored_within_two_chunk_boundaries():
    """The pipelined loop reacts to a cancel at the next pass (the purge
    dispatch needs no device data), i.e. within two chunk boundaries of
    the cancel landing — the documented round-8 semantics."""
    eng = SolverEngine(
        config=SMALL, max_batch=8, chunk_steps=1, handicap_s=0.05
    ).start()
    try:
        j = eng.submit(HARD_9[1])
        assert wait_for(lambda: len(eng._flights) > 0, timeout=30)
        fl = eng._flights[0]
        chunks_at_cancel = fl.chunks
        eng.cancel(j.uuid)
        assert j.wait(30), "cancelled job must resolve promptly"
        assert j.cancelled and not j.solved and not j.unsat
        # done was set at the purge pass; at most 2 further dispatches had
        # been enqueued when it happened (the in-flight chunk + the one
        # dispatched in the same pass as the purge), +1 slack for the pass
        # racing the cancel call itself.
        assert fl.chunks - chunks_at_cancel <= 3, (
            f"cancel took {fl.chunks - chunks_at_cancel} dispatches"
        )
        assert wait_for(lambda: not eng._flights, timeout=20)
    finally:
        eng.stop(timeout=2)


def test_deadline_honored_within_two_chunk_boundaries():
    """Deadline expiry on the static path under the pipelined loop: the
    job resolves within ~2 chunk walls of its deadline passing."""
    eng = SolverEngine(
        config=SMALL, max_batch=8, chunk_steps=1, handicap_s=0.05
    ).start()
    try:
        j = eng.submit(HARD_9[1], deadline_s=0.3)
        assert j.wait(30)
        assert j.error == "deadline expired"
        assert not j.solved and not j.unsat
        # Resolution latency: deadline + at most ~2 chunk walls (handicap
        # floor per chunk) + generous container-load slack.
        took = time.monotonic() - j.submitted_at
        assert took < 0.3 + 5.0, f"deadline reaction took {took:.2f}s"
        ok = eng.submit(EASY_9)
        assert ok.wait(60) and ok.solved, "loop died after deadline purge"
    finally:
        eng.stop(timeout=2)
