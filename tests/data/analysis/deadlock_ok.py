"""deadck clean fixture: named locks, rank-upward nesting, guarded and
waived multi-root writes.  Injected config: ranks ``t.a``=20 < ``t.b``=30,
thread roots ``root_one``/``root_two``."""

from distributed_sudoku_solver_tpu.obs import lockdep


class A:
    def __init__(self):
        self._lock = lockdep.named_lock("t.a")  # lockck: name(t.a)
        self.guarded = 0  # lockck: guard(_lock)
        self.under_lock = 0
        self.tolerated = 0

    def outer(self):
        with self._lock:
            helper()  # t.a -> t.b: rank-upward, fine

    def writes(self):
        with self._lock:
            self.guarded += 1
            self.under_lock += 1  # lexical guard satisfies the inference
        # deadck: allow(single-writer by design; readers tolerate staleness)
        self.tolerated += 1

    def flip_locked(self):
        # The *_locked caller-holds-it convention: analyzed as holding t.a.
        self.under_lock -= 1


class B:
    def __init__(self):
        self._lock = lockdep.named_lock("t.b")  # lockck: name(t.b)

    def inner(self):
        with self._lock:
            pass


def helper():
    b = B()
    b.inner()


def root_one():
    a = A()
    a.writes()


def root_two():
    a = A()
    a.writes()
    a.outer()
