"""Fixture: clean clock discipline — the injection-seam default
(reference, not call), calls through the injected clock, and a reasoned
waiver."""

import time
from typing import Callable


def paced(clock: Callable[[], float] = time.monotonic) -> float:
    # The default above is a REFERENCE — the seam itself — and passes.
    return clock()


def floor(dt: float) -> None:
    time.sleep(dt)  # clockck: allow(fixture: a documented simulator sleep)
