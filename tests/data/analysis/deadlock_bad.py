"""deadck fixture: every finding shape the rule must catch.

Driven by tests/test_deadck.py with an injected config (ranks
``t.a``=20 > ``t.b``=10, thread roots ``root_one``/``root_two``) — the
real manifest never sees this module.
"""

import threading

from distributed_sudoku_solver_tpu.obs import lockdep

raw = threading.Lock()  # unnamed creation: a deadck finding


class A:
    def __init__(self):
        self._lock = lockdep.named_lock("t.a")  # lockck: name(t.a)
        self.shared = 0

    def outer(self):
        with self._lock:
            helper()  # cross-function edge t.a -> t.b (rank-violating)

    def renest(self):
        with self._lock:
            with self._lock:  # direct self-acquisition of a plain lock
                pass

    def writes(self):
        self.shared += 1  # multi-root write, no guard, no lock held


class B:
    def __init__(self):
        # Annotation disagrees with the factory argument: a finding.
        self._lock = lockdep.named_lock("t.b")  # lockck: name(t.mismatch)

    def inner(self):
        with self._lock:
            pass


def helper():
    b = B()
    b.inner()


def root_one():
    a = A()
    a.writes()


def root_two():
    a = A()
    a.writes()
