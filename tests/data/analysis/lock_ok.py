"""Fixture: the three blessed write shapes for a guarded attribute —
lexical with-block, the ``_locked`` helper contract, and dict mutation
through a subscript under the lock."""

import threading


class Counted:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0  # lockck: guard(_lock)
        self.per_kind = {}  # lockck: guard(_lock)

    def bump(self):
        with self._lock:
            self.hits += 1
            self.per_kind["k"] = self.per_kind.get("k", 0) + 1

    def _bump_locked(self):
        self.hits += 1  # caller holds the lock: the suffix says so
