"""Fixture: hot-loop sync violations — an un-proven np.asarray and the
int()-over-device-value heuristic."""

import numpy as np


class Hot:
    def step(self, state):
        grabbed = np.asarray(state.solution)  # device value: flagged
        n = int(state.status[0])  # hot-loop scalar fetch: flagged
        return grabbed, n
