"""jaxck fixture programs: one per failure mode the rule must catch.

Loaded by tests/test_jaxck.py under a synthetic module name and driven
through ``jaxck.check_entry_points`` with an injected registry — never
imported by the fast lane (which only parses this file's AST, like every
other fixture here).
"""

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=(0,))
def good_thread(x, y):
    """Donation aliases: same shape/dtype in and out."""
    return x + y


@functools.partial(jax.jit, donate_argnums=(0,))
def dropped_donation(x, y):
    """Donated ``x`` has no same-shape/dtype output: the aliasing
    precondition fails and XLA silently drops the donation."""
    del x
    return y.astype(jnp.float32) * 2.0


@jax.jit
def hot_callback(x):
    """A debug.print in a hot program: a hidden host round-trip."""
    jax.debug.print("x sum {}", x.sum())
    return x * 2


@jax.jit
def drifting(x):
    """The drift seed: tests golden against a changed twin."""
    return x * 2


@jax.jit
def drifting_changed(x):
    """Same name in the injected registry, different HLO."""
    return x * 2 + 1


def unpinned_caller(x):
    return good_thread(x, 3)  # the weak-type cache fork jaxck flags
