"""Fixture: a guarded counter written by a helper method that neither
holds the lock lexically nor carries the ``_locked`` suffix contract."""

import threading


class Counted:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0  # lockck: guard(_lock)

    def bump(self):
        # A caller may well hold the lock here — but nothing says so, and
        # that undocumented assumption is exactly the bug family lockck
        # exists to kill.  Flagged.
        self.hits += 1
