"""Fixture: bare clock calls — the direct module call, a from-import
rename, and a module-level capture (all three laundering shapes)."""

import time as _t
from time import monotonic as mono

_grab = _t.monotonic  # module-level capture of a banned clock


def beat():
    _t.sleep(0.1)  # bare sleep through an alias


def stamp():
    return mono() + _grab()  # renamed + captured calls
