"""Fixture: clean hot loop — values proven host-side through the seam
(tuple unpack + self-attr + derived locals) and one reasoned waiver."""

import numpy as np


def host_fetch(x):
    return x  # the seam: its body is exempt by name


class Hot:
    def prime(self):
        self._status = host_fetch(self.pending)

    def step(self, state):
        info, extra = host_fetch((state.status, state.extra))
        n = int(info[0])  # host-proven via the tuple unpack
        solved = self._status["solved"]  # host-proven class-wide attr
        m = int(solved[0])  # host-proven via derivation
        pinned = np.asarray(extra[1], np.int32)  # host-proven operand
        cold = np.asarray([1, 2])  # syncck: allow(fixture: literal host data)
        return n, m, pinned, cold
