"""Fixture: clean layering — stdlib plus a declared sibling, including
the nested-lazy form."""

import json


def lazy():
    from distributed_sudoku_solver_tpu.allowed_layer import thing

    return thing, json
