"""Fixture: layering violations — a third-party import in a closed layer
and a FORBIDDEN internal import hidden inside a function body (the lazy
import idiom layerck must still see)."""

import json  # stdlib: always fine

import some_third_party_lib  # closed layers reject third-party roots


def lazy():
    # Nested-in-function import: must be flagged exactly like a top-level
    # one (tests pin the line number of this node).
    from distributed_sudoku_solver_tpu.forbidden_layer import thing

    return thing, json, some_third_party_lib
