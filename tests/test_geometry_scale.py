"""16x16 and 25x25 boards: the geometries the reference hard-coding (9/3,
``/root/reference/utils.py:20-21,48-53``) and 1024-byte wire cap
(``/root/reference/DHT_Node.py:94``, truncates 25x25 — SURVEY.md §2.5 #8/#9)
made impossible.  One generic compiled kernel serves them all here."""

import numpy as np

from distributed_sudoku_solver_tpu import native
from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_16, SUDOKU_25
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.ops.solve import solve_batch
from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution
from distributed_sudoku_solver_tpu.utils.puzzles import make_puzzle


def _check(sol, puzzle, geom):
    assert is_valid_solution(sol, geom)
    mask = puzzle != 0
    assert np.array_equal(sol[mask], puzzle[mask])
    if native.available():
        assert native.is_valid_solution(sol, geom)


def test_16x16_batch():
    puzzles = np.stack(
        [make_puzzle(SUDOKU_16, seed=s, n_clues=150, unique=False) for s in (0, 1)]
    )
    cfg = SolverConfig(min_lanes=8, stack_slots=64, max_steps=50_000)
    res = solve_batch(puzzles, SUDOKU_16, cfg)
    assert np.all(np.asarray(res.solved)), f"unsolved: {np.asarray(res.solved)}"
    for j in range(puzzles.shape[0]):
        _check(np.asarray(res.solution[j]), puzzles[j], SUDOKU_16)


def test_25x25_solve():
    puzzle = make_puzzle(SUDOKU_25, seed=3, n_clues=480, unique=False)
    cfg = SolverConfig(min_lanes=4, stack_slots=48, max_steps=50_000)
    res = solve_batch(puzzle[None], SUDOKU_25, cfg)
    assert bool(res.solved[0])
    _check(np.asarray(res.solution[0]), puzzle, SUDOKU_25)


def test_25x25_unsat_detected():
    puzzle = make_puzzle(SUDOKU_25, seed=4, n_clues=500, unique=False)
    r, c = np.argwhere(puzzle == 0)[0]
    row_digits = set(puzzle[r][puzzle[r] > 0])
    puzzle[r, c] = next(iter(row_digits))  # duplicate within the row
    cfg = SolverConfig(min_lanes=4, stack_slots=48)
    res = solve_batch(puzzle[None], SUDOKU_25, cfg)
    assert not bool(res.solved[0])
    assert bool(res.unsat[0])


def test_12x12_rectangular_boxes():
    """Non-square boxes (3x4): the geometry axis the reference could never
    parameterize; also exercises n_vboxes != n_hboxes paths."""
    from distributed_sudoku_solver_tpu.models.geometry import Geometry

    geom = Geometry(3, 4)
    assert geom.n == 12 and geom.n_vboxes == 4 and geom.n_hboxes == 3
    puzzle = make_puzzle(geom, seed=9, n_clues=90, unique=False)
    cfg = SolverConfig(min_lanes=8, stack_slots=48, max_steps=50_000)
    res = solve_batch(puzzle[None], geom, cfg)
    assert bool(res.solved[0])
    _check(np.asarray(res.solution[0]), puzzle, geom)
