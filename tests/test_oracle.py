import numpy as np
import pytest

from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_4, SUDOKU_9, SUDOKU_16
from distributed_sudoku_solver_tpu.utils.oracle import (
    count_solutions,
    is_consistent_partial,
    is_valid_solution,
    solve_oracle,
)
from distributed_sudoku_solver_tpu.utils.puzzles import (
    EASY_9,
    HARD_9,
    make_puzzle,
    parse_line,
    puzzle_batch,
    random_solution,
    to_line,
)


def test_oracle_solves_easy():
    sol = solve_oracle(EASY_9)
    assert is_valid_solution(sol)
    assert np.array_equal(sol[EASY_9 > 0], EASY_9[EASY_9 > 0])


def test_oracle_detects_unsat():
    bad = EASY_9.copy()
    bad[0, 0] = bad[0, 1] = 5
    assert solve_oracle(bad) is None
    assert not is_consistent_partial(bad)


def test_validator_rejects_bad_grids():
    sol = solve_oracle(EASY_9)
    assert is_valid_solution(sol)
    wrong = sol.copy()
    wrong[0, 0], wrong[0, 1] = wrong[0, 1], wrong[0, 0]
    assert not is_valid_solution(wrong)
    assert not is_valid_solution(np.zeros((9, 9), int))


def test_hard_boards_are_proper_puzzles():
    # hard[2] (17-clue) uniqueness takes ~1 min via count_solutions; the
    # batched solver covers it instead (test_solve).  Check the Inkala pair.
    for p in HARD_9[:2]:
        assert is_consistent_partial(p)
        assert count_solutions(p, limit=2) == 1


def test_generator_roundtrip_and_uniqueness():
    for geom, seed in ((SUDOKU_4, 0), (SUDOKU_9, 5)):
        sol = random_solution(geom, seed)
        assert is_valid_solution(sol, geom)
        p = make_puzzle(geom, seed, n_clues=geom.n_cells // 3)
        assert count_solutions(p, geom, limit=2) == 1
        got = solve_oracle(p, geom)
        assert np.array_equal(got, sol) or is_valid_solution(got, geom)


def test_generator_determinism():
    a = puzzle_batch(SUDOKU_9, 3, seed=11)
    b = puzzle_batch(SUDOKU_9, 3, seed=11)
    assert np.array_equal(a, b)


def test_parse_line_roundtrip_base36():
    sol16 = random_solution(SUDOKU_16, 1)
    line = to_line(sol16)
    assert len(line) == 256
    assert np.array_equal(parse_line(line, 16), sol16)
    with pytest.raises(ValueError):
        parse_line("123", 9)
