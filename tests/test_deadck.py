"""The thread-plane contract (ISSUE 13): deadck's static lock-order
graph, the obs/lockdep runtime witness, and the cross-check that binds
them.

Lanes:

* fixture lane — synthetic modules driven through ``deadck.check_modules``
  with injected ranks/roots, pinning that every finding shape actually
  FIRES (unnamed lock, annotation mismatch, rank-violating cross-function
  edge, cycle, unguarded multi-root write) and that the clean shapes pass;
* runtime lane — the witness raises on hierarchy-violating and
  cycle-forming acquisitions at the moment they happen, recognizes RLock
  re-entrancy, and its disabled path is one global read + branch (the
  explode microcheck);
* the contract — the slo burn-dump re-entrancy is a DECLARED edge
  exercised end to end without deadlock, and the session-wide observed
  acquisition graph is a subset of deadck's predicted graph;
* thread lifecycle — ``wire.fanout_requests`` releases its per-peer
  daemon thread once the virtual deadline expires (simnet lane, no
  sleeps).
"""

import threading
from pathlib import Path

import pytest

from distributed_sudoku_solver_tpu.analysis import deadck, manifest
from distributed_sudoku_solver_tpu.analysis.__main__ import run as analysis_run
from distributed_sudoku_solver_tpu.analysis.common import SourceModule
from distributed_sudoku_solver_tpu.obs import lockdep, slo, trace

FIXTURES = Path(__file__).resolve().parent / "data" / "analysis"

RANKS_BAD = {"t.a": 20, "t.b": 10}
RANKS_OK = {"t.a": 20, "t.b": 30}
ROOTS = {"deadlock_bad.py": ("root_one", "root_two"),
         "deadlock_ok.py": ("root_one", "root_two")}


def load(name: str) -> SourceModule:
    return SourceModule(FIXTURES / name, name, None)


def run_fixture(name, ranks, declared=None):
    return deadck.check_modules(
        [load(name)],
        ranks=ranks,
        declared=declared or {},
        base_classes={},
        thread_roots=ROOTS,
    )


# -- fixture lane --------------------------------------------------------------

def test_deadck_fires_on_every_finding_shape():
    findings, summary = run_fixture("deadlock_bad.py", RANKS_BAD)
    live = [f for f in findings if not f.waived]
    msgs = " | ".join(f.message for f in live)
    assert "unnamed lock" in msgs
    assert "disagrees with the factory argument" in msgs
    # The cross-function edge: outer holds t.a, helper() -> B.inner
    # acquires t.b; rank 20 >= 10 is a hierarchy violation.
    assert "lock-order edge 't.a'" in msgs and "'t.b'" in msgs
    # The unguarded multi-root write.
    assert "attribute 'shared' of A" in msgs and "2 thread roots" in msgs
    # Direct re-acquisition of a held non-reentrant lock.
    assert "self-acquisition of non-reentrant lock 't.a'" in msgs
    # The predicted graph carries the edge with its provenance.
    assert ["t.a", "t.b"] in summary["predicted"]


def test_deadck_clean_fixture_and_waiver():
    findings, summary = run_fixture("deadlock_ok.py", RANKS_OK)
    live = [f for f in findings if not f.waived]
    assert live == [], live
    waived = [f for f in findings if f.waived]
    assert len(waived) == 1 and "tolerated" in waived[0].message
    assert waived[0].reason
    assert ["t.a", "t.b"] in summary["predicted"]


def test_deadck_cycle_finding_via_declared_edges():
    # The static edge t.a -> t.b plus a declared reverse edge closes a
    # cycle: declared edges are part of the predicted graph, and a cycle
    # is a finding even when every edge in it is individually blessed.
    findings, _ = run_fixture(
        "deadlock_ok.py", RANKS_OK, declared={("t.b", "t.a"): "fixture"}
    )
    assert any("cycle in the predicted lock-order graph" in f.message
               for f in findings), findings


# -- runtime lane --------------------------------------------------------------

def test_lockdep_rank_violation_raises_and_is_recorded():
    w = lockdep.LockWitness(ranks={"lo": 1, "hi": 2}, declared={})
    lo, hi = lockdep.named_lock("lo"), lockdep.named_lock("hi")
    with lockdep.installed(w):
        with lo:
            with hi:
                pass  # rank-upward: fine
        with hi:
            with pytest.raises(lockdep.LockOrderError):
                lo.acquire()
    assert [v["edge"] for v in w.violations] == [["hi", "lo"]]
    # The legal edge was recorded; the witness graph is the artifact.
    assert ("lo", "hi") in set(w.graph())


def test_lockdep_cycle_raises_even_for_declared_edges():
    # a->b then b->a: both declared, but the second acquisition closes a
    # cycle in the OBSERVED graph — the witness raises at that moment
    # (declarations cannot bless an actual deadlock shape).
    w = lockdep.LockWitness(
        ranks={}, declared={("a", "b"): "r", ("b", "a"): "r"}
    )
    a, b = lockdep.named_lock("a"), lockdep.named_lock("b")
    with lockdep.installed(w):
        with a:
            with b:
                pass
        with b:
            with pytest.raises(lockdep.LockOrderError):
                a.acquire()
    assert any("cycle" in v["problem"] for v in w.violations)


def test_lockdep_self_deadlock_on_plain_lock_raises():
    # Re-acquiring a held non-RLock would block this thread forever; the
    # witness raises BEFORE the acquire blocks (review-round finding:
    # the re-entrancy fast path used to treat this as benign).
    w = lockdep.LockWitness(ranks={"x": 1}, declared={})
    x = lockdep.named_lock("x")
    with lockdep.installed(w):
        with x:
            with pytest.raises(lockdep.LockOrderError):
                x.acquire()
    assert any("self-deadlock" in v["problem"] for v in w.violations)


def test_lockdep_unknown_lock_is_a_violation():
    w = lockdep.LockWitness(ranks={"known": 1}, declared={})
    known, ghost = lockdep.named_lock("known"), lockdep.named_lock("ghost")
    with lockdep.installed(w):
        with known:
            with pytest.raises(lockdep.LockOrderError):
                ghost.acquire()
    assert "LOCK_RANKS" in w.violations[0]["problem"]


def test_lockdep_rlock_reentrancy_records_no_edge():
    # The slo shape: hold an outer RLock, take an inner lock, re-enter
    # the outer.  Re-entrant acquisition is ownership, not ordering — no
    # edge, no cycle, no violation.
    w = lockdep.LockWitness(ranks={"outer": 1, "inner": 2}, declared={})
    outer, inner = lockdep.named_rlock("outer"), lockdep.named_lock("inner")
    with lockdep.installed(w):
        with outer:
            with inner:
                with outer:  # re-entrant while holding inner
                    pass
    assert w.violations == []
    assert set(w.graph()) == {("outer", "inner")}


def test_lockdep_nonblocking_failed_acquire_does_not_corrupt_stack():
    w = lockdep.LockWitness(ranks={"x": 1, "y": 2}, declared={})
    x, y = lockdep.named_lock("x"), lockdep.named_lock("y")
    with lockdep.installed(w):
        with x:
            got = x._real.acquire(False) if False else None  # noqa: F841
            # A failed non-blocking acquire from another "thread"'s view:
            # simulate by acquiring y's real lock first so the proxy
            # attempt fails.
            y._real.acquire()
            try:
                assert y.acquire(blocking=False) is False
            finally:
                y._real.release()
        # Stack unwound cleanly: a later acquisition records only the
        # real edge.
        with y:
            pass
    assert w.violations == []
    assert set(w.graph()) == {("x", "y")}


def test_lockdep_condition_wait_keeps_stack_honest():
    w = lockdep.LockWitness(ranks={"cond": 1, "other": 2}, declared={})
    cond = lockdep.named_condition("cond")
    other = lockdep.named_lock("other")
    hits = []

    def waiter():
        with cond:
            hits.append("waiting")
            cond.wait(timeout=30)
            hits.append("woke")

    with lockdep.installed(w):
        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        ev = threading.Event()
        while not hits:
            ev.wait(0.01)
        with cond:
            cond.notify_all()
        t.join(30)
        assert hits == ["waiting", "woke"]
        # After the wait round-trip the waiter's stack is clean: an
        # unrelated acquisition on this thread records nothing stale.
        with other:
            pass
    assert w.violations == []
    assert ("cond", "other") not in set(w.graph())


def test_lockdep_disabled_path_is_one_read_one_branch(monkeypatch):
    """The explode microcheck (faults/trace/slo pattern): with no witness
    installed, acquiring a named lock must never touch the witness
    machinery — LockWitness.acquire is patched to explode, and a
    lock-heavy surface (histogram record under its named lock, trace
    record, engine counters) runs clean."""
    monkeypatch.setattr(lockdep, "_WITNESS", None)

    def boom(*a, **k):  # pragma: no cover - the test is that it never runs
        raise AssertionError("disabled lockdep path touched the witness")

    monkeypatch.setattr(lockdep.LockWitness, "acquire", boom)
    monkeypatch.setattr(lockdep.LockWitness, "released", boom)
    from distributed_sudoku_solver_tpu.obs.hist import LatencyHistogram

    h = LatencyHistogram()
    for i in range(16):
        h.record(0.001 * (i + 1))
    assert len(h) == 16
    rec = trace.TraceRecorder()
    rec.record(None, "x", "site", 0.0, 1.0)
    assert rec.metrics()["spans"] >= 1


# -- the declared slo re-entrancy contract (ISSUE 13 satellite) ----------------

def test_slo_edge_is_declared_with_reason():
    edge = ("obs.slo", "serving.engine")
    assert edge in manifest.LOCK_EDGE_DECLARED
    assert "metrics_fn" in manifest.LOCK_EDGE_DECLARED[edge]
    # Declared edges are part of deadck's predicted graph.
    report, _ = analysis_run(rules=("deadck",))
    assert ["obs.slo", "serving.engine"] in report["deadck"]["predicted"]


def test_slo_burn_dump_reenters_engine_metrics_without_deadlock(
    tmp_path, lockdep_witness
):
    """Satellite pin: a burn-dump fired inside SloMonitor._lock re-enters
    engine.metrics -> mon.metrics (the RLock) and must complete — under
    the ARMED witness, so the slo->engine acquisition is checked against
    the declared edge the moment it happens."""
    from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
    from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9

    rec = trace.TraceRecorder(dump_dir=str(tmp_path))
    mon = slo.SloMonitor(
        slo.parse_slo("job_p95_ms<=0.0001"), min_samples=1
    )
    eng = SolverEngine().start()
    mon.metrics_fn = eng.metrics
    trace.install(rec)
    slo.install(mon)
    try:
        job = eng.submit(EASY_9)
        assert job.done.wait(120)
        state = mon.metrics()  # the read-back re-enters the RLock too
    finally:
        slo.install(None)
        trace.install(None)
        eng.stop()
    assert state["burns"] >= 1 and state["dumps"] >= 1
    assert list(tmp_path.glob("flightrec-*-slo_burn.json")), "burn dump not written"
    observed = set(lockdep_witness.graph())
    assert ("obs.slo", "serving.engine") in observed
    assert lockdep_witness.violations == []


# -- the cross-check: observed subset of predicted -----------------------------

def test_observed_graph_is_subset_of_predicted(lockdep_witness):
    """The acceptance cross-check (jaxck's golden discipline applied to
    concurrency): every edge the session-wide witness has observed — this
    test runs after any number of engine/cluster/obs tests in the same
    process — must be in deadck's predicted graph (static edges UNION
    the declared table).  An observed edge deadck did not predict is a
    deadck bug: fix the resolver or declare the edge with a reason."""
    report, findings = analysis_run(rules=("deadck",))
    assert [f for f in findings if not f.waived] == []
    predicted = {tuple(e) for e in report["deadck"]["predicted"]}
    observed = set(lockdep_witness.graph())
    unpredicted = sorted(observed - predicted)
    assert not unpredicted, (
        "runtime-observed lock edges missing from deadck's predicted "
        f"graph: {unpredicted}"
    )
    assert lockdep_witness.violations == []


# -- fanout thread lifecycle (simnet lane) -------------------------------------

@pytest.mark.simnet
def test_fanout_requests_releases_blocked_thread_on_deadline(request):
    """A metrics pull to a peer whose reply is delayed past the per-peer
    deadline must not leak a blocked daemon thread: the fan-out worker
    parks on the VIRTUAL clock, the caller returns with the peer flagged
    unreachable, and advancing past the deadline releases the worker —
    thread count returns to baseline with no sleeps."""
    from distributed_sudoku_solver_tpu.cluster.node import (
        ClusterConfig,
        ClusterNode,
    )
    from distributed_sudoku_solver_tpu.cluster.simnet import SimNet, wait_until
    from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
    from distributed_sudoku_solver_tpu.serving.faults import FaultSchedule

    net = SimNet(delay_range=(10.0, 10.0))  # any delayed frame misses 0.5 s
    cfg = ClusterConfig(heartbeat_s=60.0, stats_timeout_s=0.5)
    e1 = SolverEngine().start()
    e2 = SolverEngine().start()
    n1 = ClusterNode(
        e1, host="127.0.0.1", port=0, config=cfg,
        transport=net.transport(), clock=net.clock,
    ).start()
    n2 = ClusterNode(
        e2, host="127.0.0.1", port=0, config=cfg, anchor=n1.addr,
        transport=net.transport(), clock=net.clock,
    ).start()
    try:
        assert wait_until(net, lambda: len(n1.network) == 2, timeout=120)
        net.settle()
        baseline = threading.active_count()
        # Delay the first METRICS_PULL n1 -> n2 past the 0.5 s deadline.
        site = f"link:{n1.addr_s}->{n2.addr_s}:METRICS_PULL"
        net.set_schedule(FaultSchedule.at({site: {0: "delay"}}))
        view = n1.cluster_metrics_view()
        assert view["nodes"][n2.addr_s]["unreachable"] is True
        # The fan-out worker is still parked on the virtual deadline —
        # the leak window this test pins.  Advancing virtual time past
        # the deadline (and the delayed delivery) releases it.
        net.set_schedule(None)
        net.advance(11.0)
        assert wait_until(
            net, lambda: threading.active_count() <= baseline, timeout=120
        ), f"leaked threads: {threading.active_count()} > {baseline}"
    finally:
        n2.stop()
        n1.stop()
        e2.stop()
        e1.stop()
        net.close()
