"""Native C++ oracle: build, bind, and agree bit-exactly with the Python oracle."""

import numpy as np
import pytest

from distributed_sudoku_solver_tpu import native
from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9, SUDOKU_16, Geometry
from distributed_sudoku_solver_tpu.utils import oracle
from distributed_sudoku_solver_tpu.utils.puzzles import (
    EASY_9,
    HARD_9,
    make_puzzle,
    random_solution,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain in environment"
)


def test_solves_easy_and_matches_python_oracle():
    sol, nodes = native.solve(EASY_9)
    assert sol is not None
    np.testing.assert_array_equal(sol, oracle.solve_oracle(EASY_9))
    assert nodes > 0


@pytest.mark.parametrize("i", range(len(HARD_9)))
def test_hard_boards_bit_exact(i):
    sol, _ = native.solve(HARD_9[i])
    np.testing.assert_array_equal(sol, oracle.solve_oracle(HARD_9[i]))


def test_node_counts_match_python_oracle():
    # Same search order => identical node counts, not just identical answers.
    _, py_nodes = oracle.solve_oracle(EASY_9, count_nodes=True)
    _, c_nodes = native.solve(EASY_9)
    assert c_nodes == py_nodes


def test_unsat_detection():
    bad = np.asarray(EASY_9).copy()
    bad[0, 0], bad[0, 1] = 5, 5
    sol, _ = native.solve(bad)
    assert sol is None
    assert native.count_solutions(bad) == 0


def test_count_solutions_limits():
    empty = np.zeros((4, 4), dtype=np.int32)
    geom = Geometry(2, 2)
    assert native.count_solutions(empty, geom, limit=5) == 5
    assert native.count_solutions(EASY_9, limit=2) == 1


def test_validator_geometry_generic():
    assert native.is_valid_solution(random_solution(SUDOKU_9, 3))
    assert native.is_valid_solution(random_solution(SUDOKU_16, 4), SUDOKU_16)
    bad = random_solution(SUDOKU_9, 3)
    bad[0, 0] = bad[0, 1]
    assert not native.is_valid_solution(bad)


def test_batch_solve():
    grids = np.stack([EASY_9, *HARD_9])
    sols, results, nodes = native.solve_batch(grids)
    assert (results == 1).all()
    assert (nodes > 0).all()
    for i in range(grids.shape[0]):
        assert native.is_valid_solution(sols[i])


def test_16x16_puzzle_roundtrip():
    puzzle = make_puzzle(SUDOKU_16, seed=1, n_clues=170, unique=False)
    sol, _ = native.solve(puzzle, SUDOKU_16)
    assert sol is not None
    assert native.is_valid_solution(sol, SUDOKU_16)
    mask = puzzle != 0
    assert np.array_equal(sol[mask], puzzle[mask])


def test_malformed_grid_raises():
    bad = np.full((9, 9), 11, dtype=np.int32)
    with pytest.raises(ValueError):
        native.solve(bad)
