import numpy as np
import jax.numpy as jnp

from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_4, SUDOKU_9
from distributed_sudoku_solver_tpu.ops.bitmask import decode_grid, encode_grid
from distributed_sudoku_solver_tpu.ops.propagate import (
    board_status,
    propagate,
    propagate_sweep,
)
from distributed_sudoku_solver_tpu.utils.oracle import solve_oracle
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, random_solution


def test_easy_solves_by_propagation_alone():
    cand, sweeps = propagate(encode_grid(EASY_9, SUDOKU_9), SUDOKU_9)
    st = board_status(cand, SUDOKU_9)
    assert bool(st.solved) and not bool(st.contradiction)
    assert int(sweeps) > 0
    assert np.array_equal(np.asarray(decode_grid(cand)), solve_oracle(EASY_9))


def test_propagation_soundness_never_kills_true_solution():
    # Property (SURVEY.md §4 #1): for puzzles carved from a known solution,
    # the solution digit survives every sweep in every cell.
    rng = np.random.default_rng(3)
    for seed in range(5):
        sol = random_solution(SUDOKU_9, seed)
        puzzle = sol * (rng.random(sol.shape) < 0.4)
        cand = encode_grid(puzzle, SUDOKU_9)
        sol_bits = jnp.uint32(1) << jnp.asarray(sol - 1, dtype=jnp.uint32)
        for _ in range(10):
            cand = propagate_sweep(cand, SUDOKU_9)
            assert bool(jnp.all(cand & sol_bits == sol_bits))


def test_board_status_detects_contradictions():
    geom = SUDOKU_4
    # duplicate given in a row
    bad = np.zeros((4, 4), dtype=np.int64)
    bad[0, 0] = bad[0, 3] = 2
    st = board_status(encode_grid(bad, geom), geom)
    assert bool(st.contradiction) and not bool(st.solved)

    # solved board is solved
    sol = random_solution(geom, 0)
    st = board_status(encode_grid(sol, geom), geom)
    assert bool(st.solved) and not bool(st.contradiction)

    # empty board is neither
    st = board_status(encode_grid(np.zeros((4, 4), int), geom), geom)
    assert not bool(st.solved) and not bool(st.contradiction)


def test_propagate_batched_leading_dims():
    batch = np.stack([EASY_9, np.zeros((9, 9), int)])
    cand, _ = propagate(encode_grid(batch, SUDOKU_9), SUDOKU_9)
    st = board_status(cand, SUDOKU_9)
    assert list(np.asarray(st.solved)) == [True, False]
    assert not np.asarray(st.contradiction).any()


def test_box_line_sweep_is_sound_and_fires():
    """Extended rules: strictly-tighter masks that always keep the true
    solution (checked against oracle solutions on generated puzzles)."""
    import numpy as np

    from distributed_sudoku_solver_tpu.ops.propagate import box_line_sweep, propagate
    from distributed_sudoku_solver_tpu.utils.oracle import solve_oracle
    from distributed_sudoku_solver_tpu.utils.puzzles import puzzle_batch

    grids = puzzle_batch(SUDOKU_9, 12, seed=77, n_clues=24).astype(np.int32)
    cand = encode_grid(jnp.asarray(grids), SUDOKU_9)
    basic, _ = propagate(cand, SUDOKU_9)
    ext, _ = propagate(cand, SUDOKU_9, rules="extended")
    b, e = np.asarray(basic), np.asarray(ext)
    assert ((e & ~b) == 0).all(), "extended produced a bit basic lacked"
    assert (e != b).any(), "box-line reductions never fired on a 24-clue batch"
    for i, g in enumerate(grids):
        sol = solve_oracle(g)
        for r in range(9):
            for c in range(9):
                assert (int(e[i, r, c]) >> (int(sol[r, c]) - 1)) & 1, (
                    f"board {i}: extended rules removed the true digit at {r},{c}"
                )


def test_extended_rules_solve_end_to_end():
    import numpy as np

    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.ops.solve import solve_batch
    from distributed_sudoku_solver_tpu.utils.oracle import solve_oracle
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9

    grids = np.stack(HARD_9).astype(np.int32)
    cfg = SolverConfig(min_lanes=32, stack_slots=32, rules="extended")
    res = solve_batch(grids, SUDOKU_9, cfg)
    assert np.asarray(res.solved).all()
    for g, s in zip(grids, np.asarray(res.solution)):
        np.testing.assert_array_equal(s, solve_oracle(g))  # unique solutions


def test_extended_rules_sound_on_rectangular_boxes():
    """Regression: the columns direction must use the transposed box layout
    (nh, bw, nv, bh); with rectangular boxes the row layout silently
    misaligns box boundaries and deletes true digits (caught on 12x12)."""
    import numpy as np

    from distributed_sudoku_solver_tpu.models.geometry import Geometry
    from distributed_sudoku_solver_tpu.ops.propagate import propagate
    from distributed_sudoku_solver_tpu.utils.puzzles import random_solution

    geom = Geometry(3, 4)
    rng = np.random.default_rng(0)
    for i in range(5):
        sol = random_solution(geom, i)
        keep = rng.random((12, 12)) < 0.6
        g = np.where(keep, sol, 0).astype(np.int32)
        ext, _ = propagate(
            encode_grid(jnp.asarray(g[None]), geom), geom, rules="extended"
        )
        m = np.asarray(ext)[0]
        for r in range(12):
            for c in range(12):
                assert (int(m[r, c]) >> (int(sol[r, c]) - 1)) & 1, (
                    f"board {i}: true digit eliminated at {r},{c}"
                )
