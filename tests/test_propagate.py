import numpy as np
import jax.numpy as jnp

from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_4, SUDOKU_9
from distributed_sudoku_solver_tpu.ops.bitmask import decode_grid, encode_grid
from distributed_sudoku_solver_tpu.ops.propagate import (
    board_status,
    propagate,
    propagate_sweep,
)
from distributed_sudoku_solver_tpu.utils.oracle import solve_oracle
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, random_solution


def test_easy_solves_by_propagation_alone():
    cand, sweeps = propagate(encode_grid(EASY_9, SUDOKU_9), SUDOKU_9)
    st = board_status(cand, SUDOKU_9)
    assert bool(st.solved) and not bool(st.contradiction)
    assert int(sweeps) > 0
    assert np.array_equal(np.asarray(decode_grid(cand)), solve_oracle(EASY_9))


def test_propagation_soundness_never_kills_true_solution():
    # Property (SURVEY.md §4 #1): for puzzles carved from a known solution,
    # the solution digit survives every sweep in every cell.
    rng = np.random.default_rng(3)
    for seed in range(5):
        sol = random_solution(SUDOKU_9, seed)
        puzzle = sol * (rng.random(sol.shape) < 0.4)
        cand = encode_grid(puzzle, SUDOKU_9)
        sol_bits = jnp.uint32(1) << jnp.asarray(sol - 1, dtype=jnp.uint32)
        for _ in range(10):
            cand = propagate_sweep(cand, SUDOKU_9)
            assert bool(jnp.all(cand & sol_bits == sol_bits))


def test_board_status_detects_contradictions():
    geom = SUDOKU_4
    # duplicate given in a row
    bad = np.zeros((4, 4), dtype=np.int64)
    bad[0, 0] = bad[0, 3] = 2
    st = board_status(encode_grid(bad, geom), geom)
    assert bool(st.contradiction) and not bool(st.solved)

    # solved board is solved
    sol = random_solution(geom, 0)
    st = board_status(encode_grid(sol, geom), geom)
    assert bool(st.solved) and not bool(st.contradiction)

    # empty board is neither
    st = board_status(encode_grid(np.zeros((4, 4), int), geom), geom)
    assert not bool(st.solved) and not bool(st.contradiction)


def test_propagate_batched_leading_dims():
    batch = np.stack([EASY_9, np.zeros((9, 9), int)])
    cand, _ = propagate(encode_grid(batch, SUDOKU_9), SUDOKU_9)
    st = board_status(cand, SUDOKU_9)
    assert list(np.asarray(st.solved)) == [True, False]
    assert not np.asarray(st.contradiction).any()
