"""Round-12 cluster-scope observability: mergeable log2 histograms
(obs/hist.py), the SLO/burn-rate plane (obs/slo.py), cluster-scope
aggregation (obs/agg.py + METRICS_PULL), the promck exposition lint, and
the bench regression gate (benchmarks/regress.py).

Four layers of assertions:

* **Histogram unit lane** — bucket edges, vector-add merge (scheme
  mismatch refused), quantile estimation, exemplars, the floor estimator.
* **SLO unit lane** — grammar parsing, burn-rate windowing on a fake
  clock, the exactly-one-dump-per-crossing edge semantics (simnet-marked:
  the conftest guard proves no sleeps back the determinism claim).
* **API lane** — ``GET /status`` / ``GET /slo`` /
  ``GET /metrics?scope=cluster`` live on a standalone node, the federated
  Prometheus form passing promck, and the microcheck that with no
  ``--slo`` and tracing off the hot path records nothing extra.
* **Simnet acceptance** — a 3-node ring's cluster-scope merge: rollup
  counts equal the vector sum of per-node counts, bit-identical across
  two independent runs on the virtual clock; a partitioned member is
  flagged ``unreachable`` without blocking the pull.
"""

import importlib.util
import json
import logging
import os

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.obs import agg, hist, promck, slo, trace
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

SMALL = SolverConfig(min_lanes=8, stack_slots=16)


@pytest.fixture(autouse=True)
def _clean_seams():
    """Every test leaves the process-wide obs seams empty."""
    yield
    trace.install(None)
    slo.install(None)


# -- histogram unit lane -------------------------------------------------------


def test_hist_bucket_edges_and_counts():
    h = hist.LatencyHistogram()
    # 1 µs edge scheme: 0.5 µs -> bucket 0; exactly 1 µs -> bucket 0;
    # 1.5 µs -> bucket 1; 250 ms -> the 262.144 ms bucket (1 µs * 2^18).
    h.record(0.5e-6)
    h.record(1e-6)
    h.record(1.5e-6)
    h.record(0.250)
    d = h.to_dict()
    assert len(d["counts"]) == hist.N_BUCKETS
    assert d["counts"][0] == 2
    assert d["counts"][1] == 1
    i250 = hist.bucket_index(250.0)
    assert i250 == 18
    assert hist.bucket_edge_ms(i250) == pytest.approx(262.144)
    assert d["counts"][i250] == 1
    assert len(h) == 4
    # Overflow: beyond the last finite edge lands in the +Inf bucket.
    h.record(1e9)
    assert h.to_dict()["counts"][-1] == 1
    # Edge values sit in their own bucket (le semantics): v == edge.
    assert hist.bucket_index(hist.EDGE0_MS * 8) == 3
    assert hist.bucket_index(hist.EDGE0_MS * 8.0001) == 4


def test_hist_merge_is_vector_add_and_refuses_mismatch():
    a, b = hist.LatencyHistogram(), hist.LatencyHistogram()
    for v in (0.001, 0.002, 0.5):
        a.record(v)
    for v in (0.002, 0.004):
        b.record(v)
    da, db = a.to_dict(), b.to_dict()
    merged = hist.merge_hist(hist.merge_hist(None, da), db)
    assert merged["counts"] == [
        x + y for x, y in zip(da["counts"], db["counts"])
    ]
    assert merged["sum_ms"] == pytest.approx(da["sum_ms"] + db["sum_ms"])
    assert hist.hist_count(merged) == 5
    # Merging must never change the inputs' identity semantics: a fresh
    # accumulator from `None` is a copy, not an alias.
    assert merged["counts"] != da["counts"]
    with pytest.raises(ValueError):
        hist.merge_hist({"type": "log2_hist", "edge0_ms": 1.0,
                         "counts": [0] * 8}, db)
    with pytest.raises(ValueError):
        hist.merge_hist(None, {"not": "a hist"})


def test_hist_quantiles_bracket_the_samples():
    h = hist.LatencyHistogram()
    for _ in range(95):
        h.record(0.010)  # 10 ms
    for _ in range(5):
        h.record(1.0)  # 1 s tail
    p50 = h.quantile(0.5)
    p99 = h.quantile(0.99)
    # Log-bucket estimates: p50 within the 10 ms bucket (8, 16], p99 in
    # the 1 s bucket (512, 1024].
    assert 8.0 <= p50 <= 16.0
    assert 512.0 <= p99 <= 1024.0
    assert hist.hist_quantile({"type": "log2_hist", "counts": [0] * 32}, 0.5) is None


def test_hist_exemplar_links_bucket_to_trace():
    h = hist.LatencyHistogram()
    h.record(0.5, exemplar="slow-job-uuid")
    h.record(0.5)  # no exemplar: must not clobber with None
    d = h.to_dict()
    i = hist.bucket_index(500.0)
    assert d["exemplars"] == {str(i): "slow-job-uuid"}
    # Merge keeps the donor's exemplar available on the rollup.
    merged = hist.merge_hist(None, d)
    assert merged["exemplars"][str(i)] == "slow-job-uuid"


def test_min_estimator_floor_and_recent_window():
    m = hist.MinEstimator(window=4)
    assert m.to_dict() is None
    for v in (0.080, 0.075, 0.090, 0.085):  # first window: min 75 ms
        m.record(v)
    d = m.to_dict()
    assert d["min"] == pytest.approx(75.0)
    assert d["recent"] == pytest.approx(75.0)
    for v in (0.050, 0.060, 0.055, 0.058):  # floor dropped: recent follows
        m.record(v)
    d = m.to_dict()
    assert d["min"] == pytest.approx(50.0)
    assert d["recent"] == pytest.approx(50.0)
    assert d["samples"] == 8
    # Cluster merge: min of mins, samples sum.
    other = {"type": "min_est", "min": 42.0, "recent": 44.0, "samples": 3}
    merged = hist.merge_min_est(hist.merge_min_est(None, d), other)
    assert merged["min"] == pytest.approx(42.0)
    assert merged["samples"] == 11


# -- slo unit lane -------------------------------------------------------------


def test_parse_slo_grammar():
    objs = slo.parse_slo("solve_p95_ms<=250, error_rate<=0.01")
    assert [o.kind for o in objs] == ["latency", "error_rate"]
    assert objs[0].threshold == 250.0
    assert objs[0].budget == pytest.approx(0.05)
    assert objs[0].stream == "solve" and objs[1].stream == "solve"
    assert objs[1].budget == pytest.approx(0.01)
    assert slo.parse_slo("solve_p50_ms<100")[0].budget == pytest.approx(0.5)
    assert slo.parse_slo("job_p95_ms<=250")[0].stream == "job"
    # Unknown streams fail the boot loudly — a typo'd objective must not
    # quietly monitor nothing.
    for bad in ("", "p95<=250", "solve_p95_ms>=250", "error_rate<=1.5",
                "solve_p100_ms<=250", "sovle_p95_ms<=250",
                "admission_p95_ms<=50", "nonsense"):
        with pytest.raises(ValueError):
            slo.parse_slo(bad)


@pytest.mark.simnet
def test_slo_burn_fires_dump_exactly_once_per_crossing(tmp_path, caplog):
    """The edge semantics: crossing the burn threshold dumps ONCE; staying
    over it dumps no more; recovering re-arms; a second crossing dumps
    again.  All on a fake clock — the simnet purity guard proves no
    sleeps back this determinism."""
    t = [0.0]
    rec = trace.TraceRecorder(clock=lambda: t[0], dump_dir=str(tmp_path))
    mon = slo.SloMonitor(
        slo.parse_slo("solve_p95_ms<=100"),
        window_s=60.0,
        burn_threshold=1.0,
        min_samples=5,
        clock=lambda: t[0],
        metrics_fn=lambda: {"jobs_done": 1},
    )
    with trace.installed(rec):
        for _ in range(20):  # a healthy window
            mon.observe(0.010)
        assert not mon.burning()
        with caplog.at_level(logging.WARNING):
            for _ in range(5):  # >5% of the window slow: burn >= 1.0
                mon.observe(0.500)
        assert mon.burning()
        assert mon.burns == 1 and mon.dumps == 1
        # Level, not edge: staying in breach must not dump again.
        for _ in range(5):
            mon.observe(0.500)
        assert mon.dumps == 1
        # The breach log names the objective's window (obs/logctx).
        assert any(
            "[slo solve_p95_ms<=100]" in r.getMessage()
            for r in caplog.records
        )
        # Recovery: the window ages out on the clock, state re-arms.
        t[0] += 120.0
        for _ in range(20):
            mon.observe(0.010)
        assert not mon.burning()
        # Second crossing: a second dump.
        for _ in range(6):
            mon.observe(0.500)
        assert mon.burns == 2 and mon.dumps == 2
    dumps = [f for f in os.listdir(tmp_path) if "slo_burn" in f]
    assert len(dumps) == 2, dumps
    doc = json.loads((tmp_path / sorted(dumps)[0]).read_text())
    assert doc["reason"] == "slo_burn"
    assert doc["metrics"]["objective"] == "solve_p95_ms<=100"
    assert doc["metrics"]["metrics"] == {"jobs_done": 1}


def test_slo_state_decays_without_traffic():
    t = [0.0]
    mon = slo.SloMonitor(
        slo.parse_slo("error_rate<=0.01"), window_s=10.0,
        burn_threshold=1.0, min_samples=2, clock=lambda: t[0],
    )
    for _ in range(5):
        mon.observe(0.001, error=True)
    assert mon.burning()
    t[0] += 30.0  # window ages out with NO further observations
    assert not mon.burning()
    st = mon.state()
    assert st["objectives"]["error_rate<=0.01"]["window_total"] == 0
    assert st["burns"] == 1  # history survives the decay


def test_slo_streams_are_independent():
    """A 504 storm burns the solve stream even though the underlying jobs
    merely got cancelled (no job.error), and job-stream observations
    never pollute a solve objective's window — the review finding that a
    100%-timeout outage must not read as healthy."""
    t = [0.0]
    mon = slo.SloMonitor(
        slo.parse_slo("error_rate<=0.1,job_p95_ms<=1000"),
        window_s=60.0, burn_threshold=1.0, min_samples=3,
        clock=lambda: t[0],
    )
    # The 504 path: http records solve-stream errors; the engine records
    # fast, error-free job resolutions (cancel resolves quickly).
    for _ in range(5):
        mon.observe(30.0, error=True, stream="solve")   # client saw 504
        mon.observe(0.010, error=False, stream="job")   # engine felt fine
    st = mon.state()
    assert st["objectives"]["error_rate<=0.1"]["burning"] is True
    assert st["objectives"]["error_rate<=0.1"]["window_total"] == 5
    assert st["objectives"]["job_p95_ms<=1000"]["burning"] is False
    assert st["objectives"]["job_p95_ms<=1000"]["window_total"] == 5


# -- engine/API lane -----------------------------------------------------------


def test_microcheck_no_slo_no_trace_records_no_obs_extras(monkeypatch):
    """Acceptance: with no --slo and tracing off, the per-chunk hot path
    adds no allocation beyond the always-on histogram increments — the
    SLO observe seam is never entered and no exemplar string ever reaches
    a histogram (mirrors PR 8's disabled-tracing microcheck)."""
    assert trace.active() is None and slo.active() is None

    def boom(*a, **k):  # pragma: no cover - failure path
        raise AssertionError("SLO observed while no monitor is installed")

    monkeypatch.setattr(slo.SloMonitor, "observe", boom)
    orig = hist.LatencyHistogram.record

    def checked(self, seconds, exemplar=None):
        assert exemplar is None, "exemplar built while tracing is disabled"
        return orig(self, seconds, exemplar)

    monkeypatch.setattr(hist.LatencyHistogram, "record", checked)
    eng = SolverEngine(config=SMALL, max_batch=8, chunk_steps=2).start()
    try:
        j = eng.submit(HARD_9[1])
        assert j.wait(120) and j.solved, j.error
    finally:
        eng.stop(timeout=2)


def test_engine_metrics_carry_hist_and_floor():
    eng = SolverEngine(config=SMALL, max_batch=8, chunk_steps=2).start()
    try:
        j = eng.submit(HARD_9[1])
        assert j.wait(120) and j.solved, j.error
        m = eng.metrics()
    finally:
        eng.stop(timeout=2)
    assert hist.is_hist(m["hist"]["latency_ms"])
    assert hist.hist_count(m["hist"]["latency_ms"]) >= 1
    # The flight loop ran chunks: sync walls recorded, floor estimated.
    assert hist.hist_count(m["hist"]["sync_wall_ms"]) >= 1
    assert hist.is_min_est(m["rpc_floor_ms"])
    assert m["rpc_floor_ms"]["min"] >= 0.0


def test_slo_flip_and_status_endpoints_live(tmp_path):
    """Acceptance: an induced latency burst crossing the configured SLO
    burn threshold flips GET /slo state and writes exactly one
    flight-recorder dump; GET /status and GET /metrics?scope=cluster
    serve the cluster-scope shapes on a standalone node."""
    import urllib.request

    from distributed_sudoku_solver_tpu.serving.http import (
        ApiServer,
        StandaloneNode,
    )

    def get(api, path):
        url = f"http://127.0.0.1:{api.port}{path}"
        try:
            with urllib.request.urlopen(url, timeout=60) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def post_solve(api):
        req = urllib.request.Request(
            f"http://127.0.0.1:{api.port}/solve",
            data=json.dumps({"sudoku": np.asarray(EASY_9).tolist()}).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert resp.status == 201

    rec = trace.TraceRecorder(dump_dir=str(tmp_path))
    # Any real solve blows a 1 ns p95 objective: the burst is induced by
    # construction, and every HTTP response is a "slow" observation on
    # the solve stream (fed by the /solve terminals, not the engine).
    mon = slo.SloMonitor(
        slo.parse_slo("solve_p95_ms<=0.000001"), min_samples=3,
    )
    eng = SolverEngine(config=SMALL, max_batch=8, chunk_steps=4).start()
    mon.metrics_fn = eng.metrics
    api = ApiServer(StandaloneNode(eng), host="127.0.0.1", port=0).start()
    try:
        with trace.installed(rec), slo.installed(mon):
            code, body = get(api, "/slo")
            assert code == 200 and body["burning"] is False
            for _ in range(4):
                post_solve(api)
            code, body = get(api, "/slo")
            assert code == 200
            assert body["burning"] is True
            obj = body["objectives"]["solve_p95_ms<=0.000001"]
            assert obj["burn_rate"] >= 1.0 and obj["breaches"] == 1
            dumps = [f for f in os.listdir(tmp_path) if "slo_burn" in f]
            assert len(dumps) == 1, "exactly one dump per crossing"

            code, st = get(api, "/status")
            assert code == 200
            assert st["healthy"] is False and st["degraded"] is False
            assert st["slo"]["burning"] is True
            assert "latency_ms" in st["quantiles"]

            code, cm = get(api, "/metrics?scope=cluster")
            assert code == 200 and cm["scope"] == "cluster"
            (only,) = cm["nodes"].values()
            assert only["unreachable"] is False
            ru = cm["rollup"]
            assert ru["nodes"] == 1 and ru["unreachable"] == 0
            assert ru["hist"]["latency_ms"]["counts"] == only["metrics"][
                "hist"
            ]["latency_ms"]["counts"]

            # Federated Prometheus form passes the lint.
            raw = (
                urllib.request.urlopen(
                    f"http://127.0.0.1:{api.port}"
                    "/metrics?scope=cluster&format=prometheus",
                    timeout=60,
                )
                .read()
                .decode()
            )
            assert promck.check_text(raw) == [], promck.check_text(raw)[:5]
            assert 'dsst_cluster_rollup_hist_latency_ms_bucket{le="+Inf"}' in raw
            assert "dsst_cluster_nodes_unreachable{" in raw
        # Seams uninstalled: /slo 404s again.
        code, _ = get(api, "/slo")
        assert code == 404
    finally:
        api.stop()
        eng.stop(timeout=2)


# -- aggregation unit lane -----------------------------------------------------


def _body(latencies_ms, jobs_done, floor_ms=None):
    h = hist.LatencyHistogram()
    for v in latencies_ms:
        h.record(v / 1e3)
    body = {"jobs_done": jobs_done, "solved": jobs_done,
            "hist": {"latency_ms": h.to_dict()}}
    if floor_ms is not None:
        body["rpc_floor_ms"] = {"type": "min_est", "min": floor_ms,
                                "recent": floor_ms, "samples": 10}
    return body


def test_agg_rollup_merges_hists_counters_and_floor():
    a = _body([10, 20, 30], jobs_done=3, floor_ms=50.0)
    b = _body([40], jobs_done=1, floor_ms=45.0)
    ru = agg.rollup([a, b, None, "garbage"])  # degraded entries skipped
    assert hist.hist_count(ru["hist"]["latency_ms"]) == 4
    assert ru["hist"]["latency_ms"]["counts"] == [
        x + y
        for x, y in zip(
            a["hist"]["latency_ms"]["counts"], b["hist"]["latency_ms"]["counts"]
        )
    ]
    assert ru["counters"] == {"jobs_done": 4, "solved": 4}
    assert ru["rpc_floor_ms"]["min"] == pytest.approx(45.0)
    q = ru["quantiles"]["latency_ms"]
    assert q["count"] == 4 and 0 < q["p50_ms"] <= q["p95_ms"]


def test_status_from_reflects_degradation_and_slo():
    cm = {
        "address": "a:1", "coordinator": "a:1", "view": [1, 2],
        "nodes": {
            "a:1": {"stale": False, "unreachable": False, "metrics": {}},
            "b:2": {"stale": True, "unreachable": False, "metrics": {}},
            "c:3": {"stale": False, "unreachable": True, "metrics": None},
        },
        "rollup": {"quantiles": {}, "counters": {}},
    }
    st = agg.status_from(cm)
    assert st["degraded"] is True and st["healthy"] is False
    assert st["members"]["b:2"]["stale"] is True
    assert st["members"]["c:3"]["unreachable"] is True
    assert st["unreachable"] == 1 and st["slo"] is None


def test_status_from_sees_member_slo_burning():
    """Review finding: a MEMBER burning its budget is a cluster problem —
    the pulled bodies carry each node's slo section, and /status must
    not report healthy off the serving node's local monitor alone."""
    cm = {
        "address": "a:1", "coordinator": "a:1", "view": [1, 2],
        "nodes": {
            "a:1": {"stale": False, "unreachable": False,
                    "metrics": {"slo": {"burning": False}}},
            "b:2": {"stale": False, "unreachable": False,
                    "metrics": {"slo": {"burning": True}}},
        },
        "rollup": {"quantiles": {}, "counters": {}},
    }
    st = agg.status_from(cm)
    assert st["slo_burning_members"] == ["b:2"]
    assert st["healthy"] is False and st["degraded"] is False


# -- promck unit lane ----------------------------------------------------------

GOOD = """\
dsst_jobs 4
dsst_lat_bucket{le="1"} 1
dsst_lat_bucket{le="2"} 3
dsst_lat_bucket{le="+Inf"} 4
dsst_lat_sum 7.5
dsst_lat_count 4
dsst_state{geometry="9x9",state="open"} 1
"""


def test_promck_accepts_wellformed_exposition():
    assert promck.check_text(GOOD) == []
    assert promck.check_text("") == []


def test_promck_rejects_duplicates_and_bad_labels():
    errs = promck.check_text("dsst_x 1\ndsst_x 1\n")
    assert any("duplicate series" in e for e in errs)
    # Same name, different labels: NOT a duplicate.
    assert promck.check_text('dsst_x{a="1"} 1\ndsst_x{a="2"} 1\n') == []
    # Label order must not defeat the duplicate check.
    errs = promck.check_text('dsst_x{a="1",b="2"} 1\ndsst_x{b="2",a="1"} 1\n')
    assert any("duplicate series" in e for e in errs)
    errs = promck.check_text('dsst_x{v="a"b"} 1\n')
    assert any("unescaped" in e or "malformed" in e for e in errs)
    errs = promck.check_text('dsst_x{v="a",v="b"} 1\n')
    assert any("duplicate label name" in e for e in errs)
    errs = promck.check_text("dsst_x one\n")
    assert any("value" in e for e in errs)
    assert promck.check_text('dsst_x{v="esc\\"ok\\n"} 1\n') == []


def test_promck_rejects_broken_histograms():
    non_mono = (
        'dsst_h_bucket{le="1"} 5\n'
        'dsst_h_bucket{le="2"} 3\n'
        'dsst_h_bucket{le="+Inf"} 6\n'
    )
    errs = promck.check_text(non_mono)
    assert any("non-monotone" in e for e in errs)
    no_inf = 'dsst_h_bucket{le="1"} 1\n'
    errs = promck.check_text(no_inf)
    assert any("+Inf" in e for e in errs)
    # A second histogram family with different labels is independent.
    two_geoms = (
        'dsst_h_bucket{geometry="9x9",le="1"} 5\n'
        'dsst_h_bucket{geometry="9x9",le="+Inf"} 6\n'
        'dsst_h_bucket{geometry="16x16",le="1"} 1\n'
        'dsst_h_bucket{geometry="16x16",le="+Inf"} 2\n'
    )
    assert promck.check_text(two_geoms) == []


def test_promck_cli_roundtrip(tmp_path):
    good = tmp_path / "good.txt"
    good.write_text(GOOD)
    assert promck.main([str(good)]) == 0
    bad = tmp_path / "bad.txt"
    bad.write_text("dsst_x 1\ndsst_x 2\n")
    assert promck.main([str(bad)]) == 1
    assert promck.main([]) == 2
    assert promck.check_file(str(tmp_path / "missing.txt")) != []
    # The *ck-family exit-code contract (obs/exitcodes.py): an unreadable
    # input is the tool failing (2), not the exposition failing (1).
    assert promck.main([str(tmp_path / "missing.txt")]) == 2


# -- bench regression gate -----------------------------------------------------


def _load_regress():
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks", "regress.py",
    )
    spec = importlib.util.spec_from_file_location("dsst_bench_regress", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _artifact(p50, p95, params=None):
    side = {"p50_ms": p50, "p95_ms": p95, "p99_ms": p95 * 1.2,
            "mean_ms": p50, "jobs": 48}
    return {
        "schema": "dsst-bench-poisson/1",
        "params": params or {"jobs": 48, "mean_gap_ms": 50.0,
                             "handicap_ms": 50.0, "chunk_steps": 8, "seed": 7},
        "static": dict(side),
        "resident": dict(side),
        "speedups": {"p50": 1.0, "p95": 1.0, "p99": 1.0},
        "rpc_floor_ms": {"type": "min_est", "min": 50.0, "recent": 50.0,
                         "samples": 100},
        "hist": {},
    }


def test_regress_gate_exit_codes(tmp_path):
    regress = _load_regress()

    def write(name, doc):
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    base = write("base.json", _artifact(100.0, 400.0))
    same = write("same.json", _artifact(110.0, 420.0))  # inside 25% noise
    worse = write("worse.json", _artifact(100.0, 600.0))  # p95 +50%
    better = write("better.json", _artifact(50.0, 200.0))
    other = write(
        "other.json",
        _artifact(100.0, 400.0, params={"jobs": 16, "mean_gap_ms": 50.0,
                                        "handicap_ms": 50.0,
                                        "chunk_steps": 8, "seed": 7}),
    )
    assert regress.main([base, same]) == 0
    assert regress.main([base, worse]) == 1
    assert regress.main([base, better]) == 0
    assert regress.main([base, other]) == 2  # different workloads
    assert regress.main([base, str(tmp_path / "missing.json")]) == 2
    rep = regress.compare(json.loads(open(base).read()),
                          json.loads(open(worse).read()))
    assert any("p95" in r for r in rep["regressions"])


def test_regress_gates_ring_tier(tmp_path):
    """ISSUE 17 satellite: when both artifacts carry the --ring (DHT)
    section with the same node count, the cluster-cache hit rate is
    gated like a latency quantile — and a run whose cluster rate no
    longer strictly exceeds the no-DHT control pass's best per-node
    rate fails outright (the DHT stopped sharing fills).  Mismatched
    node counts or a one-sided section only earn notes."""
    regress = _load_regress()

    def ring(cluster, best, nodes=3):
        doc = _artifact(100.0, 400.0)
        doc["ring"] = {
            "nodes": nodes, "jobs": 64, "mix": "easy:12,hard:4,repeat:48",
            "cluster_hit_rate": cluster, "best_node_hit_rate": best,
            "solo_node_hit_rates": [best] * nodes,
            "l2": {"remote_hits": 8, "puts_applied": 11},
            "per_node": {},
        }
        return doc

    def write(name, doc):
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    base = write("base.json", ring(0.72, 0.62))
    assert regress.main([base, write("same.json", ring(0.72, 0.62))]) == 0
    # Hit rate collapsed beyond tolerance -> regression exit.
    assert regress.main([base, write("drop.json", ring(0.40, 0.30))]) == 1
    # Still within tolerance but no longer beats the best solo member
    # -> the DHT-specific invariant fails even when the delta is small.
    assert regress.main([base, write("tied.json", ring(0.62, 0.62))]) == 1
    rep = regress.compare(ring(0.72, 0.62), ring(0.62, 0.62))
    assert any("no longer exceeds" in r for r in rep["regressions"])
    # Different deployment shape: noted, never gated.
    rep = regress.compare(ring(0.72, 0.62), ring(0.30, 0.10, nodes=5))
    assert not rep["regressions"]
    assert any("node counts differ" in n for n in rep["notes"])
    # One-sided ring section: noted, never gated.
    rep = regress.compare(_artifact(100.0, 400.0), ring(0.72, 0.62))
    assert not rep["regressions"]
    assert any("only the new artifact carries the ring" in n
               for n in rep["notes"])
    assert regress.main([base, write("noring.json",
                                     _artifact(100.0, 400.0))]) == 0


def test_regress_labels_cold_cache_runs(tmp_path, capsys):
    """Round-15 satellite: an artifact whose `compile` section says the
    run paid XLA compiles inside its measured window is LABELED in the
    report (and a cold-vs-warm compare earns a re-run note) instead of
    hiding compile noise inside the tolerance band.  Artifacts without
    the section stay label-free and comparable."""
    regress = _load_regress()

    def write(name, doc):
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    warm = _artifact(100.0, 400.0)
    warm["compile"] = {"programs": {}, "compiles_total": 0,
                       "wall_ms_total": 0.0, "cache": {}, "cold": False}
    cold = _artifact(100.0, 400.0)
    cold["compile"] = {
        "programs": {"advance_status": {"count": 1, "wall_ms_total": 1800.0}},
        "compiles_total": 6, "wall_ms_total": 5400.0, "cache": {},
        "cold": True,
    }
    base = write("base.json", warm)
    cold_p = write("cold.json", cold)
    assert regress.main([base, cold_p]) == 0  # labeled, still gated
    out = capsys.readouterr().out
    assert "COLD-CACHE" in out and "compile noise" in out
    assert "re-run the candidate warm" in out
    # Cold old vs warm new: the improvement-direction caveat.
    assert regress.main([cold_p, base]) == 0
    assert "re-run the baseline warm" in capsys.readouterr().out
    # compare() exposes the same labels programmatically.
    rep = regress.compare(warm, cold)
    assert any("COLD-CACHE" in n for n in rep["notes"])
    # Pre-round-15 artifacts (no compile section) stay label-free.
    rep = regress.compare(_artifact(100.0, 400.0), _artifact(100.0, 400.0))
    assert rep["notes"] == []


def test_bench_artifact_schema_matches_regress_expectations():
    """The artifacts bench_poisson writes (--out-json AND the round-18
    --workload-out trace) and the consumers' schema constants must not
    drift apart (they live in different files)."""
    import re

    src = open(
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "benchmarks", "bench_poisson.py",
        )
    ).read()
    schemas = set(re.findall(r'"schema": "([^"]+)"', src))
    assert _load_regress().SCHEMA in schemas
    from benchmarks.replay import WORKLOAD_SCHEMA

    assert WORKLOAD_SCHEMA in schemas
    # And the replay artifact's schema is the one regress.py compares.
    from benchmarks.replay import SCHEMA as REPLAY_SCHEMA

    assert REPLAY_SCHEMA == _load_regress().REPLAY_SCHEMA


# -- simnet acceptance ---------------------------------------------------------


def _seed_samples(engines):
    """Deterministic histogram samples, distinct per node: node i records
    (i+1) samples at 2^i ms into sync_wall_ms — input the solver's wall
    clock never touches, so two runs must produce bit-identical merges."""
    for i, eng in enumerate(engines):
        for _ in range(i + 1):
            eng.hist["sync_wall_ms"].record((2.0 ** i) / 1e3)
        eng.rpc_floor.record((2.0 ** i) / 1e3)


def _ring3(net, cfg):
    from distributed_sudoku_solver_tpu.cluster.node import ClusterNode
    from distributed_sudoku_solver_tpu.cluster.simnet import wait_until

    from tests.test_cluster import oracle_solve_fn

    engines = [
        SolverEngine(solve_fn=oracle_solve_fn(), batch_window_s=0.001).start()
        for _ in range(3)
    ]
    a = ClusterNode(engines[0], config=cfg, transport=net.transport(),
                    clock=net.clock).start()
    b = ClusterNode(engines[1], anchor=a.addr, config=cfg,
                    transport=net.transport(), clock=net.clock).start()
    c = ClusterNode(engines[2], anchor=a.addr, config=cfg,
                    transport=net.transport(), clock=net.clock).start()
    nodes = [a, b, c]
    assert wait_until(
        net, lambda: all(len(n.network) == 3 for n in nodes), timeout=60
    ), "ring never formed"
    return engines, nodes


def _cluster_cfg():
    from distributed_sudoku_solver_tpu.cluster.node import ClusterConfig

    return ClusterConfig(
        heartbeat_s=0.25, fail_factor=50.0, io_timeout_s=2.0,
        needwork=False, progress_interval_s=0.0, stats_timeout_s=2.0,
    )


@pytest.mark.simnet
def test_cluster_scope_merge_sums_and_is_deterministic():
    """Acceptance: on a 3-node simnet ring, GET /metrics?scope=cluster's
    rollup histogram counts equal the vector sum of the per-node counts —
    and a seeded phase merges bit-identically across two fully
    independent runs on the virtual clock."""
    from distributed_sudoku_solver_tpu.cluster.simnet import SimNet, wait_until

    net_views = []
    for _ in range(2):
        net = SimNet()
        engines, nodes = _ring3(net, _cluster_cfg())
        a = nodes[0]
        try:
            # Real traffic through the ring (remote dispatch populates the
            # wire histograms), then the deterministic seeded phase.
            jobs = [
                a._submit_remote(np.asarray(EASY_9, np.int32), n.addr_s)
                for n in nodes[1:]
            ]
            assert wait_until(
                net, lambda: all(j.done.is_set() for j in jobs), timeout=120
            ), "remote jobs never resolved"
            assert all(j.solved for j in jobs)
            _seed_samples(engines)
            cm = a.cluster_metrics_view()

            # Every member reachable, none stale, and the rollup is the
            # vector sum of the per-node histogram counts — per phase.
            assert len(cm["nodes"]) == 3
            assert all(
                not n["unreachable"] and not n["stale"]
                for n in cm["nodes"].values()
            )
            for phase, merged in cm["rollup"]["hist"].items():
                per_node = [
                    n["metrics"]["hist"][phase]["counts"]
                    for n in cm["nodes"].values()
                    if phase in n["metrics"].get("hist", {})
                ]
                vec_sum = [sum(col) for col in zip(*per_node)]
                assert merged["counts"] == vec_sum, phase
            # The seeded phase: 1+2+3 samples across known buckets.
            seeded = cm["rollup"]["hist"]["sync_wall_ms"]
            assert hist.hist_count(seeded) == 6
            # Cluster floor = min of member floors = 1 ms (node 0's seed).
            assert cm["rollup"]["rpc_floor_ms"]["min"] == pytest.approx(1.0)
            # Aggregation counters exported under cluster.agg.
            mv = a.metrics_view()
            assert mv["cluster"]["agg"]["pulls"] == 2
            assert mv["cluster"]["agg"]["merges"] == 1
            assert mv["cluster"]["agg"]["unreachable_peers"] == 0
            net_views.append(seeded["counts"])
        finally:
            for n in nodes:
                n.kill()
            for e in engines:
                e.stop(timeout=1)
            net.close()
    assert net_views[0] == net_views[1], (
        "cluster-scope merge not deterministic across two virtual-clock runs"
    )


@pytest.mark.simnet
def test_partitioned_member_flagged_unreachable_without_blocking(caplog):
    """Acceptance: the pull completes while a member is partitioned — the
    member is flagged unreachable (and the degradation logged with the
    peer identified), the reachable majority still merges."""
    from distributed_sudoku_solver_tpu.cluster.simnet import SimNet

    net = SimNet()
    engines, nodes = _ring3(net, _cluster_cfg())
    a, b, c = nodes
    try:
        _seed_samples(engines)
        net.partition([c.addr_s], [a.addr_s, b.addr_s])
        with caplog.at_level(logging.WARNING):
            cm = a.cluster_metrics_view()
        assert cm["nodes"][c.addr_s]["unreachable"] is True
        assert cm["nodes"][c.addr_s]["metrics"] is None
        assert cm["nodes"][b.addr_s]["unreachable"] is False
        assert cm["rollup"]["unreachable"] == 1
        # Rollup covers exactly the reachable members (nodes 0 and 1:
        # 1 + 2 seeded sync samples).
        assert hist.hist_count(cm["rollup"]["hist"]["sync_wall_ms"]) == 3
        assert a.agg_unreachable == 1
        assert any(
            f"[peer {c.addr_s}]" in r.getMessage() for r in caplog.records
        ), "degraded aggregation must log the peer"
        # /status derives the degradation honestly.
        st = agg.status_from(cm)
        assert st["degraded"] is True and st["healthy"] is False
        # A stale member: bump our epoch so b's reply view disagrees.
        with a._lock:
            a.net_epoch += 1
        cm2 = a.cluster_metrics_view()
        assert cm2["nodes"][b.addr_s]["stale"] is True
        assert cm2["nodes"][b.addr_s]["metrics"] is not None  # still merged
    finally:
        for n in nodes:
            n.kill()
        for e in engines:
            e.stop(timeout=1)
        net.close()


@pytest.mark.simnet
def test_cluster_scope_merge_federates_compile_and_critpath(tmp_path):
    """Round-15 satellite: the cluster rollup federates the new planes —
    per-program compile counts/walls sum across members (wall histograms
    vector-add), critpath attribution totals sum with shares re-derived
    from the merged totals, and the per-phase ``critpath_*_ms``
    histograms merge through the existing ``hist`` rule.  In the
    single-process simnet lane all three nodes share the process-wide
    watch/monitor, so every per-node body reports the same numbers and
    the rollup must read exactly 3x each — the vector-sum semantics the
    federation promises."""
    from distributed_sudoku_solver_tpu.cluster.simnet import SimNet
    from distributed_sudoku_solver_tpu.obs import compilewatch, critpath

    class _FakeProg:
        n = 0

        def _cache_size(self):
            return self.n

    net = SimNet()
    fake = _FakeProg()
    watch = compilewatch.CompileWatch(
        programs={"prog_a": fake}, warmup_s=1e9
    )
    rec = trace.TraceRecorder(ring=4096, clock=net.clock.now)
    mon = critpath.CritPathMonitor()
    engines, nodes = _ring3(net, _cluster_cfg())
    a = nodes[0]
    try:
        with trace.installed(rec), compilewatch.installed(watch), \
                critpath.installed(mon):
            # Two compiles of prog_a (real event-before-insert ordering).
            ev = compilewatch.BACKEND_COMPILE_EVENT
            watch.on_duration(ev, 0.5)
            fake.n += 1
            watch.on_duration(ev, 0.25)
            fake.n += 1
            watch.poll()
            # One decomposed job feeding the critpath plane.
            rec.record("u1", "admission", "engine.launch", 0.0, t1=0.1)
            rec.record(None, "chunk.sync", "fetch.status", 0.1, t1=0.4,
                       uuids=["u1"])
            rec.record("u1", "resolve", "engine.resolve", 0.4, t1=0.4)
            mon.observe_job("u1", 0.4)

            cm = a.cluster_metrics_view()
            per_node = [n["metrics"] for n in cm["nodes"].values()]
            assert len(per_node) == 3
            # Every member exported the shared sections identically...
            for body in per_node:
                assert body["compile"]["programs"]["prog_a"]["count"] == 2
                assert body["critpath"]["jobs"] == 1
            # ...and the rollup is their sum, program by program and
            # phase by phase.
            ru = cm["rollup"]
            prog = ru["compile"]["programs"]["prog_a"]
            assert prog["count"] == 6
            assert prog["wall_ms_total"] == pytest.approx(3 * 750.0)
            assert sum(prog["wall_ms"]["counts"]) == 6
            assert ru["compile"]["compiles_total"] == 6
            cp = ru["critpath"]
            assert cp["jobs"] == 3
            assert cp["attribution_ms"]["sync"] == pytest.approx(900.0)
            assert cp["attribution_ms"]["queue"] == pytest.approx(300.0)
            # Shares re-derived from the MERGED totals, not averaged.
            assert cp["shares_pct"]["sync"] == pytest.approx(75.0)
            # The per-phase hists rode the hist rule: 3x vector add.
            assert hist.hist_count(ru["hist"]["critpath_sync_ms"]) == 3
    finally:
        for n in nodes:
            n.kill()
        for e in engines:
            e.stop(timeout=1)
        net.close()
