"""The 'subsets' inference tier (VERDICT r2 #2): naked-subset eliminations.

The rule, keyed on cell masks: if inside a unit exactly ``popcount(m)``
nonzero cells are subsets of a cell's mask ``m``, those digits are confined
to those cells, so ``m``'s bits die everywhere else in the unit.  One rule
covers naked pairs, triples, quads... (any k); k=1 degenerates to basic
elimination.  The reference has no inference at all (its only rule is the
per-guess ``is_valid`` scan, ``/root/reference/utils.py:27-55``) — this
tier exists for deep search on giant boards, where BENCHMARKS.md's sparse
25x25 row showed near-blind branching.

Soundness oracle: a rule application may never delete the true digit of a
solvable board's solution.  Tier laddering: masks under 'subsets' are
always a subset of masks under 'extended' (strictly stronger inference).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from distributed_sudoku_solver_tpu.models.geometry import geometry_for_size
from distributed_sudoku_solver_tpu.ops.bitmask import encode_grid, decode_grid
from distributed_sudoku_solver_tpu.ops.propagate import (
    board_status,
    naked_subsets_sweep,
    propagate,
)
from distributed_sudoku_solver_tpu.utils.oracle import solve_oracle
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

SUDOKU_9 = geometry_for_size(9)


def _mask(*digits):
    m = 0
    for d in digits:
        m |= 1 << (d - 1)
    return m


def test_naked_pair_eliminates_in_row():
    """Textbook naked pair: cells 0,1 both {1,2} -> 1,2 die in the rest of
    the row and nowhere else."""
    full = SUDOKU_9.full_mask
    cand = np.full((1, 9, 9), full, np.uint32)
    cand[0, 0, 0] = _mask(1, 2)
    cand[0, 0, 1] = _mask(1, 2)
    out = np.asarray(naked_subsets_sweep(jnp.asarray(cand), SUDOKU_9))
    assert out[0, 0, 0] == _mask(1, 2)
    assert out[0, 0, 1] == _mask(1, 2)
    for c in range(2, 9):
        assert out[0, 0, c] == (full & ~_mask(1, 2)), f"col {c}"
    # The two pair cells also share box 0, so the box unit clears {1,2} from
    # the box's other cells; everything outside row 0 and box 0 is untouched
    # (in the columns the pair counts 1 subset cell < k=2 — nothing fires).
    for r in range(1, 3):
        for c in range(3):
            assert out[0, r, c] == (full & ~_mask(1, 2)), f"box cell {r},{c}"
    assert (out[0, 1:3, 3:] == full).all()
    assert (out[0, 3:, :] == full).all()


def test_naked_triple_eliminates_in_box():
    """Three cells of one box jointly holding {4,5,6} — with a witness cell
    carrying the full union — kill those digits in the box's other cells.

    (The rule is keyed on a witness cell's mask: a witness-free triple like
    {4,5},{5,6},{4,6} is deliberately out of scope — see
    ``naked_subsets_sweep``'s docstring.)"""
    full = SUDOKU_9.full_mask
    cand = np.full((1, 9, 9), full, np.uint32)
    cand[0, 0, 0] = _mask(4, 5, 6)  # the witness
    cand[0, 1, 1] = _mask(5, 6)
    cand[0, 2, 2] = _mask(4, 6)
    out = np.asarray(naked_subsets_sweep(jnp.asarray(cand), SUDOKU_9))
    tri = _mask(4, 5, 6)
    for r in range(3):
        for c in range(3):
            if (r, c) in ((0, 0), (1, 1), (2, 2)):
                continue
            assert out[0, r, c] & tri == 0, f"cell {r},{c} kept a triple digit"
    # Triple cells themselves are untouched.
    assert out[0, 0, 0] == _mask(4, 5, 6)
    assert out[0, 1, 1] == _mask(5, 6)
    assert out[0, 2, 2] == _mask(4, 6)


def test_overfull_subset_is_a_contradiction():
    """Three cells all {1,2} in a row: pigeonhole-unsat; the sweep exposes
    it (empty cell) instead of leaving it latent."""
    full = SUDOKU_9.full_mask
    cand = np.full((1, 9, 9), full, np.uint32)
    for c in range(3):
        cand[0, 0, c] = _mask(1, 2)
    out = naked_subsets_sweep(jnp.asarray(cand), SUDOKU_9)
    st = board_status(out, SUDOKU_9)
    assert bool(st.contradiction[0])


@pytest.mark.parametrize("size", [9, 12, 16])
def test_subsets_sound_and_stronger(size, heavy_compile_guard):
    """On solvable boards: 'subsets' masks are a subset of 'extended' masks
    (strictly stronger inference) and never delete the true digit.  12x12
    exercises rectangular (3x4) boxes.

    The giant-geometry subsets-sweep compile is the largest single XLA:CPU
    compilation in the suite — ``heavy_compile_guard`` (conftest.py, where
    the segfault hazard is documented) drops accumulated executables first
    when the process is crowded."""
    from distributed_sudoku_solver_tpu.models.geometry import Geometry

    geom = Geometry(3, 4) if size == 12 else geometry_for_size(size)
    if size == 9:
        boards = [np.asarray(EASY_9)] + [np.asarray(b) for b in HARD_9[:3]]
    else:
        from distributed_sudoku_solver_tpu.utils.puzzles import make_puzzle

        boards = [
            make_puzzle(geom, seed=7 + i, n_clues=int(geom.n * geom.n * 0.55))
            for i in range(3)
        ]
    for g in boards:
        sol = solve_oracle(g, geom)
        assert sol is not None
        cand = encode_grid(jnp.asarray(g[None]), geom)
        ext, _ = propagate(cand, geom, rules="extended")
        sub, _ = propagate(cand, geom, rules="subsets")
        e, s = np.asarray(ext[0]), np.asarray(sub[0])
        assert ((s & ~e) == 0).all(), "subsets produced a bit extended lacked"
        for r in range(geom.n):
            for c in range(geom.n):
                assert s[r, c] & (1 << (sol[r, c] - 1)), (
                    f"subsets removed the true digit at {r},{c}"
                )


def test_subsets_end_to_end_solve():
    """Full frontier search under the subsets tier still reproduces the
    oracle's unique solutions."""
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.ops.solve import solve_batch

    cfg = SolverConfig(min_lanes=16, stack_slots=32, rules="subsets")
    boards = np.stack([np.asarray(b) for b in HARD_9[:4]])
    res = solve_batch(jnp.asarray(boards), SUDOKU_9, cfg)
    assert bool(res.solved.all())
    for i in range(len(boards)):
        assert (np.asarray(res.solution[i]) == solve_oracle(boards[i], SUDOKU_9)).all()


@pytest.mark.parametrize("backend", ["pallas", "slices"])
def test_subsets_fixpoint_parity_all_backends(backend):
    """The Mosaic slice-algebra twin reaches the identical fixpoint on the
    subsets tier — random boards plus corpus boards, like the 'extended'
    parity tests in test_pallas.py."""
    from distributed_sudoku_solver_tpu.ops.pallas_propagate import (
        propagate_fixpoint_pallas,
        propagate_fixpoint_slices,
    )

    rng = np.random.default_rng(11)
    rand = rng.integers(1, SUDOKU_9.full_mask + 1, (32, 9, 9)).astype(np.uint32)
    corpus = encode_grid(
        jnp.asarray(np.stack([np.asarray(b) for b in HARD_9[:4]])), SUDOKU_9
    )
    for cand in (jnp.asarray(rand), corpus):
        ref, _ = propagate(cand, SUDOKU_9, rules="subsets")
        if backend == "pallas":
            got, _ = propagate_fixpoint_pallas(cand, SUDOKU_9, tile=8, rules="subsets")
        else:
            got, _ = propagate_fixpoint_slices(cand, SUDOKU_9, rules="subsets")
        assert (np.asarray(got) == np.asarray(ref)).all()


def test_subsets_banded_bit_exact():
    """The board-sharded twin (rows/boxes chip-local, columns on a gathered
    view) matches the single-device subsets tier bit-for-bit — same
    solutions AND same node counts, i.e. the identical search tree."""
    import jax

    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.ops.solve import solve_batch
    from distributed_sudoku_solver_tpu.parallel.board_sharded import (
        make_band_mesh,
        solve_batch_banded,
    )

    mesh = make_band_mesh(jax.devices()[:3])
    cfg = SolverConfig(min_lanes=8, stack_slots=32, max_steps=4096, rules="subsets")
    boards = jnp.asarray(np.stack([np.asarray(b) for b in HARD_9[:3]]))
    ref = solve_batch(boards, SUDOKU_9, cfg)
    res = solve_batch_banded(boards, SUDOKU_9, cfg, mesh=mesh)
    assert (np.asarray(res.solved) == np.asarray(ref.solved)).all()
    assert (np.asarray(res.solution) == np.asarray(ref.solution)).all()
    assert (np.asarray(res.nodes) == np.asarray(ref.nodes)).all()
