"""Engine flight-loop tests: chunked advances, mid-flight cancellation,
head-of-line fairness, snapshot/shed controls, multi-root jobs.

The chunked device loop is the answer to VERDICT r1 #2: the reference's
kernel polls for cancellation once per recursion step
(``/root/reference/DHT_Node.py:481-488``); here a host cancel or control
request takes effect at the next chunk boundary instead of after the whole
batch drains.
"""

import time

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

SMALL = SolverConfig(min_lanes=8, stack_slots=16)
# Fused flights (VERDICT r3 #1): the whole-round VMEM kernel behind the same
# chunked flight loop.  fused_steps=2 keeps purge/steal reaction tight enough
# for the cancel/fairness lanes to observe mid-flight behavior.
FUSED_SMALL = SolverConfig(
    min_lanes=8, stack_slots=16, step_impl="fused", fused_steps=2
)


def wait_for(pred, timeout=30.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return False


@pytest.fixture
def engine():
    eng = SolverEngine(config=SMALL, max_batch=8).start()
    yield eng
    eng.stop(timeout=2)


def test_flight_solves_and_counts(engine):
    jobs = [engine.submit(p) for p in HARD_9]
    for j in jobs:
        assert j.wait(60)
        assert j.solved
        assert is_valid_solution(j.solution)
    assert engine.stats()["solved"] == len(HARD_9)
    assert engine.stats()["validations"] > 0


def test_flight_unsat(engine):
    bad = np.zeros((9, 9), np.int32)
    bad[0, 0] = bad[0, 1] = 5
    j = engine.submit(bad)
    assert j.wait(60)
    assert j.unsat and not j.solved


@pytest.mark.parametrize(
    "cfg", [SMALL, FUSED_SMALL], ids=["xla", "fused"]
)
def test_mid_flight_cancel_frees_device(cfg):
    # chunk_steps=1 + per-chunk handicap: the flight is deliberately slow so
    # the cancel provably lands mid-search, not after the fact.
    eng = SolverEngine(
        config=cfg, max_batch=8, chunk_steps=1, handicap_s=0.1
    ).start()
    try:
        j = eng.submit(HARD_9[0])
        # Wait until the flight actually exists (first chunk dispatched).
        assert wait_for(lambda: len(eng._flights) > 0, timeout=30)
        eng.cancel(j.uuid)
        t0 = time.monotonic()
        assert j.wait(30), "cancelled job must resolve promptly"
        assert j.cancelled and not j.solved and not j.unsat
        # Device freed: the flight retires within a few chunks, far below
        # what the full search would have taken at 0.1 s/step (budgets are
        # sized for this 1-core container under concurrent suite load —
        # interpret-mode fused chunks stretch to seconds there).
        assert wait_for(lambda: len(eng._flights) == 0, timeout=20)
        assert time.monotonic() - t0 < 25
    finally:
        eng.stop(timeout=2)


@pytest.mark.parametrize(
    "cfg", [SMALL, FUSED_SMALL], ids=["xla", "fused"]
)
def test_no_head_of_line_blocking(cfg):
    # A long-running flight must not block a later easy job: flights
    # round-robin, so the easy job lands in its own flight and finishes
    # while the hard one is still grinding.
    eng = SolverEngine(
        config=cfg, max_batch=8, chunk_steps=1, handicap_s=0.25, max_flights=4
    ).start()
    try:
        hard = eng.submit(HARD_9[0])
        assert wait_for(lambda: len(eng._flights) > 0, timeout=30)
        easy = eng.submit(EASY_9)
        assert easy.wait(30), "easy job starved behind the hard flight"
        assert easy.solved
        assert not hard.done.is_set(), (
            "hard flight finished first — the handicap/chunking did not keep "
            "it busy long enough for the fairness assertion to mean anything"
        )
        assert hard.wait(120) and hard.solved
    finally:
        eng.stop(timeout=2)


def test_fixed_non_pow2_lane_config():
    # A fixed lane count that is not a power of two must clamp the batch
    # bucket instead of tripping resolve_lanes (regression: flight path
    # dropped the legacy min(bucket, lanes) clamp).
    eng = SolverEngine(
        config=SolverConfig(lanes=6, stack_slots=16), max_batch=8
    ).start()
    try:
        jobs = [eng.submit(p) for p in HARD_9] + [eng.submit(EASY_9)]
        for j in jobs:
            assert j.wait(120)
            assert j.solved, j.error
    finally:
        eng.stop(timeout=2)


def test_snapshot_and_resume_roots(engine):
    slow = SolverEngine(
        config=SMALL, max_batch=8, chunk_steps=1, handicap_s=0.1
    ).start()
    try:
        # Warm the compile cache (same shapes) so the chunk cadence — not a
        # one-off XLA compile — dominates the observation window below.
        warm = slow.submit(EASY_9)
        assert warm.wait(60)
        # HARD_9[1] needs ~28 steps at this width — a multi-second window at
        # 0.1 s/chunk (HARD_9[2] would collapse to one step: pure propagation).
        j = slow.submit(HARD_9[1])
        assert wait_for(lambda: len(slow._flights) > 0, timeout=30)
        snap = None
        deadline = time.monotonic() + 20
        while snap is None and time.monotonic() < deadline:
            snap = slow.snapshot_rows(j.uuid, timeout=5)
            if j.done.is_set():
                break
        assert snap is not None, "no snapshot while job in flight"
        rows, nodes, shed_parts, job_cfg = snap
        assert shed_parts == 0
        assert job_cfg["branch"] == SMALL.branch  # config rides the snapshot
        assert rows.ndim == 3 and rows.shape[0] >= 1
        assert j.wait(120) and j.solved
        # Re-entering the snapshot reproduces the same solution.
        jr = engine.submit_roots(rows, j.geom)
        assert jr.wait(120)
        assert jr.solved
        np.testing.assert_array_equal(jr.solution, j.solution)
    finally:
        slow.stop(timeout=2)


def test_stop_drains_pending_jobs():
    """Shutdown must resolve queued and in-flight jobs (error='engine
    stopped'), never strand a caller waiting without a timeout."""
    eng = SolverEngine(
        config=SMALL, max_batch=8, chunk_steps=1, handicap_s=0.2
    ).start()
    warm = eng.submit(EASY_9)
    assert warm.wait(60)
    inflight = eng.submit(HARD_9[1])  # long flight
    assert wait_for(lambda: len(eng._flights) > 0, timeout=30)
    queued = eng.submit(HARD_9[0])
    eng.stop(timeout=10)
    assert inflight.wait(5), "in-flight job stranded by stop()"
    assert queued.wait(5), "queued job stranded by stop()"
    for j in (inflight, queued):
        assert j.done.is_set()
        assert j.solved or j.error == "engine stopped"


def test_flight_failure_resolves_jobs_and_loop_survives():
    """A flight that cannot even launch (roots exceed a fixed-lanes
    frontier's capacity) must fail its job with an error — and the device
    loop must keep serving afterwards."""
    eng = SolverEngine(
        config=SolverConfig(lanes=2, stack_slots=4), max_batch=8
    ).start()
    try:
        bad_roots = np.ones((2 * (1 + 4) + 1, 9, 9), np.uint32)  # > capacity
        from distributed_sudoku_solver_tpu.models.geometry import geometry_for_size

        j = eng.submit_roots(bad_roots, geometry_for_size(9))
        assert j.wait(60)
        assert j.error and not j.solved
        ok = eng.submit(EASY_9)
        assert ok.wait(60) and ok.solved, "loop died after a failed flight"
    finally:
        eng.stop(timeout=2)


def test_legacy_solve_fn_failure_resolves_jobs():
    def boom(grids, geom, cfg):
        raise RuntimeError("backend exploded")

    eng = SolverEngine(solve_fn=boom, batch_window_s=0.001).start()
    try:
        j = eng.submit(EASY_9)
        assert j.wait(30)
        assert j.error and "backend exploded" in j.error
    finally:
        eng.stop(timeout=2)


def test_concurrent_control_surface_stress():
    """Race-discipline stress (SURVEY.md §5.2): many threads hammering
    submit/cancel/snapshot/shed/run_exclusive against live flights.  The
    single-owner loop + control mailbox must neither deadlock nor lose a
    job: every submitted job resolves, every control call returns, and the
    engine still serves afterwards."""
    import random
    import threading

    eng = SolverEngine(config=SMALL, max_batch=16, chunk_steps=2).start()
    try:
        stop = time.monotonic() + 6.0
        jobs: list = []
        jobs_lock = threading.Lock()
        errors: list = []

        def submitter():
            rng = random.Random(threading.get_ident())
            while time.monotonic() < stop:
                j = eng.submit(HARD_9[rng.randrange(len(HARD_9))])
                with jobs_lock:
                    jobs.append(j)
                if rng.random() < 0.3:
                    eng.cancel(j.uuid)
                time.sleep(rng.random() * 0.02)

        def controller():
            rng = random.Random(threading.get_ident() * 31)
            while time.monotonic() < stop:
                try:
                    op = rng.random()
                    if op < 0.4:
                        with jobs_lock:
                            j = jobs[rng.randrange(len(jobs))] if jobs else None
                        if j is not None:
                            eng.snapshot_rows(j.uuid, timeout=1.0)
                    elif op < 0.7:
                        eng.shed_work(k=2, timeout=1.0)
                    else:
                        eng.run_exclusive(lambda: 42, timeout=1.0)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                time.sleep(rng.random() * 0.01)

        threads = [threading.Thread(target=submitter) for _ in range(3)] + [
            threading.Thread(target=controller) for _ in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
            assert not t.is_alive(), "stress thread wedged"
        assert not errors, errors[:3]
        with jobs_lock:
            all_jobs = list(jobs)
        assert all_jobs, "stress submitted nothing"
        for j in all_jobs:
            assert j.wait(120), f"job lost under stress: {j.uuid}"
            assert j.solved or j.cancelled or j.exhausted or j.error, j.uuid
        # Still serving after the storm.
        final = eng.submit(EASY_9)
        assert final.wait(60) and final.solved
    finally:
        eng.stop(timeout=2)


def test_shed_work_marks_exhaustion_unreliable():
    # Shedding removes subtrees, so a later local exhaustion must not be
    # reported as proven-unsat (the cluster layer aggregates parts first).
    eng = SolverEngine(
        config=SolverConfig(min_lanes=2, stack_slots=16, branch="first"),
        max_batch=8,
        chunk_steps=1,
        handicap_s=0.1,
    ).start()
    try:
        warm = eng.submit(EASY_9)
        assert warm.wait(60)
        j = eng.submit(HARD_9[1])
        shed = None
        deadline = time.monotonic() + 30
        while shed is None and time.monotonic() < deadline:
            if j.done.is_set():
                break
            shed = eng.shed_work(k=2, timeout=5)
        if shed is None:
            pytest.skip("search resolved before any stack rows appeared")
        uuid, rows, job_cfg = shed
        assert job_cfg["branch"] == "first"  # the job's config rides the shed
        assert uuid == j.uuid and rows.shape[0] >= 1
        assert j.wait(120)
        assert j.shed_parts == 1
        if not j.solved:
            # Local space exhausted but rows were shipped: no unsat claim.
            assert j.exhausted and not j.unsat
        else:
            assert is_valid_solution(j.solution)
    finally:
        eng.stop(timeout=2)


def test_fused_flight_solves_and_verdicts():
    """VERDICT r3 #1: fused configs now serve engine flights — solved and
    proven-unsat verdicts both, with solutions matching the oracle."""
    eng = SolverEngine(config=FUSED_SMALL, max_batch=8).start()
    try:
        jobs = [eng.submit(p) for p in HARD_9]
        bad = np.zeros((9, 9), np.int32)
        bad[0, 0] = bad[0, 1] = 5
        ju = eng.submit(bad)
        for j in jobs:
            assert j.wait(120), j.error
            assert j.solved, j.error
            assert is_valid_solution(j.solution)
        assert ju.wait(120)
        assert ju.unsat and not ju.solved
        assert eng.stats()["solved"] == len(HARD_9)
        assert eng.stats()["validations"] > 0
    finally:
        eng.stop(timeout=2)


def test_fused_and_xla_jobs_share_one_engine():
    """Per-job fused configs group into their own flight alongside composite
    flights; the unique-solution board resolves identically under both."""
    eng = SolverEngine(config=SMALL, max_batch=8).start()
    try:
        jf = eng.submit(HARD_9[0], config=FUSED_SMALL)
        jx = eng.submit(HARD_9[0])
        assert jf.wait(120) and jf.solved, jf.error
        assert jx.wait(120) and jx.solved, jx.error
        np.testing.assert_array_equal(jf.solution, jx.solution)
    finally:
        eng.stop(timeout=2)


def test_fused_snapshot_and_resume_roots():
    """Snapshot/resume is impl-agnostic: a cut taken from a fused flight
    re-enters (as a packed fused flight) and reproduces the solution."""
    slow = SolverEngine(
        config=FUSED_SMALL, max_batch=8, chunk_steps=1, handicap_s=0.1
    ).start()
    try:
        warm = slow.submit(EASY_9)
        assert warm.wait(60)
        j = slow.submit(HARD_9[1])
        assert wait_for(lambda: len(slow._flights) > 0, timeout=30)
        snap = None
        deadline = time.monotonic() + 20
        while snap is None and time.monotonic() < deadline:
            snap = slow.snapshot_rows(j.uuid, timeout=5)
            if j.done.is_set():
                break
        assert j.wait(120) and j.solved
        if snap is None:
            pytest.skip("search resolved before a snapshot window opened")
        rows, nodes, shed_parts, job_cfg = snap
        assert job_cfg["step_impl"] == "fused"  # config rides the snapshot
        jr = slow.submit_roots(rows, j.geom, config=FUSED_SMALL)
        assert jr.wait(120) and jr.solved, jr.error
        np.testing.assert_array_equal(jr.solution, j.solution)
    finally:
        slow.stop(timeout=2)


def test_oversized_fused_group_splits_before_downgrading():
    """Gate bands follow the round-5 measured compile table (the r4 caps
    were artifacts of Mosaic's default scoped-vmem ceiling): 9x9 serves
    gridded tiles to S=128 now, the whole-array-only clamp band lives at
    14-16 S in (96, 128] and 25x25 S in (24, 48], and nothing fits 25x25
    past S=48.  A wide fused group at an unbounded-width config launches
    fused with zero downgrades."""
    from distributed_sudoku_solver_tpu.ops.pallas_step import max_fused_lanes

    assert max_fused_lanes(9, 32) == 1 << 30  # gridded fits since r5
    assert max_fused_lanes(16, 128) == 128  # whole-array-only band
    assert max_fused_lanes(25, 32) == 128  # whole-array-only band
    assert max_fused_lanes(25, 64) == 0  # nothing fits
    cfg = SolverConfig(stack_slots=32, step_impl="fused", fused_steps=2)
    eng = SolverEngine(config=cfg, max_batch=256, max_flights=8).start()
    try:
        jobs = [eng.submit(EASY_9) for _ in range(130)]
        for j in jobs:
            assert j.wait(300), j.error
            assert j.solved and j.error is None, j.error
        assert eng.metrics()["fused_downgrades"] == 0
    finally:
        eng.stop(timeout=2)


def test_pinned_wide_fused_lanes_clamp_to_serving_width():
    """A pinned-wide fused config serves fused without downgrading.  At
    9x9 S=32 the round-5 measured table admits gridded tiles, so 256
    lanes fly as-is; the clamp band (whole-array-only widths) now lives
    at 14-16 S in (96, 128] / 25x25 S in (24, 48] — its gate math is
    asserted in test_oversized_fused_group_splits_before_downgrading."""
    cfg = SolverConfig(lanes=256, stack_slots=32, step_impl="fused", fused_steps=2)
    eng = SolverEngine(config=cfg, max_batch=8).start()
    try:
        j = eng.submit(EASY_9)
        assert j.wait(300), j.error
        assert j.solved and j.error is None, j.error
        assert eng.metrics()["fused_downgrades"] == 0
    finally:
        eng.stop(timeout=2)


def test_packed_roots_fused_flight_clamps_like_grid_jobs():
    """A roots (resume) job under the same over-wide fused config clamps to
    the serving width and stays fused, exactly like a grid job — packed
    flights must not bypass the clamp and silently downgrade (r5 review)."""
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.bitmask import encode_grid
    import jax.numpy as jnp

    cfg = SolverConfig(lanes=256, stack_slots=32, step_impl="fused", fused_steps=2)
    roots = np.asarray(encode_grid(jnp.asarray(np.asarray(EASY_9)[None]), SUDOKU_9))
    eng = SolverEngine(config=cfg, max_batch=8).start()
    try:
        j = eng.submit_roots(roots, SUDOKU_9)
        assert j.wait(300), j.error
        assert j.solved and j.error is None, j.error
        assert eng.metrics()["fused_downgrades"] == 0
    finally:
        eng.stop(timeout=2)


def test_fused_flight_vmem_misfit_downgrades_to_composite():
    """A fused config whose kernel tile cannot fit scoped VMEM (25x25 at
    S=64 — past the round-5 measured whole-array cap of 48) downgrades
    the flight to the composite step at launch: the job serves correctly,
    no error, and the downgrade is counted on /metrics (VERDICT r4 #5 —
    a correct slower path exists, so a tuning misfit must not error
    paying jobs)."""
    from distributed_sudoku_solver_tpu.models.geometry import geometry_for_size
    from distributed_sudoku_solver_tpu.utils.puzzles import make_puzzle

    g25 = geometry_for_size(25)
    board = make_puzzle(g25, seed=7, n_clues=545, unique=False)  # propagation-easy
    eng = SolverEngine(
        config=SolverConfig(lanes=256, stack_slots=64, step_impl="fused"),
        max_batch=8,
    ).start()
    try:
        j = eng.submit(np.asarray(board, np.int32), geom=g25)
        assert j.wait(240), j.error
        assert j.error is None and j.solved, j.error
        assert eng.metrics()["fused_downgrades"] >= 1
        ok = eng.submit(EASY_9, config=SMALL)
        assert ok.wait(60) and ok.solved, "loop died after the downgraded flight"
    finally:
        eng.stop(timeout=2)


def test_fused_occupancy_histogram_on_metrics():
    """Round 6 (ROADMAP 4b): fused flights feed the in-kernel live-lane
    counters into a per-dispatch lane-occupancy histogram on metrics() —
    the data that settles the in-kernel tile-local steal question."""
    eng = SolverEngine(config=FUSED_SMALL, max_batch=8, chunk_steps=4).start()
    try:
        jobs = [eng.submit(p) for p in HARD_9]
        for j in jobs:
            assert j.wait(120)
            assert j.solved
        m = eng.metrics()
        occ = m.get("fused_lane_occupancy")
        assert occ is not None, f"no occupancy histogram in {sorted(m)}"
        assert occ["bucket_pct"] == 10
        assert len(occ["counts"]) == 10
        assert sum(occ["counts"]) > 0
        assert occ["chunks"] > 0
        assert 0.0 <= occ["mean_pct"] <= 100.0
    finally:
        eng.stop(timeout=2)


def test_composite_engine_has_no_occupancy_histogram():
    """Composite flights skip the per-chunk lane_rounds fetch entirely —
    the histogram is a fused-dispatch diagnostic, not a universal tax."""
    eng = SolverEngine(config=SMALL, max_batch=4).start()
    try:
        j = eng.submit(EASY_9)
        assert j.wait(60) and j.solved
        assert "fused_lane_occupancy" not in eng.metrics()
    finally:
        eng.stop(timeout=2)
