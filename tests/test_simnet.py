"""Deterministic cluster tests over the in-memory simnet (round 10).

Every test here is marked ``simnet``: the conftest purity guard fails any
that opens a real socket or calls ``time.sleep``.  Timing-sensitive
membership scenarios that the socket lane (tests/test_cluster.py) can only
probe with wall-clock margins — false-death eviction, part re-homing,
coordinator promotion — run here on a virtual clock where "wait 2 seconds
of heartbeats" is ``net.advance``, not fragile real sleeping.  On top of
those ports, this lane holds the scenarios real sockets cannot stage at
all: programmable drop / duplicate / reorder faults on single links
(at-least-once idempotence), symmetric partitions with two live
coordinators (split-brain heal), and the seeded chaos soak.

Fault vocabulary: ``serving/faults.FaultSchedule`` over method-scoped link
sites (``link:<src>-><dst>:<METHOD>``), kinds drop/dup/delay — see
cluster/simnet.py.
"""

import threading

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.cluster.node import (
    ClusterConfig,
    ClusterNode,
    _Exec,
    pack_rows,
)
from distributed_sudoku_solver_tpu.cluster.simnet import SimNet, wait_until
from distributed_sudoku_solver_tpu.cluster.wire import WireError
from distributed_sudoku_solver_tpu.serving.engine import Job as EngineJob
from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
from distributed_sudoku_solver_tpu.serving.faults import FaultSchedule
from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution, solve_oracle
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

from tests.test_cluster import a_geom, oracle_solve_fn

pytestmark = pytest.mark.simnet

# Virtual-clock cluster config: the margins that make the socket lane's
# FAST config "err well on the side of patience" (its module note) are
# unnecessary here — detection takes 2.0 *virtual* seconds however loaded
# the CI machine is.
SIM = ClusterConfig(
    heartbeat_s=0.25,
    fail_factor=8.0,
    io_timeout_s=2.0,
    needwork=False,
    progress_interval_s=0.0,
    retry_delay_s=0.1,
    tombstone_probe_s=600.0,
)


@pytest.fixture
def net():
    n = SimNet()
    n.nodes = []  # sim_node() registers for teardown
    yield n
    for node in n.nodes:
        node.kill()
        node.engine.stop(timeout=1)
    n.close()


def sim_node(net, anchor=None, config=SIM, engine=None):
    eng = engine or SolverEngine(
        solve_fn=oracle_solve_fn(), batch_window_s=0.001
    ).start()
    node = ClusterNode(
        eng, anchor=anchor, config=config, transport=net.transport(),
        clock=net.clock,
    ).start()
    net.nodes.append(node)
    return node


def flight_engine():
    """Real chunked-flight engine (same shapes as test_cluster's
    _flight_node, so compiled programs are shared): part re-entry needs
    submit_roots, which the oracle solve_fn path rejects."""
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig

    return SolverEngine(
        config=SolverConfig(min_lanes=4, stack_slots=32, branch="first"),
        chunk_steps=1,
        batch_window_s=0.001,
    ).start()


def form_ring(net, k, config=SIM, engines=None):
    nodes = [sim_node(net, engine=(engines or {}).get(0), config=config)]
    for i in range(1, k):
        nodes.append(
            sim_node(
                net, anchor=nodes[0].addr, engine=(engines or {}).get(i),
                config=config,
            )
        )
    assert wait_until(
        net, lambda: all(len(n.network) == k for n in nodes), timeout=60
    ), "ring never formed"
    return nodes


# -- transport contract (SimNet itself) --------------------------------------


def test_simnet_send_request_and_partition_semantics(net):
    got = []

    t1 = net.transport()
    a1 = t1.bind("127.0.0.1", 0)
    t1.serve(lambda m: got.append(m) or (
        {"method": "PONG", "n": m["n"] + 1} if m["method"] == "PING" else None
    ))
    t2 = net.transport()
    t2.bind("127.0.0.1", 0)

    t2.send(a1, {"method": "HELLO"}, 2.0)
    net.settle()
    assert got and got[0]["method"] == "HELLO"
    assert net.transport().request(a1, {"method": "PING", "n": 1}, 2.0)["n"] == 2

    # Unbound peer: connect refused, delivery unambiguous.
    with pytest.raises(WireError) as ei:
        t2.send(("127.0.0.1", 9999), {"method": "X"}, 2.0)
    assert ei.value.ambiguous_delivery is False

    # Partitioned link: connect timeout, delivery unambiguous; heal restores.
    net.partition(["127.0.0.1:7001"], ["127.0.0.1:7000"])
    with pytest.raises(WireError):
        t2.send(a1, {"method": "X"}, 2.0)
    assert net.counters["blocked"] == 1
    net.heal()
    t2.send(a1, {"method": "AGAIN"}, 2.0)
    net.settle()
    assert got[-1]["method"] == "AGAIN"


def test_simnet_drop_dup_delay_faults(net):
    got = []
    srv = net.transport()
    addr = srv.bind("127.0.0.1", 0)
    srv.serve(lambda m: got.append(m["i"]))
    cli = net.transport()
    cli.bind("127.0.0.1", 0)
    link = "link:127.0.0.1:7001->127.0.0.1:7000:M"
    net.set_schedule(
        FaultSchedule.at({link: {0: "drop", 1: "dup", 2: "delay"}})
    )
    # Event 0: dropped — the sender sees an AMBIGUOUS WireError (bytes were
    # written; its retry would be at-least-once), and nothing is delivered.
    with pytest.raises(WireError) as ei:
        cli.send(addr, {"method": "M", "i": 0}, 2.0)
    assert ei.value.ambiguous_delivery is True
    # Event 1: duplicated — one send, two deliveries (second one delayed).
    cli.send(addr, {"method": "M", "i": 1}, 2.0)
    # Event 2: delayed past event 3 — reordering.
    cli.send(addr, {"method": "M", "i": 2}, 2.0)
    cli.send(addr, {"method": "M", "i": 3}, 2.0)
    assert wait_until(net, lambda: len(got) == 4, timeout=5)
    assert 0 not in got
    assert sorted(got) == [1, 1, 2, 3]
    assert got.index(3) < got.index(2), "delay fault did not reorder"
    assert net.counters["dropped"] == 1
    assert net.counters["duplicated"] == 1
    assert net.counters["delayed"] == 1


def test_simnet_virtual_clock_sleep_and_request_timeout(net):
    t = net.transport()
    addr = t.bind("127.0.0.1", 0)
    t.serve(lambda m: None)  # never replies

    woke = []

    def sleeper():
        net.clock.sleep(1.0)
        woke.append(net.clock.now())

    th = threading.Thread(target=sleeper, daemon=True)
    th.start()
    net.settle()
    assert not woke
    net.advance(0.5)
    assert not woke
    net.advance(0.6)
    th.join(5)
    assert woke and woke[0] >= 1.0

    # request() times out on VIRTUAL time — and a no-reply timeout is
    # ambiguous (the request may have been processed).
    err = []

    def requester():
        try:
            net.transport().request(addr, {"method": "Q"}, timeout=1.0)
        except WireError as e:
            err.append(e)

    th = threading.Thread(target=requester, daemon=True)
    th.start()
    assert wait_until(net, lambda: bool(err), timeout=5)
    assert err[0].ambiguous_delivery is True


# -- ported membership scenarios (socket lane's timing-fragile trio) ----------


def test_ring_formation_and_dispatch(net):
    a, b, c = form_ring(net, 3)
    assert all(n.coordinator == a.addr_s for n in (a, b, c))
    jobs = [a.submit(EASY_9) for _ in range(6)]
    assert wait_until(net, lambda: all(j.done.is_set() for j in jobs), timeout=60)
    assert all(j.solved and is_valid_solution(j.solution) for j in jobs)
    remote = b.engine.stats()["jobs_done"] + c.engine.stats()["jobs_done"]
    assert remote > 0, "least-outstanding dispatch never left the local engine"


def test_coordinator_death_promotes_detector(net):
    """Port of the socket lane's promotion scenario: same protocol, but
    `wait 2 s of detection` is a virtual advance, not wall-clock hope."""
    a, b, c = form_ring(net, 3)
    a.kill()
    assert wait_until(
        net,
        lambda: all(
            len(n.network) == 2 and n.coordinator != a.addr_s for n in (b, c)
        ),
        timeout=60,
    )
    assert b.coordinator == c.coordinator
    assert b.net_term == 1, "promotion must open a new term"


def test_false_death_eviction_and_rejoin(net):
    """The false-death scenario the socket lane could only avoid (its FAST
    config 'errs well on the side of patience'): a live member whose
    heartbeats are suppressed long enough IS evicted — and then heals:
    the coordinator keeps probing the tombstoned member with its view, the
    evictee rejoins through it, and partitions_healed counts the event."""
    a, b, c = form_ring(net, 3)
    net.partition([c.addr_s], [a.addr_s, b.addr_s])
    assert wait_until(
        net,
        lambda: len(a.network) == 2 and c.addr_s not in a.network,
        timeout=120,
    ), "suppressed heartbeats never produced the eviction"
    assert c.addr_s in a._evicted  # tombstoned: probed, not forgotten
    net.heal()
    assert wait_until(
        net,
        lambda: all(len(n.network) == 3 for n in (a, b, c))
        and all(n.coordinator == a.addr_s for n in (a, b, c)),
        timeout=120,
    ), "evicted-but-alive member never rejoined after heal"
    m = a.metrics_view()["cluster"]["faults"]
    assert m["partitions_healed"] >= 1
    # And the cluster still serves.
    job = a.submit(EASY_9)
    assert wait_until(net, lambda: job.done.is_set(), timeout=60)
    assert job.solved


def test_reexecution_after_member_death(net):
    """Port of the socket lane's ledger re-execution test: the in-flight
    window is held open by an Event-gated solve_fn instead of a real
    sleep, and detection runs on the virtual clock."""
    gate = threading.Event()
    base = oracle_solve_fn()

    def gated(grids, geom, cfg):
        gate.wait(30)  # bounded real wait, not time.sleep; never load-bearing
        return base(grids, geom, cfg)

    slow_engine = SolverEngine(solve_fn=gated, batch_window_s=0.001).start()
    a = sim_node(net)
    b = sim_node(net, anchor=a.addr, engine=slow_engine)
    assert wait_until(
        net, lambda: len(a.network) == 2 and len(b.network) == 2, timeout=60
    )
    job = a._submit_remote(np.asarray(EASY_9, dtype=np.int32), b.addr_s)
    assert wait_until(net, lambda: len(b._execs) == 1, timeout=30), (
        "TASK never landed on the member"
    )
    b.kill()  # TASK is in b's gated engine; b goes silent mid-execution
    assert wait_until(net, lambda: job.done.is_set(), timeout=120), (
        "forwarded job must be re-executed after member death"
    )
    assert job.solved
    assert is_valid_solution(job.solution)
    gate.set()


def test_part_deadline_rehomes_from_wedged_peer(net):
    """Satellite: the --part-deadline path pinned deterministically.  A part
    shed to a peer that stays ALIVE in the view (so view-change recovery
    never fires) blows the wall-clock deadline and is re-homed locally;
    the original executor is cancelled (first-win keeps the aggregate
    sound if it were to answer later)."""
    cfg = ClusterConfig(
        heartbeat_s=0.25,
        fail_factor=64.0,  # nobody dies in this test
        io_timeout_s=2.0,
        needwork=False,
        progress_interval_s=0.0,
        part_deadline_s=1.0,
        tombstone_probe_s=600.0,
    )
    a = sim_node(net, engine=flight_engine(), config=cfg)
    b = sim_node(net, anchor=a.addr, config=cfg)
    assert wait_until(
        net, lambda: len(a.network) == 2 and len(b.network) == 2, timeout=60
    )
    g = np.asarray(EASY_9, np.int32)
    ex = _Exec(a, EngineJob(uuid="x-deadline", grid=g, geom=a_geom(g)),
               on_final=lambda r: None)
    with a._lock:
        a._execs["x-deadline"] = ex
    # All-ones candidate rows: every cell pinned to digit 1 — an instantly
    # unsat subspace, so the local re-entry resolves in one chunk.
    rows = pack_rows(np.ones((2, 9, 9), np.uint32))
    assert ex.add_part("x-deadline#p1", b.addr_s, rows_packed=rows, config=None)
    net.advance(0.5)
    with ex.lock:
        assert not ex.parts["x-deadline#p1"]["rehomed"], "re-homed early"
    assert wait_until(
        net,
        lambda: a.rehomed_parts >= 1 and ex.parts["x-deadline#p1"]["done"],
        timeout=60,
    ), "blown deadline never re-homed the part"
    with ex.lock:
        assert ex.parts["x-deadline#p1"]["exhausted"]
    # First-win: the slow-but-alive original executor was cancelled.
    assert wait_until(
        net, lambda: "x-deadline#p1" in b.engine._cancelled, timeout=30
    )
    assert a.metrics_view()["cluster"]["faults"]["rehomed_parts"] >= 1


# -- at-least-once idempotence ------------------------------------------------


def test_duplicate_task_executes_once(net):
    """Acceptance: the same TASK frame delivered twice changes no counts —
    one execution, one SOLUTION, dedupe counter incremented."""
    a, b = form_ring(net, 2)
    link = f"link:{a.addr_s}->{b.addr_s}:TASK"
    net.set_schedule(FaultSchedule.at({link: {0: "dup"}}))
    job = a._submit_remote(np.asarray(EASY_9, np.int32), b.addr_s)
    assert wait_until(net, lambda: job.done.is_set(), timeout=60)
    assert job.solved and is_valid_solution(job.solution)
    assert wait_until(
        net, lambda: b.duplicates_dropped.get("TASK", 0) == 1, timeout=30
    ), "duplicate TASK was not detected"
    assert b.engine.stats()["jobs_done"] == 1, "duplicate TASK was executed"
    assert net.counters["duplicated"] == 1


def test_duplicate_solution_changes_no_counts(net):
    """Acceptance twin: a duplicated SOLUTION finalizes once and must not
    double-decrement the outstanding ledger (placement accounting)."""
    a, b = form_ring(net, 2)
    link = f"link:{b.addr_s}->{a.addr_s}:SOLUTION"
    net.set_schedule(FaultSchedule.at({link: {0: "dup"}}))
    job = a._submit_remote(np.asarray(EASY_9, np.int32), b.addr_s)
    assert wait_until(net, lambda: job.done.is_set(), timeout=60)
    assert job.solved
    assert wait_until(
        net, lambda: a.duplicates_dropped.get("SOLUTION", 0) == 1, timeout=30
    ), "duplicate SOLUTION was not detected"
    with a._lock:
        assert a._outstanding.get(b.addr_s, 0) == 0, (
            "duplicate SOLUTION skewed least-outstanding accounting"
        )
    assert job.uuid not in a._ledger


def test_dropped_solution_is_retried(net):
    """The sender half of at-least-once: a SOLUTION lost after bytes were
    written (ambiguous WireError) is re-sent under the bounded budget —
    without the retry, a drop-faulted link would strand the origin's
    ledger entry forever while the worker stays healthy in the view."""
    a, b = form_ring(net, 2)
    link = f"link:{b.addr_s}->{a.addr_s}:SOLUTION"
    net.set_schedule(FaultSchedule.at({link: {0: "drop"}}))
    job = a._submit_remote(np.asarray(EASY_9, np.int32), b.addr_s)
    assert wait_until(net, lambda: job.done.is_set(), timeout=60), (
        "dropped SOLUTION never retried"
    )
    assert job.solved and is_valid_solution(job.solution)
    assert net.counters["dropped"] == 1
    assert job.uuid not in a._ledger


def test_stale_view_and_duplicate_join_rejected(net):
    a, b = form_ring(net, 2)
    term, epoch = b.net_term, b.net_epoch
    # Replayed older view: rejected, counted.
    net.inject(
        b.addr,
        {
            "method": "UPDATE_NETWORK",
            "network": [b.addr_s],
            "coordinator": b.addr_s,
            "term": term,
            "epoch": max(0, epoch - 1),
        },
    )
    assert wait_until(net, lambda: b.stale_views_rejected >= 1, timeout=10)
    assert len(b.network) == 2 and b.coordinator == a.addr_s
    # Replayed JOIN_REQ: no epoch bump, no duplicate member.
    e0 = a.net_epoch
    for _ in range(3):
        net.inject(a.addr, {"method": "JOIN_REQ", "addr": b.addr_s})
    assert wait_until(
        net, lambda: a.duplicates_dropped.get("JOIN_REQ", 0) == 3, timeout=10
    )
    assert a.net_epoch == e0
    assert sorted(set(a.network)) == sorted(a.network)
    # Stale-term NODE_FAILED: a death verdict formed under a superseded
    # term is void (does not evict the member it names).
    net.inject(
        a.addr,
        {"method": "NODE_FAILED", "addr": b.addr_s, "term": -1, "epoch": 0},
    )
    net.settle()
    assert b.addr_s in a.network


# -- split-brain --------------------------------------------------------------


def test_split_brain_partition_heals_to_one_coordinator(net):
    """Acceptance: symmetric partition isolates the coordinator; the other
    side promotes (new term); on heal the two live coordinators converge —
    the lower (term, epoch) holder demotes, rejoins through the winner,
    and its in-flight part re-homes through the existing orphan path.
    All asserted via the /metrics cluster.faults counters."""
    engines = {0: flight_engine()}
    a, b, c, d, e = form_ring(net, 5, engines=engines)
    assert a.coordinator == a.addr_s
    # One in-flight part shed to b, rows retained at a (the shedder).
    g = np.asarray(EASY_9, np.int32)
    ex = _Exec(a, EngineJob(uuid="x-split", grid=g, geom=a_geom(g)),
               on_final=lambda r: None)
    with a._lock:
        a._execs["x-split"] = ex
    rows = pack_rows(np.ones((2, 9, 9), np.uint32))
    assert ex.add_part("x-split#p1", b.addr_s, rows_packed=rows, config=None)

    net.partition([a.addr_s], [n.addr_s for n in (b, c, d, e)])
    # Majority side: b (a's ring watcher) promotes into term 1 and evicts a.
    assert wait_until(
        net,
        lambda: b.coordinator == b.addr_s
        and b.net_term == 1
        and all(n.coordinator == b.addr_s for n in (c, d, e))
        and a.addr_s not in b.network,
        timeout=240,
    ), "partitioned majority never promoted a new coordinator"
    # Minority side: a (still a coordinator, lower view) evicts everyone it
    # cannot reach — and re-homes the part it had shed to b via the orphan
    # path (b left a's view).
    assert wait_until(
        net,
        lambda: len(a.network) == 1 and a.rehomed_parts >= 1
        and ex.parts["x-split#p1"]["done"],
        timeout=240,
    ), "isolated coordinator never re-homed its in-flight part"
    assert a.net_term == 0 and b.net_term == 1  # two live coordinators

    net.heal()
    assert wait_until(
        net,
        lambda: all(
            len(n.network) == 5 and n.coordinator == b.addr_s
            for n in (a, b, c, d, e)
        ),
        timeout=240,
    ), "healed partition never converged to one coordinator"
    fa = a.metrics_view()["cluster"]["faults"]
    fb = b.metrics_view()["cluster"]["faults"]
    assert fa["demotions"] == 1, "the losing coordinator must demote"
    assert fa["rehomed_parts"] >= 1
    assert fb["partitions_healed"] >= 1, "winner must re-admit the loser"
    assert fa["stale_views_rejected"] + fb["stale_views_rejected"] >= 1
    # The healed ring serves, and placement accounting survived the churn.
    jobs = [a.submit(EASY_9) for _ in range(5)]
    assert wait_until(
        net, lambda: all(j.done.is_set() and j.solved for j in jobs), timeout=120
    )


# -- the seeded chaos soak ----------------------------------------------------


def test_chaos_soak_drop_dup_reorder_partition(net):
    """Acceptance: a 5-node simulated ring solves a corpus while every link
    Bernoulli-drops/duplicates/delays at >=10% per event AND two
    partitions (one member, then the coordinator — a full split-brain
    cycle) strike mid-run.  Zero lost jobs; solutions bit-identical to the
    fault-free oracle; no real sockets, no wall-clock sleeps (enforced by
    the simnet marker guard)."""
    soak_cfg = ClusterConfig(
        heartbeat_s=0.25,
        fail_factor=8.0,
        io_timeout_s=2.0,
        needwork=False,
        progress_interval_s=0.0,
        send_retries=4,  # rate-0.12 links: bound the odds of an all-drops run
        retry_delay_s=0.1,
        tombstone_probe_s=600.0,
    )
    nodes = form_ring(net, 5, config=soak_cfg)
    a, b, c, d, e = nodes
    # Corpus: EASY_9 + the two quick HARD boards.  HARD_9[2] is excluded on
    # purpose — it costs ~40 s in the native oracle, which would turn this
    # protocol soak into a solver benchmark (and each engine execution of
    # it would stall a 2-core CI box for minutes).
    boards = [np.asarray(EASY_9, np.int32)] + [
        np.asarray(h, np.int32) for h in HARD_9[:2]
    ]
    expect = [solve_oracle(g, a_geom(g)) for g in boards]
    assert all(s is not None for s in expect)

    # Ring formed cleanly; now turn on the weather.
    net.set_schedule(
        FaultSchedule.seeded(seed=11, rate=0.12, kinds=("drop", "dup", "delay"))
    )
    jobs = [(i, a.submit(boards[i % len(boards)])) for i in range(6)]

    # Partition a non-coordinator member long enough for eviction, heal.
    net.partition([d.addr_s], [n.addr_s for n in nodes if n is not d])
    assert wait_until(net, lambda: d.addr_s not in a.network, timeout=240)
    jobs += [(i, a.submit(boards[i % len(boards)])) for i in range(6, 12)]
    net.heal()
    assert wait_until(
        net, lambda: all(len(n.network) == 5 for n in nodes), timeout=240
    ), "member partition never healed"

    # Partition the coordinator: full split-brain cycle under load.
    net.partition([a.addr_s], [n.addr_s for n in nodes[1:]])
    assert wait_until(net, lambda: b.net_term >= 1, timeout=240), (
        "coordinator partition never promoted"
    )
    jobs += [(i, a.submit(boards[i % len(boards)])) for i in range(12, 18)]
    net.heal()
    assert wait_until(
        net,
        lambda: all(
            len(n.network) == 5 and n.coordinator == nodes[1].addr_s
            for n in nodes
        ),
        timeout=240,
    ), "split brain never healed"

    assert wait_until(
        net, lambda: all(j.done.is_set() for _, j in jobs), timeout=600
    ), (
        f"lost jobs: "
        f"{[(i, j.error) for i, j in jobs if not j.done.is_set()]}"
    )
    for i, j in jobs:
        assert j.solved, f"job {i} ended unsolved: {j.error!r}"
        assert np.array_equal(j.solution, expect[i % len(boards)]), (
            f"job {i} solution not bit-identical to the fault-free run"
        )
    # The soak must actually have exercised the fault plane.
    assert net.counters["dropped"] > 0
    assert net.counters["duplicated"] > 0
    assert net.counters["delayed"] > 0
    assert net.counters["blocked"] > 0
    total_faults = sum(
        sum(n.duplicates_dropped.values())
        + n.stale_views_rejected
        + n.partitions_healed
        + n.demotions
        for n in nodes
    )
    assert total_faults > 0, "chaos soak never tripped a cluster fault counter"
    with a._lock:
        assert not a._ledger, "resolved jobs left ledger entries behind"
        assert all(v == 0 for v in a._outstanding.values()), (
            f"placement accounting skewed: {a._outstanding}"
        )
