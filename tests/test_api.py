"""API tests: golden request/response pairs for /solve, /stats, /network
(SURVEY.md §4 item 5), plus engine-level batching and cancellation."""

import json
import urllib.request

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
from distributed_sudoku_solver_tpu.serving.http import ApiServer, StandaloneNode
from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9

SMALL = SolverConfig(min_lanes=8, stack_slots=24, max_steps=20_000)


@pytest.fixture(scope="module")
def server():
    engine = SolverEngine(config=SMALL, max_batch=8).start()
    node = StandaloneNode(engine=engine, address="127.0.0.1:test")
    api = ApiServer(node, host="127.0.0.1", port=0, solve_timeout_s=120).start()
    yield api
    api.stop()
    engine.stop()


def _request(api, path, body=None):
    url = f"http://127.0.0.1:{api.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method="POST" if data else "GET")
    try:
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_solve_endpoint(server):
    status, body = _request(server, "/solve", {"sudoku": np.asarray(EASY_9).tolist()})
    assert status == 201
    assert set(body) == {"solution", "duration"}
    sol = np.asarray(body["solution"])
    assert is_valid_solution(sol)
    mask = np.asarray(EASY_9) != 0
    assert np.array_equal(sol[mask], np.asarray(EASY_9)[mask])
    assert body["duration"] > 0


def test_solve_unsat_returns_422(server):
    bad = np.asarray(EASY_9).copy()
    bad[0, 0], bad[0, 1] = 5, 5
    status, body = _request(server, "/solve", {"sudoku": bad.tolist()})
    assert status == 422
    assert "unsat" in body["error"]


def test_solve_bad_body_returns_400(server):
    status, _ = _request(server, "/solve", {"wrong_key": []})
    assert status == 400
    status, _ = _request(server, "/solve", {"sudoku": [[1, 2], [3, 4], [5, 6]]})
    assert status == 400
    # Ragged rows: np.asarray raises; must be a clean 400, not a dropped
    # connection — on both the plain and portfolio paths.
    status, _ = _request(server, "/solve", {"sudoku": [[1, 2], [3]]})
    assert status == 400
    status, _ = _request(
        server, "/solve", {"sudoku": [[1, 2], [3]], "portfolio": True}
    )
    assert status == 400


def test_stats_shape(server):
    # Reference JSON shape: /root/reference/DHT_Node.py:573-586.
    status, body = _request(server, "/stats")
    assert status == 200
    assert set(body) == {"all", "nodes"}
    assert set(body["all"]) == {"solved", "validations"}
    assert body["all"]["solved"] >= 1  # test_solve_endpoint ran first
    assert isinstance(body["nodes"], list) and body["nodes"]
    assert {"address", "validations"} <= set(body["nodes"][0])


def test_network_shape(server):
    status, body = _request(server, "/network")
    assert status == 200
    for addr, (pred, succ) in body.items():
        assert isinstance(pred, str) and isinstance(succ, str)


def test_unknown_paths(server):
    assert _request(server, "/nope")[0] == 404


def test_solve_portfolio_option(server):
    """POST /solve with portfolio=true races the default strategy portfolio
    and reports the winning branch rule."""
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9

    status, body = _request(
        server,
        "/solve",
        {"sudoku": np.asarray(HARD_9[0]).tolist(), "portfolio": True},
    )
    assert status == 201
    assert is_valid_solution(np.asarray(body["solution"]))
    assert body["strategy"] in ("minrem", "minrem-desc", "first")

    bad = np.asarray(EASY_9).copy()
    bad[0, 0], bad[0, 1] = 5, 5
    status, body = _request(
        server, "/solve", {"sudoku": bad.tolist(), "portfolio": True}
    )
    assert status == 422
    assert body["strategy"] in ("minrem", "minrem-desc", "first")


def test_solve_batch_endpoint_boards(server):
    """POST /solve_batch with nested grids (VERDICT r1 #6): bulk over HTTP,
    routed through ops/bulk on the engine's device-owner thread."""
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9

    bad = np.asarray(EASY_9).copy()
    bad[0, 0], bad[0, 1] = 5, 5
    boards = [np.asarray(EASY_9), np.asarray(HARD_9[0]), bad]
    status, body = _request(
        server,
        "/solve_batch",
        {"boards": [b.tolist() for b in boards], "chunk": 2},
    )
    assert status == 200
    assert body["count"] == 3
    assert body["solved"] == 2
    assert body["unsat"] == 1
    assert body["solved_mask"] == [True, True, False]
    assert body["unsat_mask"] == [False, False, True]
    for i in (0, 1):
        sol = np.asarray(body["solutions"][i])
        assert is_valid_solution(sol)
        mask = boards[i] != 0
        assert np.array_equal(sol[mask], boards[i][mask])
    assert body["duration"] > 0


def test_solve_batch_endpoint_lines(server):
    from distributed_sudoku_solver_tpu.utils.puzzles import to_line

    status, body = _request(
        server,
        "/solve_batch",
        {"lines": [to_line(np.asarray(EASY_9))], "size": 9},
    )
    assert status == 200
    assert body["solved"] == 1
    sol_line = body["solutions"][0]
    assert len(sol_line) == 81 and "0" not in sol_line


def test_solve_batch_bad_body(server):
    assert _request(server, "/solve_batch", {"boards": [[1, 2]]})[0] == 400
    assert _request(server, "/solve_batch", {"nope": True})[0] == 400


def test_engine_batches_concurrent_jobs():
    engine = SolverEngine(config=SMALL, max_batch=8, batch_window_s=0.05).start()
    try:
        jobs = [engine.submit(EASY_9) for _ in range(5)]
        for job in jobs:
            assert job.wait(120)
            assert job.solved
            assert is_valid_solution(job.solution)
        assert engine.solved_count == 5
    finally:
        engine.stop()


def test_engine_cancel_before_run():
    engine = SolverEngine(config=SMALL)  # not started: job sits in queue
    job = engine.submit(EASY_9)
    engine.cancel(job.uuid)
    engine.start()
    try:
        assert job.wait(60)
        assert job.cancelled
        assert not job.solved
    finally:
        engine.stop()


def test_metrics_endpoint_and_window():
    import json as _json
    import urllib.request

    from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
    from distributed_sudoku_solver_tpu.serving.http import ApiServer, StandaloneNode
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9

    engine = SolverEngine(config=SolverConfig(min_lanes=8, stack_slots=16)).start()
    node = StandaloneNode(engine)
    api = ApiServer(node, host="127.0.0.1", port=0).start()
    try:
        job = engine.submit(EASY_9)
        assert job.wait(120) and job.solved
        body = _json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{api.port}/metrics", timeout=30
            ).read()
        )
        assert body["jobs_done"] >= 1
        assert body["job_latency_ms"]["count"] >= 1
        assert body["job_latency_ms"]["p50"] > 0
        assert body["batch_jobs"]["p50"] >= 1
    finally:
        api.stop()
        engine.stop()


def test_stat_window_percentiles():
    from distributed_sudoku_solver_tpu.utils.profiling import StatWindow

    w = StatWindow(capacity=8)
    assert w.snapshot() is None
    for v in range(1, 101):  # ring wraps; window = last 8 values 93..100
        w.record(float(v))
    snap = w.snapshot()
    assert snap["total"] == 100 and snap["count"] == 8
    assert 93 <= snap["p50"] <= 100


def test_solve_count_all_endpoint(server):
    """POST /solve with count_all=true enumerates every solution: the empty
    4x4 board has exactly 288 completions (a capability the reference's
    first-solution DFS cannot express)."""
    code, body = _request(server, "/solve", {
        "sudoku": [[0] * 4 for _ in range(4)],
        "count_all": True,
    })
    assert code == 200
    assert body["count"] == 288
    assert body["complete"] is True
    assert body["solution"] is not None


# -- round-11 observability endpoints (obs/) ----------------------------------


def test_trace_endpoints(server):
    """GET /trace is 404 while tracing is disabled; with a recorder
    installed, a solve is reconstructible: /trace lists recent spans,
    /trace/<uuid> returns the job's lifecycle, and ?format=perfetto
    exports Chrome-trace JSON that passes the traceck validator."""
    from distributed_sudoku_solver_tpu.obs import trace, traceck

    status, body = _request(server, "/trace")
    assert status == 404 and "tracing disabled" in body["error"]

    rec = trace.TraceRecorder(ring=2048)
    with trace.installed(rec):
        status, _ = _request(
            server, "/solve", {"sudoku": np.asarray(EASY_9).tolist()}
        )
        assert status == 201
        status, body = _request(server, "/trace")
        assert status == 200 and body["count"] >= 1
        http_spans = [s for s in body["spans"] if s["name"] == "http.solve"]
        assert http_spans, "no http.solve span in the ring"
        assert http_spans[-1]["attrs"]["status"] == 201
        uuid = http_spans[-1]["trace"]

        status, body = _request(server, f"/trace/{uuid}")
        assert status == 200 and body["uuid"] == uuid
        names = {s["name"] for s in body["spans"]}
        # HTTP accept -> admission -> chunk work -> resolution: one trace.
        assert {"http.solve", "admission", "resolve"} <= names, names

        status, doc = _request(server, "/trace?format=perfetto")
        assert status == 200
        assert traceck.check(doc) == []
        status, _ = _request(server, "/trace?limit=zzz")
        assert status == 400
    status, _ = _request(server, "/trace")
    assert status == 404  # uninstalled again


def test_trace_analyze_and_hardening(server):
    """Round-15 satellite + acceptance: ``GET /trace/<uuid>?analyze=1``
    serves the critical-path decomposition whose phase walls sum to the
    job's end-to-end wall within the documented tolerance (the
    real-clock half of the contract — the virtual-clock half lives in
    tests/test_critpath.py); unknown uuids and malformed
    ``?limit``/``?analyze`` values are structured 4xx, never a 500."""
    from distributed_sudoku_solver_tpu.obs import critpath, trace

    rec = trace.TraceRecorder(ring=4096)
    with trace.installed(rec):
        status, _ = _request(
            server, "/solve", {"sudoku": np.asarray(EASY_9).tolist()}
        )
        assert status == 201
        uuid = next(
            s["trace"] for s in reversed(rec.spans())
            if s["name"] == "http.solve"
        )

        status, body = _request(server, f"/trace/{uuid}?analyze=1")
        assert status == 200
        d = body["analysis"]
        assert body["analysis_tolerance"] == critpath.SUM_TOLERANCE
        total = sum(d["phases_ms"].values())
        assert abs(total - d["end_to_end_ms"]) <= (
            d["end_to_end_ms"] * critpath.SUM_TOLERANCE
        ), (total, d["end_to_end_ms"])
        assert d["phases_ms"]["sync"] >= 0 and "shares" in d
        # analyze + limit compose: the decomposition covers the FULL
        # trace even when the echoed spans are truncated.
        status, body = _request(server, f"/trace/{uuid}?analyze=1&limit=1")
        assert status == 200 and len(body["spans"]) == 1
        assert body["analysis"]["end_to_end_ms"] == d["end_to_end_ms"]

        # Hardening: structured 4xx on every malformed input.
        status, body = _request(server, "/trace/no-such-uuid")
        assert status == 404 and body["error"] == "unknown trace uuid"
        status, body = _request(server, f"/trace/{uuid}?analyze=2")
        assert status == 400 and "analyze" in body["error"]
        status, body = _request(server, f"/trace/{uuid}?limit=0")
        assert status == 400 and "limit" in body["error"]
        status, body = _request(server, "/trace?limit=-5")
        assert status == 400
        status, body = _request(server, "/trace?analyze=1")
        assert status == 400 and "uuid" in body["error"]


def test_metrics_prometheus_exposition(server):
    import urllib.request as _rq

    raw = (
        _rq.urlopen(
            f"http://127.0.0.1:{server.port}/metrics?format=prometheus",
            timeout=30,
        )
        .read()
        .decode()
    )
    lines = [ln for ln in raw.splitlines() if ln]
    assert lines and all(ln.startswith("dsst_") for ln in lines)
    assert any(ln.startswith("dsst_jobs_done ") for ln in lines)
    # String leaves (device info) render info-style: label on a 1 gauge.
    assert any(ln.startswith("dsst_device_platform{") for ln in lines)
    # The JSON form still serves (query param, not a breaking change).
    status, body = _request(server, "/metrics")
    assert status == 200 and "jobs_done" in body


def test_profile_window_endpoint(server, tmp_path):
    """POST /profile: a bounded jax.profiler window — 200 with the logdir,
    400 on a bad body, and self-closing so the node is never left tracing."""
    import os as _os
    import time as _time

    from distributed_sudoku_solver_tpu.utils import profiling

    status, _ = _request(server, "/profile", {"secs": -1})
    assert status == 400
    status, body = _request(
        server, "/profile", {"secs": 0.2, "logdir": str(tmp_path / "prof")}
    )
    assert status == 200
    assert body["secs"] == 0.2
    # Wait out the window so later tests see a closed profiler.
    deadline = _time.monotonic() + 15.0
    while profiling.profile_window_active() and _time.monotonic() < deadline:
        _time.sleep(0.05)
    assert not profiling.profile_window_active()
    # The capture directory exists once the window closed (jax writes the
    # trace data at stop time).
    assert _os.path.isdir(body["logdir"])


def test_access_log_opt_in(server, caplog):
    """Satellite: access logging routes through `logging` and is opt-in —
    silent by default, one INFO record per request when enabled."""
    import logging as _logging

    with caplog.at_level(_logging.INFO, logger="distributed_sudoku_solver_tpu.serving.http.access"):
        _request(server, "/stats")
        assert not [
            r for r in caplog.records if r.name.endswith("http.access")
        ], "access log must be opt-in"
        server.httpd.access_log = True
        try:
            _request(server, "/stats")
        finally:
            server.httpd.access_log = False
    access = [r for r in caplog.records if r.name.endswith("http.access")]
    assert access and "GET /stats" in access[-1].getMessage()
