"""Exact-cover family on the generic engine (BASELINE.json config 5).

The reference solves exactly one problem shape; these tests pin the second
family — generalized exact cover (primary/secondary columns) — on the same
lane-stack engine and the same multi-chip sharded path, including the
mutual cross-check of solving *Sudoku itself* through the cover kernels.
"""

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.models.cover import (
    decode_sudoku_cover,
    sudoku_clue_rows,
    sudoku_cover,
)
from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_4, SUDOKU_9
from distributed_sudoku_solver_tpu.models.nqueens import (
    decode_queens,
    is_valid_queens,
    nqueens_cover,
)
from distributed_sudoku_solver_tpu.models.pentomino import (
    decode_tiling,
    is_valid_tiling,
    pentomino_cover,
)
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.ops.solve import solve_batch, solve_csp
from distributed_sudoku_solver_tpu.parallel import make_mesh, solve_csp_sharded
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9

CFG = SolverConfig(min_lanes=16, stack_slots=128, max_steps=20_000)


def _roots(problem, n_jobs=1):
    return np.repeat(problem.initial_state()[None], n_jobs, axis=0)


@pytest.mark.parametrize("n", [4, 6, 8, 12])
def test_nqueens_solved_and_valid(n):
    p = nqueens_cover(n)
    res = solve_csp(_roots(p), p, CFG)
    assert bool(res.solved[0])
    queens = decode_queens(p, np.asarray(res.solution[0]), n)
    assert is_valid_queens(queens, n)


@pytest.mark.parametrize("n", [2, 3])
def test_nqueens_unsat_proven(n):
    p = nqueens_cover(n)
    res = solve_csp(_roots(p), p, SolverConfig(min_lanes=8, stack_slots=32))
    assert not bool(res.solved[0])
    assert bool(res.unsat[0])
    assert not bool(res.overflowed[0])


def test_pentomino_6x10_tiling():
    p = pentomino_cover(6, 10)
    cfg = SolverConfig(min_lanes=64, stack_slots=256, max_steps=50_000)
    res = solve_csp(_roots(p), p, cfg)
    assert bool(res.solved[0])
    grid = decode_tiling(p, np.asarray(res.solution[0]), 6, 10)
    assert is_valid_tiling(grid)


def test_sudoku_through_cover_engine_matches_native_kernel():
    """Solving Sudoku as exact cover must agree with the Sudoku kernels."""
    p = sudoku_cover(SUDOKU_9)
    root = p.state_with_rows_taken(sudoku_clue_rows(EASY_9))[None]
    res = solve_csp(root, p, CFG)
    assert bool(res.solved[0])
    via_cover = decode_sudoku_cover(p, np.asarray(res.solution[0]), 9)
    native = solve_batch(np.asarray(EASY_9, np.int32)[None], SUDOKU_9, CFG)
    assert np.array_equal(via_cover, np.asarray(native.solution[0]))


def test_cover_rejects_conflicting_clues():
    p = sudoku_cover(SUDOKU_4)
    grid = np.zeros((4, 4), np.int32)
    grid[0, 0] = 1
    grid[0, 1] = 1  # same digit twice in a row
    with pytest.raises(ValueError):
        p.state_with_rows_taken(sudoku_clue_rows(grid))


def test_nqueens_batch_multiple_jobs():
    """Several independent cover jobs share one frontier batch."""
    p = nqueens_cover(8)
    res = solve_csp(_roots(p, 4), p, CFG)
    assert np.asarray(res.solved).all()
    for j in range(4):
        q = decode_queens(p, np.asarray(res.solution[j]), 8)
        assert is_valid_queens(q, 8)


def test_cover_sharded_on_mesh():
    """The multi-chip path runs the cover family unchanged (8 CPU devices)."""
    p = nqueens_cover(10)
    cfg = SolverConfig(min_lanes=16, stack_slots=64, max_steps=20_000, ring_steal_k=4)
    res = solve_csp_sharded(_roots(p), p, cfg, mesh=make_mesh())
    assert bool(res.solved[0])
    q = decode_queens(p, np.asarray(res.solution[0]), 10)
    assert is_valid_queens(q, 10)


def test_count_all_nqueens_exact():
    """count_all enumeration: exact model counts on instances with known
    answers, matching the native C++ DFS over the identical matrix."""
    from distributed_sudoku_solver_tpu import native

    for n, expect in [(6, 4), (8, 92)]:
        p = nqueens_cover(n)
        cfg = SolverConfig(
            min_lanes=64, stack_slots=128, max_steps=100_000, count_all=True
        )
        res = solve_csp(_roots(p), p, cfg)
        assert int(res.sol_count[0]) == expect, f"n={n}"
        assert bool(res.unsat[0])  # exhausted == enumeration complete
        assert not bool(res.overflowed[0])
        if native.available():
            cnt, _ = native.cover_count(p)
            assert cnt == expect


def test_count_all_empty_4x4_sudoku():
    """All 288 complete 4x4 Sudoku grids, enumerated by the Sudoku path."""
    import jax.numpy as jnp

    empty = np.zeros((1, 4, 4), np.int32)
    cfg = SolverConfig(
        min_lanes=32, stack_slots=64, max_steps=100_000, count_all=True
    )
    res = solve_batch(jnp.asarray(empty), SUDOKU_4, cfg)
    assert int(res.sol_count[0]) == 288
    assert bool(res.unsat[0])
    # The first-found solution stays visible even though `solved` is False
    # by design under enumeration.
    from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution

    assert is_valid_solution(np.asarray(res.solution[0]), SUDOKU_4)


def test_sol_count_in_normal_mode():
    """Without count_all: sol_count is exactly 1 for solved jobs, 0 else,
    and verdicts are untouched (the field is additive, not behavioral)."""
    p = nqueens_cover(8)
    res = solve_csp(_roots(p), p, CFG)
    assert bool(res.solved[0])
    assert int(res.sol_count[0]) == 1


def test_count_all_overflow_is_lower_bound():
    """A 1-slot stack drops subtrees: overflow is flagged so the count is
    reported as a lower bound, never silently wrong."""
    p = nqueens_cover(8)
    cfg = SolverConfig(
        lanes=1, min_lanes=1, stack_slots=1, max_steps=100_000,
        count_all=True, steal=False,
    )
    res = solve_csp(_roots(p), p, cfg)
    assert bool(res.overflowed[0])
    assert int(res.sol_count[0]) <= 92


def test_count_all_sharded_exact():
    """Enumeration under the 8-device lane-sharded path: per-chip counts
    psum-merge to the exact global model count."""
    import jax

    from distributed_sudoku_solver_tpu.parallel import make_mesh, solve_csp_sharded

    p = nqueens_cover(8)
    cfg = SolverConfig(
        min_lanes=64, stack_slots=128, max_steps=100_000, count_all=True
    )
    res = solve_csp_sharded(_roots(p), p, cfg, mesh=make_mesh(jax.devices()))
    assert int(np.asarray(res.sol_count[0])) == 92
    assert bool(np.asarray(res.unsat[0]))
    assert not bool(np.asarray(res.overflowed[0]))
