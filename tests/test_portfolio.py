"""Portfolio racing (VERDICT r1 #10, SURVEY.md §2.2 EP analog).

The demonstration family is {HARD_9[0], its digit-mirror d -> 10-d}:
propagation and MRV are digit-relabel-invariant, but DFS *value order* is
not, so the mirror exactly swaps the ascending/descending costs.  Any fixed
digit order pays the slow side once; the portfolio pays the fast side
twice — min-over-configs of a heavy-tailed cost beats every fixed config.
"""

import time

import numpy as np
import pytest

import jax.numpy as jnp

from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
from distributed_sudoku_solver_tpu.ops.bitmask import highest_bit
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.ops.solve import solve_batch
from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
from distributed_sudoku_solver_tpu.serving.portfolio import race
from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution
from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9


def _mirror(board: np.ndarray) -> np.ndarray:
    return np.where(board > 0, 10 - board, 0).astype(np.int32)


def _cfg(rule: str) -> SolverConfig:
    # Single sequential lane: pure DFS, where value-order luck is maximal —
    # the regime the reference's own kernel always ran in.
    return SolverConfig(lanes=1, stack_slots=64, branch=rule, max_steps=20_000, steal=False)


RULES = ("minrem", "minrem-desc", "first")


def test_highest_bit():
    x = np.array([0, 1, 2, 3, 0b100110, 1 << 24], dtype=np.uint32)
    got = np.asarray(highest_bit(jnp.asarray(x)))
    np.testing.assert_array_equal(
        got, np.array([0, 1, 2, 2, 0b100000, 1 << 24], dtype=np.uint32)
    )


def test_minrem_desc_solves_same_unique_solution():
    grids = jnp.asarray(np.stack(HARD_9).astype(np.int32))
    # steal=False + one seed lane per job: independent sequential DFS per board.
    batch_cfg = lambda rule: SolverConfig(  # noqa: E731
        min_lanes=1, stack_slots=64, branch=rule, max_steps=20_000, steal=False
    )
    asc = solve_batch(grids, SUDOKU_9, batch_cfg("minrem"))
    desc = solve_batch(grids, SUDOKU_9, batch_cfg("minrem-desc"))
    assert np.asarray(asc.solved).all() and np.asarray(desc.solved).all()
    # Unique-solution boards: both orders reach the same grid.
    np.testing.assert_array_equal(np.asarray(asc.solution), np.asarray(desc.solution))


def test_portfolio_beats_every_single_config():
    """The VERDICT 'done' bar: a family where min-over-configs (what the
    race realizes) is strictly cheaper than every fixed config."""
    family = [np.asarray(HARD_9[0], np.int32), _mirror(np.asarray(HARD_9[0]))]
    steps = {
        rule: [
            int(solve_batch(jnp.asarray(b[None]), SUDOKU_9, _cfg(rule)).steps)
            for b in family
        ]
        for rule in RULES
    }
    portfolio_total = sum(min(steps[r][i] for r in RULES) for i in range(len(family)))
    for rule in RULES:
        assert portfolio_total < sum(steps[rule]), (
            f"portfolio {portfolio_total} does not beat {rule}: {steps}"
        )
    # And not marginally: the mirror construction makes it a >2x win.
    assert portfolio_total * 2 < min(sum(steps[r]) for r in RULES)


def test_race_first_verdict_wins_and_cancels_losers():
    eng = SolverEngine(chunk_steps=1, max_flights=8).start()
    try:
        board = np.asarray(HARD_9[0], np.int32)
        configs = [_cfg(r) for r in RULES]
        res = race(eng, board, configs, timeout=240)
        assert res.winner is not None
        assert res.winner.solved
        assert is_valid_solution(res.winner.solution)
        # Round-robin chunking is a fair scheduler: the fewest-steps config
        # (minrem: 16 vs 136/102 at one lane) reaches its verdict first.
        assert res.winner_index == 0
        for i, job in enumerate(res.jobs):
            assert job.wait(30)
            if i != res.winner_index:
                # Losers were cancelled mid-flight (or lost a photo finish).
                assert job.cancelled or job.solved or job.unsat
        # The engine is free again: no zombie flights.
        import time

        deadline = time.monotonic() + 10
        while eng._flights and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not eng._flights
    finally:
        eng.stop(timeout=2)


def test_cluster_race_spreads_and_cancels():
    """Fleet-level portfolio (ROADMAP r2 #6): racers dispatch to different
    members, the first verdict cancels the loser across the wire (CANCEL to
    its executing member lands mid-flight)."""
    from distributed_sudoku_solver_tpu.cluster.node import ClusterConfig
    from tests.test_cluster import _flight_node, _warm, wait_for

    ccfg = ClusterConfig(
        heartbeat_s=0.25, fail_factor=64.0, io_timeout_s=2.0, needwork=False
    )
    a = _flight_node(cluster_cfg=ccfg)
    b = _flight_node(anchor=a.addr, cluster_cfg=ccfg)
    try:
        assert wait_for(lambda: len(a.network) == 2 and len(b.network) == 2, timeout=30)
        _warm(a.engine)
        _warm(b.engine)
        base_a = a.engine.stats()["jobs_done"]
        base_b = b.engine.stats()["jobs_done"]
        res = a.race(
            np.asarray(HARD_9[0], np.int32),
            [_cfg("minrem"), _cfg("minrem-desc")],
            timeout=240,
        )
        assert res.winner is not None
        assert res.winner.solved
        assert is_valid_solution(res.winner.solution)
        for job in res.jobs:
            assert job.wait(60), "loser never resolved after cross-wire cancel"
        # Least-outstanding dispatch spread the racers over both members
        # (delta over the warm-up baseline, so this actually pins spread).
        assert a.engine.stats()["jobs_done"] >= base_a + 1
        assert b.engine.stats()["jobs_done"] >= base_b + 1
    finally:
        for n in (a, b):
            n.kill()
            n.engine.stop(timeout=1)


def test_race_unsat_verdict_wins():
    eng = SolverEngine(chunk_steps=4, max_flights=8).start()
    try:
        bad = np.zeros((9, 9), np.int32)
        bad[0, 0] = bad[0, 1] = 7
        res = race(eng, bad, [_cfg("minrem"), _cfg("minrem-desc")], timeout=240)
        assert res.winner is not None
        assert res.winner.unsat and not res.winner.solved
    finally:
        eng.stop(timeout=2)


def test_default_portfolio_includes_fused_axis_and_races():
    """Round 4: the default portfolio carries a fused racer; the race on a
    9x9 board reaches a correct verdict with all four axes live."""
    from distributed_sudoku_solver_tpu.serving.portfolio import DEFAULT_PORTFOLIO

    assert any(c.step_impl == "fused" for c in DEFAULT_PORTFOLIO)
    eng = SolverEngine(max_flights=8).start()
    try:
        res = race(
            eng, np.asarray(HARD_9[2], np.int32), DEFAULT_PORTFOLIO, timeout=240
        )
        assert res.winner is not None and res.winner.solved
        assert is_valid_solution(res.winner.solution)
    finally:
        eng.stop(timeout=2)


def test_fused_racer_misfit_downgrades_and_still_races():
    """On a geometry the fused kernel cannot serve, the engine downgrades
    the fused racer's flight to the composite step at launch — the racer
    serves correctly (no errored jobs) and the downgrade is recorded on
    the engine's metrics (VERDICT r4 #5; the docstring contract on
    DEFAULT_PORTFOLIO)."""
    from distributed_sudoku_solver_tpu.models.geometry import geometry_for_size
    from distributed_sudoku_solver_tpu.utils.puzzles import make_puzzle

    g25 = geometry_for_size(25)
    board = make_puzzle(g25, seed=11, n_clues=500, unique=False)  # propagation-easy
    configs = [
        SolverConfig(min_lanes=4, stack_slots=16, max_steps=4096),
        SolverConfig(
            min_lanes=4, stack_slots=64, max_steps=4096, step_impl="fused"
        ),  # 25x25 S=64: past the measured whole-array cap (48, round 5)
        #    -> downgraded at launch (S=16 fits fused since round 5)
    ]
    eng = SolverEngine(max_flights=8).start()
    try:
        res = race(eng, np.asarray(board, np.int32), configs, timeout=300)
        assert res.winner is not None and res.winner.solved
        fused_job = res.jobs[1]
        assert fused_job.wait(60)
        assert fused_job.error is None  # downgraded, not errored
        assert fused_job.solved or fused_job.cancelled
        assert eng.metrics()["fused_downgrades"] >= 1
    finally:
        eng.stop(timeout=2)


def test_cover_race_small_instance_finishes_at_native_speed():
    """Round 6 (VERDICT r5 missing #2b): small exact-cover jobs are served
    by the measured-winning engine.  n-queens-12 sits deep in the native
    DFS's winning regime (0.108 s class natively vs 0.409 s device on
    hardware; the device-entrant gap is far larger on the CPU test mesh),
    so the race must return the native count long before the device
    entrant finishes — and the count is the exact OEIS value."""
    from distributed_sudoku_solver_tpu import native
    from distributed_sudoku_solver_tpu.models.nqueens import nqueens_cover
    from distributed_sudoku_solver_tpu.serving.portfolio import (
        NATIVE_COVER_MAX_ROWS,
        race_cover,
    )

    if not native.available():
        pytest.skip("no native compiler in this environment")
    problem = nqueens_cover(12)
    assert problem.n_rows <= NATIVE_COVER_MAX_ROWS  # admission gate holds
    t0 = time.monotonic()
    res = race_cover(problem, timeout=120.0)
    wall = time.monotonic() - t0
    assert res.count == 14_200  # OEIS A000170(12), all solutions
    assert res.complete
    assert res.winner == "native", f"device won?! {res}"
    assert res.nodes > 0
    # "Native speed class": the race returns in single-digit seconds on a
    # loaded CI host (native alone is ~0.1-0.5 s) — far below the minutes
    # the CPU device entrant would need (its compile alone exceeds this).
    assert wall < 30.0, f"race took {wall:.1f}s — native result was not used"


def test_cover_race_device_covers_native_absence(monkeypatch):
    """With the native entrant unavailable, the device entrant alone must
    still produce the exact count (tiny instance: n-queens-5)."""
    from distributed_sudoku_solver_tpu import native
    from distributed_sudoku_solver_tpu.models.nqueens import nqueens_cover
    from distributed_sudoku_solver_tpu.serving.portfolio import race_cover

    monkeypatch.setattr(native, "available", lambda: False)
    res = race_cover(
        nqueens_cover(5),
        config=SolverConfig(
            min_lanes=16, stack_slots=16, count_all=True, max_steps=4096
        ),
        timeout=300.0,
    )
    assert res.winner == "device"
    assert res.count == 10  # A000170(5)
    assert res.complete


# -- the mirror partition, tested beyond construction (ISSUE 19 satellite) -----


def test_minrem_desc_mirror_explores_the_reflected_tree_exactly():
    """The docstring's relabel argument, pinned at bit level: d -> 10-d
    reverses value order but preserves MRV counts and cell tie-breaks, so
    ``minrem-desc`` on the mirror walks the EXACT tree ``minrem`` walks on
    the original — same nodes, same steps, mirrored solution.  This is the
    invariant that makes the asc/desc pair a work PARTITION: whatever one
    racer explores first, the other explores last, never twice."""
    b = np.asarray(HARD_9[0], np.int32)
    mb = _mirror(b)

    def run(board, rule):
        r = solve_batch(jnp.asarray(board[None]), SUDOKU_9, _cfg(rule))
        assert bool(r.solved[0])
        return int(r.nodes[0]), int(r.steps), np.asarray(r.solution[0])

    n_asc, s_asc, sol_asc = run(b, "minrem")
    n_dm, s_dm, sol_dm = run(mb, "minrem-desc")
    assert (n_asc, s_asc) == (n_dm, s_dm)
    np.testing.assert_array_equal(_mirror(sol_asc), sol_dm)

    n_desc, s_desc, _ = run(b, "minrem-desc")
    n_am, s_am, _ = run(mb, "minrem")
    assert (n_desc, s_desc) == (n_am, s_am)
    # And the pair is genuinely complementary on this board: one order is
    # much luckier than the other (the portfolio's whole reason to exist).
    assert n_asc != n_desc


def test_value_orders_partition_subtrees_no_duplicates():
    """'No duplicated subtree verdicts': exhaustive enumeration visits
    every model exactly once under EITHER value order, so asc and desc
    must report the identical exact count — a duplicated (or dropped)
    subtree would show up as a count mismatch."""
    from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9

    few = np.asarray(EASY_9, np.int32).copy()
    rng = np.random.default_rng(3)
    idx = np.flatnonzero(few.ravel())
    few.ravel()[rng.choice(idx, size=4, replace=False)] = 0  # 62 solutions
    grids = jnp.asarray(few[None])
    cfg = lambda rule: SolverConfig(  # noqa: E731
        min_lanes=8, stack_slots=32, branch=rule, max_steps=100_000,
        count_all=True,
    )
    asc = solve_batch(grids, SUDOKU_9, cfg("minrem"))
    desc = solve_batch(grids, SUDOKU_9, cfg("minrem-desc"))
    assert not bool(asc.overflowed[0]) and not bool(desc.overflowed[0])
    # 62 is the exhaustive count (pinned against the native DFS by
    # tests/test_fused_step.py) — matching it proves BOTH orders walked
    # the complete tree, not truncated-by-budget partials.
    assert int(asc.sol_count[0]) == int(desc.sol_count[0]) == 62


def test_branch_site_guess_sets_are_disjoint():
    """At a shared branch state the two orders pick the SAME cell (the key
    ignores direction) but disjoint first guesses (lowest vs highest
    candidate bit) — the root split each racer hands the other."""
    from distributed_sudoku_solver_tpu.ops import ordering as _ord
    from distributed_sudoku_solver_tpu.ops.bitmask import lowest_bit
    from distributed_sudoku_solver_tpu.ops.pallas_step import branch_onehot_full

    n = 9
    g = np.asarray(HARD_9[0], np.int64)
    m = np.full((n, n), (1 << n) - 1, dtype=np.int64)
    nz = g > 0
    m[nz] = np.int64(1) << (g[nz] - 1)
    m, status = _ord._np_propagate(m, SUDOKU_9)
    assert status == "open"

    cand = jnp.asarray(m[..., None].astype(np.uint32))  # boards-last [n, n, 1]
    one_asc = np.asarray(branch_onehot_full(cand, SUDOKU_9, "minrem"))
    one_desc = np.asarray(branch_onehot_full(cand, SUDOKU_9, "minrem-desc"))
    np.testing.assert_array_equal(one_asc, one_desc)  # same cell either way
    assert one_asc.sum() == 1

    r, c, _ = np.argwhere(one_asc)[0]
    cell = int(m[r, c])
    low = int(np.asarray(lowest_bit(jnp.asarray(np.uint32(cell)))))
    high = int(np.asarray(highest_bit(jnp.asarray(np.uint32(cell)))))
    assert low & high == 0  # disjoint first subtrees
    assert (low | high) & ~cell == 0  # both are real candidates
