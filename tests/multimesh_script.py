"""Helper process for tests/test_multihost.py::test_cross_process_mesh.

One controller process per mesh *half* (VERDICT r2 #3): the parent starts
two of these, each with 4 virtual CPU devices
(``--xla_force_host_platform_device_count=4``), joined into ONE
``jax.distributed`` runtime — so ``jax.devices()`` is a global 8-device
list spanning both OS processes.  Both controllers issue the identical
``solve_batch_sharded`` program over a global 8-device mesh; the
``shard_map`` body's collectives (``psum``/``pmin``/``ppermute`` ring
steals, ``parallel/sharded.py``) therefore cross the process boundary —
the multi-host data path the reference ran over sockets
(``/root/reference/DHT_Node.py:623-665``), here as XLA collectives the way
they would ride DCN on real multi-host TPU.

Each role dumps the full replicated result; the parent (which owns a
single-process 8-device mesh) asserts bit-identity against its own run of
the same program — the only difference between the two executions is the
process boundary in the middle of the mesh.
"""

import json
import os
import socket
import subprocess
import sys


def free_port() -> int:
    """Kernel-assigned free TCP port (shared by the multihost tests)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn_mesh_pair(workdir, devices_per_proc: int = 4, timeout: float = 240):
    """Launch the two mesh-half controllers; return [(returncode, output)].

    The one launch recipe shared by ``tests/test_multihost.py`` and
    ``__graft_entry__.dryrun_multichip`` (so env-scrub rules can't drift):
    scrub the TPU-tunnel trigger, force the CPU backend with
    ``devices_per_proc`` virtual devices, and prepend the repo to
    PYTHONPATH.  Every exit path reaps both children: a child that hangs
    is killed and reported via its returncode (never an uncaught
    TimeoutExpired), and a child that dies early can't orphan its sibling
    in a collective wait.
    """
    coord = free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={max(1, devices_per_proc)}"
    )
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                os.path.abspath(__file__),
                str(role),
                str(coord),
                str(workdir),
            ],
            env=env,
            cwd=repo,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for role in (0, 1)
    ]
    try:
        results = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            results.append((p.returncode, out.decode(errors="replace")))
        return results
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def main() -> None:
    role = int(sys.argv[1])
    coord_port = int(sys.argv[2])
    workdir = sys.argv[3]

    import jax

    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{coord_port}",
        num_processes=2,
        process_id=role,
    )

    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.parallel.mesh import make_mesh
    from distributed_sudoku_solver_tpu.parallel.sharded import solve_batch_sharded
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9

    out = {
        "process_count": jax.process_count(),
        "global_devices": jax.device_count(),
        "local_devices": jax.local_device_count(),
    }

    grids = np.stack([np.asarray(b) for b in HARD_9[:4]]).astype(np.int32)
    cfg = SolverConfig(min_lanes=32, stack_slots=32, max_steps=4096)

    mesh = make_mesh(jax.devices())  # 8 devices spanning both processes
    out["mesh_spans_processes"] = (
        len({d.process_index for d in mesh.devices.flat}) == 2
    )

    # Replicated global input: every process supplies the same host array.
    sharding = NamedSharding(mesh, P())
    garr = jax.make_array_from_callback(
        grids.shape, sharding, lambda idx: grids[idx]
    )
    res = solve_batch_sharded(garr, SUDOKU_9, cfg, mesh=mesh)

    # Out-specs are replicated, so every process holds the full result.
    out["solved"] = np.asarray(res.solved).tolist()
    out["solution"] = np.asarray(res.solution).tolist()
    out["nodes"] = np.asarray(res.nodes).tolist()
    out["steals"] = int(np.asarray(res.steals))
    out["steps"] = int(np.asarray(res.steps))

    with open(os.path.join(workdir, f"mesh_result{role}.json"), "w") as f:
        json.dump(out, f)
    jax.distributed.shutdown()
    os._exit(0)


if __name__ == "__main__":
    main()
