"""Board-sharded (band-parallel) solve: SURVEY.md §5.7's ring-exchange axis.

Each board's rows are sharded over the mesh; column-unit aggregates travel
around a ``ppermute`` ring each sweep.  The contract under test: results are
*bit-identical* to the single-device engine — same solutions, same node
counts, same branch order — because the collectives are exact all-reduces.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9, SUDOKU_16, SUDOKU_25
from distributed_sudoku_solver_tpu.ops.bitmask import once_twice_reduce, or_reduce
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.ops.solve import solve_batch
from distributed_sudoku_solver_tpu.parallel.board_sharded import (
    make_band_mesh,
    ring_once_twice,
    ring_or,
    solve_batch_banded,
)
from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution
from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9, make_puzzle


def _band_mesh(n: int) -> Mesh:
    return make_band_mesh(jax.devices()[:n])


def _assert_matches_single_device(grids, geom, cfg, mesh):
    ref = solve_batch(grids, geom, cfg)
    res = solve_batch_banded(grids, geom, cfg, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(res.solved), np.asarray(ref.solved))
    np.testing.assert_array_equal(np.asarray(res.solution), np.asarray(ref.solution))
    np.testing.assert_array_equal(np.asarray(res.nodes), np.asarray(ref.nodes))
    np.testing.assert_array_equal(np.asarray(res.unsat), np.asarray(ref.unsat))
    return res


def test_ring_reduces_match_global():
    """ring_or / ring_once_twice == the one-chip reduction of the full array."""
    n_dev = 4
    mesh = _band_mesh(n_dev)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 2**25, size=(n_dev * 3, 16), dtype=np.uint32)

    def local(xs, axis):
        o, t = once_twice_reduce(xs, 0)
        return ring_or(or_reduce(xs, 0), axis, n_dev), *ring_once_twice(
            o, t, axis, n_dev
        )

    from distributed_sudoku_solver_tpu.parallel.mesh import shard_map

    got = jax.jit(
        shard_map(
            functools.partial(local, axis=mesh.axis_names[0]),
            mesh=mesh,
            in_specs=jax.sharding.PartitionSpec(mesh.axis_names[0]),
            out_specs=jax.sharding.PartitionSpec(None),
            check_vma=False,
        )
    )(jnp.asarray(x))
    want_or = or_reduce(jnp.asarray(x), 0)
    want_o, want_t = once_twice_reduce(jnp.asarray(x), 0)
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(want_or))
    np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(want_o))
    np.testing.assert_array_equal(np.asarray(got[2]), np.asarray(want_t))


def test_9x9_exact_band_fit_bit_exact():
    """3 chips x 1 band: hard boards (real branching) match single-device."""
    grids = np.stack(HARD_9[:2]).astype(np.int32)
    cfg = SolverConfig(min_lanes=8, stack_slots=32, max_steps=4096)
    res = _assert_matches_single_device(grids, SUDOKU_9, cfg, _band_mesh(3))
    assert np.asarray(res.solved).all()
    assert int(np.asarray(res.nodes).sum()) > 0  # branching actually happened


def test_9x9_padded_bands_bit_exact():
    """8 chips over 3 bands: 5 chips hold only pad rows, still bit-exact."""
    grids = np.stack(HARD_9[:2]).astype(np.int32)
    cfg = SolverConfig(min_lanes=8, stack_slots=32, max_steps=4096)
    res = _assert_matches_single_device(grids, SUDOKU_9, cfg, _band_mesh(8))
    assert np.asarray(res.solved).all()


def test_16x16_banded():
    puzzles = np.stack(
        [make_puzzle(SUDOKU_16, seed=s, n_clues=170, unique=False) for s in (0, 1)]
    )
    cfg = SolverConfig(min_lanes=8, stack_slots=64, max_steps=20_000)
    res = solve_batch_banded(puzzles, SUDOKU_16, cfg, mesh=_band_mesh(4))
    assert np.asarray(res.solved).all()
    for j in range(puzzles.shape[0]):
        sol = np.asarray(res.solution[j])
        assert is_valid_solution(sol, SUDOKU_16)
        mask = puzzles[j] != 0
        assert np.array_equal(sol[mask], puzzles[j][mask])


def test_25x25_banded_bit_exact():
    """The giant-board config the reference's wire cap breaks on
    (``/root/reference/DHT_Node.py:94``, SURVEY.md §2.5 #8): one board's
    25 rows = 5 box bands over 5 chips."""
    puzzle = make_puzzle(SUDOKU_25, seed=3, n_clues=480, unique=False)
    cfg = SolverConfig(min_lanes=4, stack_slots=48, max_steps=50_000)
    res = _assert_matches_single_device(puzzle[None], SUDOKU_25, cfg, _band_mesh(5))
    assert bool(res.solved[0])
    assert is_valid_solution(np.asarray(res.solution[0]), SUDOKU_25)


def test_9x9_extended_rules_bit_exact():
    """rules='extended' (banded box-line reductions, VERDICT r1 #5): hard
    boards match the single-device extended solver bit-for-bit — same
    solutions AND same node counts, so the cross-chip pointing/claiming
    eliminations are exactly the unsharded ones."""
    grids = np.stack(HARD_9[:2]).astype(np.int32)
    cfg = SolverConfig(min_lanes=8, stack_slots=32, max_steps=4096, rules="extended")
    res = _assert_matches_single_device(grids, SUDOKU_9, cfg, _band_mesh(3))
    assert np.asarray(res.solved).all()


def test_9x9_extended_rules_padded_bands_bit_exact():
    grids = np.stack(HARD_9[:2]).astype(np.int32)
    cfg = SolverConfig(min_lanes=8, stack_slots=32, max_steps=4096, rules="extended")
    res = _assert_matches_single_device(grids, SUDOKU_9, cfg, _band_mesh(8))
    assert np.asarray(res.solved).all()


def test_25x25_extended_rules_banded():
    """The case board-sharding exists for: giant boards with the stronger
    inference.  Extended rules must close the board with no more nodes than
    basic (strictly stronger propagation) and stay bit-exact vs one device."""
    puzzle = make_puzzle(SUDOKU_25, seed=3, n_clues=480, unique=False)
    cfg = SolverConfig(min_lanes=4, stack_slots=48, max_steps=50_000, rules="extended")
    res = _assert_matches_single_device(puzzle[None], SUDOKU_25, cfg, _band_mesh(5))
    assert bool(res.solved[0])
    assert is_valid_solution(np.asarray(res.solution[0]), SUDOKU_25)
    basic = solve_batch_banded(
        puzzle[None],
        SUDOKU_25,
        SolverConfig(min_lanes=4, stack_slots=48, max_steps=50_000),
        mesh=_band_mesh(5),
    )
    assert int(res.nodes[0]) <= int(basic.nodes[0])


def test_12x12_extended_rules_rectangular_boxes():
    """Rectangular boxes exercise the transposed box layout in the banded
    columns direction (the misalignment trap box_line_one_direction's
    docstring warns about)."""
    from distributed_sudoku_solver_tpu.models.geometry import Geometry

    geom = Geometry(3, 4)  # 12x12, boxes 3 rows x 4 cols
    puzzle = make_puzzle(geom, seed=7, n_clues=75, unique=False)
    cfg = SolverConfig(min_lanes=8, stack_slots=32, max_steps=20_000, rules="extended")
    res = _assert_matches_single_device(puzzle[None], geom, cfg, _band_mesh(4))
    assert bool(res.solved[0])
    assert is_valid_solution(np.asarray(res.solution[0]), geom)


def test_banded_unsat_detected():
    """A row-duplicate contradiction is proven unsat across shards."""
    puzzle = np.stack(HARD_9[:1]).astype(np.int32)[0]
    r, c = np.argwhere(puzzle == 0)[0]
    row_digits = set(puzzle[r][puzzle[r] > 0])
    puzzle = puzzle.copy()
    puzzle[r, c] = next(iter(row_digits))
    cfg = SolverConfig(min_lanes=8, stack_slots=32, max_steps=4096)
    res = solve_batch_banded(puzzle[None], SUDOKU_9, cfg, mesh=_band_mesh(3))
    assert not bool(res.solved[0])
    assert bool(res.unsat[0])
