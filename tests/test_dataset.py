"""Dataset IO: native loader vs Python fallback, streaming, file solve."""

import numpy as np
import pytest

from distributed_sudoku_solver_tpu import native
from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig
from distributed_sudoku_solver_tpu.utils import dataset
from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution
from distributed_sudoku_solver_tpu.utils.puzzles import (
    EASY_9,
    HARD_9,
    puzzle_batch,
    to_line,
)


@pytest.fixture(scope="module")
def corpus():
    gen = puzzle_batch(SUDOKU_9, 10, seed=41, n_clues=30)
    return np.concatenate([np.stack([EASY_9, *HARD_9]), gen]).astype(np.int32)


def test_parse_roundtrip(corpus):
    blob = ("\n".join(to_line(b) for b in corpus) + "\n").encode()
    got = dataset.parse_boards(blob, SUDOKU_9)
    np.testing.assert_array_equal(got, corpus)


def test_parse_python_and_native_agree(corpus):
    blob = ("\n".join(to_line(b) for b in corpus) + "\n").encode()
    py = dataset._parse_python(blob, 9, allow_header=True)
    np.testing.assert_array_equal(py, corpus)
    if native.available():
        np.testing.assert_array_equal(native.parse_boards(blob, 9), corpus)


def test_parse_kaggle_csv_with_header(corpus):
    rows = [f"{to_line(b)},{to_line(b)}" for b in corpus]
    blob = ("quizzes,solutions\n" + "\n".join(rows) + "\n").encode()
    got = dataset.parse_boards(blob, SUDOKU_9)
    np.testing.assert_array_equal(got, corpus)


def test_parse_dot_notation():
    line = to_line(EASY_9).replace("0", ".")
    got = dataset.parse_boards((line + "\n").encode(), SUDOKU_9)
    np.testing.assert_array_equal(got[0], EASY_9)


def test_malformed_line_raises(corpus):
    blob = (to_line(corpus[0]) + "\nnot-a-board\n").encode()
    with pytest.raises(ValueError):
        dataset.parse_boards(blob, SUDOKU_9, allow_header=False)


def test_save_load_roundtrip(tmp_path, corpus):
    path = str(tmp_path / "boards.txt")
    dataset.save_boards(path, corpus)
    np.testing.assert_array_equal(dataset.load_boards(path, SUDOKU_9), corpus)


def test_iter_batches_streams_everything(tmp_path, corpus):
    big = np.tile(corpus, (20, 1, 1))
    path = str(tmp_path / "big.txt")
    dataset.save_boards(path, big)
    got = np.concatenate(list(dataset.iter_board_batches(path, SUDOKU_9, batch=64)))
    np.testing.assert_array_equal(got, big)


def test_solve_file_end_to_end(tmp_path, corpus):
    in_path = str(tmp_path / "in.txt")
    out_path = str(tmp_path / "out.txt")
    dataset.save_boards(in_path, corpus)
    stats = dataset.solve_file(
        in_path,
        out_path,
        SUDOKU_9,
        batch=8,
        bulk_config=BulkConfig(chunk=8, search_lanes=32),
    )
    assert stats["total"] == len(corpus) and stats["solved"] == len(corpus)
    sols = dataset.load_boards(out_path, SUDOKU_9)
    assert len(sols) == len(corpus)
    for g, s in zip(corpus, sols):
        assert is_valid_solution(s)
        assert ((g == 0) | (s == g)).all()


def test_whitespace_lines_skipped_like_python(corpus):
    blob = (to_line(corpus[0]) + "\n   \n\t\n" + to_line(corpus[1]) + "\n").encode()
    got = dataset.parse_boards(blob, SUDOKU_9, allow_header=False)
    np.testing.assert_array_equal(got, corpus[:2])
    py = dataset._parse_python(blob, 9, allow_header=False)
    np.testing.assert_array_equal(py, corpus[:2])


def test_streaming_error_index_is_file_absolute(tmp_path, corpus):
    path = str(tmp_path / "bad.txt")
    lines = [to_line(b) for b in np.tile(corpus, (40, 1, 1))]
    lines.insert(500, "xx-not-a-board")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    with pytest.raises(ValueError, match="data line 500"):
        for _ in dataset.iter_board_batches(path, SUDOKU_9, batch=64):
            pass


def test_solve_file_empty_input(tmp_path):
    in_path = str(tmp_path / "empty.txt")
    out_path = str(tmp_path / "out.txt")
    open(in_path, "w").close()
    stats = dataset.solve_file(in_path, out_path, SUDOKU_9, batch=8)
    assert stats == {"total": 0, "solved": 0, "unsat": 0, "searched": 0}
    assert open(out_path).read() == ""


def test_right_length_bad_first_line_raises_not_skips(corpus):
    # A first line with correct length but an invalid char is a malformed
    # board, NOT a header: silently skipping it would misalign every output.
    bad = "x" * 81  # 'x'=33 > 9, right length
    blob = (bad + "\n" + to_line(corpus[0]) + "\n").encode()
    with pytest.raises(ValueError):
        dataset.parse_boards(blob, SUDOKU_9, allow_header=True)
    py_err = None
    try:
        dataset._parse_python(blob, 9, allow_header=True)
    except ValueError as e:
        py_err = e
    assert py_err is not None


def test_padded_and_uppercase_lines_parse_same(corpus):
    line = "  " + to_line(corpus[0]).upper() + "  "
    blob = (line + "\n").encode()
    got = dataset.parse_boards(blob, SUDOKU_9, allow_header=False)
    py = dataset._parse_python(blob, 9, allow_header=False)
    np.testing.assert_array_equal(got, corpus[:1])
    np.testing.assert_array_equal(py, corpus[:1])


def test_space_before_comma_parses_like_python(corpus):
    blob = (to_line(corpus[0]) + " ,solutioncolumn\n").encode()
    got = dataset.parse_boards(blob, SUDOKU_9, allow_header=False)
    py = dataset._parse_python(blob, 9, allow_header=False)
    np.testing.assert_array_equal(got, corpus[:1])
    np.testing.assert_array_equal(py, corpus[:1])
