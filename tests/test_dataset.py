"""Dataset IO: native loader vs Python fallback, streaming, file solve."""

import numpy as np
import pytest

from distributed_sudoku_solver_tpu import native
from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig
from distributed_sudoku_solver_tpu.utils import dataset
from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution
from distributed_sudoku_solver_tpu.utils.puzzles import (
    EASY_9,
    HARD_9,
    puzzle_batch,
    to_line,
)


@pytest.fixture(scope="module")
def corpus():
    gen = puzzle_batch(SUDOKU_9, 10, seed=41, n_clues=30)
    return np.concatenate([np.stack([EASY_9, *HARD_9]), gen]).astype(np.int32)


def test_parse_roundtrip(corpus):
    blob = ("\n".join(to_line(b) for b in corpus) + "\n").encode()
    got = dataset.parse_boards(blob, SUDOKU_9)
    np.testing.assert_array_equal(got, corpus)


def test_parse_python_and_native_agree(corpus):
    blob = ("\n".join(to_line(b) for b in corpus) + "\n").encode()
    py = dataset._parse_python(blob, 9, allow_header=True)
    np.testing.assert_array_equal(py, corpus)
    if native.available():
        np.testing.assert_array_equal(native.parse_boards(blob, 9), corpus)


def test_parse_kaggle_csv_with_header(corpus):
    rows = [f"{to_line(b)},{to_line(b)}" for b in corpus]
    blob = ("quizzes,solutions\n" + "\n".join(rows) + "\n").encode()
    got = dataset.parse_boards(blob, SUDOKU_9)
    np.testing.assert_array_equal(got, corpus)


def test_parse_dot_notation():
    line = to_line(EASY_9).replace("0", ".")
    got = dataset.parse_boards((line + "\n").encode(), SUDOKU_9)
    np.testing.assert_array_equal(got[0], EASY_9)


def test_malformed_line_raises(corpus):
    blob = (to_line(corpus[0]) + "\nnot-a-board\n").encode()
    with pytest.raises(ValueError):
        dataset.parse_boards(blob, SUDOKU_9, allow_header=False)


def test_save_load_roundtrip(tmp_path, corpus):
    path = str(tmp_path / "boards.txt")
    dataset.save_boards(path, corpus)
    np.testing.assert_array_equal(dataset.load_boards(path, SUDOKU_9), corpus)


def test_iter_batches_streams_everything(tmp_path, corpus):
    big = np.tile(corpus, (20, 1, 1))
    path = str(tmp_path / "big.txt")
    dataset.save_boards(path, big)
    got = np.concatenate(list(dataset.iter_board_batches(path, SUDOKU_9, batch=64)))
    np.testing.assert_array_equal(got, big)


def test_solve_file_end_to_end(tmp_path, corpus):
    in_path = str(tmp_path / "in.txt")
    out_path = str(tmp_path / "out.txt")
    dataset.save_boards(in_path, corpus)
    stats = dataset.solve_file(
        in_path,
        out_path,
        SUDOKU_9,
        batch=8,
        bulk_config=BulkConfig(chunk=8),
    )
    assert stats["total"] == len(corpus) and stats["solved"] == len(corpus)
    sols = dataset.load_boards(out_path, SUDOKU_9)
    assert len(sols) == len(corpus)
    for g, s in zip(corpus, sols):
        assert is_valid_solution(s)
        assert ((g == 0) | (s == g)).all()


def test_solve_file_resumes_after_crash_byte_identical(tmp_path, corpus):
    """Kill solve-file mid-run, rerun, byte-identical output (VERDICT #6)."""
    import distributed_sudoku_solver_tpu.ops.bulk as bulk_mod

    big = np.tile(corpus, (3, 1, 1))  # 42 boards -> 6 batches of 8
    in_path = str(tmp_path / "in.txt")
    dataset.save_boards(in_path, big)
    cfg = BulkConfig(chunk=8)

    ref_path = str(tmp_path / "ref.txt")
    dataset.solve_file(in_path, ref_path, SUDOKU_9, batch=8, bulk_config=cfg)

    out_path = str(tmp_path / "out.txt")
    real = bulk_mod.solve_bulk
    calls = {"n": 0}

    def dying(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 3:
            raise KeyboardInterrupt("simulated kill")
        return real(*a, **kw)

    bulk_mod.solve_bulk = dying
    try:
        with np.testing.assert_raises(KeyboardInterrupt):
            dataset.solve_file(in_path, out_path, SUDOKU_9, batch=8, bulk_config=cfg)
    finally:
        bulk_mod.solve_bulk = real

    import os

    assert os.path.exists(out_path + ".partial")  # partial output survives
    assert os.path.exists(out_path + ".progress")
    stats = dataset.solve_file(in_path, out_path, SUDOKU_9, batch=8, bulk_config=cfg)
    assert stats["total"] == len(big) and stats["solved"] == len(big)
    assert stats["unresolved"] == 0
    assert open(out_path, "rb").read() == open(ref_path, "rb").read()
    assert not os.path.exists(out_path + ".partial")
    assert not os.path.exists(out_path + ".progress")


def test_solve_file_resume_ignores_stale_partial_without_progress(tmp_path, corpus):
    in_path = str(tmp_path / "in.txt")
    out_path = str(tmp_path / "out.txt")
    dataset.save_boards(in_path, corpus)
    with open(out_path + ".partial", "wb") as f:
        f.write(b"garbage from an unrelated run\n")
    stats = dataset.solve_file(
        in_path, out_path, SUDOKU_9, batch=8, bulk_config=BulkConfig(chunk=8)
    )
    assert stats["solved"] == len(corpus)
    sols = dataset.load_boards(out_path, SUDOKU_9)
    assert len(sols) == len(corpus)


def test_solve_file_resume_rejects_other_runs_sidecar(tmp_path, corpus):
    """A progress sidecar from a different input must not be resumed."""
    import distributed_sudoku_solver_tpu.ops.bulk as bulk_mod

    cfg = BulkConfig(chunk=8)
    in_a = str(tmp_path / "a.txt")
    in_b = str(tmp_path / "b.txt")
    out_path = str(tmp_path / "out.txt")
    dataset.save_boards(in_a, np.tile(corpus, (2, 1, 1)))
    dataset.save_boards(in_b, corpus[::-1].copy())

    real = bulk_mod.solve_bulk
    calls = {"n": 0}

    def dying(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            raise KeyboardInterrupt("simulated kill")
        return real(*a, **kw)

    bulk_mod.solve_bulk = dying
    try:
        with np.testing.assert_raises(KeyboardInterrupt):
            dataset.solve_file(in_a, out_path, SUDOKU_9, batch=8, bulk_config=cfg)
    finally:
        bulk_mod.solve_bulk = real
    import os

    assert os.path.exists(out_path + ".progress")

    # Same out_path, different input: sidecar must be discarded, not spliced.
    stats = dataset.solve_file(in_b, out_path, SUDOKU_9, batch=8, bulk_config=cfg)
    assert stats["total"] == len(corpus)
    sols = dataset.load_boards(out_path, SUDOKU_9)
    assert len(sols) == len(corpus)
    for g, s in zip(corpus[::-1], sols):
        assert is_valid_solution(s)
        assert ((g == 0) | (s == g)).all()


def test_whitespace_lines_skipped_like_python(corpus):
    blob = (to_line(corpus[0]) + "\n   \n\t\n" + to_line(corpus[1]) + "\n").encode()
    got = dataset.parse_boards(blob, SUDOKU_9, allow_header=False)
    np.testing.assert_array_equal(got, corpus[:2])
    py = dataset._parse_python(blob, 9, allow_header=False)
    np.testing.assert_array_equal(py, corpus[:2])


def test_streaming_error_index_is_file_absolute(tmp_path, corpus):
    path = str(tmp_path / "bad.txt")
    lines = [to_line(b) for b in np.tile(corpus, (40, 1, 1))]
    lines.insert(500, "xx-not-a-board")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    with pytest.raises(ValueError, match="data line 500"):
        for _ in dataset.iter_board_batches(path, SUDOKU_9, batch=64):
            pass


def test_solve_file_empty_input(tmp_path):
    in_path = str(tmp_path / "empty.txt")
    out_path = str(tmp_path / "out.txt")
    open(in_path, "w").close()
    stats = dataset.solve_file(in_path, out_path, SUDOKU_9, batch=8)
    assert stats == {
        "total": 0, "solved": 0, "unsat": 0, "searched": 0, "unresolved": 0,
    }
    assert open(out_path).read() == ""


def test_right_length_bad_first_line_raises_not_skips(corpus):
    # A first line with correct length but an invalid char is a malformed
    # board, NOT a header: silently skipping it would misalign every output.
    bad = "x" * 81  # 'x'=33 > 9, right length
    blob = (bad + "\n" + to_line(corpus[0]) + "\n").encode()
    with pytest.raises(ValueError):
        dataset.parse_boards(blob, SUDOKU_9, allow_header=True)
    py_err = None
    try:
        dataset._parse_python(blob, 9, allow_header=True)
    except ValueError as e:
        py_err = e
    assert py_err is not None


def test_padded_and_uppercase_lines_parse_same(corpus):
    line = "  " + to_line(corpus[0]).upper() + "  "
    blob = (line + "\n").encode()
    got = dataset.parse_boards(blob, SUDOKU_9, allow_header=False)
    py = dataset._parse_python(blob, 9, allow_header=False)
    np.testing.assert_array_equal(got, corpus[:1])
    np.testing.assert_array_equal(py, corpus[:1])


def test_space_before_comma_parses_like_python(corpus):
    blob = (to_line(corpus[0]) + " ,solutioncolumn\n").encode()
    got = dataset.parse_boards(blob, SUDOKU_9, allow_header=False)
    py = dataset._parse_python(blob, 9, allow_header=False)
    np.testing.assert_array_equal(got, corpus[:1])
    np.testing.assert_array_equal(py, corpus[:1])


def test_solve_file_stages_actually_overlap(tmp_path, monkeypatch):
    """VERDICT r2 weak #4: the reader/solver/writer software pipeline was
    claimed but never proven to overlap.  Instrument all three stages with
    sleeps + wall-clock intervals and assert (a) a solve interval overlaps
    a read interval AND a write interval, and (b) total wall clock beats
    the serial sum — on any host, no device timing involved.
    """
    import time

    from distributed_sudoku_solver_tpu.ops import bulk as bulk_mod

    n_batches, batch = 5, 8
    in_path = tmp_path / "boards.txt"
    line = to_line(np.asarray(EASY_9))
    in_path.write_text("\n".join([line] * (n_batches * batch)) + "\n")

    stage_sleep = 0.12
    intervals: dict[str, list] = {"read": [], "solve": [], "write": []}

    real_iter = dataset.iter_board_batches

    def slow_iter(path, geom, b):
        for boards in real_iter(path, geom, b):
            t0 = time.monotonic()
            time.sleep(stage_sleep)
            intervals["read"].append((t0, time.monotonic()))
            yield boards

    def slow_solve(boards, geom, cfg):
        t0 = time.monotonic()
        time.sleep(stage_sleep)
        k = len(boards)
        out = bulk_mod.BulkResult(
            solution=np.repeat(np.asarray(EASY_9)[None], k, axis=0),
            solved=np.ones(k, bool),
            unsat=np.zeros(k, bool),
            by_propagation=np.ones(k, bool),
            searched=0,
        )
        intervals["solve"].append((t0, time.monotonic()))
        return out

    real_format = dataset._format_lines

    def slow_format(boards):
        t0 = time.monotonic()
        time.sleep(stage_sleep)
        out = real_format(boards)
        intervals["write"].append((t0, time.monotonic()))
        return out

    monkeypatch.setattr(dataset, "iter_board_batches", slow_iter)
    monkeypatch.setattr(bulk_mod, "solve_bulk", slow_solve)
    monkeypatch.setattr(dataset, "_format_lines", slow_format)

    t0 = time.monotonic()
    stats = dataset.solve_file(
        str(in_path), str(tmp_path / "out.txt"), SUDOKU_9, batch=batch
    )
    wall = time.monotonic() - t0
    assert stats["total"] == n_batches * batch
    assert stats["solved"] == n_batches * batch

    def overlaps(a, b):
        return any(s1 < e2 and s2 < e1 for s1, e1 in a for s2, e2 in b)

    assert overlaps(intervals["solve"], intervals["read"]), (
        "reader never ran concurrently with a solve"
    )
    assert overlaps(intervals["solve"], intervals["write"]), (
        "writer never ran concurrently with a solve"
    )
    serial = 3 * n_batches * stage_sleep
    assert wall < serial * 0.85, (
        f"pipeline gave no speedup: wall {wall:.2f}s vs serial {serial:.2f}s"
    )
