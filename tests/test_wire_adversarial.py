"""Adversarial wire-layer + cluster-churn tests (VERDICT r1 #9).

The reference trusted the network completely: `pickle.loads` on every
datagram (an RCE in any non-classroom setting, SURVEY.md §2.3) and no
framing, so garbage or truncation corrupted state silently.  Here the
contract is: a node must survive — and keep serving — arbitrary bytes,
oversized frames, truncated frames, duplicates, and stale views; and the
membership layer must converge through sustained join/leave/kill churn
under continuous job load.
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.cluster import wire
from distributed_sudoku_solver_tpu.cluster.node import ClusterNode
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9

from tests.test_cluster import make_node, wait_for


def _raw_send(addr, payload: bytes) -> None:
    with socket.create_connection(addr, timeout=2) as s:
        s.sendall(payload)


@pytest.fixture
def node():
    n = make_node()
    yield n
    n.kill()
    n.engine.stop(timeout=1)


def _assert_still_serving(n: ClusterNode) -> None:
    job = n.submit(EASY_9)
    assert job.wait(10) and job.solved


def test_garbage_bytes_survived(node):
    _raw_send(node.addr, b"\x00\x00\x00\x05hello")  # framed non-JSON
    _raw_send(node.addr, b"not even a frame")
    _assert_still_serving(node)


def test_oversized_frame_rejected(node):
    # Length prefix far beyond MAX_FRAME: the server must refuse without
    # allocating or reading the body.
    _raw_send(node.addr, struct.pack(">I", 1 << 30))
    _assert_still_serving(node)


def test_truncated_frame_survived(node):
    # Claim 100 bytes, send 3, hang up.
    _raw_send(node.addr, struct.pack(">I", 100) + b"abc")
    _assert_still_serving(node)


def test_non_dict_and_missing_method_survived(node):
    import json

    for bad in ([1, 2, 3], "hi", {"no_method": True}, None):
        data = json.dumps(bad).encode()
        _raw_send(node.addr, struct.pack(">I", len(data)) + data)
    _assert_still_serving(node)


def test_unknown_method_survived(node):
    wire.send_msg(node.addr, {"method": "FROBNICATE", "x": 1}, 2.0)
    _assert_still_serving(node)


# -- WireError delivery-ambiguity flavors (round 10) --------------------------
#
# Retry paths branch on WireError.ambiguous_delivery: False proves the
# frame never reached the peer (safe to re-dispatch with no duplicate
# possible), True means bytes were written first (the peer MAY have
# processed the frame — re-dispatch is at-least-once and receivers must
# dedupe).  Both flavors pinned here against the real socket layer.


def test_connect_failure_is_unambiguous():
    # Nothing listens on port 1: the connect itself fails, so no byte was
    # ever written — delivery provably did not happen.
    with pytest.raises(wire.WireError) as ei:
        wire.send_msg(("127.0.0.1", 1), {"method": "X"}, 0.5)
    assert ei.value.ambiguous_delivery is False
    with pytest.raises(wire.WireError) as ei:
        wire.request(("127.0.0.1", 1), {"method": "X"}, 0.5)
    assert ei.value.ambiguous_delivery is False


def test_reply_timeout_after_bytes_written_is_ambiguous():
    # A server that accepts, reads the whole request, and never replies:
    # the failure happens strictly after the frame went out, so the peer
    # may have processed it — the retry layer must assume at-least-once.
    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]
    served = threading.Event()

    def serve_once():
        conn, _ = srv.accept()
        with conn:
            wire.recv_msg(conn)
            served.wait(5)  # hold the connection open, never reply

    t = threading.Thread(target=serve_once, daemon=True)
    t.start()
    try:
        with pytest.raises(wire.WireError) as ei:
            wire.request(("127.0.0.1", port), {"method": "PING"}, 0.5)
        assert ei.value.ambiguous_delivery is True
    finally:
        served.set()
        srv.close()


def test_send_failure_after_connect_is_ambiguous(monkeypatch):
    # A frame that dies mid-sendall (reset after the connect): some bytes
    # may be in the peer's buffers.  Forced deterministically — a real
    # loopback reset races kernel buffering.
    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]

    def boom(sock, msg):
        raise OSError("connection reset by peer (forced)")

    monkeypatch.setattr(wire, "_send_frame", boom)
    try:
        with pytest.raises(wire.WireError) as ei:
            wire.send_msg(("127.0.0.1", port), {"method": "X"}, 0.5)
        assert ei.value.ambiguous_delivery is True
    finally:
        srv.close()


def test_oversize_frame_refused_before_send_is_unambiguous():
    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]
    try:
        with pytest.raises(wire.WireError) as ei:
            wire.send_msg(
                ("127.0.0.1", port),
                {"method": "X", "pad": "x" * (wire.MAX_FRAME + 1)},
                2.0,
            )
        # The size check rejects before any byte is written.
        assert ei.value.ambiguous_delivery is False
    finally:
        srv.close()


def test_handler_fuzz_malformed_fields(node):
    """Round-10 satellite: drive the dispatch layer with truncated /
    missing-field / wrong-typed messages for EVERY method and assert the
    node logs-and-drops each one — no wedged accept loop, no leaked lock,
    no garbage installed into the membership view."""
    methods = [
        "JOIN_REQ", "UPDATE_NETWORK", "HEARTBEAT", "NODE_FAILED", "LEAVE",
        "TASK", "SOLUTION", "CANCEL", "NEEDWORK", "SUBTASK", "PART_RESULT",
        "PROGRESS", "STATS_REQ",
    ]
    cases = []
    for m in methods:
        cases.append({"method": m})  # every field missing
        cases.append(  # every field present, every type wrong
            {
                "method": m, "addr": 123, "uuid": {}, "part": [], "root": 7,
                "grid": "not-a-grid", "origin": None, "network": 42,
                "coordinator": [], "term": "x", "epoch": None, "from": 9,
                "rows": {"shape": "x", "data": "!!not-base64!!"},
                "nodes": "NaN", "solved": "y", "unsat": {}, "solution": "z",
                "config": "bogus", "report_to": 1, "error": 0,
            }
        )
    cases += [
        # Structurally plausible but hostile membership frames: a valid-form
        # address that was never a member, and frames naming the node itself
        # dead — neither may corrupt the view.
        {"method": "NODE_FAILED", "addr": "203.0.113.1:9"},
        {"method": "LEAVE", "addr": node.addr_s},
        {"method": "NODE_FAILED", "addr": node.addr_s},
        {"method": "UPDATE_NETWORK", "network": [1, 2], "coordinator": "a:1",
         "term": 99, "epoch": 99},
        {"method": "SUBTASK", "part": "p#x", "root": "r", "report_to": "1:1",
         "rows": {"shape": [1, 9, 9], "data": "AAAA"}},  # truncated payload
        {"method": "PROGRESS", "uuid": "u", "rows": "nope", "nodes": 1},
    ]
    before = list(node.network)
    for msg in cases:
        wire.send_msg(node.addr, msg, 2.0)
    # Drain: all conn threads log-and-drop, nothing wedges.
    _assert_still_serving(node)
    # The lock is not leaked by any failed handler.
    assert node._lock.acquire(timeout=2), "node lock leaked by a fuzz case"
    node._lock.release()
    # Membership is untouched: no garbage members, node still in its view.
    assert node.network == before
    assert node.addr_s in node.network
    assert all(isinstance(m, str) and ":" in m for m in node.network)
    # Views still render.
    node.metrics_view()
    node.network_view()


def test_duplicate_join_idempotent(node):
    peer = make_node(anchor=node.addr)
    try:
        assert wait_for(lambda: len(node.network) == 2)
        for _ in range(3):  # replayed JOIN_REQs must not duplicate members
            wire.send_msg(
                node.addr, {"method": "JOIN_REQ", "addr": peer.addr_s}, 2.0
            )
        time.sleep(0.3)
        assert len(node.network) == 2
        assert sorted(set(node.network)) == sorted(node.network)
    finally:
        peer.kill()
        peer.engine.stop(timeout=1)


def test_stale_view_dropped(node):
    peer = make_node(anchor=node.addr)
    try:
        assert wait_for(lambda: len(peer.network) == 2)
        term, epoch = peer.net_term, peer.net_epoch
        # Replay an older (term, epoch) view claiming the peer is alone:
        # must be ignored, not installed (out-of-order UPDATE_NETWORK).
        wire.send_msg(
            peer.addr,
            {
                "method": "UPDATE_NETWORK",
                "network": [peer.addr_s],
                "coordinator": peer.addr_s,
                "term": term,
                "epoch": max(0, epoch - 1),
            },
            2.0,
        )
        time.sleep(0.3)
        assert len(peer.network) == 2
        assert peer.coordinator == node.addr_s
    finally:
        peer.kill()
        peer.engine.stop(timeout=1)


def test_duplicate_solution_message_ignored(node):
    """A replayed SOLUTION for an already-settled uuid is a no-op."""
    grid = np.asarray(EASY_9, dtype=np.int32)
    payload = {
        "method": "SOLUTION",
        "uuid": "nonexistent-uuid",
        "solved": True,
        "unsat": False,
        "nodes": 1,
        "error": None,
        "solution": grid.tolist(),
    }
    for _ in range(2):
        wire.send_msg(node.addr, payload, 2.0)
    _assert_still_serving(node)


@pytest.mark.slow
def test_churn_soak_under_load():
    """Sustained join/leave/kill churn with jobs in flight throughout.

    Every job submitted to the stable anchor must resolve correctly even as
    other members die mid-execution and newcomers join; the view must
    converge back to the survivor set after every cycle.

    Duration defaults to ~40 s; set ``DSST_SOAK_SECS`` for a long-haul lane
    (e.g. ``DSST_SOAK_SECS=7200 pytest -m slow -k churn`` for the 2-hour
    leak lane, VERDICT r2 #6).

    Leak assertions: RSS and open-fd counts are sampled throughout; after
    a warmup third (compile caches and socket pools legitimately grow
    early), the fitted RSS slope must stay under 1 MB/min and the fd count
    must return to within a small constant of its post-warmup level — so a
    slow per-cycle leak in the engine/cluster threads fails the lane
    instead of passing every functional check (VERDICT r2 weak #7).
    """
    import os

    def rss_mb() -> float:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
        return 0.0

    def fd_count() -> int:
        return len(os.listdir("/proc/self/fd"))

    soak_secs = float(os.environ.get("DSST_SOAK_SECS", "40"))
    a = make_node()
    extras: list[ClusterNode] = [make_node(anchor=a.addr) for _ in range(2)]
    assert wait_for(lambda: len(a.network) == 3, timeout=30)

    results = []
    done_ok = [0]
    pump_failures: list[str] = []
    stop = threading.Event()
    samples: list[tuple[float, float, int]] = []  # (t, rss_mb, fds)

    def pump():
        while not stop.is_set():
            job = a.submit(EASY_9)
            results.append(job)
            time.sleep(0.05)
            # Validate-and-discard resolved jobs as we go: retaining every
            # handle (with its solution array) for hours would read as an
            # RSS leak in the measurement below — harness growth, not a
            # product leak.  Failures are recorded, not asserted: an
            # AssertionError in a daemon thread dies silently and the
            # popped job would vanish from the finally-block recheck.
            while results and results[0].done.is_set():
                j = results.pop(0)
                if not j.solved:
                    pump_failures.append(f"job {j.uuid} ended unsolved")
                    stop.set()
                    return
                done_ok[0] += 1

    pump_t = threading.Thread(target=pump, daemon=True)
    pump_t.start()
    try:
        t0 = time.monotonic()
        deadline = t0 + soak_secs
        sample_every = max(5.0, soak_secs / 120.0)  # <= ~120 samples
        next_sample = t0
        cycle = 0
        while time.monotonic() < deadline:
            cycle += 1
            if time.monotonic() >= next_sample:
                samples.append((time.monotonic() - t0, rss_mb(), fd_count()))
                next_sample += sample_every
            # Kill one member abruptly (odd cycles) or leave gracefully.
            victim = extras.pop(0)
            if cycle % 2:
                victim.kill()
            else:
                victim.stop(graceful=True)
            victim.engine.stop(timeout=1)
            assert wait_for(
                lambda: len(a.network) == 1 + len(extras), timeout=20
            ), f"view never converged after removal (cycle {cycle})"
            newcomer = make_node(anchor=a.addr)
            extras.append(newcomer)
            assert wait_for(
                lambda: len(a.network) == 1 + len(extras), timeout=20
            ), f"view never converged after join (cycle {cycle})"
        assert cycle >= 3, "soak too short to mean anything"
        # Leak-curve evidence prints BEFORE any load-correctness assertion:
        # the round-4 device-backed run lost its whole 2 h RSS/fd record
        # because a pump failure (a real recovery bug, since fixed) raised
        # first — a soak must never discard the measurements it ran for.
        samples.append((time.monotonic() - t0, rss_mb(), fd_count()))
        warm = samples[len(samples) // 3 :]  # drop compile/pool warmup
        if len(warm) >= 5:
            ts = np.asarray([s[0] for s in warm])
            rss = np.asarray([s[1] for s in warm])
            slope_mb_per_min = float(np.polyfit(ts, rss, 1)[0]) * 60.0
            fd_delta = warm[-1][2] - warm[0][2]
            print(
                f"soak leak curve: {len(samples)} samples over "
                f"{samples[-1][0]:.0f}s, rss {samples[0][1]:.1f} -> "
                f"{samples[-1][1]:.1f} MB, post-warmup slope "
                f"{slope_mb_per_min:.3f} MB/min, fd {samples[0][2]} -> "
                f"{samples[-1][2]}"
            )
            # The slope assertions need a long window: in a sub-10-minute
            # lane the post-warmup fit spans seconds, where <1 MB of
            # allocator/GC noise already exceeds any sane threshold.  The
            # curve prints for every lane; only the DSST_SOAK_SECS
            # long-haul lane enforces it.
            if soak_secs >= 600:
                assert slope_mb_per_min < 1.0, (
                    f"RSS grows {slope_mb_per_min:.2f} MB/min post-warmup: "
                    f"{[(round(t), round(r, 1)) for t, r, _ in samples]}"
                )
                assert fd_delta <= 8, (
                    f"fd count drifted by {fd_delta} post-warmup: "
                    f"{[(round(t), f) for t, _, f in samples]}"
                )
    finally:
        stop.set()
        pump_t.join(5)
        for j in results:
            assert j.wait(30), "a job was lost in the churn"
            assert j.solved
        assert not pump_failures, pump_failures
        assert done_ok[0] + len(results) >= 3, "pump barely ran"
        # Counters on killed members die with them, so the surviving view's
        # totals legitimately undercount; assert shape + liveness only.
        stats = a.stats_view()
        assert stats["all"]["solved"] > 0
        assert len(stats["nodes"]) == len(a.network)
        for n in (a, *extras):
            n.kill()
            n.engine.stop(timeout=1)
