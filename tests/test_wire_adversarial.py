"""Adversarial wire-layer + cluster-churn tests (VERDICT r1 #9).

The reference trusted the network completely: `pickle.loads` on every
datagram (an RCE in any non-classroom setting, SURVEY.md §2.3) and no
framing, so garbage or truncation corrupted state silently.  Here the
contract is: a node must survive — and keep serving — arbitrary bytes,
oversized frames, truncated frames, duplicates, and stale views; and the
membership layer must converge through sustained join/leave/kill churn
under continuous job load.
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.cluster import wire
from distributed_sudoku_solver_tpu.cluster.node import ClusterNode
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9

from tests.test_cluster import make_node, wait_for


def _raw_send(addr, payload: bytes) -> None:
    with socket.create_connection(addr, timeout=2) as s:
        s.sendall(payload)


@pytest.fixture
def node():
    n = make_node()
    yield n
    n.kill()
    n.engine.stop(timeout=1)


def _assert_still_serving(n: ClusterNode) -> None:
    job = n.submit(EASY_9)
    assert job.wait(10) and job.solved


def test_garbage_bytes_survived(node):
    _raw_send(node.addr, b"\x00\x00\x00\x05hello")  # framed non-JSON
    _raw_send(node.addr, b"not even a frame")
    _assert_still_serving(node)


def test_oversized_frame_rejected(node):
    # Length prefix far beyond MAX_FRAME: the server must refuse without
    # allocating or reading the body.
    _raw_send(node.addr, struct.pack(">I", 1 << 30))
    _assert_still_serving(node)


def test_truncated_frame_survived(node):
    # Claim 100 bytes, send 3, hang up.
    _raw_send(node.addr, struct.pack(">I", 100) + b"abc")
    _assert_still_serving(node)


def test_non_dict_and_missing_method_survived(node):
    import json

    for bad in ([1, 2, 3], "hi", {"no_method": True}, None):
        data = json.dumps(bad).encode()
        _raw_send(node.addr, struct.pack(">I", len(data)) + data)
    _assert_still_serving(node)


def test_unknown_method_survived(node):
    wire.send_msg(node.addr, {"method": "FROBNICATE", "x": 1}, 2.0)
    _assert_still_serving(node)


def test_duplicate_join_idempotent(node):
    peer = make_node(anchor=node.addr)
    try:
        assert wait_for(lambda: len(node.network) == 2)
        for _ in range(3):  # replayed JOIN_REQs must not duplicate members
            wire.send_msg(
                node.addr, {"method": "JOIN_REQ", "addr": peer.addr_s}, 2.0
            )
        time.sleep(0.3)
        assert len(node.network) == 2
        assert sorted(set(node.network)) == sorted(node.network)
    finally:
        peer.kill()
        peer.engine.stop(timeout=1)


def test_stale_view_dropped(node):
    peer = make_node(anchor=node.addr)
    try:
        assert wait_for(lambda: len(peer.network) == 2)
        term, epoch = peer.net_term, peer.net_epoch
        # Replay an older (term, epoch) view claiming the peer is alone:
        # must be ignored, not installed (out-of-order UPDATE_NETWORK).
        wire.send_msg(
            peer.addr,
            {
                "method": "UPDATE_NETWORK",
                "network": [peer.addr_s],
                "coordinator": peer.addr_s,
                "term": term,
                "epoch": max(0, epoch - 1),
            },
            2.0,
        )
        time.sleep(0.3)
        assert len(peer.network) == 2
        assert peer.coordinator == node.addr_s
    finally:
        peer.kill()
        peer.engine.stop(timeout=1)


def test_duplicate_solution_message_ignored(node):
    """A replayed SOLUTION for an already-settled uuid is a no-op."""
    grid = np.asarray(EASY_9, dtype=np.int32)
    payload = {
        "method": "SOLUTION",
        "uuid": "nonexistent-uuid",
        "solved": True,
        "unsat": False,
        "nodes": 1,
        "error": None,
        "solution": grid.tolist(),
    }
    for _ in range(2):
        wire.send_msg(node.addr, payload, 2.0)
    _assert_still_serving(node)


@pytest.mark.slow
def test_churn_soak_under_load():
    """Sustained join/leave/kill churn with jobs in flight throughout.

    Every job submitted to the stable anchor must resolve correctly even as
    other members die mid-execution and newcomers join; the view must
    converge back to the survivor set after every cycle.

    Duration defaults to ~40 s; set ``DSST_SOAK_SECS`` for a long-haul lane
    (e.g. ``DSST_SOAK_SECS=1800 pytest -m slow -k churn``).
    """
    import os

    soak_secs = float(os.environ.get("DSST_SOAK_SECS", "40"))
    a = make_node()
    extras: list[ClusterNode] = [make_node(anchor=a.addr) for _ in range(2)]
    assert wait_for(lambda: len(a.network) == 3, timeout=30)

    results = []
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            job = a.submit(EASY_9)
            results.append(job)
            time.sleep(0.05)

    pump_t = threading.Thread(target=pump, daemon=True)
    pump_t.start()
    try:
        deadline = time.monotonic() + soak_secs
        cycle = 0
        while time.monotonic() < deadline:
            cycle += 1
            # Kill one member abruptly (odd cycles) or leave gracefully.
            victim = extras.pop(0)
            if cycle % 2:
                victim.kill()
            else:
                victim.stop(graceful=True)
            victim.engine.stop(timeout=1)
            assert wait_for(
                lambda: len(a.network) == 1 + len(extras), timeout=20
            ), f"view never converged after removal (cycle {cycle})"
            newcomer = make_node(anchor=a.addr)
            extras.append(newcomer)
            assert wait_for(
                lambda: len(a.network) == 1 + len(extras), timeout=20
            ), f"view never converged after join (cycle {cycle})"
        assert cycle >= 3, "soak too short to mean anything"
    finally:
        stop.set()
        pump_t.join(5)
        for j in results:
            assert j.wait(30), "a job was lost in the churn"
            assert j.solved
        # Counters on killed members die with them, so the surviving view's
        # totals legitimately undercount; assert shape + liveness only.
        stats = a.stats_view()
        assert stats["all"]["solved"] > 0
        assert len(stats["nodes"]) == len(a.network)
        for n in (a, *extras):
            n.kill()
            n.engine.stop(timeout=1)
