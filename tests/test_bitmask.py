import numpy as np
import jax.numpy as jnp
import pytest

from distributed_sudoku_solver_tpu.models.geometry import (
    SUDOKU_4,
    SUDOKU_6,
    SUDOKU_9,
    SUDOKU_16,
    SUDOKU_25,
    Geometry,
    geometry_for_size,
)
from distributed_sudoku_solver_tpu.ops.bitmask import (
    decode_grid,
    encode_grid,
    from_boxes,
    lowest_bit,
    mask_to_value,
    once_twice_reduce,
    or_reduce,
    popcount,
    to_boxes,
)


def test_geometry_props():
    assert SUDOKU_9.n == 9 and SUDOKU_9.full_mask == 0x1FF
    assert SUDOKU_25.n == 25 and SUDOKU_25.full_mask == (1 << 25) - 1
    assert SUDOKU_6.n_vboxes == 3 and SUDOKU_6.n_hboxes == 2
    assert geometry_for_size(9) is SUDOKU_9
    with pytest.raises(ValueError):
        Geometry(6, 6)  # 36 digits exceed uint32
    with pytest.raises(ValueError):
        geometry_for_size(7)


def test_popcount_lowest_bit():
    x = jnp.asarray(np.arange(0, 1 << 10, dtype=np.uint32))
    pc = np.asarray(popcount(x))
    lb = np.asarray(lowest_bit(x))
    for v in range(1, 1 << 10):
        assert pc[v] == bin(v).count("1")
        assert lb[v] == v & -v
    assert lb[0] == 0


def test_encode_decode_roundtrip():
    rng = np.random.default_rng(0)
    for geom in (SUDOKU_4, SUDOKU_9, SUDOKU_16, SUDOKU_25):
        grid = rng.integers(0, geom.n + 1, size=(geom.n, geom.n))
        cand = encode_grid(grid, geom)
        dec = np.asarray(decode_grid(cand))
        # Given cells decode back; empty cells decode to 0 (full mask != single)
        assert np.array_equal(dec[grid > 0], grid[grid > 0])
        assert np.all(dec[grid == 0] == (0 if geom.n > 1 else dec[grid == 0]))
        assert np.asarray(cand)[grid == 0][0] == geom.full_mask if (grid == 0).any() else True


def test_mask_to_value_all_digits():
    for geom in (SUDOKU_9, SUDOKU_25):
        masks = jnp.asarray(np.uint32(1) << np.arange(geom.n, dtype=np.uint32))
        vals = np.asarray(mask_to_value(masks))
        assert np.array_equal(vals, np.arange(1, geom.n + 1))


def test_or_reduce_matches_numpy():
    rng = np.random.default_rng(1)
    x = rng.integers(0, 1 << 25, size=(5, 9, 9)).astype(np.uint32)
    for ax in range(3):
        got = np.asarray(or_reduce(jnp.asarray(x), ax))
        want = np.bitwise_or.reduce(x, axis=ax)
        assert np.array_equal(got, want)


def test_once_twice_reduce():
    rng = np.random.default_rng(2)
    for width in (3, 9, 16, 25):
        x = rng.integers(0, 1 << 20, size=(7, width)).astype(np.uint32)
        once, twice = once_twice_reduce(jnp.asarray(x), -1)
        once, twice = np.asarray(once), np.asarray(twice)
        for row in range(7):
            counts = np.zeros(32, dtype=int)
            for v in x[row]:
                for b in range(32):
                    counts[b] += (int(v) >> b) & 1
            want_once = sum(1 << b for b in range(32) if counts[b] >= 1)
            want_twice = sum(1 << b for b in range(32) if counts[b] >= 2)
            assert once[row] == want_once
            assert twice[row] == want_twice


def test_boxes_roundtrip_and_grouping():
    for geom in (SUDOKU_4, SUDOKU_6, SUDOKU_9, SUDOKU_16):
        n = geom.n
        grid = jnp.asarray(np.arange(n * n, dtype=np.uint32).reshape(n, n))
        boxes = np.asarray(to_boxes(grid, geom))
        # Box b, cell k should be cell (row, col) of box b in row-major order.
        for b in range(n):
            br, bc = divmod(b, geom.n_hboxes)
            for k in range(n):
                kr, kc = divmod(k, geom.box_w)
                r = br * geom.box_h + kr
                c = bc * geom.box_w + kc
                assert boxes[b, k] == r * n + c
        back = np.asarray(from_boxes(jnp.asarray(boxes), geom))
        assert np.array_equal(back, np.asarray(grid))
