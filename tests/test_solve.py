import numpy as np
import jax.numpy as jnp
import pytest

from distributed_sudoku_solver_tpu.models.geometry import (
    SUDOKU_4,
    SUDOKU_9,
    SUDOKU_16,
    SUDOKU_25,
)
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.ops.solve import solve_batch, solve_one
from distributed_sudoku_solver_tpu.utils.oracle import (
    is_valid_solution,
    solve_oracle,
)
from distributed_sudoku_solver_tpu.utils.puzzles import (
    EASY_9,
    HARD_9,
    make_puzzle,
    puzzle_batch,
    random_solution,
)


def _check_matches_oracle(puzzles, res, geom):
    for i, p in enumerate(puzzles):
        assert bool(res.solved[i]), f"puzzle {i} unsolved"
        sol = np.asarray(res.solution[i])
        assert is_valid_solution(sol, geom)
        assert np.array_equal(sol[p > 0], p[p > 0]), "clues not preserved"
        assert np.array_equal(sol, solve_oracle(p, geom)), f"puzzle {i} != oracle"


def test_embedded_corpus_bit_exact_vs_oracle():
    batch = np.stack([EASY_9] + HARD_9)
    res = solve_batch(jnp.asarray(batch), SUDOKU_9)
    _check_matches_oracle(batch, res, SUDOKU_9)
    assert not np.asarray(res.overflowed).any()


def test_generated_batch_bit_exact_vs_oracle():
    batch = puzzle_batch(SUDOKU_9, 8, seed=100, n_clues=24)
    res = solve_batch(jnp.asarray(batch), SUDOKU_9)
    _check_matches_oracle(batch, res, SUDOKU_9)


def test_reference_branch_order_mode():
    batch = np.stack([EASY_9] + HARD_9)
    cfg = SolverConfig(branch="first")
    res = solve_batch(jnp.asarray(batch), SUDOKU_9, cfg)
    _check_matches_oracle(batch, res, SUDOKU_9)


def test_batch_equals_per_puzzle():
    # SURVEY.md §4 #2: vmap/batch results must equal per-puzzle results.
    batch = puzzle_batch(SUDOKU_9, 4, seed=7, n_clues=26)
    res = solve_batch(jnp.asarray(batch), SUDOKU_9)
    for i, p in enumerate(batch):
        sol, one = solve_one(p, SUDOKU_9)
        assert bool(one.solved[0])
        assert np.array_equal(sol, np.asarray(res.solution[i]))


def test_unsat_proven():
    bad = EASY_9.copy()
    bad[0, 0] = bad[0, 1] = 5
    empty_unsat = np.zeros((9, 9), int)
    empty_unsat[0, :8] = range(1, 9)
    empty_unsat[1, 8] = 9  # cell (0,8) has no candidate left
    for grid in (bad, empty_unsat):
        res = solve_batch(jnp.asarray(grid[None]), SUDOKU_9)
        assert not bool(res.solved[0])
        assert bool(res.unsat[0])
        assert solve_oracle(grid) is None


def test_multi_solution_returns_some_valid_solution():
    # Two empty cells swappable -> 2 solutions; any valid one is acceptable
    # in fast mode (unique-solution puzzles are bit-exact by construction).
    sol = random_solution(SUDOKU_9, 17)
    p = sol.copy()
    # blank a pair of cells that forms a rectangle with two digits
    p[0, 0] = p[0, 1] = p[1, 0] = p[1, 1] = 0
    res = solve_batch(jnp.asarray(p[None]), SUDOKU_9)
    assert bool(res.solved[0])
    assert is_valid_solution(np.asarray(res.solution[0]), SUDOKU_9)


def test_empty_board_all_geometries():
    for geom in (SUDOKU_4, SUDOKU_9):
        empty = np.zeros((geom.n, geom.n), int)
        sol, res = solve_one(empty, geom)
        assert bool(res.solved[0])
        assert is_valid_solution(sol, geom)


def test_16x16():
    geom = SUDOKU_16
    batch = np.stack(
        [make_puzzle(geom, s, n_clues=140, unique=False) for s in range(2)]
    )
    res = solve_batch(jnp.asarray(batch), geom)
    for i, p in enumerate(batch):
        assert bool(res.solved[i])
        sol = np.asarray(res.solution[i])
        assert is_valid_solution(sol, geom)
        assert np.array_equal(sol[p > 0], p[p > 0])


@pytest.mark.slow
def test_25x25():
    geom = SUDOKU_25
    p = make_puzzle(geom, 0, n_clues=420, unique=False)
    sol, res = solve_one(p, geom, SolverConfig(stack_slots=192))
    assert bool(res.solved[0])
    assert is_valid_solution(sol, geom)
    assert np.array_equal(sol[p > 0], p[p > 0])


def test_nodes_counter_populated():
    batch = np.stack(HARD_9[:2])
    res = solve_batch(jnp.asarray(batch), SUDOKU_9)
    nodes = np.asarray(res.nodes)
    assert (nodes >= 0).all()
    assert int(res.expansions) == nodes.sum()
    # Inkala boards need actual search
    assert nodes.sum() > 0


def test_mixed_branch_rule_solves_and_validates():
    import numpy as np

    from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution
    from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

    grids = np.stack([EASY_9, *HARD_9]).astype(np.int32)
    cfg = SolverConfig(min_lanes=16, stack_slots=32, branch="mixed")
    res = solve_batch(grids, SUDOKU_9, cfg)
    assert np.asarray(res.solved).all()
    for s in np.asarray(res.solution):
        assert is_valid_solution(s)


def test_multi_round_steal_equivalent_results():
    import numpy as np

    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9

    grids = np.stack(HARD_9).astype(np.int32)
    r1 = solve_batch(grids, SUDOKU_9, SolverConfig(min_lanes=64, stack_slots=32))
    r4 = solve_batch(
        grids, SUDOKU_9, SolverConfig(min_lanes=64, stack_slots=32, steal_rounds=4)
    )
    np.testing.assert_array_equal(np.asarray(r1.solved), np.asarray(r4.solved))
    np.testing.assert_array_equal(np.asarray(r1.solution), np.asarray(r4.solution))
    # more pairings may not reduce steps, but must never break verdicts
    assert int(r4.steals) >= 0


def test_branch_k3_solves_and_proves_unsat():
    """branch_k=3 (two singleton children + rest per expansion) is a
    distinct deterministic strategy: same verdicts, valid solutions, and a
    sound unsat proof with the double-push stack bookkeeping.  Measured
    neutral-to-slightly-negative on the bulk corpus (BENCHMARKS.md), so the
    default stays binary; this pins the gated path's correctness."""
    import numpy as np

    from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution
    from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

    grids = np.stack([EASY_9, *HARD_9]).astype(np.int32)
    cfg = SolverConfig(min_lanes=16, stack_slots=32, branch_k=3)
    res = solve_batch(grids, SUDOKU_9, cfg)
    assert np.asarray(res.solved).all()
    for g, s in zip(grids, np.asarray(res.solution)):
        assert is_valid_solution(s)
        assert np.array_equal(s[g > 0], g[g > 0])

    deep = np.asarray(HARD_9[1]).copy()
    deep[1, 6] = 8  # consistent-looking wrong clue: deep unsat search
    r = solve_batch(
        np.asarray(deep[None]),
        SUDOKU_9,
        SolverConfig(min_lanes=4, stack_slots=32, branch="first", branch_k=3),
    )
    assert bool(r.unsat[0]) and not bool(r.solved[0])
