"""Static-analysis gate (distributed_sudoku_solver_tpu/analysis): the
tier-1 wiring that turns an invariant regression into a test failure
instead of a review-round catch.

Lanes:
* fixture lane — one violating and one clean synthetic module per rule
  (tests/data/analysis), driven through the checkers with injected
  configs, pinning that each rule actually FIRES (a linter that never
  fires passes any tree);
* the gate — `python -m distributed_sudoku_solver_tpu.analysis` over the
  real package tree exits 0 (all findings fixed or reason-waived), never
  imports jax, and finishes inside the acceptance budget;
* determinism — two runs produce byte-identical --json reports;
* contract cross-pins — the *ck-family exit codes are one scheme
  (obs/exitcodes.py) and the simnet runtime guard's banned list covers
  clockck's sleep/monotonic half (one list, two lanes).
"""

import json
import subprocess
import sys
import time
from pathlib import Path

from distributed_sudoku_solver_tpu.analysis import clockck, layerck, lockck, syncck
from distributed_sudoku_solver_tpu.analysis import manifest
from distributed_sudoku_solver_tpu.analysis.__main__ import main, run
from distributed_sudoku_solver_tpu.analysis.common import SourceModule
from distributed_sudoku_solver_tpu.obs import exitcodes, promck, traceck

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "data" / "analysis"


def load(name: str, modname=None) -> SourceModule:
    path = FIXTURES / name
    return SourceModule(path, name, modname)


# -- layerck fixtures ----------------------------------------------------------

def test_layerck_fires_on_nested_import_and_third_party():
    mod = load("layer_bad.py", modname="layer_bad")
    layers = {
        "layer_bad": {
            "closed": True,
            "allow": ("allowed_layer",),
            "third_party": (),
        }
    }
    findings = layerck.check_module(mod, layers)
    msgs = {(f.line, f.waived) for f in findings}
    assert len(findings) == 2, findings
    # The nested-in-function import is seen and attributed to its line.
    nested_line = next(
        i + 1
        for i, ln in enumerate(mod.text.splitlines())
        if "forbidden_layer" in ln
    )
    assert (nested_line, False) in msgs
    # Open-layer form catches the same nested import via forbid.
    open_layers = {"layer_bad": {"closed": False, "forbid": ("forbidden_layer",)}}
    open_findings = layerck.check_module(mod, open_layers)
    assert [f.line for f in open_findings] == [nested_line]


def test_layerck_clean_fixture():
    mod = load("layer_ok.py", modname="layer_ok")
    layers = {
        "layer_ok": {"closed": True, "allow": ("allowed_layer",), "third_party": ()}
    }
    assert layerck.check_module(mod, layers) == []


def test_layerck_declared_exception_carves_out():
    # The real tree's one declared up-import: ops -> serving.faults.
    mod = load("layer_bad.py", modname="layer_bad")
    layers = {
        "layer_bad": {
            "closed": False,
            "forbid": ("forbidden_layer",),
            "except": ("forbidden_layer.thing",),
        }
    }
    assert layerck.check_module(mod, layers) == []


# -- clockck fixtures ----------------------------------------------------------

def _clock_findings(mod):
    return clockck.check_module(
        mod,
        manifest.CLOCK_SCOPED_DIRS,
        manifest.CLOCK_BANNED_CALLS,
        manifest.CLOCK_SEAMS,
        scope_all=True,
    )


def test_clockck_fires_on_alias_rename_and_capture():
    findings = _clock_findings(load("clock_bad.py"))
    live = [f for f in findings if not f.waived]
    # _t.sleep, mono(), _grab() — the three laundering shapes.
    assert len(live) == 3, findings
    dotted = " ".join(f.message for f in live)
    assert "time.sleep" in dotted and "time.monotonic" in dotted


def test_clockck_clean_fixture_reference_default_and_waiver():
    findings = _clock_findings(load("clock_ok.py"))
    assert [f for f in findings if not f.waived] == []
    waived = [f for f in findings if f.waived]
    assert len(waived) == 1 and waived[0].reason  # the reasoned sleep


# -- syncck fixtures -----------------------------------------------------------

def _sync_findings(name):
    return syncck.check_module(
        load(name),
        scoped_files=(name,),
        hot_regions={name: ("Hot.step",)},
        seam_funcs=manifest.SYNC_SEAM_FUNCS,
        host_sources=manifest.SYNC_HOST_SOURCES,
        numpy_calls=manifest.SYNC_NUMPY_CALLS,
        method_calls=manifest.SYNC_METHOD_CALLS,
        jax_calls=manifest.SYNC_JAX_CALLS,
    )


def test_syncck_fires_on_unproven_asarray_and_hot_int():
    findings = _sync_findings("sync_bad.py")
    live = [f for f in findings if not f.waived]
    assert len(live) == 2, findings
    kinds = " ".join(f.message for f in live)
    assert "np.asarray" in kinds and "int()" in kinds


def test_syncck_host_proof_and_waiver():
    findings = _sync_findings("sync_ok.py")
    assert [f for f in findings if not f.waived] == []
    waived = [f for f in findings if f.waived]
    assert len(waived) == 1 and waived[0].reason  # the literal-data waiver


# -- lockck fixtures -----------------------------------------------------------

def test_lockck_fires_on_unlocked_helper_write():
    findings = lockck.check_modules([load("lock_bad.py")])
    assert len(findings) == 1 and not findings[0].waived
    assert "hits" in findings[0].message


def test_lockck_clean_fixture_with_block_suffix_and_subscript():
    assert lockck.check_modules([load("lock_ok.py")]) == []


def test_lockck_cross_module_write_checks_base_lock(tmp_path):
    # The http.py shape: another module bumps engine.fault_bulk_retries —
    # OK under `with engine._lock:`, flagged bare.
    decl = tmp_path / "decl.py"
    decl.write_text(
        "class E:\n"
        "    def __init__(self):\n"
        "        self.jobs = 0  # lockck: guard(_lock)\n"
    )
    writer = tmp_path / "writer.py"
    writer.write_text(
        "def good(engine):\n"
        "    with engine._lock:\n"
        "        engine.jobs += 1\n"
        "def bad(engine):\n"
        "    engine.jobs += 1\n"
    )
    mods = [
        SourceModule(decl, "decl.py", None),
        SourceModule(writer, "writer.py", None),
    ]
    findings = lockck.check_modules(mods)
    assert [(f.path, f.line) for f in findings] == [("writer.py", 5)]


def test_clockck_catches_two_level_datetime_and_ns_family(tmp_path):
    # Review-round finding: `import datetime; datetime.datetime.now()`
    # and the perf_counter/*_ns spellings used to launder straight
    # through.
    p = tmp_path / "w.py"
    p.write_text(
        "import datetime\nimport time\n\n\n"
        "def f():\n"
        "    a = datetime.datetime.now()\n"
        "    b = time.perf_counter()\n"
        "    c = time.monotonic_ns()\n"
        "    return a, b, c\n"
    )
    findings = clockck.check_module(
        SourceModule(p, "w.py", None),
        manifest.CLOCK_SCOPED_DIRS,
        manifest.CLOCK_BANNED_CALLS,
        {},
        scope_all=True,
    )
    dotted = " ".join(f.message for f in findings)
    assert len(findings) == 3, findings
    assert "datetime.now" in dotted
    assert "time.perf_counter" in dotted and "time.monotonic_ns" in dotted


def test_lockck_self_writes_scope_to_the_declaring_class(tmp_path):
    # Review-round finding: the registry used to key on the bare attr
    # name, so an unrelated class's own (unguarded) `admitted` was
    # falsely constrained by another class's declaration.
    p = tmp_path / "two.py"
    p.write_text(
        "import threading\n\n\n"
        "class Guarded:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.admitted = 0  # lockck: guard(_lock)\n\n"
        "    def bad(self):\n"
        "        self.admitted += 1\n\n\n"
        "class Unrelated:\n"
        "    def __init__(self):\n"
        "        self.admitted = 0\n\n"
        "    def fine(self):\n"
        "        self.admitted += 1\n"
    )
    findings = lockck.check_modules([SourceModule(p, "two.py", None)])
    assert [f.line for f in findings] == [10], findings


def test_runner_refuses_empty_scan_root(tmp_path, capsys):
    # Review-round finding: a typo'd --root used to report "0 violations
    # over 0 files" and exit 0 — a gate that checks nothing must fail
    # as a tool error, not pass.
    assert main(["--root", str(tmp_path / "nope")]) == exitcodes.EXIT_INTERNAL
    capsys.readouterr()


# -- waiver grammar ------------------------------------------------------------

def test_waiver_without_reason_stays_a_violation(tmp_path):
    p = tmp_path / "w.py"
    p.write_text(
        "import time as _t\n\n\ndef f():\n    _t.sleep(1)  # clockck: allow()\n"
    )
    findings = clockck.check_module(
        SourceModule(p, "w.py", None),
        manifest.CLOCK_SCOPED_DIRS,
        manifest.CLOCK_BANNED_CALLS,
        {},
        scope_all=True,
    )
    assert len(findings) == 1 and not findings[0].waived
    assert "no reason" in findings[0].message


def test_def_level_waiver_covers_the_function(tmp_path):
    p = tmp_path / "w.py"
    p.write_text(
        "import time as _t\n\n\n"
        "def f():  # clockck: allow(whole function is a declared simulator)\n"
        "    _t.sleep(1)\n    _t.sleep(2)\n"
    )
    findings = clockck.check_module(
        SourceModule(p, "w.py", None),
        manifest.CLOCK_SCOPED_DIRS,
        manifest.CLOCK_BANNED_CALLS,
        {},
        scope_all=True,
    )
    assert len(findings) == 2 and all(f.waived for f in findings)


# -- stale-waiver detection ----------------------------------------------------

def test_stale_waiver_reported_and_used_waiver_is_not(tmp_path, capsys):
    # One real violation whose waiver is consumed, one waiver whose rule
    # no longer fires on that line: only the second is stale.
    p = tmp_path / "w.py"
    p.write_text(
        "import time as _t\n\n\n"
        "def f():\n"
        "    _t.sleep(1)  # clockck: allow(declared simulator pace)\n"
        "    x = 1  # clockck: allow(left behind after a refactor)\n"
        "    return x\n"
    )
    from distributed_sudoku_solver_tpu.analysis.common import stale_waivers

    mod = SourceModule(p, "w.py", None)
    findings = clockck.check_module(
        mod,
        manifest.CLOCK_SCOPED_DIRS,
        manifest.CLOCK_BANNED_CALLS,
        {},
        scope_all=True,
    )
    assert [f.waived for f in findings] == [True]
    stale = stale_waivers([mod], ("clockck",))
    assert stale == [("w.py", 6, "clockck", "left behind after a refactor")]
    # Scoped to the rules that RAN: clockck's waiver is not stale just
    # because only lockck ran this time.
    assert stale_waivers([mod], ("lockck",)) == []


def test_strict_waivers_gates_the_exit_code(tmp_path, capsys):
    p = tmp_path / "w.py"
    p.write_text("x = 1  # clockck: allow(rule never fires here)\n")
    root = str(tmp_path)
    # Report-only by default; --strict-waivers turns stale into exit 1.
    assert main(["--root", root]) == exitcodes.EXIT_CLEAN
    assert main(["--root", root, "--strict-waivers"]) == exitcodes.EXIT_VIOLATIONS
    # Scoping: the stale clockck waiver is invisible to a lockck-only run.
    assert (
        main(["--root", root, "--rule", "lockck", "--strict-waivers"])
        == exitcodes.EXIT_CLEAN
    )
    out = capsys.readouterr()
    assert "stale-waiver" in out.out


def test_update_golden_requires_jaxck(capsys):
    assert main(["--update-golden"]) == exitcodes.EXIT_INTERNAL
    capsys.readouterr()


def test_package_tree_has_no_stale_waivers():
    report, _ = run()
    assert report["stale_waivers"] == [], report["stale_waivers"]


# -- the tier-1 gate -----------------------------------------------------------

def test_runner_clean_and_jax_free_over_package():
    """The acceptance pin: all five fast rules over the real tree, exit 0, no
    jax in the process, inside the <5 s budget (measured ~1 s; the budget
    includes interpreter start on a loaded 2-core container)."""
    code = (
        "import sys\n"
        "from distributed_sudoku_solver_tpu.analysis.__main__ import main\n"
        "rc = main(['--json'])\n"
        "assert 'jax' not in sys.modules, 'analysis runner imported jax'\n"
        "sys.exit(rc)\n"
    )
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=60,
    )
    elapsed = time.monotonic() - t0
    assert proc.returncode == exitcodes.EXIT_CLEAN, (
        proc.stdout[-4000:],
        proc.stderr[-4000:],
    )
    report = json.loads(proc.stdout)
    assert set(report["rules"]) == {
        "layerck", "clockck", "syncck", "lockck", "deadck",
    }
    # The thread-plane rule ships its predicted graph for the runtime
    # cross-check (tests/test_deadck.py).
    assert report["deadck"]["predicted"], report.get("deadck")
    assert all(
        r["violations"] == [] for r in report["rules"].values()
    ), report
    # Every committed waiver carries a reason (the "ships clean or
    # reason-waived" acceptance).
    for r in report["rules"].values():
        for w in r["waived"]:
            assert w["reason"].strip()
    assert elapsed < 5.0, f"analysis run took {elapsed:.1f}s"


def test_runner_json_is_deterministic():
    r1, _ = run()
    r2, _ = run()
    a = json.dumps(r1, indent=2, sort_keys=True)
    b = json.dumps(r2, indent=2, sort_keys=True)
    assert a == b


def test_runner_exit_codes_per_rule_over_fixtures(capsys):
    # The fixture dir seeds exactly one real-manifest violation
    # (lock_bad.py): whole run exits 1, --rule lockck exits 1, while
    # --rule layerck alone exits 0 — the per-rule exit-code contract.
    root = str(FIXTURES)
    assert main(["--root", root]) == exitcodes.EXIT_VIOLATIONS
    assert main(["--root", root, "--rule", "lockck"]) == exitcodes.EXIT_VIOLATIONS
    assert main(["--root", root, "--rule", "layerck"]) == exitcodes.EXIT_CLEAN
    capsys.readouterr()


def test_benchmarks_scope_is_report_only(capsys):
    # Benchmark scripts are wall-clock tools: findings are reported, the
    # exit stays 0 (pyproject/README document the lane as report-only).
    assert main(["--scope", "benchmarks"]) == exitcodes.EXIT_CLEAN
    out = capsys.readouterr()
    assert "scope=benchmarks" in out.out


def test_usage_error_exits_internal(capsys):
    assert main(["--rule", "nosuchrule"]) == exitcodes.EXIT_INTERNAL
    capsys.readouterr()


# -- contract cross-pins -------------------------------------------------------

def test_ck_family_shares_one_exit_code_scheme():
    assert (traceck.EXIT_CLEAN, traceck.EXIT_VIOLATIONS, traceck.EXIT_INTERNAL) == (
        exitcodes.EXIT_CLEAN,
        exitcodes.EXIT_VIOLATIONS,
        exitcodes.EXIT_INTERNAL,
    )
    assert (promck.EXIT_CLEAN, promck.EXIT_VIOLATIONS, promck.EXIT_INTERNAL) == (
        exitcodes.EXIT_CLEAN,
        exitcodes.EXIT_VIOLATIONS,
        exitcodes.EXIT_INTERNAL,
    )
    assert (exitcodes.EXIT_CLEAN, exitcodes.EXIT_VIOLATIONS, exitcodes.EXIT_INTERNAL) == (0, 1, 2)


def test_runtime_guard_list_covers_clockck_sleep_half():
    """One list, two lanes: every runtime-bannable clock in
    CLOCK_BANNED_CALLS is in the simnet guard's list.  time.time is the
    documented exception (logging.LogRecord reads it at runtime) and
    datetime construction never paces anything."""
    runtime = set(manifest.SIMNET_RUNTIME_BANNED)
    assert ("time", "sleep") in runtime
    assert ("time", "monotonic") in runtime
    assert {("socket", "socket"), ("select", "select")} <= runtime
