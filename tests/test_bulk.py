"""Bulk pipeline: verdicts must match the per-batch solver and the oracle."""

import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig, solve_bulk
from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution, solve_oracle
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9, puzzle_batch


def _corpus(n_gen=12, n_clues=30):
    gen = puzzle_batch(SUDOKU_9, n_gen, seed=21, n_clues=n_clues)
    return np.concatenate([np.stack([EASY_9, *HARD_9]), gen]).astype(np.int32)


def test_bulk_solves_everything_and_validates():
    grids = _corpus()
    res = solve_bulk(grids, SUDOKU_9, BulkConfig(chunk=8))
    assert res.solved.all() and not res.unsat.any()
    for g, s in zip(grids, res.solution):
        assert is_valid_solution(s)
        assert ((g == 0) | (s == g)).all()  # clues preserved
    # the easy board needs no search; the hard trio does
    assert res.by_propagation[0]
    assert res.searched >= 3


def test_bulk_chunking_is_invisible():
    grids = _corpus(n_gen=6)
    a = solve_bulk(grids, SUDOKU_9, BulkConfig(chunk=4))
    b = solve_bulk(grids, SUDOKU_9, BulkConfig(chunk=64))
    np.testing.assert_array_equal(a.solution, b.solution)
    np.testing.assert_array_equal(a.solved, b.solved)


def test_bulk_stepped_rungs_match_defaults():
    """Force every board through the escalation rungs (first_pass_steps=1)
    with tiny bounded-step dispatches: the stepped rung driver must produce
    exactly the default pipeline's verdicts and solutions.  This is the
    regression net for the watchdog fix — straggler searches advance in
    dispatch_steps chunks instead of one unbounded while_loop dispatch."""
    grids = _corpus(n_gen=6)
    ref = solve_bulk(grids, SUDOKU_9, BulkConfig(chunk=32))
    stepped = solve_bulk(
        grids,
        SUDOKU_9,
        BulkConfig(
            chunk=32,
            first_pass_steps=1,
            dispatch_steps=3,
            rungs=((64, 2, 32), (64, 8, 64)),
        ),
    )
    np.testing.assert_array_equal(ref.solved, stepped.solved)
    np.testing.assert_array_equal(ref.unsat, stepped.unsat)
    np.testing.assert_array_equal(ref.solution, stepped.solution)


def test_bulk_rung_stack_budget_caps_gang_width():
    """A giant-geometry rung must narrow its gang to fit the stack budget
    (naive 9x9-tuned widths compile multi-GB stacks that crash the TPU
    compiler); verdicts stay correct at the narrowed width."""
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_16

    grids = puzzle_batch(
        SUDOKU_16, 4, seed=3, n_clues=150, unique=False
    ).astype(np.int32)
    res = solve_bulk(
        grids,
        SUDOKU_16,
        BulkConfig(
            chunk=4,
            first_pass_steps=1,  # force the rungs
            rungs=((64, 64, 256),),  # would be 1.07 GB at full width
            rung_stack_mb=64,  # forces lanes_per_job down to fit
            stack_slots=8,
        ),
    )
    assert res.solved.all()
    for g, s in zip(grids, res.solution):
        assert is_valid_solution(s, SUDOKU_16)
        assert ((g == 0) | (s == g)).all()


def test_bulk_reports_unsat():
    bad = np.stack([EASY_9, EASY_9]).astype(np.int32)
    bad[1, 0, 2] = 5  # row already holds a 5 -> contradiction
    res = solve_bulk(bad, SUDOKU_9, BulkConfig(chunk=2))
    assert res.solved[0] and not res.solved[1]
    assert res.unsat[1]
    assert solve_oracle(bad[1]) is None


def test_bulk_matches_oracle_solution_on_unique_puzzles():
    grids = puzzle_batch(SUDOKU_9, 4, seed=33, n_clues=28).astype(np.int32)
    res = solve_bulk(grids, SUDOKU_9, BulkConfig(chunk=4))
    assert res.solved.all()
    for g, s in zip(grids, res.solution):
        np.testing.assert_array_equal(s, solve_oracle(g))


def test_bulk_sharded_matches_single_device():
    import jax

    from distributed_sudoku_solver_tpu.parallel import make_mesh

    grids = _corpus(n_gen=8)
    mesh = make_mesh(jax.devices())
    a = solve_bulk(grids, SUDOKU_9, BulkConfig(chunk=8))
    s = solve_bulk(grids, SUDOKU_9, BulkConfig(chunk=8), mesh=mesh)
    np.testing.assert_array_equal(a.solved, s.solved)
    assert s.solved.all()
    for g, sol in zip(grids, s.solution):
        assert is_valid_solution(sol)
        assert ((g == 0) | (sol == g)).all()


def test_bulk_sharded_ragged_chunk_pads_evenly():
    import jax

    from distributed_sudoku_solver_tpu.parallel import make_mesh

    grids = _corpus(n_gen=1)[:5]  # 5 boards over 8 devices: pad path
    mesh = make_mesh(jax.devices())
    res = solve_bulk(grids, SUDOKU_9, BulkConfig(chunk=16), mesh=mesh)
    assert res.solved.all() and len(res.solved) == 5


def test_corrupt_values_stay_unsat_through_int8_wire():
    """The nibble 15-marker path: 9x9 defaults to the dense format now,
    so this pins the legacy packing explicitly (still the live format
    for 10 <= n <= 14 geometries and the mesh branch)."""
    from unittest import mock

    from distributed_sudoku_solver_tpu.ops import wire

    bad = np.stack([EASY_9, EASY_9]).astype(np.int32)
    bad[1, 0, 0] = 257  # would wrap to a legal-looking 1 via a bare int8 cast
    with mock.patch.object(wire, "best_format", return_value="packed"):
        res = solve_bulk(bad, SUDOKU_9, BulkConfig(chunk=2))
    assert res.solved[0] and not res.solved[1] and res.unsat[1]


def test_fused_rungs_solve_and_fall_back_by_admission():
    """Explicit fused rungs serve escalations correctly; a rung whose
    stack depth the kernel cannot serve (S=256) silently falls back to
    the composite step for that rung — verdicts identical either way."""
    grids = _corpus(n_gen=28, n_clues=24)
    shallow = BulkConfig(
        chunk=32, stack_slots=2, first_pass_steps=4,
        rungs=((64, 2, 8, 128), (64, 4, 256)),
    )
    import dataclasses

    fused = dataclasses.replace(shallow, rung_step_impl="fused")
    a = solve_bulk(grids, SUDOKU_9, shallow)
    tr: dict = {}
    b = solve_bulk(grids, SUDOKU_9, fused, trace=tr)
    assert a.solved.all() and b.solved.all()
    np.testing.assert_array_equal(a.solved, b.solved)
    for s in b.solution:
        assert is_valid_solution(s)
    # first rung fused-admitted (lanes rounded to the 128 tile), second
    # falls back: S=256 exceeds every measured compile boundary
    assert tr["rungs"][0]["lanes"] % 128 == 0
    if len(tr["rungs"]) > 1:
        assert tr["rungs"][1]["slots"] == 256


def test_dense_wire_bulk_matches_oracle():
    """The dense (10-bit triplet) wire format is the 9x9 single-chip
    default: solutions must match the oracle bit-for-bit and the corrupt
    contract must hold without a wire code point."""
    from distributed_sudoku_solver_tpu.ops import wire

    assert wire.best_format(SUDOKU_9) == "dense"
    grids = _corpus(n_gen=6)
    bad = grids.copy()
    bad[2, 0, 0] = -3
    res = solve_bulk(bad, SUDOKU_9, BulkConfig(chunk=8))
    assert res.unsat[2] and not res.solved[2]
    ok = np.ones(len(bad), bool)
    ok[2] = False
    assert res.solved[ok].all()
    assert np.array_equal(res.solution[0], solve_oracle(grids[0]))
