"""Checkpoint/resume: interrupted solves continue bit-exactly (SURVEY.md §5.4)."""

import os

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.ops.solve import solve_batch
from distributed_sudoku_solver_tpu.utils.checkpoint import (
    advance_frontier,
    frontier_done,
    grids_digest,
    load_frontier,
    save_frontier,
    solve_batch_checkpointed,
    start_frontier,
)
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

CFG = SolverConfig(min_lanes=16, stack_slots=48)


def test_checkpointed_equals_direct(tmp_path):
    grids = np.stack([EASY_9, HARD_9[0], HARD_9[1]])
    ckpt = str(tmp_path / "front.npz")
    saves = []
    res = solve_batch_checkpointed(
        grids, SUDOKU_9, CFG, checkpoint_path=ckpt, chunk_steps=4,
        on_chunk=lambda st: saves.append(int(st.steps)),
    )
    direct = solve_batch(grids, SUDOKU_9, CFG)
    np.testing.assert_array_equal(np.asarray(res.solution), np.asarray(direct.solution))
    np.testing.assert_array_equal(np.asarray(res.solved), np.asarray(direct.solved))
    assert int(res.steps) == int(direct.steps)
    assert saves, "expected at least one checkpoint chunk"
    assert not os.path.exists(ckpt), "checkpoint removed after completion"


def test_resume_after_simulated_crash(tmp_path):
    grids = np.stack([HARD_9[0]])
    ckpt = str(tmp_path / "front.npz")

    # "Crash" after a few chunks: drive manually, save, drop all live state.
    state = start_frontier(np.asarray(grids), SUDOKU_9, CFG)
    state = advance_frontier(state, np.int32(6), SUDOKU_9, CFG)
    assert not frontier_done(state)
    save_frontier(ckpt, state, SUDOKU_9, CFG, grids_hash=grids_digest(grids))
    steps_at_crash = int(state.steps)
    del state

    # Restart: resumes from the file, no recomputation of the first chunk.
    res = solve_batch_checkpointed(
        grids, SUDOKU_9, CFG, checkpoint_path=ckpt, chunk_steps=64
    )
    direct = solve_batch(grids, SUDOKU_9, CFG)
    assert int(res.steps) == int(direct.steps) >= steps_at_crash
    np.testing.assert_array_equal(np.asarray(res.solution), np.asarray(direct.solution))


def test_signature_mismatch_rejected(tmp_path):
    ckpt = str(tmp_path / "front.npz")
    state = start_frontier(np.stack([EASY_9]), SUDOKU_9, CFG)
    save_frontier(ckpt, state, SUDOKU_9, CFG)
    other = SolverConfig(min_lanes=32, stack_slots=48)
    with pytest.raises(ValueError, match="signature mismatch"):
        load_frontier(ckpt, SUDOKU_9, other)


def test_checkpoint_for_different_grids_rejected(tmp_path):
    # A stale checkpoint from batch A must not resume for batch B.
    ckpt = str(tmp_path / "front.npz")
    grids_a = np.stack([HARD_9[0]])
    state = start_frontier(grids_a, SUDOKU_9, CFG)
    state = advance_frontier(state, np.int32(4), SUDOKU_9, CFG)
    save_frontier(ckpt, state, SUDOKU_9, CFG, grids_hash=grids_digest(grids_a))
    grids_b = np.stack([HARD_9[1]])
    with pytest.raises(ValueError, match="signature mismatch"):
        load_frontier(ckpt, SUDOKU_9, CFG, grids_hash=grids_digest(grids_b))
