"""Randomized differential tests: the TPU pipeline vs the Python oracle.

The SURVEY.md §4 property layer: on *arbitrary* random boards (not just
well-formed puzzles) every verdict must agree with the independent oracle —
solved implies a valid completion of the input, unsat implies the oracle
finds no solution, and unique-solution boards decode bit-exactly.
"""

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig, solve_bulk
from distributed_sudoku_solver_tpu.utils.oracle import (
    is_valid_solution,
    solve_oracle,
)
from distributed_sudoku_solver_tpu.utils.puzzles import random_solution


def _random_boards(seed: int, count: int, keep_lo=0.3, keep_hi=0.9):
    """Boards made by masking random *valid* solutions plus random noise
    boards (which are usually inconsistent): both verdict paths get hit."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(count):
        if i % 3 < 2:  # masked valid solution: sat (maybe multi-solution)
            sol = random_solution(SUDOKU_9, seed * 1000 + i)
            keep = rng.random((9, 9)) < rng.uniform(keep_lo, keep_hi)
            out.append(np.where(keep, sol, 0))
        else:  # random scribble: usually unsat or inconsistent
            board = np.zeros((9, 9), dtype=np.int64)
            for _ in range(rng.integers(8, 30)):
                r, c = rng.integers(0, 9, 2)
                board[r, c] = rng.integers(1, 10)
            out.append(board)
    return np.stack(out).astype(np.int32)


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_bulk_verdicts_match_oracle_on_random_boards(seed):
    grids = _random_boards(seed, 24)
    res = solve_bulk(grids, SUDOKU_9, BulkConfig(chunk=24))
    for i, g in enumerate(grids):
        oracle_sol = solve_oracle(g)
        if res.solved[i]:
            s = res.solution[i]
            assert is_valid_solution(s), f"board {i}: invalid solution"
            assert ((g == 0) | (s == g)).all(), f"board {i}: clue changed"
            assert oracle_sol is not None, f"board {i}: oracle says unsat"
        elif res.unsat[i]:
            assert oracle_sol is None, f"board {i}: oracle disagrees on unsat"
        # neither solved nor unsat (budget exhausted) never happens at 9x9
        assert res.solved[i] or res.unsat[i], f"board {i}: unresolved"


@pytest.mark.parametrize("seed", [11, 12])
def test_strategy_matrix_verdicts_agree(seed):
    """Every solver strategy is sound and complete, so on ANY board the
    verdict (solved / unsat) must be identical across the whole strategy
    matrix — branch rules, digit orders, branch_k, inference tiers — even
    though the searches (and, on multi-solution boards, the returned
    solutions) differ.  Each returned solution must be a valid completion
    of its input."""
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.ops.solve import solve_batch

    boards = _random_boards(seed, 9)
    configs = [
        SolverConfig(min_lanes=8, stack_slots=32, branch="minrem"),
        SolverConfig(min_lanes=8, stack_slots=32, branch="minrem-desc"),
        SolverConfig(min_lanes=8, stack_slots=32, branch="first"),
        SolverConfig(min_lanes=8, stack_slots=32, branch="mixed"),
        SolverConfig(min_lanes=8, stack_slots=32, branch_k=3),
        SolverConfig(min_lanes=8, stack_slots=32, rules="extended"),
        SolverConfig(min_lanes=8, stack_slots=32, branch="minrem-desc", branch_k=3),
    ]
    results = [solve_batch(boards, SUDOKU_9, cfg) for cfg in configs]
    ref_solved = np.asarray(results[0].solved)
    ref_unsat = np.asarray(results[0].unsat)
    for cfg, res in zip(configs, results):
        np.testing.assert_array_equal(
            np.asarray(res.solved), ref_solved, err_msg=f"solved mismatch: {cfg}"
        )
        np.testing.assert_array_equal(
            np.asarray(res.unsat), ref_unsat, err_msg=f"unsat mismatch: {cfg}"
        )
        for i in range(len(boards)):
            if ref_solved[i]:
                s = np.asarray(res.solution[i])
                assert is_valid_solution(s), f"{cfg} invalid solution {i}"
                mask = boards[i] > 0
                assert np.array_equal(s[mask], boards[i][mask])
    # Cross-check the verdict against the oracle on every board.
    for i in range(len(boards)):
        oracle_sol = solve_oracle(boards[i], SUDOKU_9)
        assert ref_solved[i] == (oracle_sol is not None)


@pytest.mark.parametrize("seed", [11, 12])
def test_count_all_fused_matches_composite_on_random_boards(seed):
    """Differential enumeration fuzz (round 4): on random boards with
    modest clue density (counts stay tractable), the fused count-mode
    kernel and the composite step must report IDENTICAL model counts and
    completion verdicts — purge/steal granularity may change which first
    solution is reported, never how many exist."""
    from distributed_sudoku_solver_tpu import native
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.ops.solve import solve_batch

    # Pre-screen with the native counter: a random mask occasionally
    # leaves a many-thousand-solution board whose exhaustive enumeration
    # takes minutes in interpret mode — skip those deterministically (the
    # property is count EQUALITY, which small-count boards test just as
    # hard), keeping the lane bounded.
    raw = _random_boards(seed, 16, keep_lo=0.6, keep_hi=0.95)
    if native.available():
        keep = [
            b for b in raw
            if native.count_solutions(b, SUDOKU_9, limit=300) < 300
        ]
        grids = np.stack(keep[:12]) if keep else raw[:4]
    else:
        grids = raw[:4]
    kw = dict(min_lanes=16, stack_slots=32, max_steps=50_000, count_all=True)
    ref = solve_batch(grids, SUDOKU_9, SolverConfig(**kw))
    got = solve_batch(grids, SUDOKU_9, SolverConfig(step_impl="fused", **kw))
    ref_c = np.asarray(ref.sol_count)
    got_c = np.asarray(got.sol_count)
    complete = np.asarray(ref.unsat) & np.asarray(got.unsat)
    np.testing.assert_array_equal(got_c[complete], ref_c[complete])
    np.testing.assert_array_equal(np.asarray(got.unsat), np.asarray(ref.unsat))
    if native.available():
        for i in np.flatnonzero(complete)[:4]:
            assert (
                native.count_solutions(grids[i], SUDOKU_9, limit=1_000_000)
                == int(got_c[i])
            ), f"board {i}"
