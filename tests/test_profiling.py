"""utils/profiling.py coverage (round-11 satellites): StatWindow edge
cases that were never pinned (empty / single-sample / wraparound /
concurrent torn-window tolerance), the narrowed ``stop_trace`` swallow,
and the bounded serving profile window behind ``POST /profile``."""

import logging
import threading
import time

import pytest

from distributed_sudoku_solver_tpu.utils import profiling
from distributed_sudoku_solver_tpu.utils.profiling import StatWindow


# -- StatWindow ----------------------------------------------------------------


def test_statwindow_empty_and_single_sample():
    w = StatWindow(capacity=8)
    assert w.snapshot() is None
    w.record(5.0)
    snap = w.snapshot()
    assert snap["count"] == 1 and snap["total"] == 1
    # One sample: every percentile IS that sample.
    assert snap["p50"] == snap["p95"] == snap["p99"] == 5.0


def test_statwindow_capacity_plus_one_wraparound():
    """capacity+1 records: the ring holds exactly the last `capacity`
    values (the oldest was overwritten), and percentiles read the window
    content, not stale slots."""
    w = StatWindow(capacity=4)
    for v in (1.0, 2.0, 3.0, 4.0, 5.0):
        w.record(v)
    snap = w.snapshot()
    assert snap["count"] == 4 and snap["total"] == 5
    # Window = {2, 3, 4, 5}: the evicted 1.0 must not drag p50 down, and
    # p99 must not exceed the maximum surviving sample.
    assert 3.0 <= snap["p50"] <= 4.0
    assert snap["p99"] <= 5.0
    assert snap["p50"] >= 2.0


def test_statwindow_full_wraparound_correctness():
    """Many wraps: the window is exactly the last `capacity` samples."""
    w = StatWindow(capacity=8)
    for v in range(1, 101):
        w.record(float(v))
    snap = w.snapshot()
    assert snap["count"] == 8 and snap["total"] == 100
    # Survivors are 93..100.
    assert 93.0 <= snap["p50"] <= 100.0
    assert snap["p99"] <= 100.0
    assert snap["p95"] >= snap["p50"] >= 93.0


def test_statwindow_concurrent_writer_reader_torn_window():
    """The documented contract: a reader racing the writer gets a
    consistent-enough snapshot — never an exception, never a value outside
    the recorded range (every slot always holds a recorded value or the
    initial 0.0 before the window fills, and count never exceeds
    capacity)."""
    w = StatWindow(capacity=64)
    stop = threading.Event()
    errors = []

    def writer():
        v = 0
        while not stop.is_set():
            w.record((v % 100) / 100.0)  # all values in [0, 1)
            v += 1

    def reader():
        try:
            while not stop.is_set():
                snap = w.snapshot()
                if snap is None:
                    continue
                assert 1 <= snap["count"] <= 64
                assert 0.0 <= snap["p50"] <= 1.0
                assert 0.0 <= snap["p99"] <= 1.0
                assert snap["total"] >= snap["count"]
        except Exception as e:  # noqa: BLE001 - recorded for the assert below
            errors.append(e)

    threads = [threading.Thread(target=writer), threading.Thread(target=reader)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join(5)
    assert not errors, errors


# -- device_trace stop swallow (satellite fix) ---------------------------------


def test_device_trace_swallows_only_already_stopped(tmp_path, caplog):
    """The documented race — the bounded window timer stopped the trace
    first — stays silent; any OTHER stop_trace failure is logged instead
    of hidden (the pre-round-11 bare `except RuntimeError: pass`)."""
    import jax

    from distributed_sudoku_solver_tpu.utils.profiling import device_trace

    with caplog.at_level(logging.ERROR):
        with device_trace(str(tmp_path / "t1")):
            jax.profiler.stop_trace()  # the window timer fired "early"
    assert not caplog.records, "already-stopped case must stay silent"


def test_device_trace_logs_real_stop_failures(tmp_path, caplog, monkeypatch):
    import jax

    from distributed_sudoku_solver_tpu.utils.profiling import device_trace

    real_stop = jax.profiler.stop_trace
    with caplog.at_level(logging.ERROR):
        with device_trace(str(tmp_path / "t2")):
            monkeypatch.setattr(
                jax.profiler,
                "stop_trace",
                lambda: (_ for _ in ()).throw(
                    RuntimeError("trace export failed: disk full")
                ),
            )
    assert any("stop_trace failed" in r.getMessage() for r in caplog.records)
    monkeypatch.setattr(jax.profiler, "stop_trace", real_stop)
    real_stop()  # the real session is still open: close it for later tests


# -- the bounded profile window (POST /profile backend) ------------------------


def test_profile_window_is_exclusive_and_self_closing(tmp_path):
    assert not profiling.profile_window_active()
    assert profiling.start_profile_window(str(tmp_path / "w1"), 0.2) is True
    assert profiling.profile_window_active()
    # Exclusive while open.
    assert profiling.start_profile_window(str(tmp_path / "w2"), 0.2) is False
    # Self-closing: the daemon timer stops the trace without a second call.
    deadline = time.monotonic() + 10.0
    while profiling.profile_window_active() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not profiling.profile_window_active(), "window never self-closed"
    # Reusable after close.
    assert profiling.start_profile_window(str(tmp_path / "w3"), 0.1) is True
    deadline = time.monotonic() + 10.0
    while profiling.profile_window_active() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not profiling.profile_window_active()


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-v"]))
